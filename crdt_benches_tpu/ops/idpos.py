"""Epoch-structured element-id -> physical-position resolution.

The id-based integration paths (downstream update apply, concurrent merge)
must answer, inside the TIMED region: *where is element ``s`` in my document
right now?*  This is the work the reference's timed ``apply_update`` performs
through each CRDT's internal index (diamond-types' order tree,
reference src/rope.rs:222-224); skipping it by shipping encode-time-resolved
positions would under-count the timed workload (round-1 advisor finding).

A slot-indexed position array is the obvious structure, but keeping it exact
every batch needs either a capacity-sized scatter (serializes: ~18ms at
R=128, C=295k, measured by tools/micro_idpos.py) or a capacity-sized gather
(worse).  The TPU-shaped answer is an **epoch structure**:

- ``snap`` int32[R, C]: slot -> physical position, exact as of the last
  epoch boundary.  Rebuilt by ONE scatter every ``K`` batches (amortized
  ~18/K ms).
- per batch inside the epoch, a **level**: the batch's insert destinations
  in ``D_i - i`` form (sorted dests minus their index — the count_le array
  that maps a pre-batch position to its post-batch shift) plus the
  (slot, dest) pairs for same-epoch id matches.

A query gathers the stale position from ``snap`` (a B-row ``take_along_axis``
— ~0.9ms, the cheap direction) and walks the epoch's levels oldest->newest:
add the level's shift (#{D_i - i <= p}, a B x B compare), then override with
the exact destination if the id was inserted *at* that level (B x B equality
on slot ids).  Every step is a fused VPU compare-reduce; nothing touches a
capacity-sized scatter/gather until the next epoch boundary.

Positions here are physical (tombstones included), so deletes never move
anything — only insert destinations shift positions, which is what makes the
level form exact.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

# Host-side on purpose (np, not jnp): a module-scope DEVICE scalar is
# created inside whatever trace context is live at first import — the
# serve runner imports engines lazily from inside jitted regions on this
# jax version, and the leaked tracer kills __graft_entry__.dryrun_multichip.
# A committed device constant also forces the slow dispatch path on the
# axon tunnel (README environment notes).
BIG = np.int32(2**30)


class Level(NamedTuple):
    """One batch's contribution to the epoch position map, in RUN form —
    each entry is a contiguous block of ``rlen`` consecutive slots
    (slot0..slot0+rlen-1) inserted at post-batch positions
    dest0..dest0+rlen-1.  Unit-op batches are rlen == 1 runs.

    ``sub[i] = dest0[i] - (chars of runs placed at smaller dest0)`` is the
    weighted count_le form: an old element at pre-batch position p gains
    ``sum_i rlen[i] * [sub[i] <= p]`` new left neighbors (a run never
    splits around an old element — it fills one gap contiguously)."""

    sub: jax.Array  # int32[R, B] (BIG for invalid rows)
    rlen: jax.Array  # int32[R, B] run length (0 for invalid rows)
    slot0: jax.Array  # int32[R, B] first slot id (BIG for invalid rows)
    dest0: jax.Array  # int32[R, B] post-batch position of slot0


def snap_rebuild(doc: jax.Array) -> jax.Array:
    """slot -> physical position from the packed doc (one scatter; epoch
    boundaries only).  Unused slots stay 0 — queries never ask for absent
    ids (CRDT causality: an op's origin/target is always integrated)."""
    R, C = doc.shape
    slot = jnp.right_shift(doc, 1) - 2
    idx = jax.lax.broadcasted_iota(jnp.int32, (R, C), 1)
    tgt = jnp.where(slot >= 0, slot, C)
    return jax.vmap(
        lambda t, i: jnp.zeros(C, jnp.int32).at[t].set(i, mode="drop")
    )(tgt, idx)


def snap_init(n_replicas: int, capacity: int) -> jax.Array:
    """Epoch snapshot for a fresh document (slots 0..n_init-1 laid out in
    order; identity covers every present slot)."""
    return jnp.broadcast_to(
        jnp.arange(capacity, dtype=jnp.int32), (n_replicas, capacity)
    )


def make_level_runs(
    dest0: jax.Array, rlen: jax.Array, slot0: jax.Array, live: jax.Array
) -> Level:
    """Build a level from a batch's insert runs.

    dest0: int32[R, B] post-batch position of each run's first char
    (garbage where ``~live``); rlen: run lengths; slot0: first slot ids.
    ``sub[i] = dest0[i] - P[i]`` where P[i] = total chars of runs with
    smaller dest0 (a B x B weighted count — runs fill distinct gaps, so
    dest0 ties cannot occur among live runs).
    """
    L = jnp.where(live, rlen, 0)
    d = jnp.where(live, dest0, BIG)
    before = jnp.sum(
        jnp.where(d[:, None, :] < d[:, :, None], L[:, None, :], 0), axis=2
    )
    return Level(
        sub=jnp.where(live, d - before, BIG),
        rlen=L,
        slot0=jnp.where(live, slot0, BIG),
        dest0=dest0,
    )


def make_level(dest: jax.Array, is_ins: jax.Array, slot: jax.Array) -> Level:
    """Unit-op level: each insert is a length-1 run."""
    return make_level_runs(dest, jnp.ones_like(dest), slot, is_ins)


def query(
    snap: jax.Array, levels: list[Level], ids: jax.Array
) -> jax.Array:
    """Current physical positions of ``ids`` (int32[R, B]; rows with
    ids < 0 return garbage — mask at the call site).  ``levels`` are the
    epoch's batches oldest-first; each is applied as shift-then-override
    (an id inserted at level k takes its in-run position, already in that
    level's frame, then shifts through newer levels)."""
    R, C = snap.shape
    # ids < 0 is IN the contract (docstring): the clamp region's
    # garbage is masked by every caller, which lives outside this
    # module (engine/downstream*), so the in-module mask-pair rule
    # cannot see it — suppressed, not annotated
    p = jnp.take_along_axis(snap, jnp.clip(ids, 0, C - 1), axis=1)  # graftlint: disable=G026
    for lv in levels:
        shift = jnp.sum(
            jnp.where(
                lv.sub[:, None, :] <= p[:, :, None], lv.rlen[:, None, :], 0
            ),
            axis=2,
        )
        p = p + shift
        off = ids[:, :, None] - lv.slot0[:, None, :]
        m = (off >= 0) & (off < lv.rlen[:, None, :])
        found = jnp.any(m, axis=2)
        pd = jnp.sum(jnp.where(m, lv.dest0[:, None, :] + off, 0), axis=2)
        p = jnp.where(found, pd, p)
    return p
