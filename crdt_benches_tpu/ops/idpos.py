"""Epoch-structured element-id -> physical-position resolution.

The id-based integration paths (downstream update apply, concurrent merge)
must answer, inside the TIMED region: *where is element ``s`` in my document
right now?*  This is the work the reference's timed ``apply_update`` performs
through each CRDT's internal index (diamond-types' order tree,
reference src/rope.rs:222-224); skipping it by shipping encode-time-resolved
positions would under-count the timed workload (round-1 advisor finding).

A slot-indexed position array is the obvious structure, but keeping it exact
every batch needs either a capacity-sized scatter (serializes: ~18ms at
R=128, C=295k, measured by tools/micro_idpos.py) or a capacity-sized gather
(worse).  The TPU-shaped answer is an **epoch structure**:

- ``snap`` int32[R, C]: slot -> physical position, exact as of the last
  epoch boundary.  Rebuilt by ONE scatter every ``K`` batches (amortized
  ~18/K ms).
- per batch inside the epoch, a **level**: the batch's insert destinations
  in ``D_i - i`` form (sorted dests minus their index — the count_le array
  that maps a pre-batch position to its post-batch shift) plus the
  (slot, dest) pairs for same-epoch id matches.

A query gathers the stale position from ``snap`` (a B-row ``take_along_axis``
— ~0.9ms, the cheap direction) and walks the epoch's levels oldest->newest:
add the level's shift (#{D_i - i <= p}, a B x B compare), then override with
the exact destination if the id was inserted *at* that level (B x B equality
on slot ids).  Every step is a fused VPU compare-reduce; nothing touches a
capacity-sized scatter/gather until the next epoch boundary.

Positions here are physical (tombstones included), so deletes never move
anything — only insert destinations shift positions, which is what makes the
level form exact.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

BIG = jnp.int32(2**31 - 1)


class Level(NamedTuple):
    """One batch's contribution to the epoch position map."""

    sub: jax.Array  # int32[R, B] sorted (dest_i - i), invalid rows = BIG
    slot: jax.Array  # int32[R, B] inserted slot ids (-1 = no insert)
    dest: jax.Array  # int32[R, B] post-batch destination of slot


def snap_rebuild(doc: jax.Array) -> jax.Array:
    """slot -> physical position from the packed doc (one scatter; epoch
    boundaries only).  Unused slots stay 0 — queries never ask for absent
    ids (CRDT causality: an op's origin/target is always integrated)."""
    R, C = doc.shape
    slot = jnp.right_shift(doc, 1) - 2
    idx = jax.lax.broadcasted_iota(jnp.int32, (R, C), 1)
    tgt = jnp.where(slot >= 0, slot, C)
    return jax.vmap(
        lambda t, i: jnp.zeros(C, jnp.int32).at[t].set(i, mode="drop")
    )(tgt, idx)


def snap_init(n_replicas: int, capacity: int) -> jax.Array:
    """Epoch snapshot for a fresh document (slots 0..n_init-1 laid out in
    order; identity covers every present slot)."""
    return jnp.broadcast_to(
        jnp.arange(capacity, dtype=jnp.int32), (n_replicas, capacity)
    )


def make_level(dest: jax.Array, is_ins: jax.Array, slot: jax.Array) -> Level:
    """Build a level from a batch's insert destinations.

    dest: int32[R, B] post-batch destinations (garbage where ``~is_ins``);
    slot: int32[R, B] inserted slot ids.  The count_le form: with dests
    sorted ascending (pads at the end as BIG), the i-th smallest dest has
    exactly ``D_i - i`` old elements before it, so an old element at
    pre-batch position p gains ``#{i : D_i - i <= p}`` new left neighbors.
    """
    d = jnp.sort(jnp.where(is_ins, dest, BIG), axis=1)
    i = jax.lax.broadcasted_iota(jnp.int32, d.shape, 1)
    sub = jnp.where(d < BIG, d - i, BIG)
    return Level(
        sub=sub,
        slot=jnp.where(is_ins, slot, -1),
        dest=dest,
    )


def query(
    snap: jax.Array, levels: list[Level], ids: jax.Array
) -> jax.Array:
    """Current physical positions of ``ids`` (int32[R, B]; rows with
    ids < 0 return garbage — mask at the call site).  ``levels`` are the
    epoch's batches oldest-first; each is applied as shift-then-override."""
    R, C = snap.shape
    p = jnp.take_along_axis(snap, jnp.clip(ids, 0, C - 1), axis=1)
    for lv in levels:
        shift = jnp.sum(
            (lv.sub[:, None, :] <= p[:, :, None]).astype(jnp.int32), axis=2
        )
        p = p + shift
        eq = ids[:, :, None] == lv.slot[:, None, :]
        found = jnp.any(eq, axis=2)
        pd = jnp.sum(jnp.where(eq, lv.dest[:, None, :], 0), axis=2)
        p = jnp.where(found, pd, p)
    return p
