"""Shared Pallas-TPU import with cross-version compat.

The kernels target the current pallas API (``pltpu.CompilerParams``);
jax <= 0.4.x still names it ``TPUCompilerParams`` (the rename landed in
0.5).  Every Pallas module imports ``pltpu`` from here so the shim lives
in exactly one place.
"""

from __future__ import annotations

from jax.experimental import pallas as pl  # noqa: F401  (re-export)
from jax.experimental.pallas import tpu as pltpu

if not hasattr(pltpu, "CompilerParams"):  # pragma: no cover
    pltpu.CompilerParams = pltpu.TPUCompilerParams

__all__ = ["pl", "pltpu"]
