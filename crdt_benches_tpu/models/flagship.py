"""Flagship model: the batched CRDT replay engine, as one configured object.

The reference's "models" are its four CRDT adapters behind the Upstream /
Downstream traits (reference src/rope.rs:6-33,185-191).  Here the analogous
surface is a single TPU-native engine family parameterized by configuration
rather than four separate implementations:

- ``upstream(trace)``   — local-edit replay (Upstream capability)
- ``downstream(trace)`` — remote-update apply (Downstream capability)
- both batched over a replica axis and built from the same kernel stack
  (fused Pallas resolver -> packed doc-order apply).

``FlagshipConfig()`` with no arguments IS the headline configuration
bench.py runs: the RLE-coalesced RANGE engine through the fused v4 kernel
(ops/apply_range_fused.py), 1024 replicas, op batch 1536.  The per-char
unit engine remains reachable via ``layout="unit"`` — it is the
differential twin the tests replay against the same oracle, and the
labeled ``jax-unit`` bench column.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..backends.jax_backend import JaxReplayBackend
from ..engine.downstream import JaxDownstreamEngine
from ..traces.loader import TestData, load_testing_data
from ..traces.tensorize import tensorize


@dataclass
class FlagshipConfig:
    """Tuned defaults of the headline benchmark (bench.py knobs:
    CRDT_BENCH_REPLICAS=1024, CRDT_BENCH_BATCH=1536, auto layout)."""

    n_replicas: int = 1024  # replica-parallel width (the DP analog)
    batch: int = 1536  # ops per resolver kernel launch
    pack: int = 8  # op batches per scan step
    #: 'auto' picks the coalesced range engine when RLE shrinks the op
    #: stream >= 2x (all four reference traces); 'range'/'unit' force.
    layout: str = "auto"
    #: range-path apply: 'v4' = fused Pallas kernel, 'v3' = XLA per-pass
    #: twin (the auto-fallback above the VMEM gate).  None = env default.
    range_engine: str | None = "v4"
    #: unit-path apply generation, used only when layout resolves to
    #: 'unit' (ReplayEngine: v4 fused / v3 packed / v2 / v1 legacy).
    unit_engine: str = "v4"
    resolver: str | None = None  # unit-op resolver (None = auto: pallas on TPU)
    downstream_engine: str | None = None  # None = CRDT_DOWN_ENGINE (v5)


def backend(cfg: FlagshipConfig | None = None) -> JaxReplayBackend:
    """The flagship as a bench-table backend (the ``jax`` column)."""
    cfg = cfg or FlagshipConfig()
    return JaxReplayBackend(
        n_replicas=cfg.n_replicas,
        batch=cfg.batch,
        layout=None if cfg.layout == "auto" else cfg.layout,
        pack=cfg.pack,
        range_engine=cfg.range_engine,
        unit_engine=cfg.unit_engine,
        resolver=cfg.resolver,
    )


def upstream(trace: TestData | str, cfg: FlagshipConfig | None = None):
    """Local-edit replay engine for ``trace`` under ``cfg`` —
    RangeReplayEngine on the headline path, ReplayEngine when the layout
    resolves to 'unit'.  Engine selection is delegated to
    JaxReplayBackend.prepare so the flagship object and the benchmark
    can never drift apart."""
    cfg = cfg or FlagshipConfig()
    if isinstance(trace, str):
        trace = load_testing_data(trace)
    bk = backend(cfg)
    bk.prepare(trace)
    return bk.engine


def downstream(
    trace: TestData | str, cfg: FlagshipConfig | None = None
) -> JaxDownstreamEngine:
    cfg = cfg or FlagshipConfig()
    if isinstance(trace, str):
        trace = load_testing_data(trace)
    tt = tensorize(trace, batch=cfg.batch)
    return JaxDownstreamEngine(
        tt, n_replicas=cfg.n_replicas, engine=cfg.downstream_engine
    )
