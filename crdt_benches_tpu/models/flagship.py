"""Flagship model: the batched CRDT replay engine, as one configured object.

The reference's "models" are its four CRDT adapters behind the Upstream /
Downstream traits (reference src/rope.rs:6-33,185-191).  Here the analogous
surface is a single TPU-native engine family parameterized by configuration
rather than four separate implementations:

- ``upstream(trace)``   — local-edit replay (Upstream capability)
- ``downstream(trace)`` — remote-update apply (Downstream capability)
- both batched over a replica axis and built from the same kernel stack
  (fused Pallas resolver -> packed doc-order apply).

``FlagshipConfig`` pins the tuned defaults the headline benchmark uses.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..engine.downstream import JaxDownstreamEngine
from ..engine.replay import ReplayEngine, default_resolver
from ..traces.loader import TestData, load_testing_data
from ..traces.tensorize import tensorize


@dataclass
class FlagshipConfig:
    n_replicas: int = 128  # replica-parallel width (the DP analog)
    batch: int = 512  # ops per resolver kernel launch
    pack: int = 8  # op batches per scan step
    engine: str = "v3"  # packed doc-order apply
    resolver: str | None = None  # None = auto (pallas on TPU)


def upstream(trace: TestData | str, cfg: FlagshipConfig | None = None) -> ReplayEngine:
    cfg = cfg or FlagshipConfig()
    if isinstance(trace, str):
        trace = load_testing_data(trace)
    tt = tensorize(trace, batch=cfg.batch)
    return ReplayEngine(
        tt,
        n_replicas=cfg.n_replicas,
        resolver=cfg.resolver or default_resolver(),
        engine=cfg.engine,
        pack=cfg.pack,
    )


def downstream(
    trace: TestData | str, cfg: FlagshipConfig | None = None
) -> JaxDownstreamEngine:
    cfg = cfg or FlagshipConfig()
    if isinstance(trace, str):
        trace = load_testing_data(trace)
    tt = tensorize(trace, batch=cfg.batch)
    return JaxDownstreamEngine(
        tt, n_replicas=cfg.n_replicas, engine=cfg.engine
    )
