"""Range-layout downstream: run-granular update generation + timed apply.

The unit-op downstream (engine/downstream.py) explodes block edits into
per-char ops — up to 24x op inflation on rustcode (SURVEY.md section 6) —
so its timed apply does O(chars) sequential-batch work.  This module keeps
updates at the reference's own granularity (diamond-types run-length-encodes
sequential-insert runs into its binary updates, reference src/rope.rs:214):
one wire op per contiguous insert RUN or delete INTERVAL, so batch count
scales with patches, not characters.

Wire form (generated UNTIMED, like reference ``upstream_updates``):

- insert run: (anchor, rank, slot0, rlen, alive) — the run's ``rlen``
  consecutive slots integrate directly after ``anchor`` (an element the
  receiver has already integrated; -1 = document head), ordered among
  same-anchor runs by ``rank``; ``alive=0`` runs are inserted already
  tombstoned (every char is deleted later in the SAME batch — generation
  splits runs at kill boundaries so aliveness is uniform per wire run).
- delete interval: (dfirst, dlast) — element ids of the first and
  last earlier-batch targets; at apply time every *visible* element in the
  physical interval [pos(dfirst), pos(dlast)] is a target (tombstones in
  between were deleted earlier; same-batch targets are not in the pre-batch
  doc at all — they arrive dead via ``alive=0`` runs).

The TIMED apply resolves anchor/dfirst/dlast ids to current physical
positions per RUN inside the timed region (ops/idpos.py epoch structure —
the like-for-like CRDT integration work, see engine/downstream.py), then
integrates whole batches with interval spreads, two capacity cumsums, the
arithmetic run fill (delta painting, like ops/apply_range.py), and the
fused expansion kernel.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..lint.boundary import boundary
from ..traces.loader import TestData
from ..traces.tensorize import tensorize
from .downstream import DownPacked
from .replay import _round_up


@dataclass
class RangeUpdates:
    """One trace's range-granular updates as batched tensors (rows = wire
    batches, width = max wire ops per batch; -1/0 padding)."""

    anchor: np.ndarray  # int32[nb, W] insert-run anchor (-1 head, -2 pad)
    rank: np.ndarray  # int32[nb, W]
    slot0: np.ndarray  # int32[nb, W] first slot (-1 = not an insert)
    rlen: np.ndarray  # int32[nb, W]
    alive: np.ndarray  # int32[nb, W] 0/1
    dfirst: np.ndarray  # int32[nb, W] delete-interval first id (-1 = none)
    dlast: np.ndarray  # int32[nb, W]
    capacity: int
    n_init: int
    chars: np.ndarray
    end_content: str
    n_patches: int

    def nbytes(self) -> int:
        return sum(
            a.nbytes
            for a in (
                self.anchor, self.rank, self.slot0, self.rlen, self.alive,
                self.dfirst, self.dlast,
            )
        )


def generate_range_updates(
    trace: TestData, batch_ops: int = 256, lane: int = 128
) -> RangeUpdates:
    """UNTIMED generation: one unit-op upstream replay (device) for the
    final order + delete targets, then host-side run extraction.

    Wire batches are ``batch_ops`` consecutive original range ops (one per
    patch component); anchors always reference elements from EARLIER wire
    batches (or init content), ranks order same-anchor runs, and runs are
    split wherever a same-batch delete kills part of them.
    """
    tt = tensorize(trace, batch=512)
    capacity = _round_up(max(tt.capacity, 1), lane)
    n_init = len(tt.init_chars)

    # Insertion-faithful replay via the native treap (local inserts splice
    # DIRECTLY after their origin): the JAX engine's final order is only
    # content-equivalent — tombstone-relative placement can differ, which
    # is invisible in content but breaks the delete-interval contiguity
    # this wire form relies on.  The native dump's order is the exact
    # order the receiver's anchor-directly-after rule reproduces.
    from ..backends.native import lib
    from ..traces.patches import patch_arrays

    pa = patch_arrays(trace)
    n_del_total = int(pa.del_count.sum())
    order_buf = np.zeros(capacity, np.int32)
    vis_buf = np.zeros(capacity, np.uint8)
    dtgt_buf = np.zeros(max(n_del_total, 1), np.int32)
    length = int(
        lib().crdt_replay_dump(
            pa.init, len(pa.init), pa.pos, pa.del_count, pa.ins_off,
            pa.ins_flat, pa.n_patches,
            order_buf, capacity, vis_buf, dtgt_buf, len(dtgt_buf),
        )
    )
    assert length > 0, "native replay dump failed (buffer too small?)"
    order = order_buf[:length]

    # delete-target slots per unit delete op, in unit-op order: the native
    # oplog records deletes patch-interleaved exactly like the unit
    # explosion (del_count deletes then the insert chars per patch).
    dslot_unit = np.full(tt.n_ops, -1, np.int32)
    u = 0
    t = 0
    for pos, dcount, ins in trace.iter_patches():
        if dcount:
            dslot_unit[u : u + dcount] = dtgt_buf[t : t + dcount]
            u += dcount
            t += dcount
        u += len(ins)
    assert t == n_del_total

    # ---- range ops (one per patch component), in unit-op order ----
    # unit ops were emitted per patch as del_count DELETEs then the insert
    # chars (traces/tensorize.py explode_unit_ops); walk patches to segment
    # the unit streams into range ops and assign wire batches.
    r_kind: list[int] = []  # INSERT=1 / DELETE=2
    r_a: list[int] = []  # unit-op start index
    r_len: list[int] = []
    u = 0
    for pos, dcount, ins in trace.iter_patches():
        if dcount:
            r_kind.append(2)
            r_a.append(u)
            r_len.append(dcount)
            u += dcount
        if ins:
            r_kind.append(1)
            r_a.append(u)
            r_len.append(len(ins))
            u += len(ins)
    assert u == tt.n_ops
    n_rops = len(r_kind)
    r_kind_a = np.asarray(r_kind, np.int32)
    r_a_a = np.asarray(r_a, np.int64)
    r_len_a = np.asarray(r_len, np.int64)
    rbatch = np.arange(n_rops, dtype=np.int64) // batch_ops
    nb = int(rbatch[-1]) + 1 if n_rops else 1

    # wire-batch index of every slot (insert unit ops) / -1 for init
    batch_of_slot = np.full(capacity, -1, np.int64)
    is_rins = r_kind_a == 1
    for i in np.nonzero(is_rins)[0]:
        s0 = tt.slot[r_a_a[i]]
        batch_of_slot[s0 : s0 + r_len_a[i]] = rbatch[i]

    pos_of_slot = np.full(capacity, -1, np.int64)
    pos_of_slot[order] = np.arange(length)
    arrb = batch_of_slot[order]

    from .downstream import _prev_smaller

    a_pos_all = _prev_smaller(arrb)

    # killed[slot]: deleted by a delete op in the SAME wire batch
    del_batch = np.full(capacity, -1, np.int64)  # wire batch that deletes it
    for i in np.nonzero(r_kind_a == 2)[0]:
        tgt = dslot_unit[r_a_a[i] : r_a_a[i] + r_len_a[i]]
        del_batch[tgt] = rbatch[i]
    killed = (del_batch >= 0) & (del_batch == batch_of_slot)

    # per-batch sorted final positions of that batch's slots — used to
    # split runs wherever a SAME-batch later op inserted inside them (the
    # wire form requires runs contiguous at end-of-own-batch; later-batch
    # interposers don't matter, they integrate afterwards).
    pos_by_batch: dict[int, np.ndarray] = {}
    for b in range(nb):
        sl = np.nonzero(batch_of_slot == b)[0]
        pos_by_batch[b] = np.sort(pos_of_slot[sl])

    # ---- build wire ops per batch ----
    rows: list[list[tuple]] = [[] for _ in range(nb)]
    for i in range(n_rops):
        b = int(rbatch[i])
        if r_kind_a[i] == 2:
            tgt = dslot_unit[r_a_a[i] : r_a_a[i] + r_len_a[i]]
            prev = tgt[~killed[tgt]]  # earlier-batch targets, doc order
            if len(prev):
                rows[b].append(
                    ("D", int(prev[0]), int(prev[-1]), len(prev))
                )
        else:
            s0 = int(tt.slot[r_a_a[i]])
            L = int(r_len_a[i])
            k = killed[s0 : s0 + L]
            q = pos_of_slot[s0 : s0 + L]
            # split at kill-uniformity changes and at same-batch
            # interpositions (consecutive chars not adjacent among this
            # batch's positions)
            idx_pb = np.searchsorted(pos_by_batch[b], q)
            cut = (np.diff(k.astype(np.int8)) != 0) | (
                np.diff(idx_pb) > 1
            )
            cuts = np.nonzero(cut)[0] + 1
            seg0 = np.concatenate([[0], cuts])
            seg1 = np.concatenate([cuts, [L]])
            for a0, a1 in zip(seg0, seg1):
                rows[b].append(
                    ("I", s0 + int(a0), int(a1 - a0), 0 if k[a0] else 1)
                )

    # anchors/ranks for every insert segment, from the final order
    seg_batch, seg_slot0, seg_len, seg_alive = [], [], [], []
    seg_row_idx = []  # (batch, index within batch rows)
    for b, ops in enumerate(rows):
        for j, op in enumerate(ops):
            if op[0] == "I":
                seg_batch.append(b)
                seg_slot0.append(op[1])
                seg_len.append(op[2])
                seg_alive.append(op[3])
                seg_row_idx.append((b, j))
    if seg_slot0:
        q0 = pos_of_slot[np.asarray(seg_slot0, np.int64)]
        a_pos = a_pos_all[q0]
        a_slot = np.where(a_pos >= 0, order[np.clip(a_pos, 0, None)], -1)
        sb = np.asarray(seg_batch, np.int64)
        srt = np.lexsort((q0, a_pos, sb))
        kb, ka = sb[srt], a_pos[srt]
        grp = np.concatenate(
            [[True], (kb[1:] != kb[:-1]) | (ka[1:] != ka[:-1])]
        )
        idx = np.arange(len(srt))
        r_sorted = idx - np.maximum.accumulate(np.where(grp, idx, 0))
        rank = np.empty_like(r_sorted)
        rank[srt] = r_sorted
    else:
        a_slot = rank = np.zeros(0, np.int64)

    W = max((len(ops) for ops in rows), default=1)
    W = max(W, 1)
    anchor = np.full((nb, W), -2, np.int32)
    rank_a = np.zeros((nb, W), np.int32)
    slot0_a = np.full((nb, W), -1, np.int32)
    rlen_a = np.zeros((nb, W), np.int32)
    alive_a = np.zeros((nb, W), np.int32)
    dfirst = np.full((nb, W), -1, np.int32)
    dlast = np.full((nb, W), -1, np.int32)
    si = 0
    for b, ops in enumerate(rows):
        for j, op in enumerate(ops):
            if op[0] == "I":
                anchor[b, j] = a_slot[si]
                rank_a[b, j] = rank[si]
                slot0_a[b, j] = op[1]
                rlen_a[b, j] = op[2]
                alive_a[b, j] = op[3]
                si += 1
            else:
                dfirst[b, j] = op[1]
                dlast[b, j] = op[2]

    from .replay import slot_char_table

    return RangeUpdates(
        anchor=anchor, rank=rank_a, slot0=slot0_a, rlen=rlen_a,
        alive=alive_a, dfirst=dfirst, dlast=dlast,
        capacity=capacity, n_init=n_init,
        chars=slot_char_table(tt, capacity),
        end_content=tt.end_content, n_patches=tt.n_patches,
    )


def _apply_range_update_batch5(
    doc, length, nvis, snap, levels,
    anchor, rank, slot0, rlen, alive, dfirst, dlast,
    *, nbits: int,
):
    """Integrate one range wire batch with id->position resolution inside
    the timed region.  Wire rows are shared across replicas (shape (W,))."""
    from ..ops.apply2 import _mxu_spread, _excl_cumsum_small, LANE
    from ..ops.idpos import make_level_runs, query

    R, C = doc.shape
    W = anchor.shape[0]
    drop = jnp.int32(C + 7)
    col = jax.lax.broadcasted_iota(jnp.int32, (R, C), 1)
    is_ins = slot0 >= 0
    has_del = dfirst >= 0
    bc = lambda x: jnp.broadcast_to(x[None], (R, W))

    # ---- resolve ids: anchors + delete interval endpoints in ONE query
    # (a (R, 3W) id batch shares the per-level shift/override passes) ----
    allq = query(
        snap, levels,
        jnp.concatenate([bc(anchor), bc(dfirst), bc(dlast)], axis=1),
    )
    a_phys = allq[:, :W]
    lo_phys = allq[:, W : 2 * W]
    hi_phys = allq[:, 2 * W :]
    gap = jnp.where(
        bc(is_ins), jnp.where(bc(anchor) >= 0, a_phys + 1, 0), drop
    )

    # ---- deletes: clear visible bits over [lo, hi] (guarded) ----
    lo_phys = jnp.where(bc(has_del), lo_phys, drop)
    hi_phys = jnp.where(bc(has_del), hi_phys, drop - 7)
    (starts,) = _mxu_spread(
        lo_phys, [jnp.ones((R, W), jnp.int32)], C
    )
    (stops,) = _mxu_spread(
        hi_phys + 1, [jnp.ones((R, W), jnp.int32)], C
    )
    in_del = jnp.cumsum(starts - stops, axis=1) > 0
    vis_bit = jnp.bitwise_and(doc, 1)
    sub = vis_bit * in_del.astype(jnp.int32)
    doc_predel = doc - sub
    n_del_eff = jnp.sum(sub, axis=1)

    # ---- run destinations: gap + chars of runs ordered before me ----
    # lexicographic (gap, rank) weighted prefix, per replica
    L = jnp.where(is_ins, rlen, 0)
    g = gap
    r_ = bc(rank)
    earlier = (
        (g[:, None, :] < g[:, :, None])
        | ((g[:, None, :] == g[:, :, None]) & (r_[:, None, :] < r_[:, :, None]))
    ) & bc(is_ins)[:, None, :]
    chars_before = jnp.sum(
        jnp.where(earlier, bc(L)[:, None, :], 0), axis=2
    )
    dest0 = jnp.where(bc(is_ins), g + chars_before, drop)
    dstop = jnp.where(bc(is_ins), dest0 + bc(rlen), drop - 7)

    # ---- insert indicator + expansion count base ----
    (s1,) = _mxu_spread(dest0, [jnp.ones((R, W), jnp.int32)], C)
    (s2,) = _mxu_spread(dstop, [jnp.ones((R, W), jnp.int32)], C)
    ind = (jnp.cumsum(s1 - s2, axis=1) > 0).astype(jnp.int32)
    nt = C // LANE
    cnt_base = _excl_cumsum_small(
        jnp.sum(ind.reshape(R, nt, LANE), axis=2)
    )

    # ---- arithmetic fill: slot(d) = d + delta(run), vis per run ----
    # per-run delta = slot0 - dest0, painted as cumsum of differences at
    # run starts (runs processed in dest order).
    ordk = jnp.where(bc(is_ins), dest0, drop)
    perm = jnp.argsort(ordk, axis=1)
    d_sorted = jnp.take_along_axis(dest0, perm, axis=1)
    s_sorted = jnp.take_along_axis(bc(slot0), perm, axis=1)
    v_sorted = jnp.take_along_axis(bc(alive), perm, axis=1)
    live_sorted = jnp.take_along_axis(bc(is_ins), perm, axis=1)
    delta = jnp.where(live_sorted, s_sorted - d_sorted, 0)
    pd = jnp.concatenate(
        [jnp.zeros((R, 1), jnp.int32), delta[:, :-1]], axis=1
    )
    pl = jnp.concatenate(
        [jnp.zeros((R, 1), bool), live_sorted[:, :-1]], axis=1
    )
    ddelta = jnp.where(live_sorted, delta - jnp.where(pl, pd, 0), 0)
    dvis = jnp.where(
        live_sorted,
        v_sorted - jnp.where(
            pl,
            jnp.concatenate(
                [jnp.zeros((R, 1), jnp.int32), v_sorted[:, :-1]], axis=1
            ),
            0,
        ),
        0,
    )
    dpos_ = jnp.where(live_sorted, d_sorted, drop)
    chunks = [
        jnp.bitwise_and(jnp.where(ddelta > 0, ddelta, 0), 127),
        jnp.bitwise_and(
            jnp.right_shift(jnp.where(ddelta > 0, ddelta, 0), 7), 127
        ),
        jnp.bitwise_and(
            jnp.right_shift(jnp.where(ddelta > 0, ddelta, 0), 14), 127
        ),
        jnp.bitwise_and(jnp.where(ddelta < 0, -ddelta, 0), 127),
        jnp.bitwise_and(
            jnp.right_shift(jnp.where(ddelta < 0, -ddelta, 0), 7), 127
        ),
        jnp.bitwise_and(
            jnp.right_shift(jnp.where(ddelta < 0, -ddelta, 0), 14), 127
        ),
        jnp.where(dvis > 0, dvis, 0),
        jnp.where(dvis < 0, -dvis, 0),
    ]
    p0, p1, p2, n0, n1, n2, vp, vn = _mxu_spread(dpos_, chunks, C)
    dd_dense = (
        p0 + jnp.left_shift(p1, 7) + jnp.left_shift(p2, 14)
        - n0 - jnp.left_shift(n1, 7) - jnp.left_shift(n2, 14)
    )
    delta_cum = jnp.cumsum(dd_dense, axis=1)
    vis_run = jnp.cumsum(vp - vn, axis=1)
    fill_slot = col + delta_cum
    combo = jnp.where(
        ind > 0,
        jnp.left_shift(
            (jnp.left_shift(fill_slot + 2, 1) | vis_run), 1
        )
        | 1,
        0,
    )

    n_ins = jnp.sum(jnp.where(is_ins, rlen, 0))
    n_live = jnp.sum(jnp.where(is_ins, rlen * alive, 0))
    length2 = length + n_ins

    from ..ops.expand_pallas import fused_apply_nocv_dispatch

    doc2 = fused_apply_nocv_dispatch(
        doc_predel, combo, cnt_base, length2, nbits=nbits
    )
    level = make_level_runs(dest0, bc(rlen), bc(slot0), bc(is_ins))
    return doc2, length2, nvis + n_live - n_del_eff, level


@boundary(
    dtypes=(None, "int32", "int32", "int32", "int32", None,
            "int32", "int32"),
    shapes=(None, "N B", "N B", "N B", "N B", "N B", "N B",
            "N B"),
    donates=(0,),
)
@partial(jax.jit, static_argnames=("nbits", "epoch"), donate_argnums=(0,))
def apply_range_updates5(
    state: DownPacked,
    anchor_b, rank_b, slot0_b, rlen_b, alive_b, dfirst_b, dlast_b,
    *, nbits: int, epoch: int = 32,
) -> DownPacked:
    """Scan all range wire batches; snapshot epoch structure as in
    engine/downstream.py apply_updates5."""
    from ..ops.idpos import snap_rebuild

    NB, W = anchor_b.shape
    K = min(epoch, NB)
    if NB % K:
        raise ValueError(f"batch count {NB} not a multiple of epoch {K}")
    rs = lambda x: x.reshape(NB // K, K, W)

    def step(st, upd):
        a, r, s0, ln, al, df, dl = upd
        doc, snap, length, nvis = st
        levels: list = []
        for k in range(K):
            doc, length, nvis, lv = _apply_range_update_batch5(
                doc, length, nvis, snap, levels,
                a[k], r[k], s0[k], ln[k], al[k], df[k], dl[k],
                nbits=nbits,
            )
            levels.append(lv)
        return DownPacked(doc, snap_rebuild(doc), length, nvis), None

    state, _ = jax.lax.scan(
        step, state,
        tuple(
            rs(x)
            for x in (
                anchor_b, rank_b, slot0_b, rlen_b, alive_b,
                dfirst_b, dlast_b,
            )
        ),
    )
    return state


class JaxRangeDownstreamEngine:
    """Host-side driver: untimed range-update generation, timed apply."""

    def __init__(self, trace: TestData, n_replicas: int = 1,
                 batch_ops: int = 256, epoch: int | None = None):
        import os

        self.upd = generate_range_updates(trace, batch_ops=batch_ops)
        # |ddelta| < 2C must fit the 3x7-bit run-delta chunks (fail loudly,
        # ADVICE round 1): capacity < 2^20.
        if self.upd.capacity >= 1 << 20:
            raise ValueError(
                f"capacity {self.upd.capacity} >= 2^20 exceeds the"
                " run-delta chunked-arithmetic range"
            )
        self.n_replicas = n_replicas
        self.epoch = (
            epoch
            if epoch is not None
            else int(os.environ.get("CRDT_DOWN_EPOCH", "32"))
        )
        self.epoch = min(self.epoch, max(1, self.upd.anchor.shape[0]))
        pad = (-self.upd.anchor.shape[0]) % self.epoch
        f = lambda a, fill: jnp.asarray(
            np.concatenate(
                [a, np.full((pad, a.shape[1]), fill, np.int32)]
            )
            if pad
            else a
        )
        self.anchor_b = f(self.upd.anchor, -2)
        self.rank_b = f(self.upd.rank, 0)
        self.slot0_b = f(self.upd.slot0, -1)
        self.rlen_b = f(self.upd.rlen, 0)
        self.alive_b = f(self.upd.alive, 0)
        self.dfirst_b = f(self.upd.dfirst, -1)
        self.dlast_b = f(self.upd.dlast, -1)
        self.chars = jnp.asarray(self.upd.chars)
        self.nbits = max(
            1, int(self.upd.rlen.sum(axis=1).max(initial=1)).bit_length()
        )

    def run(self) -> DownPacked:
        from ..ops.apply2 import init_state3
        from ..ops.idpos import snap_init

        s3 = init_state3(
            self.n_replicas, self.upd.capacity, self.upd.n_init
        )
        st = DownPacked(
            doc=s3.doc,
            snap=snap_init(self.n_replicas, self.upd.capacity),
            length=s3.length,
            nvis=s3.nvis,
        )
        return apply_range_updates5(
            st, self.anchor_b, self.rank_b, self.slot0_b, self.rlen_b,
            self.alive_b, self.dfirst_b, self.dlast_b,
            nbits=self.nbits, epoch=self.epoch,
        )

    def decode(self, state: DownPacked, replica: int = 0) -> str:
        from ..ops.apply2 import PackedState, decode_state3

        codes, nvis = jax.jit(
            decode_state3, static_argnames=("replica",)
        )(
            PackedState(
                doc=state.doc, length=state.length, nvis=state.nvis
            ),
            self.chars,
            replica=replica,
        )
        return "".join(map(chr, np.asarray(codes)[: int(nvis)].tolist()))


class JaxRangeDownstreamBackend:
    """Downstream bench backend on range-granular updates (bench column
    ``jax-*-range``): timed region = fresh replica + full apply + length
    fetch (reference src/main.rs:62-69 semantics; element = patch)."""

    def __init__(self, n_replicas: int = 1, batch_ops: int = 2048):
        # Big op batches win here: per-batch O(C) vector passes dominate,
        # and the W x W interleave compares stay cheap (measured on
        # rustcode: batch_ops 256 -> 2048 is ~4x aggregate throughput).
        self.n_replicas = n_replicas
        self.batch_ops = batch_ops
        self._eng: JaxRangeDownstreamEngine | None = None

    @property
    def NAME(self) -> str:
        plat = jax.devices()[0].platform
        tag = f"-r{self.n_replicas}" if self.n_replicas > 1 else ""
        return f"jax-{plat}{tag}-range"

    @property
    def replicas(self) -> int:
        return self.n_replicas

    def prepare(self, trace: TestData) -> None:
        self._eng = JaxRangeDownstreamEngine(
            trace, n_replicas=self.n_replicas, batch_ops=self.batch_ops
        )
        self._end_len = len(trace.end_content)

    def replay_once(self) -> int:
        state = self._eng.run()
        lengths = np.asarray(state.nvis)
        assert (lengths == self._end_len).all(), (
            f"length mismatch: {lengths} != {self._end_len}"
        )
        return int(lengths.reshape(-1)[0])

    def final_content(self) -> str:
        return self._eng.decode(self._eng.run())
