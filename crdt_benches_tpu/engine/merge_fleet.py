"""Batched downstream merge for the document fleet: remote-apply rows.

The serve engine's macro scan body — resolve one round of per-row RANGE
ops against the running visible counts, then apply them to the packed
row states — IS the fleet's downstream-merge primitive: when a row is a
*replica* of a document whose writer lives elsewhere (serve/replicate/),
the ops staged into that row's lanes are **remote** ops delivered by the
broadcast bus, and this body integrates them exactly like the reference's
``apply_update`` path (engine/downstream.py) integrates pre-resolved
updates.  This module makes that primitive a first-class engine entry
point instead of an anonymous closure duplicated across the pool's scan
kernel and the recovery replayer:

- :func:`merge_rows_body` — the traceable body (resolve + apply for one
  round over R rows).  ``serve/pool.py _build_macro_fn`` scans it for
  the ``--serve-kernel scan`` form, and ``serve/journal.py _replayer``
  replays recovery intervals through it, so the scan serve kernel, the
  crash-recovery path, and the replication merge are ONE code path.
  The ``--serve-kernel fused`` form is the accelerated twin of the same
  semantics (``ops/serve_fused.py`` detaches the resolve recurrence
  from the apply); fused-vs-scan byte parity is pinned by
  tests/test_serve_macro.py and tests/test_serve_fused.py, which is
  what licenses routing replication through either kernel.
- :func:`merge_rows_round` / :func:`merge_rows_macro` — the public
  jitted ``@boundary`` entry points (one round / K scanned rounds) for
  direct engine users; tests/test_serve_replicate.py pins BOTH against
  the sequential-interleaving oracle (a writer group's assembled
  broadcast stream replayed through them equals the oracle replay
  byte-for-byte, and round-by-round equals the K-scanned form).

Commutativity note (the ``merge_reorder`` chaos fault relies on this):
remote batches are sequenced by the broadcast bus — each replica
assembles blocks by sequence number before any op reaches these
kernels — so *delivery* order is free to permute while the *applied*
stream stays the arbitration order.  The merge itself is deterministic
in that assembled order; the commutation happens at the reassembly
layer, the same split diamond-types makes between transport and
integration.
"""

from __future__ import annotations

from functools import partial

import jax

from ..lint.boundary import boundary
from ..ops.apply_range import apply_range_batch
from ..ops.resolve_range_scan import resolve_ranges_rows


def merge_rows_body(state, kind, pos, rlen, slot0, *, nbits: int):
    """One round's batched merge for R rows — resolve each row's range
    batch against its running visible count, apply on the packed state.
    Traceable (no jit of its own): the pool's scan kernel and the
    recovery replayer inline it into their own executables."""
    tokens, dints, _ = resolve_ranges_rows(kind, pos, rlen, slot0, state.nvis)
    return apply_range_batch(state, tokens, dints, nbits=nbits)


@boundary(
    dtypes=(None, "int32", "int32", "int32", "int32"),
    shapes=(None, "R B", "R B", "R B", "R B"),
    donates=(0,),
)
@partial(jax.jit, static_argnames=("nbits",), donate_argnums=(0,))
def merge_rows_round(state, kind, pos, rlen, slot0, *, nbits: int):
    """Jitted single-round merge: integrate one (R, B) broadcast batch
    into R replica rows (row r = the next batch for the doc/replica in
    row r; ``kind == PAD`` lanes are no-ops end to end)."""
    return merge_rows_body(state, kind, pos, rlen, slot0, nbits=nbits)


@boundary(
    dtypes=(None, "int32", "int32", "int32", "int32"),
    shapes=(None, "K R B", "K R B", "K R B", "K R B"),
    donates=(0,),
)
@partial(jax.jit, static_argnames=("nbits",), donate_argnums=(0,))
def merge_rows_macro(state, kind, pos, rlen, slot0, *, nbits: int):
    """K scanned rounds of :func:`merge_rows_round` in one dispatch —
    the engine-level form of ``DocPool.macro_step``'s scan kernel: an
    assembled broadcast stream replayed through it over a fresh replica
    row is the sequential-interleaving oracle's device twin
    (differentially pinned in tests/test_serve_replicate.py)."""

    def body(st, sl):
        k, p, ln, s0 = sl
        return merge_rows_body(st, k, p, ln, s0, nbits=nbits), None

    out, _ = jax.lax.scan(body, state, (kind, pos, rlen, slot0))
    return out
