"""Run-granular concurrent merge: integrate whole insert RUNS per step.

The unit-op merge (engine/merge.py) integrates the delivered union one
element at a time — 1.35M sequential unit ops for the rustcode+seph-blog1
concurrent-agents workload (~178k patches), which left that cell slower
than one CPU core (round-2 verdict).  diamond-types' own wire encoding is
run-length encoded (reference src/rope.rs:214 encodes positional runs);
this module brings the same granularity to the merge path: one wire op per
contiguous insert run / delete interval, so the sequential batch count
scales with RUNS (~33k for the traces config) instead of characters.

Correctness design
------------------
Element ids are (lamport, agent) with lamport-consecutive runs: a run's
j-th element has key ``head_key + j*MAX_AGENTS``.  Like the unit path, the
union is integrated in ASCENDING HEAD-KEY order, so at integration time
every previously-placed sibling (run head under the same origin element)
has a smaller head key — RGA's newest-first sibling rule then places each
new run DIRECTLY after its anchor element, no sibling skipping (the same
classical fact engine/merge.py relies on, lifted from elements to runs).

Runs are atomic per batch, which is only sound when a run head anchoring
at element ``o`` either finds no chain-child of ``o`` (o is its run's last
element) or outranks that chain-child's key.  The one violating pattern is
an exact lamport tie with a smaller agent id; :func:`check_no_skip`
verifies the precondition host-side at wire-translation time and callers
fall back to the unit merge when it fails (it cannot occur for agents
diverging from a shared base — they only anchor on base or own elements).

Within a batch the run forest (same-batch anchor containment) is resolved
in parallel with the W x W boolean-matmul closure of engine/merge.py
``_chain_structure``, extended to runs: a child run anchored mid-parent
SPLITS the parent into pieces, so the batch emits up to 2W FRAGMENTS,
each with (external anchor, char-offset rank, slot0, len) — exactly the
wire form of the range downstream apply
(engine/downstream_range.py ``_apply_range_update_batch5``), which this
module reuses verbatim for the position-resolved integration.

Deletes commute and positions are PHYSICAL (tombstones never move,
ops/idpos.py), so delete intervals are folded ONCE after all inserts:
paint a killed-slot indicator from the id intervals, then one
capacity-sized scatter through the final slot->position snapshot clears
visibility — the same cost class as a single epoch snapshot rebuild.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..lint.boundary import boundary
from ..traces.tensorize import DELETE, INSERT
from .downstream import DownPacked, down_packed_init
from .merge import MAX_AGENTS, MergeSimulation, OpLog


@dataclass
class RunLog:
    """One agent's op log as runs (the RLE wire form).

    Insert runs: ``rlen`` lamport/slot-consecutive elements; ``origin`` is
    the HEAD's origin slot (-1 = document head) and element j chains on
    element j-1.  Delete intervals: inclusive slot ranges [dlo, dhi]."""

    lamport: np.ndarray  # int32[Nr] head lamport
    agent: np.ndarray  # int32[Nr]
    slot0: np.ndarray  # int32[Nr]
    rlen: np.ndarray  # int32[Nr]
    origin: np.ndarray  # int32[Nr] head origin slot (-1 = doc head)
    dlo: np.ndarray  # int32[Nd] delete interval first slot
    dhi: np.ndarray  # int32[Nd] delete interval last slot
    n_unit_ops: int  # unit ops this log RLE-compresses (element count)


def runs_from_oplog(
    log: OpLog, patch_start: np.ndarray | None = None
) -> RunLog:
    """RLE a lamport-ascending unit-op log into insert runs + delete
    intervals (host, untimed — wire translation, the analog of the cpp
    baseline's untimed ``to_native_ops``).

    ``patch_start`` (optional bool[len(log)], aligned with the log's
    unit-op emission order): force a run/interval break wherever True —
    the PER-PATCH wire granularity (one update per trace patch, matching
    the reference's generation loop src/rope.rs:196-220; no coalescing
    across patch boundaries).  None = maximal RLE (the coalesced wire,
    the form diamond-types' own binary updates take internally)."""
    lam, ag, kind = log.lamport, log.agent, log.kind
    elem, orig = log.elem, log.origin
    is_ins = kind == INSERT
    prev_elem = np.roll(elem, 1)
    prev_lam = np.roll(lam, 1)
    cont = (
        is_ins
        & np.roll(is_ins, 1)
        & (orig == prev_elem)
        & (lam == prev_lam + 1)
        & (elem == prev_elem + 1)
    )
    if patch_start is not None:
        cont &= ~patch_start
    if len(cont):
        cont[0] = False
    head = is_ins & ~cont
    hidx = np.nonzero(head)[0]
    # run lengths: distance to the next head within the insert stream
    run_id = np.cumsum(head) - 1
    rlen = np.bincount(
        run_id[is_ins], minlength=len(hidx)
    ).astype(np.int32)

    # delete intervals: ascending-contiguous target slots coalesce; any
    # other step starts a new interval (deletes commute — interval
    # structure is just wire compactness)
    is_del = kind == DELETE
    didx = np.nonzero(is_del)[0]
    dtgt = elem[didx]
    if len(dtgt):
        brk = np.concatenate([[True], np.diff(dtgt) != 1])
        if patch_start is not None:
            brk |= patch_start[didx]
        d0 = np.nonzero(brk)[0]
        d1 = np.concatenate([d0[1:], [len(dtgt)]])
        dlo = dtgt[d0].astype(np.int32)
        dhi = dtgt[d1 - 1].astype(np.int32)
    else:
        dlo = dhi = np.zeros(0, np.int32)

    return RunLog(
        lamport=lam[hidx].astype(np.int32),
        agent=ag[hidx].astype(np.int32),
        slot0=elem[hidx].astype(np.int32),
        rlen=rlen,
        origin=orig[hidx].astype(np.int32),
        dlo=dlo,
        dhi=dhi,
        n_unit_ops=int(is_ins.sum() + is_del.sum()),
    )


def check_no_skip(runlogs: list[RunLog]) -> bool:
    """Host precondition for run-atomic integration (module docstring):
    every run head anchoring at a non-last element ``o`` of some run must
    outrank o's chain child, i.e. NOT (head.lamport == o.lamport + 1 AND
    head.agent < o.agent).  True = the fast path is exact."""
    slot0 = np.concatenate([r.slot0 for r in runlogs])
    rlen = np.concatenate([r.rlen for r in runlogs])
    lam0 = np.concatenate([r.lamport for r in runlogs])
    ag = np.concatenate([r.agent for r in runlogs])
    if not len(slot0):
        return True
    order = np.argsort(slot0)
    s0, rl, l0, a0 = slot0[order], rlen[order], lam0[order], ag[order]
    for r in runlogs:
        o = r.origin
        m = o >= 0
        if not m.any():
            continue
        j = np.searchsorted(s0, o[m], side="right") - 1
        j = np.clip(j, 0, len(s0) - 1)
        off = o[m] - s0[j]
        inside = (off >= 0) & (off < rl[j])
        has_child = inside & (off < rl[j] - 1)
        o_lam = l0[j] + off
        bad = has_child & (r.lamport[m] == o_lam + 1) & (
            r.agent[m] < a0[j]
        )
        if bad.any():
            return False
    return True


# ---- device integration -----------------------------------------------------

# Host-side on purpose (np, not jnp): a module-scope DEVICE scalar is
# created inside whatever trace context is live at first import and gets
# captured by every jit as a committed buffer (the ops/idpos.py BIG
# tracer-leak incident; graftlint G001 enforces this now).
BIGKEY = np.int32(2**31 - 1)


def _run_batch_fragments(key, slot0, rlen, origin):
    """In-batch run forest -> integration fragments, all parallel W x W
    work shared across replicas (the run-granular ``_chain_structure``).

    Inputs are one batch's runs sorted ascending by ``key`` (head key;
    BIGKEY rows = padding).  Returns fragment arrays of width 2W:
    (anchor slot, char-offset rank within the anchor's gap group, slot0,
    rlen); invalid fragments have slot0 == -1, rlen == 0.
    """
    W = key.shape[0]
    j = jnp.arange(W, dtype=jnp.int32)
    live = (key < BIGKEY) & (rlen > 0)

    # parent: the same-batch run containing my head's origin element.
    inside = (
        (origin[:, None] >= slot0[None, :])
        & (origin[:, None] < (slot0 + rlen)[None, :])
        & live[None, :]
        & live[:, None]
    )
    parent = jnp.sum(jnp.where(inside, j[None, :] + 1, 0), axis=1) - 1
    internal = parent >= 0
    # chars of the parent before my splice point (cut after this many)
    off = jnp.where(
        internal,
        origin - jnp.sum(jnp.where(inside, slot0[None, :], 0), axis=1) + 1,
        0,
    )

    # ancestor closure (proper ancestors), log W boolean squarings.
    A = (parent[:, None] == j[None, :]) & internal[:, None]
    for _ in range(max(1, (W - 1).bit_length())):
        prod = (
            jnp.einsum(
                "xm,ma->xa",
                A.astype(jnp.bfloat16),
                A.astype(jnp.bfloat16),
                preferred_element_type=jnp.float32,
            )
            > 0
        )
        A = A | prod
    AoS = A | ((j[:, None] == j[None, :]) & live[:, None])
    # subtree char sizes
    size = rlen + jnp.sum(
        jnp.where(A, rlen[:, None], 0), axis=0
    )

    # frame precedence M[a, b]: a's subtree entirely before b's at a
    # shared frame — same internal parent, or roots sharing an external
    # anchor (off == 0 there).  Newest-first: same offset -> larger op
    # index (= larger key) first.
    both = live[:, None] & live[None, :]
    same_int = (
        internal[:, None]
        & internal[None, :]
        & (parent[:, None] == parent[None, :])
    )
    root_pair = (
        ~internal[:, None]
        & ~internal[None, :]
        & (origin[:, None] == origin[None, :])
    )
    framed = both & (same_int | root_pair) & (j[:, None] != j[None, :])
    less = (off[:, None] < off[None, :]) | (
        (off[:, None] == off[None, :]) & (j[:, None] > j[None, :])
    )
    M = framed & less

    # whole-subtree precedence of g before r: g directly frame-precedes
    # some ancestor-or-self of r (maximal preceding subtree roots only —
    # no double counting).
    topb = (
        jnp.einsum(
            "gs,rs->gr",
            M.astype(jnp.bfloat16),
            AoS.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )
        > 0
    )
    # char rank of each run's first char within its gap group
    rank_chars = jnp.sum(
        jnp.where(topb, size[:, None], 0), axis=0
    ) + jnp.sum(jnp.where(AoS, jnp.where(internal, off, 0)[None, :], 0),
                axis=1)

    # external anchor: my root's origin.
    is_root = live & ~internal
    root = (
        jnp.sum(jnp.where(AoS & is_root[None, :], j[None, :] + 1, 0), axis=1)
        - 1
    )
    anchor = jnp.where(
        live, origin[jnp.clip(root, 0, W - 1)], -2
    )
    anchor = jnp.where(live & ~internal, origin, anchor)

    # ---- fragments ----
    # head piece of run w: chars [0, first cut); parent piece after w's
    # cut: chars [off_w, next cut), owned by the OLDEST (min op index)
    # child at (parent, off).
    child_of = (parent[None, :] == j[:, None]) & internal[None, :]  # [p, c]
    first_cut = jnp.min(
        jnp.where(child_of, off[None, :], jnp.int32(1 << 30)), axis=1
    )
    head_len = jnp.minimum(rlen, first_cut)

    next_cut = jnp.min(
        jnp.where(
            child_of[parent] & (off[None, :] > off[:, None]),
            off[None, :],
            jnp.int32(1 << 30),
        ),
        axis=1,
    )
    p_rlen = rlen[jnp.clip(parent, 0, W - 1)]
    piece_len = jnp.minimum(p_rlen, next_cut) - off
    owner = internal & (
        jnp.sum(
            jnp.where(
                (parent[None, :] == parent[:, None])
                & internal[None, :]
                & (off[None, :] == off[:, None])
                & (j[None, :] < j[:, None]),
                1,
                0,
            ),
            axis=1,
        )
        == 0
    )
    # chars of sibling subtrees cut at or before my offset
    sib_before = jnp.sum(
        jnp.where(
            (parent[None, :] == parent[:, None])
            & internal[None, :]
            & (off[None, :] <= off[:, None]),
            size[None, :],
            0,
        ),
        axis=1,
    )
    p_idx = jnp.clip(parent, 0, W - 1)
    piece_rank = rank_chars[p_idx] + off + sib_before
    piece_slot0 = slot0[p_idx] + off
    piece_anchor = anchor[p_idx]

    f_anchor = jnp.concatenate(
        [jnp.where(live, anchor, -2), jnp.where(owner, piece_anchor, -2)]
    )
    f_rank = jnp.concatenate(
        [jnp.where(live, rank_chars, 0), jnp.where(owner, piece_rank, 0)]
    )
    f_slot0 = jnp.concatenate(
        [
            jnp.where(live & (head_len > 0), slot0, -1),
            jnp.where(owner & (piece_len > 0), piece_slot0, -1),
        ]
    )
    f_rlen = jnp.concatenate(
        [
            jnp.where(live, head_len, 0),
            jnp.where(owner, jnp.maximum(piece_len, 0), 0),
        ]
    )
    f_rlen = jnp.where(f_slot0 >= 0, f_rlen, 0)
    return f_anchor, f_rank, f_slot0, f_rlen


@boundary(
    dtypes=(None, "int32", "int32", "int32", "int32", "int32"),
    shapes=(None, "N", "N", "N", "N", "N"),
    donates=(0,),
)
@partial(
    jax.jit,
    static_argnames=("batch", "epoch", "nbits"),
    donate_argnums=(0,),
)
def merge_runlogs(
    state: DownPacked,
    lamport, agent, slot0, rlen, origin,
    *,
    batch: int = 256,
    epoch: int = 8,
    nbits: int = 18,
) -> DownPacked:
    """Integrate a union of insert-run logs (delete intervals fold
    separately, :func:`delete_fold`).  The causal-order sort of run heads,
    the per-batch forest resolution, the id->position queries and the
    fused expansion all run on device inside this call — N runs must be a
    multiple of ``batch * epoch`` (pad with rlen == 0 rows).
    """
    from ..ops.idpos import snap_rebuild
    from .downstream_range import _apply_range_update_batch5

    key = jnp.where(rlen > 0, lamport * MAX_AGENTS + agent, BIGKEY)
    perm = jnp.argsort(key)
    key, slot0, rlen, origin = (
        key[perm], slot0[perm], rlen[perm], origin[perm]
    )

    NB = key.shape[0] // batch
    K = min(epoch, NB)
    rs = lambda x: x.reshape(NB // K, K, batch)
    neg1 = jnp.full((batch,), -1, jnp.int32)

    def step(st, upd):
        k_e, s0_e, rl_e, or_e = upd
        doc, snap, length, nvis = st
        levels: list = []
        for k in range(K):
            fa, fr, fs, fl = _run_batch_fragments(
                k_e[k], s0_e[k], rl_e[k], or_e[k]
            )
            doc, length, nvis, lv = _apply_range_update_batch5(
                doc, length, nvis, snap, levels,
                fa, fr, fs, fl,
                jnp.ones_like(fa),  # alive: deletes fold later
                jnp.concatenate([neg1, neg1]),  # no dfirst
                jnp.concatenate([neg1, neg1]),  # no dlast
                nbits=nbits,
            )
            levels.append(lv)
        return DownPacked(doc, snap_rebuild(doc), length, nvis), None

    state, _ = jax.lax.scan(
        step, state, (rs(key), rs(slot0), rs(rlen), rs(origin))
    )
    return state


@partial(jax.jit, donate_argnums=(0,))
def delete_fold(state: DownPacked, dlo, dhi) -> DownPacked:
    """Fold all delete intervals in one pass: paint a killed-slot
    indicator from the id intervals (deletes commute; a complete causal
    log lets every tombstone land after integration), scatter it through
    the final slot->position snapshot, clear visibility."""
    R, C = state.doc.shape
    starts = (
        jnp.zeros(C + 1, jnp.int32)
        .at[jnp.clip(dlo, 0, C)]
        .add(jnp.where(dlo >= 0, 1, 0), mode="drop")
    )
    stops = (
        jnp.zeros(C + 1, jnp.int32)
        .at[jnp.clip(dhi + 1, 0, C)]
        .add(jnp.where(dlo >= 0, 1, 0), mode="drop")
    )
    killed = (jnp.cumsum(starts - stops)[:C] > 0).astype(jnp.int32)

    # state.snap is exact here: merge_runlogs ends every scan step with
    # snap_rebuild(doc), so no extra rebuild is needed.
    kill_doc = jax.vmap(
        lambda s: jnp.zeros(C, jnp.int32).at[s].add(killed, mode="drop")
    )(state.snap)
    vis = jnp.bitwise_and(state.doc, 1)
    newvis = vis * (kill_doc == 0).astype(jnp.int32)
    col = jax.lax.broadcasted_iota(jnp.int32, (R, C), 1)
    in_doc = col < state.length[:, None]
    return DownPacked(
        doc=state.doc - (vis - newvis),
        snap=state.snap,
        length=state.length,
        nvis=jnp.sum(newvis * in_doc.astype(jnp.int32), axis=1),
    )


# ---- host-side driver -------------------------------------------------------


class RunMergeSimulation:
    """Run-granular view over a :class:`MergeSimulation`: RLE wire
    translation (untimed), precondition check, device merge + delete fold.
    """

    def __init__(self, sim: MergeSimulation, batch: int = 256,
                 epoch: int = 8,
                 patch_starts: list[np.ndarray] | None = None):
        # _apply_range_update_batch5 paints per-run slot deltas in 3x7-bit
        # chunks (|ddelta| <= 2*capacity < 2^21), the same bound the range
        # downstream engine guards (engine/downstream_range.py) — without
        # this check a wrapped delta would corrupt content identically on
        # every replica, invisible to the convergence digest.
        if sim.capacity >= 1 << 20:
            raise ValueError(
                f"capacity {sim.capacity} >= 2^20 exceeds the run-delta"
                " chunked-arithmetic range; use the unit merge"
            )
        self.sim = sim
        self.batch = batch
        self.epoch = epoch
        # patch_starts: per-agent forced break masks (per-patch wire
        # granularity — see runs_from_oplog); None = maximal RLE.
        self.runlogs = [
            runs_from_oplog(
                l, None if patch_starts is None else patch_starts[i]
            )
            for i, l in enumerate(sim.agent_logs)
        ]
        self.fast_ok = check_no_skip(self.runlogs)
        self.n_runs = int(sum(len(r.slot0) for r in self.runlogs))
        self.n_unit_ops = int(sum(r.n_unit_ops for r in self.runlogs))
        cat = lambda f: np.concatenate([getattr(r, f) for r in self.runlogs])
        n = self.n_runs
        m = batch * min(epoch, max(1, -(-n // batch)))
        pad = (-n) % m
        z = lambda fill: np.full(pad, fill, np.int32)
        # Pre-sort by head key HOST-side so per-batch sizing (nbits) is
        # computed on the same batches the device forms: merge_runlogs
        # re-sorts on device (the causal-order arrangement is timed work),
        # which is then an identical permutation.
        lamport = np.concatenate([cat("lamport"), z(0)])
        agent = np.concatenate([cat("agent"), z(0)])
        slot0 = np.concatenate([cat("slot0"), z(-1)])
        rlen = np.concatenate([cat("rlen"), z(0)])
        origin = np.concatenate([cat("origin"), z(-2)])
        assert int(lamport.max(initial=0)) * MAX_AGENTS + MAX_AGENTS \
            < 2**31 - 1, "lamport too large for the packed run key"
        key = np.where(
            rlen > 0, lamport * MAX_AGENTS + agent, np.int32(2**31 - 1)
        )
        perm = np.argsort(key, kind="stable")
        self.lamport = lamport[perm]
        self.agent = agent[perm]
        self.slot0 = slot0[perm]
        self.rlen = rlen[perm]
        self.origin = origin[perm]
        self.dlo = cat("dlo")
        self.dhi = cat("dhi")
        nb = len(self.lamport) // batch
        per_batch_chars = (
            np.where(self.rlen > 0, self.rlen, 0)
            .reshape(nb, batch)
            .sum(axis=1)
        )
        self.nbits = max(1, int(per_batch_chars.max(initial=1)).bit_length())
        self.epoch_eff = min(epoch, nb)
        # device upload ONCE (untimed, matching the unit merge cell's
        # hoisted upload) — merge() only dispatches
        self._dev = tuple(
            jnp.asarray(a)
            for a in (self.lamport, self.agent, self.slot0, self.rlen,
                      self.origin)
        )
        self._dev_del = (
            (jnp.asarray(self.dlo), jnp.asarray(self.dhi))
            if len(self.dlo)
            else None
        )

    def merge(self, n_replicas: int = 1) -> DownPacked:
        """Timed region: fresh replicas + full run integration + delete
        fold (callers add digest/convergence checks)."""
        if not self.fast_ok:
            raise ValueError(
                "run-atomic precondition violated; use the unit merge"
            )
        st = down_packed_init(
            n_replicas, self.sim.capacity, self.sim.n_base
        )
        if self.n_runs:
            st = merge_runlogs(
                st, *self._dev,
                batch=self.batch, epoch=self.epoch_eff, nbits=self.nbits,
            )
        if self._dev_del is not None:
            st = delete_fold(st, *self._dev_del)
        return st

    def merge_flat(self, n_replicas: int = 1) -> DownPacked:
        """Timed region of the ONE-SHOT schedule: the whole wire
        integrates in a single fused pass (engine/downstream_flat.py —
        segmented sort + pointer-doubling list rank), then the delete
        fold.  Same wire tensors, same preconditions, same final state
        as :meth:`merge`; no sequential batch loop."""
        from .downstream_flat import flatten_runs

        if not self.fast_ok:
            raise ValueError(
                "run-atomic precondition violated; use the unit merge"
            )
        lam, ag, s0, rl, orig = self._dev
        key = jnp.where(rl > 0, lam * MAX_AGENTS + ag, BIGKEY)
        st = flatten_runs(
            key, s0, rl, orig,
            n_base=self.sim.n_base, capacity=self.sim.capacity,
            n_elems=self.sim.n_base + int(self.rlen.sum()),
            n_replicas=n_replicas,
        )
        if self._dev_del is not None:
            st = delete_fold(st, *self._dev_del)
        return st

    def decode(self, state: DownPacked, replica: int = 0) -> str:
        from ..ops.apply2 import PackedState, decode_state3

        codes, nvis = jax.jit(
            decode_state3, static_argnames=("replica",)
        )(
            PackedState(
                doc=state.doc, length=state.length, nvis=state.nvis
            ),
            self.sim.chars,
            replica=replica,
        )
        return "".join(map(chr, np.asarray(codes)[: int(nvis)].tolist()))


class JaxRunDownstreamBackend:
    """Downstream bench backend at RUN granularity (column
    ``jax-*-runs``): a single-writer log is the one-agent special case of
    the run merge, so the RLE'd wire stream (the form diamond-types' own
    binary updates take, reference src/rope.rs:214) integrates through
    merge_runlogs — id->position anchor resolution, fragment placement
    and the delete fold all INSIDE the timed region.  Wire translation
    (per-patch updates -> runs) is untimed, like the reference's update
    generation (src/main.rs:60).
    """

    def __init__(self, n_replicas: int = 1, batch: int | None = None,
                 epoch: int = 8, granularity: str = "coalesced"):
        import os

        # 512 runs/batch measured ~1.4x over 256 on automerge-paper at
        # 64 replicas (fewer sequential batches, same per-batch shape);
        # CRDT_DOWN_RUNS_BATCH overrides for schedule sweeps.
        self.n_replicas = n_replicas
        self.batch = batch if batch is not None else int(
            os.environ.get("CRDT_DOWN_RUNS_BATCH", "512")
        )
        self.epoch = epoch
        #: 'coalesced' = maximal RLE wire (cross-patch runs — the form
        #: diamond-types' internal oplog RLE takes, src/rope.rs:119-126);
        #: 'patch' = one wire update per trace patch component, NO
        #: cross-patch coalescing — the reference's own generation
        #: granularity (one update per patch, src/rope.rs:196-220), the
        #: strict like-for-like downstream cell (VERDICT r3 weak #1);
        #: 'unit' = one wire update per UNIT op (every run length 1) —
        #: the v5 engine's wire granularity, finer than the reference's.
        if granularity not in ("coalesced", "patch", "unit"):
            raise ValueError(f"unknown granularity {granularity!r}")
        self.granularity = granularity
        #: apply schedule: 'flat' (default) = one-shot fused integration
        #: (engine/downstream_flat.py); 'batched' = the epoch/batch scan
        #: (merge_runlogs).  Same wire, same final state either way.
        self.schedule = os.environ.get("CRDT_DOWN_SCHEDULE", "flat")
        if self.schedule not in ("flat", "batched"):
            raise ValueError(f"unknown schedule {self.schedule!r}")
        self._rm: RunMergeSimulation | None = None

    @property
    def NAME(self) -> str:
        plat = jax.devices()[0].platform
        tag = f"-r{self.n_replicas}" if self.n_replicas > 1 else ""
        kind = {"coalesced": "runs", "patch": "patch",
                "unit": "unitwire"}[self.granularity]
        # the schedule changes the timed algorithm (one-shot flatten vs
        # the r4 batched scan) — bench ids must stay distinguishable
        # across rounds (code-review r5)
        kind += "-flat" if self.schedule == "flat" else ""
        return f"jax-{plat}{tag}-{kind}"

    @property
    def replicas(self) -> int:
        return self.n_replicas

    def prepare(self, trace) -> None:
        from ..traces.tensorize import tensorize

        tt = tensorize(trace, batch=512)
        sim = MergeSimulation(
            [tt], base=trace.start_content, batch=self.batch
        )
        patch_starts = None
        if self.granularity == "patch":
            ps = np.zeros(tt.n_ops, bool)
            u = 0
            for _pos, d, ins in trace.iter_patches():
                ps[u] = True
                u += d + len(ins)
            assert u == tt.n_ops
            patch_starts = [ps]
        elif self.granularity == "unit":
            patch_starts = [np.ones(tt.n_ops, bool)]
        self._rm = RunMergeSimulation(
            sim, batch=self.batch, epoch=self.epoch,
            patch_starts=patch_starts,
        )
        assert self._rm.fast_ok  # single writer: always holds
        self._end_len = len(trace.end_content)

    def _merge(self) -> DownPacked:
        fn = (
            self._rm.merge_flat if self.schedule == "flat"
            else self._rm.merge
        )
        return fn(n_replicas=self.n_replicas)

    def replay_once(self) -> int:
        state = self._merge()
        lengths = np.asarray(state.nvis)  # device -> host sync point
        assert (lengths == self._end_len).all(), (
            f"length mismatch: {lengths} != {self._end_len}"
        )
        return int(lengths.reshape(-1)[0])

    def final_content(self) -> str:
        state = self._merge()
        jax.block_until_ready(state)
        return self._rm.decode(state)
