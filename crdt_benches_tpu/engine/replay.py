"""Full-trace replay: lax.scan over op batches, vmap over replicas.

The TPU analog of the reference's timed closure (src/main.rs:28-37): document
init (``from_str``), the hot per-patch loop, and the final check — except the
loop is a compiled scan over op *batches* and the whole thing is batched over
a replica axis.  Throughput comes from the replica axis and from vectorizing
the within-batch work, not from parallelizing the op stream (SURVEY.md
section 7, hard part 1).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..lint.boundary import boundary
from ..ops.apply import (
    DocState,
    apply_batch,
    apply_batch_collect,
    decode_state,
    init_state,
)
from ..ops.resolve import resolve_batch
from ..traces.tensorize import TensorizedTrace


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _stage_capacity(need: int, lane: int = 128) -> int:
    """Smallest staged capacity >= need from a sqrt(2)-spaced grid
    (lane * {8, 12, 16, 24, 32, ...}) — bounds wasted capacity at ~20%
    average while keeping the number of distinct compiled shapes ~2 per
    doubling."""
    s = 8 * lane
    while s < need:
        s2 = s + s // 2
        if s2 >= need:
            return s2
        s *= 2
    return s


def _grow_state4(state, new_cap: int):
    """Pad a PackedState4's capacity axis to new_cap (doc pads with
    pack_doc(-1, 0) == 2, prefix structures with zeros)."""
    from ..ops.apply2 import LANE, PackedState4

    R, C = state.doc.shape
    if new_cap <= C:
        return state
    pad = new_cap - C
    return PackedState4(
        doc=jnp.concatenate(
            [state.doc, jnp.full((R, pad), 2, jnp.int32)], axis=1
        ),
        cv_intile=jnp.concatenate(
            [state.cv_intile, jnp.zeros((R, pad), state.cv_intile.dtype)],
            axis=1,
        ),
        vis_tile=jnp.concatenate(
            [state.vis_tile, jnp.zeros((R, pad // LANE), jnp.int32)], axis=1
        ),
        length=state.length,
        nvis=state.nvis,
    )


#: Module-level jit so repeated decodes reuse one compilation per shape.
decode_state_jit = jax.jit(decode_state)


def slot_char_table(tt: TensorizedTrace, capacity: int) -> np.ndarray:
    """slot -> codepoint table: static per trace (init content in slots
    0..S-1, each insert op's preassigned slot gets its char)."""
    chars = np.zeros(capacity, np.int32)
    chars[: len(tt.init_chars)] = tt.init_chars
    ins = tt.slot >= 0
    chars[tt.slot[ins]] = tt.ch[ins]
    return chars


def broadcast_replicas(state, n_replicas: int):
    """Tile a single-replica state pytree along a leading replica axis."""
    if n_replicas == 1:
        return state
    return jax.tree.map(
        lambda x: jnp.broadcast_to(x, (n_replicas,) + jnp.shape(x)), state
    )


def select_replica(state, replica: int, n_replicas: int):
    return (
        jax.tree.map(lambda x: x[replica], state) if n_replicas > 1 else state
    )


def decode_to_str(state, chars) -> str:
    """Materialize a single replica's visible document as a Python string.
    Works for any state pytree with order/visible/length fields (DocState,
    DownState)."""
    codes, nvis = decode_state_jit(state, chars)
    codes = np.asarray(codes)[: int(nvis)]
    return "".join(map(chr, codes.tolist()))


@boundary(
    dtypes=(None, "int32", "int32", "int32"),
    shapes=(None, "N B", "N B", "N B"),
    donates=(0,),
)
@partial(jax.jit, donate_argnums=(0,))
def replay_batches(state: DocState, kind_b, pos_b, slot_b) -> DocState:
    """Scan all op batches into the document state.  Shapes:
    kind_b/pos_b/slot_b are int32[n_batches, B]."""

    def step(st, batch):
        kind, pos, slot = batch
        resolved = resolve_batch(kind, pos, st.nvis)
        return apply_batch(st, resolved, slot), None

    state, _ = jax.lax.scan(step, state, (kind_b, pos_b, slot_b))
    return state


@partial(jax.jit, static_argnames=("resolver",), donate_argnums=(0,))
def replay_batches_r(
    state: DocState, kind_b, pos_b, slot_b, *, resolver: str = "scan"
) -> DocState:
    """Replica-batched replay: state leaves carry a leading replica axis R.

    ``resolver`` picks the sequential-resolution implementation:
    - ``"scan"``: the lax.scan token-list resolver (ops/resolve.py), vmapped
      over replicas — portable, used on CPU.
    - ``"pallas"``: the fused TPU kernel (ops/resolve_pallas.py) — one kernel
      launch per op batch with replicas on the VPU sublane axis, avoiding the
      per-op dispatch overhead that makes the scan resolver ~1000x slower
      than its arithmetic on TPU.
    Apply stays XLA either way (wide vectorized scatters, vmapped over R).
    """
    if resolver == "pallas":
        from ..ops.resolve_pallas import resolve_batch_pallas

        def resolve_r(kind, pos, nvis):
            return resolve_batch_pallas(kind, pos, nvis)

    else:

        def resolve_r(kind, pos, nvis):
            return jax.vmap(resolve_batch, in_axes=(None, None, 0))(
                kind, pos, nvis
            )

    def step(st, batch):
        kind, pos, slot = batch
        resolved = resolve_r(kind, pos, st.nvis)
        st = jax.vmap(apply_batch, in_axes=(0, 0, None))(st, resolved, slot)
        return st, None

    state, _ = jax.lax.scan(step, state, (kind_b, pos_b, slot_b))
    return state


def _make_resolver(
    resolver: str, emit_origin: bool = True, token_cap: int | None = None
):
    if resolver == "pallas":
        from ..ops.resolve_pallas import resolve_batch_pallas

        return lambda kind, pos, nvis: resolve_batch_pallas(
            kind, pos, nvis, emit_origin=emit_origin, token_cap=token_cap
        )
    return lambda kind, pos, nvis: jax.vmap(
        resolve_batch, in_axes=(None, None, 0)
    )(kind, pos, nvis)


@partial(jax.jit, static_argnames=("resolver", "pack"), donate_argnums=(0,))
def replay_batches_r2(
    state, kind_b, pos_b, slot_b, *, resolver: str = "scan", pack: int = 4
):
    """Replay on the scatter-free doc-order state (ops/apply2.py).

    ``pack`` batches are applied per scan step (python-unrolled) to amortize
    the fixed per-scan-iteration cost (~1.8ms on the TPU runtime in use)
    over more work.  NB must be a multiple of ``pack`` (pad with PAD
    batches — they are no-ops end to end).
    """
    from ..ops.apply2 import apply_batch2

    # The upstream replay consumes no CRDT origins (v2 apply is doc-order
    # only); skipping them drops ~25% of the resolve kernel's per-op work.
    resolve_r = _make_resolver(resolver, emit_origin=False)
    NB, B = kind_b.shape
    K = min(pack, NB)
    if NB % K:
        raise ValueError(f"batch count {NB} not a multiple of pack {K}")
    rs = lambda x: x.reshape(NB // K, K, B)

    def step(st, batch):
        k, p, sl = batch
        for i in range(K):
            resolved = resolve_r(k[i], p[i], st.nvis)
            st = apply_batch2(st, resolved, sl[i])
        return st, None

    state, _ = jax.lax.scan(
        step, state, (rs(kind_b), rs(pos_b), rs(slot_b))
    )
    return state


@partial(jax.jit, static_argnames=("resolver", "pack"), donate_argnums=(0,))
def replay_batches_r3(
    state, kind_b, pos_b, slot_b, *, resolver: str = "scan", pack: int = 4
):
    """replay_batches_r2 on the packed single-array state (apply_batch3)."""
    from ..ops.apply2 import apply_batch3

    resolve_r = _make_resolver(resolver, emit_origin=False)
    NB, B = kind_b.shape
    K = min(pack, NB)
    if NB % K:
        raise ValueError(f"batch count {NB} not a multiple of pack {K}")
    rs = lambda x: x.reshape(NB // K, K, B)

    def step(st, batch):
        k, p, sl = batch
        for i in range(K):
            resolved = resolve_r(k[i], p[i], st.nvis)
            st = apply_batch3(st, resolved, sl[i])
        return st, None

    state, _ = jax.lax.scan(
        step, state, (rs(kind_b), rs(pos_b), rs(slot_b))
    )
    return state


@partial(
    jax.jit,
    static_argnames=("resolver", "pack", "token_cap"),
    donate_argnums=(0,),
)
def replay_batches_r4(
    state, kind_b, pos_b, slot_b, *, resolver: str = "scan", pack: int = 4,
    token_cap: int | None = None,
):
    """replay_batches_r3 on the cumvis-maintained state (apply_batch4 —
    fused delete/expand/fill kernel, no per-batch capacity-sized cumsum)."""
    from ..ops.apply2 import apply_batch4

    resolve_r = _make_resolver(resolver, emit_origin=False, token_cap=token_cap)
    NB, B = kind_b.shape
    K = min(pack, NB)
    if NB % K:
        raise ValueError(f"batch count {NB} not a multiple of pack {K}")
    rs = lambda x: x.reshape(NB // K, K, B)

    def step(st, batch):
        k, p, sl = batch
        for i in range(K):
            resolved = resolve_r(k[i], p[i], st.nvis)
            st = apply_batch4(st, resolved, sl[i])
        return st, None

    state, _ = jax.lax.scan(
        step, state, (rs(kind_b), rs(pos_b), rs(slot_b))
    )
    return state


@partial(jax.jit, static_argnames=("resolver",), donate_argnums=(0,))
def replay_batches_collect(
    state: DocState, kind_b, pos_b, slot_b, *, resolver: str = "scan"
):
    """Like :func:`replay_batches` but also stacks each op's tombstoned slot:
    returns (state, dslot_b int32[n_batches, B]).  Used by update generation
    (engine/downstream.py) — the untimed upstream replay that the reference's
    ``upstream_updates`` performs (reference src/rope.rs:196-220)."""

    def step(st, batch):
        kind, pos, slot = batch
        if resolver == "pallas":
            from ..ops.resolve_pallas import resolve_batch_pallas

            resolved = jax.tree.map(
                lambda x: x[0], resolve_batch_pallas(kind, pos, st.nvis[None])
            )
        else:
            resolved = resolve_batch(kind, pos, st.nvis)
        st, dslot = apply_batch_collect(st, resolved, slot)
        return st, dslot

    return jax.lax.scan(step, state, (kind_b, pos_b, slot_b))


def default_resolver() -> str:
    """'pallas' on TPU, 'scan' elsewhere; override with CRDT_ENGINE_RESOLVER."""
    import os

    r = os.environ.get("CRDT_ENGINE_RESOLVER", "auto")
    if r != "auto":
        return r
    return "pallas" if jax.default_backend() == "tpu" else "scan"


class ReplayEngine:
    """Host-side driver for replaying one tensorized trace on-device.

    ``n_replicas > 1`` batches the whole replay over a replica axis — every
    replica carries and computes its own full state (the honest equivalent of
    running the reference's single-threaded loop N times in parallel).  Use
    ``parallel/`` for sharding replicas across a device mesh.

    The op stream is replayed in host-level chunks of ``chunk`` batches per
    device call (donated state between calls) so a single device execution
    stays bounded regardless of trace length.
    """

    def __init__(
        self,
        tt: TensorizedTrace,
        n_replicas: int = 1,
        lane: int = 128,
        resolver: str | None = None,
        chunk: int = 32,
        engine: str | None = None,
        pack: int = 8,
    ):
        import os

        self.tt = tt
        self.n_replicas = n_replicas
        self.capacity = _round_up(max(tt.capacity, 1), lane)
        # Packed arithmetic preconditions (fail loudly, ADVICE round 1):
        # tile_base/gvis travel as 3x7-bit bf16 chunks (< 2^21), packed
        # fills shift slot ids by 2 bits (< 2^29), and the B>1024 dest sort
        # key needs capacity * (B + 1) < 2^31.
        if self.capacity >= 1 << 21:
            raise ValueError(
                f"capacity {self.capacity} >= 2^21 exceeds the packed"
                " engine's chunked-arithmetic range"
            )
        if self.capacity * (tt.batch + 1) >= 1 << 31:
            raise ValueError("capacity * (batch + 1) must fit int32")
        self.n_init = len(tt.init_chars)
        self.resolver = resolver or default_resolver()
        self.chunk = int(os.environ.get("CRDT_ENGINE_CHUNK", str(chunk)))
        #: 'v2' = scatter-free doc-order apply (ops/apply2.py, the fast
        #: path); 'v1' = the original slot-indexed apply (ops/apply.py).
        self.engine = engine or os.environ.get("CRDT_ENGINE_APPLY", "v4")
        self.pack = int(os.environ.get("CRDT_ENGINE_PACK", str(pack)))
        if self.chunk % self.pack:
            self.chunk = _round_up(self.chunk, self.pack)

        kind_b, pos_b, _, slot_b = tt.batched()
        if self.engine in ("v2", "v3", "v4"):
            # Pad the batch count to a multiple of `pack` with PAD batches
            # (no-ops end to end) so every scan step carries `pack` batches.
            n_pad = (-tt.n_batches) % self.pack
            if n_pad:
                z = np.zeros((n_pad, tt.batch), np.int32)
                kind_b = np.concatenate([kind_b, z])
                pos_b = np.concatenate([pos_b, z])
                slot_b = np.concatenate([slot_b, z - 1])
        # Pre-slice chunks once so the timed replay loop does no host-side
        # array work — just one replay dispatch per chunk.
        self.chunks = [
            (
                jnp.asarray(kind_b[i : i + self.chunk]),
                jnp.asarray(pos_b[i : i + self.chunk]),
                jnp.asarray(slot_b[i : i + self.chunk]),
            )
            for i in range(0, len(kind_b), self.chunk)
        ]
        self.kind_b = jnp.asarray(kind_b)
        self.pos_b = jnp.asarray(pos_b)
        self.slot_b = jnp.asarray(slot_b)

        # Capacity staging (live-prefix): every apply cost is proportional
        # to the state capacity, but the document grows over the replay —
        # early chunks run at a geometrically-staged capacity that covers
        # their end-of-chunk used length (host-known: n_init + running
        # insert count; slot ids are insertion-ordered so they always fit,
        # traces/tensorize.py).  Each distinct stage is one extra compile.
        self.stage_caps: list[int] = []
        if self.engine == "v4":
            from ..traces.tensorize import INSERT as _INS

            ins_per_batch = (kind_b == _INS).sum(axis=1)
            end_len = self.n_init + np.cumsum(ins_per_batch)
            for i in range(0, len(kind_b), self.chunk):
                need = int(end_len[min(i + self.chunk, len(end_len)) - 1])
                self.stage_caps.append(
                    min(self.capacity, _stage_capacity(need, lane))
                )
            # Capacities must be nondecreasing (state only ever grows).
            for i in range(1, len(self.stage_caps)):
                self.stage_caps[i] = max(
                    self.stage_caps[i], self.stage_caps[i - 1]
                )

        # Per-chunk resolver token caps from the exact host simulation
        # (ops/token_sim.py) — editing traces run near B+2 tokens, far
        # below the 2B+2 worst case the kernel otherwise allocates.
        self.token_caps: list[int | None] = [None] * len(self.chunks)
        if (
            self.engine == "v4"
            and self.resolver == "pallas"
            and os.environ.get("CRDT_ENGINE_TOKENSIM", "1") != "0"
        ):
            from ..ops.token_sim import simulate_token_counts

            tc = simulate_token_counts(kind_b, pos_b, self.n_init)
            # Round to the 128-lane grid HERE so chunks with the same
            # rounded cap share one compiled executable.
            self.token_caps = [
                _round_up(int(tc[i : i + self.chunk].max()) + 8, 128)
                for i in range(0, len(kind_b), self.chunk)
            ]

        self.chars = jnp.asarray(slot_char_table(tt, self.capacity))

    def fresh_state(self) -> DocState:
        return broadcast_replicas(
            init_state(self.capacity, self.n_init), self.n_replicas
        )

    def _fresh_r(self) -> DocState:
        """R-leading state (leading axis present even for R=1)."""
        st = init_state(self.capacity, self.n_init)
        return jax.tree.map(
            lambda x: jnp.broadcast_to(x, (self.n_replicas,) + jnp.shape(x)),
            st,
        )

    def run(self, state=None):
        """Replay the full trace; returns final state (device).

        engine 'v2': returns a replica-batched ReplayState (leading R axis).
        engine 'v1': DocState following the fresh_state convention (no
        leading axis at R=1).
        """
        if self.engine in ("v2", "v3", "v4"):
            from ..ops.apply2 import init_state2, init_state3, init_state4

            init = {
                "v2": init_state2, "v3": init_state3, "v4": init_state4
            }[self.engine]
            fn = {
                "v2": replay_batches_r2,
                "v3": replay_batches_r3,
                "v4": replay_batches_r4,
            }[self.engine]
            if self.engine == "v4" and self.stage_caps:
                st = (
                    init(self.n_replicas, self.stage_caps[0], self.n_init)
                    if state is None
                    else state
                )
                for cap, tcap, (kind, pos, slot) in zip(
                    self.stage_caps, self.token_caps, self.chunks
                ):
                    st = _grow_state4(st, cap)
                    st = fn(
                        st, kind, pos, slot,
                        resolver=self.resolver, pack=self.pack,
                        token_cap=tcap,
                    )
                return st
            st = (
                init(self.n_replicas, self.capacity, self.n_init)
                if state is None
                else state
            )
            for kind, pos, slot in self.chunks:
                st = fn(
                    st, kind, pos, slot,
                    resolver=self.resolver, pack=self.pack,
                )
            return st
        if state is None:
            st = self._fresh_r()
        elif self.n_replicas == 1:
            st = jax.tree.map(lambda x: x[None], state)
        else:
            st = state
        for kind, pos, slot in self.chunks:
            st = replay_batches_r(st, kind, pos, slot, resolver=self.resolver)
        if self.n_replicas == 1:
            st = jax.tree.map(lambda x: x[0], st)
        return st

    def run_blocking(self) -> DocState:
        state = self.run()
        jax.block_until_ready(state)
        return state

    # ---- decode / checks -------------------------------------------------

    def decode(self, state, replica: int = 0) -> str:
        """Materialize a replica's visible document as a Python string."""
        from ..ops.apply2 import (
            PackedState,
            PackedState4,
            ReplayState,
            decode_state2,
            decode_state3,
            decode_state4,
        )

        if isinstance(state, (ReplayState, PackedState, PackedState4)):
            dec = (
                decode_state4 if isinstance(state, PackedState4) else
                decode_state3 if isinstance(state, PackedState) else
                decode_state2
            )
            codes, nvis = jax.jit(dec, static_argnames=("replica",))(
                state, self.chars, replica=replica
            )
            return "".join(map(chr, np.asarray(codes)[: int(nvis)].tolist()))
        return decode_to_str(
            select_replica(state, replica, self.n_replicas), self.chars
        )

    def lengths(self, state: DocState) -> np.ndarray:
        """Per-replica visible char counts — the reference's length oracle
        (src/main.rs:35), available without full decode."""
        return np.atleast_1d(np.asarray(state.nvis))


def replay_trace_jax(tt: TensorizedTrace) -> str:
    """Convenience: single-replica replay -> final content string."""
    eng = ReplayEngine(tt, n_replicas=1)
    return eng.decode(eng.run_blocking())
