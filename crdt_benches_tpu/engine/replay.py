"""Full-trace replay: lax.scan over op batches, vmap over replicas.

The TPU analog of the reference's timed closure (src/main.rs:28-37): document
init (``from_str``), the hot per-patch loop, and the final check — except the
loop is a compiled scan over op *batches* and the whole thing is batched over
a replica axis.  Throughput comes from the replica axis and from vectorizing
the within-batch work, not from parallelizing the op stream (SURVEY.md
section 7, hard part 1).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.apply import (
    DocState,
    apply_batch,
    apply_batch_collect,
    decode_state,
    init_state,
)
from ..ops.resolve import resolve_batch
from ..traces.tensorize import TensorizedTrace


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


#: Module-level jit so repeated decodes reuse one compilation per shape.
decode_state_jit = jax.jit(decode_state)


def slot_char_table(tt: TensorizedTrace, capacity: int) -> np.ndarray:
    """slot -> codepoint table: static per trace (init content in slots
    0..S-1, each insert op's preassigned slot gets its char)."""
    chars = np.zeros(capacity, np.int32)
    chars[: len(tt.init_chars)] = tt.init_chars
    ins = tt.slot >= 0
    chars[tt.slot[ins]] = tt.ch[ins]
    return chars


def broadcast_replicas(state, n_replicas: int):
    """Tile a single-replica state pytree along a leading replica axis."""
    if n_replicas == 1:
        return state
    return jax.tree.map(
        lambda x: jnp.broadcast_to(x, (n_replicas,) + jnp.shape(x)), state
    )


def select_replica(state, replica: int, n_replicas: int):
    return (
        jax.tree.map(lambda x: x[replica], state) if n_replicas > 1 else state
    )


def decode_to_str(state, chars) -> str:
    """Materialize a single replica's visible document as a Python string.
    Works for any state pytree with order/visible/length fields (DocState,
    DownState)."""
    codes, nvis = decode_state_jit(state, chars)
    codes = np.asarray(codes)[: int(nvis)]
    return "".join(map(chr, codes.tolist()))


@partial(jax.jit, donate_argnums=(0,))
def replay_batches(state: DocState, kind_b, pos_b, slot_b) -> DocState:
    """Scan all op batches into the document state.  Shapes:
    kind_b/pos_b/slot_b are int32[n_batches, B]."""

    def step(st, batch):
        kind, pos, slot = batch
        resolved = resolve_batch(kind, pos, st.nvis)
        return apply_batch(st, resolved, slot), None

    state, _ = jax.lax.scan(step, state, (kind_b, pos_b, slot_b))
    return state


@partial(jax.jit, static_argnames=("resolver",), donate_argnums=(0,))
def replay_batches_r(
    state: DocState, kind_b, pos_b, slot_b, *, resolver: str = "scan"
) -> DocState:
    """Replica-batched replay: state leaves carry a leading replica axis R.

    ``resolver`` picks the sequential-resolution implementation:
    - ``"scan"``: the lax.scan token-list resolver (ops/resolve.py), vmapped
      over replicas — portable, used on CPU.
    - ``"pallas"``: the fused TPU kernel (ops/resolve_pallas.py) — one kernel
      launch per op batch with replicas on the VPU sublane axis, avoiding the
      per-op dispatch overhead that makes the scan resolver ~1000x slower
      than its arithmetic on TPU.
    Apply stays XLA either way (wide vectorized scatters, vmapped over R).
    """
    if resolver == "pallas":
        from ..ops.resolve_pallas import resolve_batch_pallas

        def resolve_r(kind, pos, nvis):
            return resolve_batch_pallas(kind, pos, nvis)

    else:

        def resolve_r(kind, pos, nvis):
            return jax.vmap(resolve_batch, in_axes=(None, None, 0))(
                kind, pos, nvis
            )

    def step(st, batch):
        kind, pos, slot = batch
        resolved = resolve_r(kind, pos, st.nvis)
        st = jax.vmap(apply_batch, in_axes=(0, 0, None))(st, resolved, slot)
        return st, None

    state, _ = jax.lax.scan(step, state, (kind_b, pos_b, slot_b))
    return state


@partial(jax.jit, donate_argnums=(0,))
def replay_batches_collect(state: DocState, kind_b, pos_b, slot_b):
    """Like :func:`replay_batches` but also stacks each op's tombstoned slot:
    returns (state, dslot_b int32[n_batches, B]).  Used by update generation
    (engine/downstream.py) — the untimed upstream replay that the reference's
    ``upstream_updates`` performs (reference src/rope.rs:196-220)."""

    def step(st, batch):
        kind, pos, slot = batch
        resolved = resolve_batch(kind, pos, st.nvis)
        st, dslot = apply_batch_collect(st, resolved, slot)
        return st, dslot

    return jax.lax.scan(step, state, (kind_b, pos_b, slot_b))


def default_resolver() -> str:
    """'pallas' on TPU, 'scan' elsewhere; override with CRDT_ENGINE_RESOLVER."""
    import os

    r = os.environ.get("CRDT_ENGINE_RESOLVER", "auto")
    if r != "auto":
        return r
    return "pallas" if jax.default_backend() == "tpu" else "scan"


class ReplayEngine:
    """Host-side driver for replaying one tensorized trace on-device.

    ``n_replicas > 1`` batches the whole replay over a replica axis — every
    replica carries and computes its own full state (the honest equivalent of
    running the reference's single-threaded loop N times in parallel).  Use
    ``parallel/`` for sharding replicas across a device mesh.

    The op stream is replayed in host-level chunks of ``chunk`` batches per
    device call (donated state between calls) so a single device execution
    stays bounded regardless of trace length.
    """

    def __init__(
        self,
        tt: TensorizedTrace,
        n_replicas: int = 1,
        lane: int = 128,
        resolver: str | None = None,
        chunk: int = 32,
    ):
        import os

        self.tt = tt
        self.n_replicas = n_replicas
        self.capacity = _round_up(max(tt.capacity, 1), lane)
        self.n_init = len(tt.init_chars)
        self.resolver = resolver or default_resolver()
        self.chunk = int(os.environ.get("CRDT_ENGINE_CHUNK", str(chunk)))

        kind_b, pos_b, _, slot_b = tt.batched()
        # Pre-slice chunks once so the timed replay loop does no host-side
        # array work — just one replay_batches_r dispatch per chunk.
        self.chunks = [
            (
                jnp.asarray(kind_b[i : i + self.chunk]),
                jnp.asarray(pos_b[i : i + self.chunk]),
                jnp.asarray(slot_b[i : i + self.chunk]),
            )
            for i in range(0, tt.n_batches, self.chunk)
        ]
        self.kind_b = jnp.asarray(kind_b)
        self.pos_b = jnp.asarray(pos_b)
        self.slot_b = jnp.asarray(slot_b)

        self.chars = jnp.asarray(slot_char_table(tt, self.capacity))

    def fresh_state(self) -> DocState:
        return broadcast_replicas(
            init_state(self.capacity, self.n_init), self.n_replicas
        )

    def _fresh_r(self) -> DocState:
        """R-leading state (leading axis present even for R=1)."""
        st = init_state(self.capacity, self.n_init)
        return jax.tree.map(
            lambda x: jnp.broadcast_to(x, (self.n_replicas,) + jnp.shape(x)),
            st,
        )

    def run(self, state: DocState | None = None) -> DocState:
        """Replay the full trace; returns final state (device).  Input and
        output follow the fresh_state convention (no leading axis at R=1)."""
        if state is None:
            st = self._fresh_r()
        elif self.n_replicas == 1:
            st = jax.tree.map(lambda x: x[None], state)
        else:
            st = state
        for kind, pos, slot in self.chunks:
            st = replay_batches_r(st, kind, pos, slot, resolver=self.resolver)
        if self.n_replicas == 1:
            st = jax.tree.map(lambda x: x[0], st)
        return st

    def run_blocking(self) -> DocState:
        state = self.run()
        jax.block_until_ready(state)
        return state

    # ---- decode / checks -------------------------------------------------

    def decode(self, state: DocState, replica: int = 0) -> str:
        """Materialize a replica's visible document as a Python string."""
        return decode_to_str(
            select_replica(state, replica, self.n_replicas), self.chars
        )

    def lengths(self, state: DocState) -> np.ndarray:
        """Per-replica visible char counts — the reference's length oracle
        (src/main.rs:35), available without full decode."""
        return np.atleast_1d(np.asarray(state.nvis))


def replay_trace_jax(tt: TensorizedTrace) -> str:
    """Convenience: single-replica replay -> final content string."""
    eng = ReplayEngine(tt, n_replicas=1)
    return eng.decode(eng.run_blocking())
