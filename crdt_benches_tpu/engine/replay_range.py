"""Range-op replay driver: the block-edit fast path.

Same shape as engine/replay.py's v3 path but over RANGE ops
(traces/tensorize.py tensorize_ranges): resolver work scales with patches
instead of chars, which on the block-edit traces is an ~3-24x reduction in
sequential op count (SURVEY.md section 6, 'per-char-exploded unit ops').
Byte-identical output is asserted against the oracle in tests.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..lint.boundary import boundary
from ..ops.apply2 import PackedState, init_state3, init_state4
from ..ops.apply_range import apply_range_batch
from ..traces.tensorize import INSERT, RangeTrace
from .replay import _round_up, _stage_capacity


def _grow_state3(state: PackedState, new_cap: int) -> PackedState:
    """Pad a PackedState's capacity axis to new_cap (doc pads with
    pack_doc(-1, 0) == 2 — the same beyond-length coding apply_range_batch
    re-stamps every batch)."""
    R, C = state.doc.shape
    if new_cap <= C:
        return state
    return PackedState(
        doc=jnp.concatenate(
            [state.doc, jnp.full((R, new_cap - C), 2, jnp.int32)], axis=1
        ),
        length=state.length,
        nvis=state.nvis,
    )


@boundary(
    dtypes=(None, "int32", "int32", "int32", "int32"),
    shapes=(None, "N B", "N B", "N B", "N B"),
    donates=(0,),
)
@partial(
    jax.jit,
    static_argnames=("nbits", "pack", "interpret", "token_cap", "engine"),
    donate_argnums=(0,),
)
def replay_ranges(
    state, kind_b, pos_b, rlen_b, slot0_b,
    *, nbits: int, pack: int = 4, interpret: bool = False,
    token_cap: int | None = None, engine: str = "v3",
):
    """Scan all range batches into the state.  ``engine`` picks the
    apply: 'v3' = per-pass XLA apply on PackedState
    (ops/apply_range.py), 'v4' = fused-kernel apply on the maintained-cv
    PackedState4 (ops/apply_range_fused.py) — the state pytree must
    match.

    Resolver selection rides the ``interpret`` flag: on TPU (interpret
    False) the fused Pallas kernel; off-TPU the native-XLA scan resolver
    (ops/resolve_range_scan.py) — differentially tested equal — instead
    of interpret-mode emulation of the kernel, which pays ref-tracking
    overhead for no hardware reason.  The scan resolver's token list is
    always the full 2B+2, so ``token_cap`` (a VMEM sizing lever) only
    shapes the Pallas path."""
    if engine == "v4":
        from ..ops.apply_range_fused import apply_range_batch4

        apply_fn = partial(apply_range_batch4, interpret=interpret)
    else:
        apply_fn = apply_range_batch

    if interpret:
        from ..ops.resolve_range_scan import resolve_ranges_shared

        def resolve(k, p, ln, s0, nvis):
            return resolve_ranges_shared(k, p, ln, s0, nvis)
    else:
        from ..ops.resolve_range_pallas import resolve_range_pallas

        def resolve(k, p, ln, s0, nvis):
            return resolve_range_pallas(
                k, p, ln, s0, nvis, interpret=False, token_cap=token_cap
            )

    NB, B = kind_b.shape
    K = min(pack, NB)
    while NB % K:
        K -= 1
    rs = lambda x: x.reshape(NB // K, K, B)

    def step(carry, batch):
        st, mx = carry
        k, p, ln, s0 = batch
        for i in range(K):
            tokens, dints, nused = resolve(
                k[i], p[i], ln[i], s0[i], st.nvis
            )
            mx = jnp.maximum(mx, jnp.max(nused))
            st = apply_fn(st, tokens, dints, nbits=nbits)
        return (st, mx), None

    (state, max_nused), _ = jax.lax.scan(
        step, (state, jnp.int32(0)),
        (rs(kind_b), rs(pos_b), rs(rlen_b), rs(slot0_b)),
    )
    return state, max_nused


#: Module-level jitted inits (all-static args -> one compile per shape):
#: fresh-document init is TIMED (the reference times from_str,
#: src/main.rs:29) but must not run eagerly — op-by-op dispatch costs
#: ~25ms each on this runtime (code-review r4: a per-run jax.jit wrapper
#: would retrace every benchmark iteration).
_init_state3_jit = jax.jit(init_state3, static_argnums=(0, 1, 2))
_init_state4_jit = jax.jit(init_state4, static_argnums=(0, 1, 2))


class RangeReplayEngine:
    """Host-side driver for range-op replay (API parallel to ReplayEngine)."""

    def __init__(
        self,
        rt: RangeTrace,
        n_replicas: int = 1,
        lane: int = 128,
        chunk: int = 32,
        pack: int = 4,
        interpret: bool | None = None,
        engine: str | None = None,
    ):
        import os

        if interpret is None:
            # The range resolver has no XLA twin in this driver; off-TPU
            # (bench.py's CPU fallback, virtual-device runs) the Pallas
            # kernel must run in interpret mode or pallas_call errors out.
            interpret = jax.default_backend() != "tpu"

        self.rt = rt
        self.n_replicas = n_replicas
        #: 'v4' = fused-kernel apply on the maintained-cv PackedState4
        #: (ops/apply_range_fused.py); 'v3' = the per-pass XLA apply
        #: (ops/apply_range.py).  v4 needs the doc to fit the kernel's
        #: VMEM stack budget on TPU; above the gate fall back to v3.
        self.engine = engine or os.environ.get("CRDT_RANGE_APPLY", "v4")
        if self.engine == "v4":
            # The fused kernel's cross-tile scan runs sublane-axis shifts
            # over (Rt, nt, 1) tile totals; nt must be a multiple of 8 or
            # Mosaic's unaligned sublane copies blow up compilation.
            lane = max(lane, 8 * 128)
        self.capacity = _round_up(max(rt.capacity, 1), lane)
        # v4 no longer downgrades to v3 above the monolithic VMEM gate:
        # apply_range_batch4 dispatches to the halo-blocked kernel there
        # (ops/apply_range_fused.py range_fused_blocked, round-5).
        # CRDT_RANGE_APPLY=v3 still forces the per-pass XLA apply.
        # Arithmetic-range preconditions of the packed spread paths,
        # conservatively gated at the TIGHTEST bound any selected path
        # carries: the MONOLITHIC fused kernel's shifted ddelta level
        # accumulation is int32-exact only while 128 * 2 * capacity
        # < 2^31 (capacity <= 2^22), and the producer's one-cell f32
        # spread accumulation needs 2 * capacity < 2^24 (<= 2^23).  The
        # halo-blocked kernel itself is int32-exact beyond that, but it
        # shares the producer and the interpret/CPU paths share the
        # monolithic math, so raising this guard requires auditing those
        # two bounds, not the blocked kernel (code-review r5).  Fail
        # loudly instead of silently truncating (ADVICE r1).
        if self.capacity > 1 << 22:
            raise ValueError(
                f"capacity {self.capacity} > 2^22 exceeds the monolithic"
                " fused kernel's int32 level-accumulation bound (the"
                " blocked kernel is exact but the shared producer caps at"
                " 2^23); use the unit engine or split the ddelta spread"
            )
        self.n_init = len(rt.init_chars)
        self.pack = pack
        self.chunk = _round_up(
            int(os.environ.get("CRDT_ENGINE_CHUNK", str(chunk))), pack
        )
        self.interpret = interpret
        self.nbits = max(1, int(rt.max_batch_ins).bit_length())

        kind_b, pos_b, rlen_b, slot0_b = rt.batched()
        self.chunks = [
            (
                jnp.asarray(kind_b[i : i + self.chunk]),
                jnp.asarray(pos_b[i : i + self.chunk]),
                jnp.asarray(rlen_b[i : i + self.chunk]),
                jnp.asarray(slot0_b[i : i + self.chunk]),
            )
            for i in range(0, rt.n_batches, self.chunk)
        ]
        # Per-chunk resolver token caps from the exact host simulation
        # (ops/token_sim.py) — resolver cost is linear in the VMEM token
        # list, and real batches sit far below the 2B+2 worst case.
        self.token_caps: list[int | None] = [None] * len(self.chunks)
        if os.environ.get("CRDT_ENGINE_TOKENSIM", "1") != "0":
            from ..ops.token_sim import simulate_range_token_counts

            tc = simulate_range_token_counts(
                kind_b, pos_b, rlen_b, self.n_init
            )
            self.token_caps = [
                _round_up(int(tc[i : i + self.chunk].max()) + 8, 128)
                for i in range(0, rt.n_batches, self.chunk)
            ]
        # Capacity staging (live-prefix), same scheme as the unit v4
        # engine (engine/replay.py): every apply pass streams the full
        # (R, C) doc, but the document grows over the replay — early
        # chunks run at a geometrically-staged capacity covering their
        # end-of-chunk used length (host-known: n_init + running insert
        # chars; slot ids are insertion-ordered so they always fit).
        ins_chars = np.where(kind_b == INSERT, rlen_b, 0).sum(axis=1)
        end_len = self.n_init + np.cumsum(ins_chars)
        self.stage_caps: list[int] = []
        for i in range(0, rt.n_batches, self.chunk):
            need = int(end_len[min(i + self.chunk, len(end_len)) - 1])
            self.stage_caps.append(
                min(self.capacity, _stage_capacity(need, lane))
            )
        for i in range(1, len(self.stage_caps)):
            self.stage_caps[i] = max(
                self.stage_caps[i], self.stage_caps[i - 1]
            )
        if not self.stage_caps:
            self.stage_caps = [self.capacity]

        chars = np.zeros(self.capacity, np.int32)
        chars[: rt.capacity] = rt.chars
        self.chars = jnp.asarray(chars)

    def run(self, state=None):
        if self.engine == "v4":
            from .replay import _grow_state4

            init, grow = _init_state4_jit, _grow_state4
        else:
            init, grow = _init_state3_jit, _grow_state3
        st = (
            init(self.n_replicas, self.stage_caps[0], self.n_init)
            if state is None
            else state
        )
        # (effective kernel T, device max nused) per chunk; a single
        # host fetch AFTER the loop keeps syncs out of the chunk loop
        # while turning an undersized token cap into a loud failure
        # instead of silent corruption (ADVICE r3).
        demands: list[tuple[int, jax.Array]] = []
        from ..ops.resolve_range_pallas import effective_token_list_size

        for cap, tcap, (kind, pos, rlen, slot0) in zip(
            self.stage_caps, self.token_caps, self.chunks
        ):
            st = grow(st, cap)
            st, mx = replay_ranges(
                st, kind, pos, rlen, slot0,
                nbits=self.nbits, pack=self.pack, interpret=self.interpret,
                token_cap=tcap, engine=self.engine,
            )
            # Off-TPU the scan resolver always carries the exact 2B+2
            # worst-case list — token_cap (a Pallas VMEM lever) must not
            # shrink the bound the demand is checked against.
            B = kind.shape[1]
            t_eff = (
                2 * B + 2 if self.interpret
                else effective_token_list_size(B, tcap)
            )
            demands.append((t_eff, mx))
        for i, (t_eff, mx) in enumerate(demands):
            got = int(mx)
            if got > t_eff:  # not assert: must survive python -O
                raise RuntimeError(
                    f"range resolver token overflow in chunk {i}: demand"
                    f" {got} > VMEM list size {t_eff} (token_sim drift?)"
                )
        return st

    def decode(self, state, replica: int = 0) -> str:
        from ..ops.apply2 import decode_state3

        if not isinstance(state, PackedState):
            state = PackedState(
                doc=state.doc, length=state.length, nvis=state.nvis
            )
        codes, nvis = jax.jit(
            decode_state3, static_argnames=("replica",)
        )(state, self.chars, replica=replica)
        return "".join(map(chr, np.asarray(codes)[: int(nvis)].tolist()))

    def lengths(self, state: PackedState) -> np.ndarray:
        return np.atleast_1d(np.asarray(state.nvis))
