"""Downstream path: remote-update generation + timed batched apply.

The capability of the reference's ``Downstream`` trait and its diamond-types
implementation (reference src/rope.rs:185-225, bench at src/main.rs:50-81):

- ``upstream_updates`` (UNTIMED, reference src/main.rs:60): replay the trace
  on a fresh upstream replica and emit one encoded update per edit.  The
  reference's encoding is diamond-types' incremental binary format from a
  version frontier (``oplog.encode_from``, src/rope.rs:214); ours is the
  TPU-native equivalent — **updates are integer tensors**: per op-batch, the
  inserted element ids (slots), each insert's *anchor* (the nearest preceding
  element from an earlier batch, i.e. an element the receiver has already
  integrated), a rank among same-anchor inserts, and each delete's target
  element id.  This is the same structural summarization diamond-types
  performs when it run-length-encodes sequential-insert runs into updates —
  resolved structure at encode time, pure merge work at apply time.

- ``apply_update`` (TIMED, reference src/main.rs:64-67): integrate updates
  into a downstream replica that starts from ``start_content`` only.  With
  anchors resolved to already-integrated elements, integration is fully
  vectorized per batch — slot->position scatter, counting merge of the new
  elements into the order permutation, visibility scatters — with **no
  sequential scan at all**: the per-op dependency was discharged at encode
  time, so the timed path is O(capacity) vectorized work per batch.

Correctness argument for anchor-based integration: once two elements are both
present in a sequence CRDT, their relative order never changes (tombstones
preserve positions).  Hence each batch insert's nearest preceding
earlier-batch element in the *final* upstream order is exactly the element it
must follow at integration time, and same-anchor inserts keep their final
relative order as consecutive ranks.  Induction over batches reproduces the
upstream order permutation element-for-element; byte-identical final content
is asserted in tests (upgrading the reference's length-only check,
src/main.rs:68).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..lint.boundary import boundary
from ..ops.apply import init_state
from ..traces.loader import TestData
from ..traces.tensorize import INSERT, TensorizedTrace, tensorize
from .replay import (
    _round_up,
    broadcast_replicas,
    decode_to_str,
    replay_batches_collect,
    select_replica,
    slot_char_table,
)


class DownState(NamedTuple):
    """Downstream replica state — like DocState minus origins (origins were
    consumed at encode time)."""

    order: jax.Array  # int32[C] slot ids in document order (incl. tombstones)
    visible: jax.Array  # bool[C] by slot id
    length: jax.Array  # int32  used entries of `order`
    nvis: jax.Array  # int32  visible char count


@dataclass
class DownstreamUpdates:
    """One trace's pre-generated updates, as batched tensors.

    Each row b is one update covering a batch of B unit ops:
    ``ins_slot[b]`` int32[B] inserted element ids (-1 = not an insert),
    ``anchor[b]`` int32[B] already-integrated element the insert follows
    (-1 = document head), ``rank[b]`` int32[B] order among same-anchor
    inserts, ``dslot[b]`` int32[B] deleted element ids (-1 = not a delete).
    """

    ins_slot: np.ndarray  # int32[n_batches, B]
    anchor: np.ndarray  # int32[n_batches, B]
    rank: np.ndarray  # int32[n_batches, B]
    dslot: np.ndarray  # int32[n_batches, B]
    capacity: int  # padded physical buffer size
    n_init: int  # start-content length (slots 0..n_init-1)
    chars: np.ndarray  # int32[capacity] slot -> codepoint
    end_content: str
    n_patches: int
    #: Positional form of the same updates, resolved against the receiver's
    #: state at each batch's integration point (still encode-time work, like
    #: the anchor/rank fields): ``ins_gap`` = physical position the insert
    #: lands after in the pre-batch doc (0 = head), ``del_pos`` = physical
    #: position of the delete target in the POST-batch doc.  These drive the
    #: scatter-free packed apply (apply_updates3).
    ins_gap: np.ndarray | None = None  # int32[n_batches, B]
    del_pos: np.ndarray | None = None  # int32[n_batches, B]

    def nbytes(self, engine: str = "v5") -> int:
        """Wire size of the update tensors the given apply engine actually
        ships and integrates (the analog of the encoded update byte
        payloads the reference ships, src/rope.rs:199; per-form reporting
        per ADVICE round 1).  ``v5``/``v1`` consume the id-based
        anchor/rank form; ``v3`` consumes ins_slot/rank plus the
        encode-time positional form (ins_gap/del_pos)."""
        if engine == "v3":
            arrays = [self.ins_slot, self.rank]
            arrays += [
                a for a in (self.ins_gap, self.del_pos) if a is not None
            ]
        else:
            arrays = [self.ins_slot, self.anchor, self.rank, self.dslot]
        return sum(a.nbytes for a in arrays)


def _prev_smaller(vals: np.ndarray) -> np.ndarray:
    """For each i: the largest j < i with vals[j] < vals[i], else -1
    (classic previous-smaller-value monotonic stack, amortized O(n))."""
    out = np.empty(len(vals), np.int64)
    stack: list[int] = []
    v = vals.tolist()
    for i, x in enumerate(v):
        while stack and v[stack[-1]] >= x:
            stack.pop()
        out[i] = stack[-1] if stack else -1
        stack.append(i)
    return out


def generate_updates(
    tt: TensorizedTrace, lane: int = 128, positional: bool = True
) -> DownstreamUpdates:
    """UNTIMED update generation: one upstream replay (device) + anchor/rank
    extraction (host, single pass).  The analog of reference
    ``upstream_updates`` (src/rope.rs:196-220), which is likewise untimed
    (src/main.rs:60).  ``positional=False`` skips the encode-time-resolved
    ins_gap/del_pos form (an O(n_batches x doc_length) host pass consumed
    only by the v3 engine)."""
    capacity = _round_up(max(tt.capacity, 1), lane)
    n_init = len(tt.init_chars)
    kind_b, pos_b, _, slot_b = tt.batched()
    n_batches, B = kind_b.shape

    from .replay import default_resolver

    state, dslot_b = replay_batches_collect(
        init_state(capacity, n_init),
        jnp.asarray(kind_b),
        jnp.asarray(pos_b),
        jnp.asarray(slot_b),
        resolver=default_resolver(),
    )
    length = int(state.length)
    order = np.asarray(state.order)[:length]  # final doc order, incl. tombstones
    dslot_b = np.asarray(dslot_b)

    # batch index of every slot: -1 for init content, op_index // B for inserts
    batch_of_slot = np.full(capacity, -1, np.int32)
    is_ins = tt.kind == INSERT
    op_of_ins = np.nonzero(is_ins)[0]
    batch_of_slot[tt.slot[is_ins]] = (op_of_ins // B).astype(np.int32)

    pos_of_slot = np.full(capacity, -1, np.int64)
    pos_of_slot[order] = np.arange(length)
    arrb = batch_of_slot[order]  # batch index at each final doc position

    # Anchor of the element at position q = nearest p < q with a smaller
    # batch index (an element integrated in an earlier batch, or init = -1).
    a_pos_all = _prev_smaller(arrb)

    ins_slot = np.full((n_batches, B), -1, np.int32)
    anchor = np.full((n_batches, B), -1, np.int32)
    rank = np.zeros((n_batches, B), np.int32)

    slots = tt.slot[is_ins]  # every insert's slot, in op order
    q = pos_of_slot[slots]
    a_pos = a_pos_all[q]
    a_slot = np.where(a_pos >= 0, order[np.clip(a_pos, 0, None)], -1)
    # rank among inserts of the same batch sharing an anchor, in doc order
    b_of_ins = (op_of_ins // B).astype(np.int64)
    sort = np.lexsort((q, a_pos, b_of_ins))
    key_b, key_a = b_of_ins[sort], a_pos[sort]
    grp_start = np.concatenate(
        [[True], (key_b[1:] != key_b[:-1]) | (key_a[1:] != key_a[:-1])]
    )
    idx = np.arange(len(sort))
    r_sorted = idx - np.maximum.accumulate(np.where(grp_start, idx, 0))
    r = np.empty_like(r_sorted)
    r[sort] = r_sorted

    row, col = np.divmod(op_of_ins, B)
    ins_slot[row, col] = slots
    anchor[row, col] = a_slot
    rank[row, col] = r.astype(np.int32)

    # Positional update form (encode-time resolution against the receiver's
    # integration-point state; one O(length) pass per batch, untimed):
    # physical position of final-order index q at time b (batches < b
    # integrated) = #{p < q : arrb[p] < b}.
    ins_gap = del_pos = None
    if positional:
        ins_gap = np.zeros((n_batches, B), np.int32)
        del_pos = np.full((n_batches, B), -1, np.int32)
        qd_all = np.where(
            dslot_b >= 0, pos_of_slot[np.clip(dslot_b, 0, None)], 0
        )
        for b in range(n_batches):
            ex_lt = np.concatenate([[0], np.cumsum(arrb < b)[:-1]])
            ex_le = np.concatenate([[0], np.cumsum(arrb <= b)[:-1]])
            sel = row == b
            ap = a_pos[sel]
            ins_gap[b, col[sel]] = np.where(
                ap >= 0, ex_lt[np.clip(ap, 0, None)] + 1, 0
            ).astype(np.int32)
            hd = dslot_b[b] >= 0
            del_pos[b, hd] = ex_le[qd_all[b, hd]].astype(np.int32)

    chars = slot_char_table(tt, capacity)
    return DownstreamUpdates(
        ins_slot=ins_slot,
        anchor=anchor,
        rank=rank,
        dslot=dslot_b,
        capacity=capacity,
        n_init=n_init,
        chars=chars,
        end_content=tt.end_content,
        n_patches=tt.n_patches,
        ins_gap=ins_gap,
        del_pos=del_pos,
    )


def init_down_state(capacity: int, n_init: int) -> DownState:
    idx = jnp.arange(capacity, dtype=jnp.int32)
    return DownState(
        order=jnp.where(idx < n_init, idx, -1),
        visible=idx < n_init,
        length=jnp.int32(n_init),
        nvis=jnp.int32(n_init),
    )


def apply_update_batch(
    state: DownState, ins: jax.Array, anchor: jax.Array, rank: jax.Array,
    dslot: jax.Array
) -> DownState:
    """Integrate one update batch — fully vectorized (no scan).  The timed
    analog of ``oplog.decode_and_add`` (reference src/rope.rs:222-224)."""
    C = state.order.shape[0]
    drop = jnp.int32(C)
    idx = jnp.arange(C, dtype=jnp.int32)
    valid = idx < state.length
    is_ins = ins >= 0

    # slot -> current physical position
    phys = (
        jnp.zeros(C, jnp.int32)
        .at[jnp.where(valid, state.order, drop)]
        .set(idx, mode="drop")
    )
    a_phys = jnp.where(anchor >= 0, phys[jnp.clip(anchor, 0, C - 1)], -1)
    gap = jnp.where(is_ins, a_phys + 1, C + 1)

    # counting merge of the new elements into the order permutation
    bump = jnp.zeros(C + 1, jnp.int32).at[gap].add(1, mode="drop")
    csum = jnp.cumsum(bump)
    new_idx_old = idx + csum[idx]
    n_before = jnp.where(gap > 0, csum[jnp.clip(gap - 1, 0)], 0)
    new_idx_ins = gap + n_before + rank

    order = (
        jnp.full(C, -1, jnp.int32)
        .at[jnp.where(valid, new_idx_old, drop)]
        .set(jnp.where(valid, state.order, -1), mode="drop")
        .at[jnp.where(is_ins, new_idx_ins, drop)]
        .set(ins, mode="drop")
    )
    # visibility: new inserts visible, then this batch's deletes tombstone
    # (covers same-batch insert+delete: set-True then set-False)
    visible = (
        state.visible.at[jnp.where(is_ins, ins, drop)]
        .set(True, mode="drop")
        .at[jnp.where(dslot >= 0, dslot, drop)]
        .set(False, mode="drop")
    )
    length = state.length + jnp.sum(is_ins.astype(jnp.int32))
    valid2 = idx < length
    nvis = jnp.sum(
        valid2 & visible[jnp.where(valid2, order, 0)], dtype=jnp.int32
    )
    return DownState(order=order, visible=visible, length=length, nvis=nvis)


@partial(jax.jit, donate_argnums=(0,))
def apply_updates(state: DownState, ins_b, anchor_b, rank_b, dslot_b) -> DownState:
    """Scan all update batches into the downstream state (the timed hot loop,
    reference src/main.rs:65-67)."""

    def step(st, upd):
        return apply_update_batch(st, *upd), None

    state, _ = jax.lax.scan(step, state, (ins_b, anchor_b, rank_b, dslot_b))
    return state


def apply_update_batch3(state, ins, gap, rank, del_pos):
    """Positional update integration on the packed doc-order state
    (ops/apply2.py PackedState) — the scatter-free fast path: counting merge
    via MXU one-hot spreads + the fused expansion kernel, deletes cleared at
    post-batch positions.  Replica-batched: state leaves (R, ...), update
    leaves (R, B) or broadcastable (B,) handled by the caller."""
    from ..ops.apply2 import (
        PackedState,
        _expand,
        _mxu_spread,
        pack_doc,
    )

    R, C = state.doc.shape
    B = ins.shape[1]
    drop = jnp.int32(C + 7)
    col = jax.lax.broadcasted_iota(jnp.int32, (R, C), 1)

    is_ins = ins >= 0
    gap = jnp.where(is_ins, gap, drop)
    smaller = (gap[:, :, None] > gap[:, None, :]) & is_ins[:, None, :]
    n_before = jnp.sum(smaller.astype(jnp.int32), axis=2)
    dest = jnp.where(is_ins, gap + n_before + rank, drop)

    fill = jnp.where(is_ins, pack_doc(ins, jnp.ones_like(ins)), 0)
    chunks = [
        is_ins.astype(jnp.int32),
        jnp.bitwise_and(fill, 127),
        jnp.bitwise_and(jnp.right_shift(fill, 7), 127),
        jnp.bitwise_and(jnp.right_shift(fill, 14), 127),
        jnp.bitwise_and(jnp.right_shift(fill, 21), 127),
    ]
    ind, f0, f1, f2, f3 = _mxu_spread(dest, chunks, C)
    fill_dense = (
        f0
        + jnp.left_shift(f1, 7)
        + jnp.left_shift(f2, 14)
        + jnp.left_shift(f3, 21)
    )

    cnt = jnp.cumsum(ind, axis=1)
    nbits = max(1, (B).bit_length())
    cntind = jnp.left_shift(cnt, 1) | ind
    if jax.default_backend() == "tpu":
        from ..ops.expand_pallas import expand_packed

        doc = expand_packed(state.doc, cntind, nbits=nbits)
    else:
        (doc,) = _expand([state.doc], cnt, nbits)
        doc = jnp.where(ind != 0, 0, doc)
    doc = doc + fill_dense

    # Deletes at post-batch positions (each target currently visible).
    has_del = del_pos >= 0
    (del_ind,) = _mxu_spread(
        jnp.where(has_del, del_pos, drop), [has_del.astype(jnp.int32)], C
    )
    doc = doc - del_ind

    n_ins = jnp.sum(is_ins.astype(jnp.int32), axis=1)
    n_del = jnp.sum(has_del.astype(jnp.int32), axis=1)
    length = state.length + n_ins
    beyond = col >= length[:, None]
    return PackedState(
        doc=jnp.where(beyond, pack_doc(-1, 0), doc),
        length=length,
        nvis=state.nvis + n_ins - n_del,
    )


@partial(jax.jit, static_argnames=("pack",), donate_argnums=(0,))
def apply_updates3(state, ins_b, gap_b, rank_b, dpos_b, *, pack: int = 8):
    """Scan all positional update batches into replica-batched packed state,
    ``pack`` batches per scan step."""
    NB, B = ins_b.shape
    K = min(pack, NB)
    while NB % K:
        K -= 1
    R = state.doc.shape[0]
    bc = lambda x: jnp.broadcast_to(x[None], (R,) + x.shape)
    rs = lambda x: x.reshape(NB // K, K, B)

    def step(st, upd):
        i, g, r, d = upd
        for k in range(K):
            st = apply_update_batch3(
                st, bc(i[k]), bc(g[k]), bc(r[k]), bc(d[k])
            )
        return st, None

    state, _ = jax.lax.scan(
        step, state, (rs(ins_b), rs(gap_b), rs(rank_b), rs(dpos_b))
    )
    return state




class DownPacked(NamedTuple):
    """Packed downstream state for the id-resolved (v5) apply: the packed
    doc plus the epoch position snapshot (ops/idpos.py)."""

    doc: jax.Array  # int32[R, C] packed ((slot+2)<<1)|vis
    snap: jax.Array  # int32[R, C] slot -> position as of the epoch boundary
    length: jax.Array  # int32[R]
    nvis: jax.Array  # int32[R]


def down_packed_init(
    n_replicas: int, capacity: int, n_init: int
) -> DownPacked:
    """Fresh replica-batched DownPacked (base content laid out in order)."""
    from ..ops.apply2 import init_state3
    from ..ops.idpos import snap_init

    s3 = init_state3(n_replicas, capacity, n_init)
    return DownPacked(
        doc=s3.doc,
        snap=snap_init(n_replicas, capacity),
        length=s3.length,
        nvis=s3.nvis,
    )


def _apply_update_batch5(doc, length, nvis, snap, levels, ins, anchor,
                         rank, dslot, *, nbits: int):
    """Integrate one anchor/rank update batch with id->position resolution
    INSIDE the timed region (ops/idpos.py) — the honest analog of the
    reference's timed ``decode_and_add`` (src/rope.rs:222-224), which
    likewise locates each op's anchor in the receiver's current structure.

    Wire rows (shared across replicas): ``ins`` inserted slot ids (-1 = not
    an insert), ``anchor`` already-integrated element the insert follows
    (-1 = head), ``rank`` order among same-anchor inserts, ``dslot`` deleted
    element ids.  Returns (doc, length, nvis, level).
    """
    from ..ops.apply2 import _mxu_spread_tc, pack_doc, spread_fill_combo
    from ..ops.idpos import make_level, query

    R, C = doc.shape
    B = ins.shape[0]
    drop = jnp.int32(C + 7)
    is_ins = ins >= 0
    has_del = dslot >= 0
    bc = lambda x: jnp.broadcast_to(x[None], (R, B))

    # ---- resolve anchors (id -> current physical position) ----
    a_phys = query(snap, levels, bc(anchor))
    gap = jnp.where(
        bc(is_ins),
        jnp.where(bc(anchor) >= 0, a_phys + 1, 0),
        drop,
    )

    # ---- same-batch insert+delete: the insert integrates dead ----
    kill = (
        (dslot[:, None] == ins[None, :]) & has_del[:, None] & is_ins[None, :]
    )  # [d, i]: delete row d targets insert row i
    killed = jnp.any(kill, axis=0)  # per insert row
    alive = is_ins & ~killed
    del_prev = has_del & ~jnp.any(kill, axis=1)  # targets an older element

    # ---- resolve deletes of older elements ----
    dphys = jnp.where(
        bc(del_prev), query(snap, levels, bc(dslot)), drop
    )

    # ---- insert destinations (counting merge) ----
    smaller = (gap[:, :, None] > gap[:, None, :]) & bc(is_ins)[:, None, :]
    n_before = jnp.sum(smaller.astype(jnp.int32), axis=2)
    dest = jnp.where(bc(is_ins), gap + n_before + bc(rank), drop)

    # ---- deletes: clear a guaranteed-visible bit (guarded subtract) ----
    (del_cnt,), _ = _mxu_spread_tc(
        dphys, [jnp.ones((R, B), jnp.int32)], C
    )
    sub = jnp.minimum(del_cnt, 1) * jnp.bitwise_and(doc, 1)
    doc_predel = doc - sub
    n_del_eff = jnp.sum(sub, axis=1)

    # ---- fills + fused expansion (apply2.apply_batch4's integrate half) ----
    fill = bc(
        jnp.where(is_ins, pack_doc(ins, alive.astype(jnp.int32)), 0)
    )
    combo, cnt_base = spread_fill_combo(dest, fill, C)

    n_ins = jnp.sum(is_ins.astype(jnp.int32))
    n_live = jnp.sum(alive.astype(jnp.int32))
    length2 = length + n_ins

    from ..ops.expand_pallas import fused_apply_nocv_dispatch

    doc2 = fused_apply_nocv_dispatch(
        doc_predel, combo, cnt_base, length2, nbits=nbits
    )
    level = make_level(dest, bc(is_ins), bc(ins))
    return doc2, length2, nvis + n_live - n_del_eff, level


@boundary(
    dtypes=(None, "int32", "int32", "int32", "int32"),
    shapes=(None, "N B", "N B", "N B", "N B"),
    donates=(0,),
)
@partial(jax.jit, static_argnames=("nbits", "epoch"), donate_argnums=(0,))
def apply_updates5(
    state: DownPacked, ins_b, anchor_b, rank_b, dslot_b,
    *, nbits: int, epoch: int = 32
) -> DownPacked:
    """Scan all anchor/rank update batches into the packed state; the epoch
    snapshot is rebuilt (one scatter) every ``epoch`` batches, with the
    in-between batches resolved through per-batch levels (ops/idpos.py).
    NB must be a multiple of ``epoch`` (pad with PAD batches)."""
    from ..ops.idpos import snap_rebuild

    NB, B = ins_b.shape
    K = min(epoch, NB)
    if NB % K:
        raise ValueError(f"batch count {NB} not a multiple of epoch {K}")
    rs = lambda x: x.reshape(NB // K, K, B)

    def step(st, upd):
        i_b, a_b, r_b, d_b = upd
        doc, snap, length, nvis = st
        levels: list = []
        for k in range(K):
            doc, length, nvis, lv = _apply_update_batch5(
                doc, length, nvis, snap, levels,
                i_b[k], a_b[k], r_b[k], d_b[k], nbits=nbits,
            )
            levels.append(lv)
        return DownPacked(doc, snap_rebuild(doc), length, nvis), None

    state, _ = jax.lax.scan(
        step, state,
        (rs(ins_b), rs(anchor_b), rs(rank_b), rs(dslot_b)),
    )
    return state


class JaxDownstreamEngine:
    """Host-side driver: untimed generation, timed repeated apply.

    ``n_replicas > 1`` batches the apply over a replica axis (every replica
    integrates the same update stream — the batched-downstream analog of the
    upstream replica axis).

    Engines:
    - ``"v5"`` (default): consumes the anchor/rank id-based wire form and
      resolves every anchor/delete target to its current position INSIDE
      the timed apply (ops/idpos.py epoch structure) — like-for-like with
      the reference's timed CRDT integration (src/main.rs:62-69).
    - ``"v3"``: consumes the positional form (``ins_gap``/``del_pos``,
      resolved at encode time).  Faster, but the timed region excludes the
      anchor->position work — reported separately as ``jax-*-pos``
      (round-1 advisor finding).
    - ``"v1"``: anchor/rank form on the unpacked DownState with per-batch
      capacity scatters (portable reference path; CPU tests).
    """

    def __init__(self, tt: TensorizedTrace, n_replicas: int = 1,
                 engine: str | None = None, epoch: int | None = None):
        import os

        self.engine = engine or os.environ.get("CRDT_DOWN_ENGINE", "v5")
        # The positional form is an O(n_batches x doc_length) host pass
        # consumed only by the v3 engine — skip it elsewhere.
        self.upd = generate_updates(tt, positional=self.engine == "v3")
        # Packed-arithmetic precondition (fail loudly, ADVICE round 1): the
        # v5/v3 integrate paths spread fill = ((slot+2)<<1)|vis in chunked
        # bf16 form and tile_base in 3x7-bit chunks — both require
        # capacity < 2^21 (same bound ReplayEngine asserts).
        if self.upd.capacity >= 1 << 21:
            raise ValueError(
                f"capacity {self.upd.capacity} >= 2^21 exceeds the packed"
                " engine's chunked-arithmetic range"
            )
        self.n_replicas = n_replicas
        # Explicit argument beats the env knob (same precedence as engine).
        self.epoch = (
            epoch
            if epoch is not None
            else int(os.environ.get("CRDT_DOWN_EPOCH", "32"))
        )
        self.epoch = min(
            self.epoch, max(1, self.upd.ins_slot.shape[0])
        )
        pad = (-self.upd.ins_slot.shape[0]) % self.epoch
        if pad and self.engine == "v5":
            z = np.full(
                (pad, self.upd.ins_slot.shape[1]), -1, np.int32
            )
            padf = lambda a, fill: np.concatenate(
                [a, np.full_like(z, fill)]
            )
            self.ins_b = jnp.asarray(padf(self.upd.ins_slot, -1))
            self.anchor_b = jnp.asarray(padf(self.upd.anchor, -1))
            self.rank_b = jnp.asarray(padf(self.upd.rank, 0))
            self.dslot_b = jnp.asarray(padf(self.upd.dslot, -1))
        else:
            self.ins_b = jnp.asarray(self.upd.ins_slot)
            self.anchor_b = jnp.asarray(self.upd.anchor)
            self.rank_b = jnp.asarray(self.upd.rank)
            self.dslot_b = jnp.asarray(self.upd.dslot)
        if self.upd.ins_gap is not None:
            self.gap_b = jnp.asarray(self.upd.ins_gap)
            self.dpos_b = jnp.asarray(self.upd.del_pos)
        self.chars = jnp.asarray(self.upd.chars)
        self.nbits = max(1, int(self.upd.ins_slot.shape[1]).bit_length())
        if n_replicas == 1:
            self._apply = apply_updates
        else:
            self._apply = jax.jit(
                jax.vmap(apply_updates, in_axes=(0, None, None, None, None)),
                donate_argnums=(0,),
            )

    def fresh_state(self) -> DownState:
        return broadcast_replicas(
            init_down_state(self.upd.capacity, self.upd.n_init),
            self.n_replicas,
        )

    def run(self):
        if self.engine == "v5":
            st = down_packed_init(
                self.n_replicas, self.upd.capacity, self.upd.n_init
            )
            return apply_updates5(
                st, self.ins_b, self.anchor_b, self.rank_b, self.dslot_b,
                nbits=self.nbits, epoch=self.epoch,
            )
        # v3/v1 never apply the v5 epoch padding (construction-time branch),
        # so the wire tensors are exactly the generated batches here.
        if self.engine == "v3":
            from ..ops.apply2 import init_state3

            st = init_state3(
                self.n_replicas, self.upd.capacity, self.upd.n_init
            )
            return apply_updates3(
                st, self.ins_b, self.gap_b, self.rank_b, self.dpos_b
            )
        return self._apply(
            self.fresh_state(), self.ins_b, self.anchor_b, self.rank_b,
            self.dslot_b,
        )

    def decode(self, state, replica: int = 0) -> str:
        from ..ops.apply2 import PackedState, decode_state3

        if isinstance(state, DownPacked):
            state = PackedState(
                doc=state.doc, length=state.length, nvis=state.nvis
            )
        if isinstance(state, PackedState):
            codes, nvis = jax.jit(
                decode_state3, static_argnames=("replica",)
            )(state, self.chars, replica=replica)
            import numpy as _np

            return "".join(
                map(chr, _np.asarray(codes)[: int(nvis)].tolist())
            )
        return decode_to_str(
            select_replica(state, replica, self.n_replicas), self.chars
        )


class JaxDownstreamBackend:
    """Downstream bench backend (bench/runner.py): timed region = fresh
    replica init + full update apply + final length fetch, matching the
    reference's timed closure (clone + apply loop + length assert,
    src/main.rs:62-69)."""

    def __init__(self, n_replicas: int = 1, batch: int = 256,
                 engine: str | None = None):
        self.n_replicas = n_replicas
        self.batch = batch
        self.engine = engine
        self._eng: JaxDownstreamEngine | None = None

    @property
    def NAME(self) -> str:
        plat = jax.devices()[0].platform
        tag = f"-r{self.n_replicas}" if self.n_replicas > 1 else ""
        # The positional engine's timed region excludes anchor->position
        # resolution (encode-time resolved) — labeled so it is never read
        # as like-for-like with id-integrating backends (ADVICE round 1).
        etag = "-pos" if (self._eng and self._eng.engine == "v3") else ""
        return f"jax-{plat}{tag}{etag}"

    @property
    def replicas(self) -> int:
        return self.n_replicas

    def prepare(self, trace: TestData) -> None:
        tt = tensorize(trace, batch=self.batch)
        self._eng = JaxDownstreamEngine(
            tt, n_replicas=self.n_replicas, engine=self.engine
        )
        self._end_len = len(trace.end_content)

    def replay_once(self) -> int:
        state = self._eng.run()
        lengths = np.asarray(state.nvis)  # device -> host sync point
        assert (lengths == self._end_len).all(), (
            f"length mismatch: {lengths} != {self._end_len}"
        )
        return int(lengths.reshape(-1)[0])

    def final_content(self) -> str:
        state = self._eng.run()
        jax.block_until_ready(state)
        return self._eng.decode(state)
