"""Multi-agent concurrent merge: divergent replicas -> converged document.

The reference's merge capability is exercised through diamond-types'
``decode_and_add`` (reference src/rope.rs:222-224) and automerge's
``doc.merge`` (src/rope.rs:235): integrate concurrent remote edits into a
local replica so that all replicas converge to one deterministic document.
The reference never *tests* concurrency (its downstream topology is one
writer, SURVEY.md section 4); this module makes concurrent merge a
first-class, batched, device-resident operation (BASELINE.md configs 4-5).

Design — merge as sort + batched integration
--------------------------------------------
Every element has a globally unique id ``(lamport, agent)``; every op is
``INSERT(elem, origin, ch)`` or ``DELETE(target)``.  Lamport clocks respect
causality (an op's clock exceeds every op it has seen), so sorting the union
of op logs by ``(lamport, agent)`` yields a causal total order with
deterministic tie-breaks — the reference's deterministic-merge analog of
diamond-types' agent/seq ordering.

The key classical fact (causal-tree / RGA equivalence): **integrating ops in
ascending id order, placing each insert directly after its origin, produces
the RGA document order** — a later sibling under the same origin lands closer
to the origin, which is exactly RGA's newest-first sibling rule, and
causality guarantees the origin is already present.  A sequential O(1)
insertion rule becomes a batched kernel:

1. sort + dedup (idempotence under duplicated delivery) — ``jnp.sort`` on
   packed int64 ids, O(N log N) on device;
2. per op-batch: a tiny ``lax.scan`` threads same-batch origin chains
   (successor-pointer splicing in op-index space, O(B) state);
3. pointer-doubling list ranking turns chains into (head, rank) pairs —
   O(B log B), no sequential dependence;
4. one counting merge splices all batch inserts into the order permutation
   (same O(C) vectorized pass as ops/apply.py), deletes clear visibility.

Convergence is then checked by digest equality across replicas/devices via
collectives (parallel/mesh.py).  Delivery order, duplication, and batch
boundaries cannot change the result (tests/test_merge.py fault-injection).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..lint.boundary import boundary
from ..ops.apply import init_state
from ..traces.tensorize import DELETE, INSERT, PAD, TensorizedTrace
from .downstream import DownState, init_down_state
from .replay import _round_up, decode_to_str, replay_batches_collect

# Agent-id capacity of the packed rank key (lamport * MAX_AGENTS + agent).
# Single source for the key packing in _rank_sorted_segments and the
# n_agents guards — the two previously lived in different functions and
# could drift (VERDICT r2 weak #8).
MAX_AGENTS = 64


@dataclass
class OpLog:
    """One agent's op log in exchange ("wire") format — the update-exchange
    tensors that replace the reference's ``Vec<Update>`` in-memory network
    (reference src/rope.rs:199,216,257).

    ``elem``: inserted element's global slot (INSERT) or target slot
    (DELETE).  ``origin``: global slot of the left-origin element (-1 =
    document head); -2 for deletes.  ``lamport``: per-op Lamport clock.
    """

    lamport: np.ndarray  # int32[N]
    agent: np.ndarray  # int32[N]
    kind: np.ndarray  # int32[N]  PAD / INSERT / DELETE
    elem: np.ndarray  # int32[N]
    origin: np.ndarray  # int32[N]
    ch: np.ndarray  # int32[N]

    def __len__(self) -> int:
        return len(self.lamport)

    @staticmethod
    def concat(logs: "list[OpLog]") -> "OpLog":
        return OpLog(
            *(
                np.concatenate([getattr(l, f) for l in logs])
                for f in ("lamport", "agent", "kind", "elem", "origin", "ch")
            )
        )


def agent_oplog(
    tt: TensorizedTrace, agent: int, slot_base: int, n_base: int
) -> OpLog:
    """Build agent ``agent``'s op log by replaying its local edit stream
    (UNTIMED, like the reference's update generation, src/main.rs:60).

    The agent starts from the shared base document (``tt.init_chars``, global
    slots ``0..n_base-1``, which must be identical across agents); its local
    insert slot ``k`` (local ``k >= n_base``) maps to global slot
    ``slot_base + (k - n_base)``.  Local op ``i`` gets Lamport clock
    ``n_base + 1 + i`` — it has seen the base plus its own prior ops.
    """
    if len(tt.init_chars) != n_base:
        raise ValueError("all agents must share the same base document")
    capacity = _round_up(max(tt.capacity, 1), 128)
    kind_b, pos_b, _, slot_b = tt.batched()
    from .replay import default_resolver

    state, dslot_b = replay_batches_collect(
        init_state(capacity, n_base),
        jnp.asarray(kind_b),
        jnp.asarray(pos_b),
        jnp.asarray(slot_b),
        resolver=default_resolver(),
    )
    origin_local = np.asarray(state.origin)
    dslot = np.asarray(dslot_b).reshape(-1)[: tt.n_ops]

    def to_global(local: np.ndarray) -> np.ndarray:
        return np.where(
            local < 0, local, np.where(
                local < n_base, local, slot_base + (local - n_base)
            )
        ).astype(np.int32)

    kind = tt.kind[: tt.n_ops].astype(np.int32)
    is_ins = kind == INSERT
    elem = np.where(is_ins, to_global(tt.slot[: tt.n_ops]), to_global(dslot))
    origin = np.where(
        is_ins, to_global(origin_local[np.clip(tt.slot[: tt.n_ops], 0, None)]),
        -2,
    ).astype(np.int32)
    n = tt.n_ops
    return OpLog(
        lamport=(n_base + 1 + np.arange(n, dtype=np.int32)),
        agent=np.full(n, agent, np.int32),
        kind=kind,
        elem=elem.astype(np.int32),
        origin=origin,
        ch=tt.ch[: tt.n_ops].astype(np.int32),
    )


# ---- device merge kernel ---------------------------------------------------


def _rank_sorted_segments(
    lamport, agent, kind, elem, origin, ch, segments: tuple[int, ...]
):
    """Causal-total-order arrangement for a union that is a CONCATENATION
    of per-agent logs, each already lamport-sorted (agents emit ops in
    clock order — the natural wire layout).  XLA's sort costs seconds at
    millions of ops (it dominated the traces merge at 77% of device
    time); with sorted segments, every op's global rank is its segment
    index plus count_lt of its key in each other segment — tiled
    count_le passes (ops/apply2.py count_le_tiled) instead of a sort.

    Keys are (lamport, agent) packed as lamport * K + agent (asserted to
    fit int32 by the caller); segment boundaries are static.  No
    duplicates exist across distinct agents' logs (dedup is the shuffled
    path's job), so ranks are a permutation and one scatter per array
    materializes the order.
    """
    from ..ops.apply2 import count_le_tiled

    n = lamport.shape[0]
    nseg = len(segments)
    maxa = jnp.int32(MAX_AGENTS)
    key = lamport * maxa + agent
    inf = jnp.int32(2**31 - 1)
    bounds = np.concatenate([[0], np.cumsum(np.asarray(segments))])
    assert bounds[-1] == n
    CHUNK = 1 << 16
    LANEPAD = 128

    # PAD keys: per-SEGMENT distinct sentinels just below int32 max, so
    # every rank (pads included) is globally unique — the final scatter
    # can then promise unique_indices (a duplicate-capable scatter lowers
    # to a SORT on TPU, which is the entire cost this path removes).
    seg_id = jnp.zeros(n, jnp.int32)
    for s in range(1, nseg):
        seg_id = seg_id.at[bounds[s] :].add(1)
    key = jnp.where(kind == PAD, inf - nseg + seg_id, key)

    seg_keys = []
    for s in range(nseg):
        ks = jax.lax.slice_in_dim(key, bounds[s], bounds[s + 1])
        pad = (-ks.shape[0]) % LANEPAD
        if pad:
            ks = jnp.concatenate([ks, jnp.full(pad, inf, jnp.int32)])
        seg_keys.append(ks[None, :])  # (1, C_s)

    parts = []
    for s in range(nseg):
        qs = jax.lax.slice_in_dim(key, bounds[s], bounds[s + 1])
        r = jnp.arange(qs.shape[0], dtype=jnp.int32)
        for s2 in range(nseg):
            if s2 == s:
                continue
            for c0 in range(0, qs.shape[0], CHUNK):
                cb = min(CHUNK, qs.shape[0] - c0)
                q = jax.lax.slice_in_dim(qs, c0, c0 + cb)[None, :]
                # count_lt via count_le(q - 1): all keys unique by
                # construction (lamport*64+agent for reals, per-segment
                # sentinels for pads)
                cnt = count_le_tiled(seg_keys[s2], q - 1)[0]
                r = r.at[c0 : c0 + cb].add(cnt)
        parts.append(r)
    rank = jnp.concatenate(parts)

    # TPU lowers every value scatter through a sort (~0.5s at 1.35M) and
    # large arbitrary-index gathers are slower still, so materialize the
    # arrangement with exactly TWO scatters by packing the only fields
    # integration consumes: A = (elem+2)*4 + kind (elem < 2^28 per the
    # capacity guard; (2^28+2)*4 still fits int32, 2^29 would not),
    # B = origin + 2.  lamport/agent/ch are fully consumed by the ranking
    # itself (ch travels via the slot->char table).
    a = (elem + 2) * 4 + kind
    b = origin + 2
    arrange = lambda x: (
        jnp.zeros_like(x)
        .at[rank]
        .set(x, mode="promise_in_bounds", unique_indices=True)
    )
    a2, b2 = arrange(a), arrange(b)
    kind2 = jnp.bitwise_and(a2, 3)
    elem2 = jnp.right_shift(a2, 2) - 2
    origin2 = b2 - 2
    return lamport, agent, kind2, elem2, origin2, ch


def _sort_dedup(lamport, agent, kind, elem, origin, ch):
    """Sort ops by (lamport, agent) — a causal total order with deterministic
    tie-breaks — and PAD-out exact duplicates (idempotent delivery).  PAD ops
    sort to the end.  Two stable int32 argsorts give the lexicographic order
    without int64 keys (x64 is typically disabled)."""
    inf = jnp.int32(2**31 - 1)
    is_pad = kind == PAD
    lam_k = jnp.where(is_pad, inf, lamport)
    p1 = jnp.argsort(agent, stable=True)
    p2 = jnp.argsort(lam_k[p1], stable=True)
    perm = p1[p2]
    lam_s, ag_s = lam_k[perm], agent[perm]
    dup = jnp.concatenate(
        [
            jnp.zeros(1, bool),
            (lam_s[1:] == lam_s[:-1])
            & (ag_s[1:] == ag_s[:-1])
            & (lam_s[1:] < inf),
        ]
    )
    take = lambda x: x[perm]
    kind = jnp.where(dup, PAD, take(kind))
    return take(lamport), take(agent), kind, take(elem), take(origin), take(ch)


def _integrate_batch(state: DownState, kind, elem, origin, ch_unused):
    """Integrate one id-sorted op batch (B ops) into the document.

    Steps: locate same-batch origins; scan-splice successor chains in
    op-index space; pointer-double to (head, rank); counting-merge the new
    elements after their external anchors; scatter visibility."""
    C = state.order.shape[0]
    B = kind.shape[0]
    drop = jnp.int32(C)
    idx = jnp.arange(C, dtype=jnp.int32)
    j32 = jnp.arange(B, dtype=jnp.int32)
    is_ins = kind == INSERT
    is_del = kind == DELETE

    # Which batch op (if any) inserted each element: elem -> op index.
    opof = (
        jnp.full(C, -1, jnp.int32)
        .at[jnp.where(is_ins, elem, drop)]
        .set(j32, mode="drop")
    )
    org_op = jnp.where(
        origin >= 0, opof[jnp.clip(origin, 0, C - 1)], -1
    )  # batch op that inserted my origin (-1 = external)
    internal = is_ins & (org_op >= 0) & (org_op < j32)

    # Representative head per external-origin group: smallest op index sharing
    # my external origin (others chain after it in the scan).
    ext_origin = jnp.where(is_ins & ~internal, origin, -2)
    headof = (
        jnp.full(C + 1, jnp.int32(B), jnp.int32)
        .at[jnp.clip(ext_origin, -1, C - 1) + 1]
        .min(jnp.where(ext_origin >= -1, j32, B), mode="drop")
    )
    rep = jnp.where(
        is_ins & ~internal,
        headof[jnp.clip(ext_origin, -1, C - 1) + 1],
        -1,
    )

    # Node space for chain splicing: 0..B-1 = batch inserts,
    # B..2B-1 = external-head sentinels (sentinel B+r for rep r), 2B = nil.
    NIL = 2 * B

    def splice(nxt, op):
        j, ins, intern, k, r = op
        pred = jnp.where(intern, k, B + r)  # insert directly after this node
        old = nxt[pred]
        nxt = jnp.where(
            ins, nxt.at[j].set(old).at[pred].set(j), nxt
        )
        return nxt, None

    nxt0 = jnp.full(2 * B + 1, NIL, jnp.int32)
    nxt, _ = jax.lax.scan(
        splice, nxt0, (j32, is_ins, internal, org_op, rep)
    )

    # Pointer-double predecessors to find (sentinel head, rank) per insert.
    pred0 = (
        jnp.full(2 * B + 1, NIL, jnp.int32)
        .at[jnp.where(nxt < NIL, nxt, NIL)]
        .set(jnp.arange(2 * B + 1, dtype=jnp.int32), mode="promise_in_bounds")
    )
    pred0 = pred0.at[NIL].set(NIL)
    # sentinels and nil are roots: point to themselves with distance 0
    node = jnp.arange(2 * B + 1, dtype=jnp.int32)
    is_root = node >= B
    par = jnp.where(is_root, node, pred0[node])
    dist = jnp.where(is_root | (par == node), 0, 1).astype(jnp.int32)
    n_rounds = max(1, (2 * B).bit_length())

    def double(pd, _):
        par, dist = pd
        return (par[par], dist + jnp.where(par != node, dist[par], 0)), None

    (par, dist), _ = jax.lax.scan(double, (par, dist), None, length=n_rounds)
    # per-insert: head sentinel (par in B..2B-1) and rank = dist - 1
    head_sent = par[j32]
    rank = dist[j32] - 1
    head_op = head_sent - B  # the rep op whose external origin anchors chain

    # External anchor element and its physical position.
    valid = idx < state.length
    phys = (
        jnp.zeros(C, jnp.int32)
        .at[jnp.where(valid, state.order, drop)]
        .set(idx, mode="drop")
    )
    anchor_elem = origin[jnp.clip(head_op, 0, B - 1)]  # -1 = document head
    a_phys = jnp.where(
        anchor_elem >= 0, phys[jnp.clip(anchor_elem, 0, C - 1)], -1
    )
    gap = jnp.where(is_ins, a_phys + 1, C + 1)

    bump = jnp.zeros(C + 1, jnp.int32).at[gap].add(1, mode="drop")
    csum = jnp.cumsum(bump)
    new_idx_old = idx + csum[idx]
    n_before = jnp.where(gap > 0, csum[jnp.clip(gap - 1, 0)], 0)
    new_idx_ins = gap + n_before + rank

    order = (
        jnp.full(C, -1, jnp.int32)
        .at[jnp.where(valid, new_idx_old, drop)]
        .set(jnp.where(valid, state.order, -1), mode="drop")
        .at[jnp.where(is_ins, new_idx_ins, drop)]
        .set(elem, mode="drop")
    )
    visible = (
        state.visible.at[jnp.where(is_ins, elem, drop)]
        .set(True, mode="drop")
        .at[jnp.where(is_del, elem, drop)]
        .set(False, mode="drop")
    )
    length = state.length + jnp.sum(is_ins.astype(jnp.int32))
    valid2 = idx < length
    nvis = jnp.sum(
        valid2 & visible[jnp.where(valid2, order, 0)], dtype=jnp.int32
    )
    return DownState(order=order, visible=visible, length=length, nvis=nvis)


@partial(jax.jit, static_argnames=("batch",))
def merge_oplogs(
    state: DownState,
    lamport: jax.Array,
    agent: jax.Array,
    kind: jax.Array,
    elem: jax.Array,
    origin: jax.Array,
    ch: jax.Array,
    *,
    batch: int = 256,
) -> DownState:
    """Merge a union of op logs (any delivery order, duplicates allowed) into
    ``state``.  N must be a multiple of ``batch`` (PAD-pad beforehand)."""
    lamport, agent, kind, elem, origin, ch = _sort_dedup(
        lamport, agent, kind, elem, origin, ch
    )
    nb = kind.shape[0] // batch
    rs = lambda x: x.reshape(nb, batch)

    def step(st, ops):
        return _integrate_batch(st, *ops), None

    state, _ = jax.lax.scan(
        step, state, (rs(kind), rs(elem), rs(origin), rs(ch))
    )
    return state


# ---- packed fast path (TPU) ------------------------------------------------


def _chain_structure(kind, elem, origin):
    """Per-batch RGA chain structure, computed in parallel (no sequential
    splice scan — each XLA loop iteration costs ~ms on this runtime).

    The batch's inserts form a forest: an insert whose origin was inserted
    in this same batch points at that op (its parent); the rest are roots
    grouped by external origin.  Integrating in ascending id order places
    each insert directly after its origin, so children of a node end up in
    DESCENDING op order — the final in-batch sequence under one external
    anchor is the DFS of that group's trees, roots in descending order.
    Both outputs of the old splice+pointer-double pipeline are therefore
    order statistics of this forest:

      rank(x) = depth(x) + sum over ancestors-or-self x' of
                (total subtree size of x''s larger-index siblings)

    with sibling = same parent, or same external origin among roots.  The
    ancestor closure is log2(B) boolean B x B matrix squarings (exact in
    bf16 matmuls — sums of <= B ones), everything else is B x B compares:
    all VPU/MXU work shared across replicas.

    Returns (ins, anchor, rank, dslot), each int32[B] in the downstream
    anchor/rank wire form (engine/downstream.py _apply_update_batch5).
    """
    B = kind.shape[0]
    j = jnp.arange(B, dtype=jnp.int32)
    is_ins = kind == INSERT
    is_del = kind == DELETE
    ins = jnp.where(is_ins, elem, -1)
    dslot = jnp.where(is_del, elem, -1)

    # parent op: the same-batch op that inserted my origin (-1 = external).
    eq = (
        (origin[:, None] == ins[None, :])
        & is_ins[:, None]
        & (ins[None, :] >= 0)
    )
    org_op = jnp.sum(jnp.where(eq, j[None, :] + 1, 0), axis=1) - 1
    parent = jnp.where(is_ins & (org_op >= 0), org_op, -1)

    # ancestor closure (proper ancestors): A <- A | A@A, log2 B rounds.
    A = (parent[:, None] == j[None, :]) & (parent[:, None] >= 0)
    for _ in range(max(1, (B - 1).bit_length())):
        prod = (
            jnp.einsum(
                "xm,ma->xa",
                A.astype(jnp.bfloat16),
                A.astype(jnp.bfloat16),
                preferred_element_type=jnp.float32,
            )
            > 0
        )
        A = A | prod
    depth = jnp.sum(A.astype(jnp.int32), axis=1)
    size = 1 + jnp.sum(A.astype(jnp.int32), axis=0)  # subtree size

    # siblings: same internal parent, or both roots sharing an external
    # origin (they splice after one sentinel, descending root order).
    both_ins = is_ins[:, None] & is_ins[None, :]
    same_par = parent[:, None] == parent[None, :]
    root_pair = (
        (parent[:, None] < 0)
        & (parent[None, :] < 0)
        & (origin[:, None] == origin[None, :])
    )
    sib = (
        both_ins
        & jnp.where(parent[:, None] >= 0, same_par, root_pair)
        & (j[:, None] != j[None, :])
    )
    larger = sib & (j[None, :] > j[:, None])
    W = jnp.sum(jnp.where(larger, size[None, :], 0), axis=1)

    AoS = A | (j[:, None] == j[None, :])
    rank = depth + jnp.sum(jnp.where(AoS, W[None, :], 0), axis=1)

    # external anchor element: my root's own origin (-1 = document head).
    is_root = is_ins & (parent < 0)
    root = (
        jnp.sum(
            jnp.where(AoS & is_root[None, :], j[None, :] + 1, 0), axis=1
        )
        - 1
    )
    anchor = jnp.where(
        is_ins, origin[jnp.clip(root, 0, B - 1)], -1
    )
    return ins, anchor, jnp.where(is_ins, rank, 0), dslot


@boundary(
    dtypes=(None, "int32", "int32", "int32", "int32", "int32",
            "int32"),
    shapes=(None, "N", "N", "N", "N", "N", "N"),
    donates=(0,),
)
@partial(
    jax.jit,
    static_argnames=("batch", "epoch", "nbits", "max_unique", "segments"),
    donate_argnums=(0,),
)
def merge_oplogs_packed(
    state,
    lamport: jax.Array,
    agent: jax.Array,
    kind: jax.Array,
    elem: jax.Array,
    origin: jax.Array,
    ch: jax.Array,
    *,
    batch: int = 512,
    epoch: int = 32,
    nbits: int | None = None,
    max_unique: int | None = None,
    segments: tuple[int, ...] | None = None,
):
    """merge_oplogs on the packed doc-order state (engine/downstream.py
    DownPacked) — sort + dedup, then batched chain-structure + id-resolved
    integration through the same fused-kernel core as the downstream v5
    apply.  N must be a multiple of ``batch * epoch`` (PAD-pad).

    The whole merge is timed work: causal-order sort, duplicate
    suppression, origin-chain resolution, id->position resolution
    (ops/idpos.py), counting merge and expansion all run on device inside
    this call — the capability of the reference's ``decode_and_add`` loop
    (reference src/rope.rs:222-224) for arbitrarily divergent op logs.

    ``max_unique`` (static) bounds the DISTINCT op count: under
    duplicated/reordered delivery the full N-op stream is sorted and
    deduplicated, but integration only walks the unique prefix (sorted
    PADs sink to the end) — the receiver-side analog of an op-log
    capacity, so a 10x-duplicated delivery doesn't pay 10x integration.

    ``segments`` (static): lengths of concatenated per-agent logs, each
    already lamport-sorted (no cross-agent duplicates) — arranges the
    causal order with count_le rank passes instead of the XLA sort
    (~100x cheaper at millions of ops; see _rank_sorted_segments).
    """
    from ..ops.idpos import snap_rebuild
    from .downstream import DownPacked, _apply_update_batch5

    if segments is not None:
        lamport, agent, kind, elem, origin, ch = _rank_sorted_segments(
            lamport, agent, kind, elem, origin, ch, segments
        )
    else:
        lamport, agent, kind, elem, origin, ch = _sort_dedup(
            lamport, agent, kind, elem, origin, ch
        )
    B = batch
    if max_unique is not None and max_unique < kind.shape[0]:
        keep = -(-max_unique // (B * epoch)) * (B * epoch)
        if keep < kind.shape[0]:
            # Deduplication PADs duplicates IN PLACE (they sit next to
            # their survivor in id order); compact survivors to the front
            # (stable, order-preserving) before slicing the unique prefix.
            perm = jnp.argsort((kind == PAD).astype(jnp.int8), stable=True)
            sl = lambda x: jax.lax.slice_in_dim(x[perm], 0, keep, axis=0)
            kind, elem, origin = sl(kind), sl(elem), sl(origin)
    nb = kind.shape[0] // B
    if nbits is None:
        nbits = max(1, B.bit_length())
    K = min(epoch, nb)
    if nb % K:
        raise ValueError(f"batch count {nb} not a multiple of epoch {K}")
    rs = lambda x: x.reshape(nb // K, K, B)

    def step(st, ops):
        kind_k, elem_k, origin_k = ops
        doc, snap, length, nvis = st
        levels: list = []
        for k in range(K):
            ins, anchor, rank, dslot = _chain_structure(
                kind_k[k], elem_k[k], origin_k[k]
            )
            doc, length, nvis, lv = _apply_update_batch5(
                doc, length, nvis, snap, levels, ins, anchor, rank, dslot,
                nbits=nbits,
            )
            levels.append(lv)
        return DownPacked(doc, snap_rebuild(doc), length, nvis), None

    state, _ = jax.lax.scan(
        step, state, (rs(kind), rs(elem), rs(origin))
    )
    return state


# ---- host-side driver ------------------------------------------------------


class MergeSimulation:
    """Simulate A agents editing concurrently from a shared base, then every
    replica merging the union of op logs (BASELINE.md configs 4-5).

    ``streams``: one TensorizedTrace per agent (its local edit stream).  All
    must share the same base document.
    """

    def __init__(self, streams: list[TensorizedTrace], base: str = "",
                 batch: int = 256):
        self.batch = batch
        self.n_agents = len(streams)
        if self.n_agents >= MAX_AGENTS - 1:
            raise ValueError(
                f"{self.n_agents} agents exceeds the packed rank key's"
                f" MAX_AGENTS={MAX_AGENTS} (agent ids 1..A must stay below"
                " the key's agent field)"
            )
        n_base = len(base)
        if any(len(tt.init_chars) != n_base for tt in streams):
            raise ValueError("all agent streams must share the base document")
        slot_base = n_base
        logs, self.chars_parts = [], []
        for a, tt in enumerate(streams):
            logs.append(agent_oplog(tt, agent=a + 1, slot_base=slot_base,
                                    n_base=n_base))
            ins = tt.slot >= n_base
            self.chars_parts.append(tt.ch[ins])
            slot_base += tt.n_inserts
        self.capacity = _round_up(max(slot_base, 1), 128)
        self.n_base = n_base
        chars = np.zeros(self.capacity, np.int32)
        chars[:n_base] = np.asarray([ord(c) for c in base], np.int32)
        off = n_base
        for part in self.chars_parts:
            chars[off : off + len(part)] = part
            off += len(part)
        self.chars = jnp.asarray(chars)
        self.agent_logs = logs  # per-agent, for distributed exchange
        self.log = OpLog.concat(logs)

    def stacked_logs(self) -> dict[str, np.ndarray]:
        """Per-agent logs padded to a common batch-multiple length and
        stacked to int32[A, N] — the sharded update-exchange layout
        (parallel/mesh.py sharded_merge_and_converge)."""
        n = _round_up(max(len(l) for l in self.agent_logs), self.batch)
        fills = dict(lamport=0, agent=0, kind=PAD, elem=-1, origin=-2, ch=0)
        out = {}
        for f, fill in fills.items():
            out[f] = np.stack(
                [
                    np.concatenate(
                        [
                            getattr(l, f),
                            np.full(n - len(l), fill, np.int32),
                        ]
                    )
                    for l in self.agent_logs
                ]
            )
        return out

    def _padded(self, log: OpLog, multiple: int | None = None) -> OpLog:
        n = len(log)
        m = multiple or self.batch
        n_pad = (-n) % m if n else m
        if not n_pad:
            return log
        z = lambda fill: np.full(n_pad, fill, np.int32)
        return OpLog(
            lamport=np.concatenate([log.lamport, z(0)]),
            agent=np.concatenate([log.agent, z(0)]),
            kind=np.concatenate([log.kind, z(PAD)]),
            elem=np.concatenate([log.elem, z(-1)]),
            origin=np.concatenate([log.origin, z(-2)]),
            ch=np.concatenate([log.ch, z(0)]),
        )

    def merge(self, log: OpLog | None = None) -> DownState:
        """One replica integrates the (padded) union of op logs."""
        log = self._padded(log if log is not None else self.log)
        state = init_down_state(self.capacity, self.n_base)
        return merge_oplogs(
            state,
            jnp.asarray(log.lamport),
            jnp.asarray(log.agent),
            jnp.asarray(log.kind),
            jnp.asarray(log.elem),
            jnp.asarray(log.origin),
            jnp.asarray(log.ch),
            batch=self.batch,
        )

    def merge_packed(self, log: OpLog | None = None, n_replicas: int = 1,
                     epoch: int = 32, max_unique: int | None = None):
        """Replica-batched merge on the packed fast path
        (merge_oplogs_packed); returns a DownPacked state.  For delivered
        streams with duplicates, pass ``max_unique`` (the distinct-op
        bound — ``len(self.log)``) so integration walks only the deduped
        prefix.  When ``log`` is None (the plain per-agent union), the
        sorted-segments rank path replaces the device sort."""
        from .downstream import down_packed_init

        # spread_fill_combo grows a fourth fill chunk beyond 2^21 slots
        # and caps out where combo = (fill << 1) | ind leaves int32 —
        # capacity < 2^28 (fail loudly — high slot bits would silently
        # drop, identically on every replica, so even the convergence
        # check would pass on corrupt content).
        if self.capacity >= 1 << 28:
            raise ValueError(
                f"capacity {self.capacity} >= 2^28 exceeds the packed fill"
                " range (int32 combo)"
            )
        src = log if log is not None else self.log
        # never pad beyond the real batch count (a 32-wide unrolled scan
        # step over a 2-batch log only bloats compile time).  Clamp BEFORE
        # computing segments: the pad segment must match _padded's target
        # multiple or _rank_sorted_segments' bounds[-1] == n assert fires.
        epoch = min(epoch, max(1, -(-max(len(src), 1) // self.batch)))

        segments = None
        if log is None:
            n = sum(len(l) for l in self.agent_logs)
            n_pad = (-n) % (self.batch * epoch) if n else self.batch * epoch
            segments = tuple(
                len(l) for l in self.agent_logs if len(l)
            ) + ((n_pad,) if n_pad else ())
            max_lamport = max(
                (int(l.lamport.max(initial=0)) for l in self.agent_logs),
                default=0,
            )
            # real packed keys (lamport * MAX_AGENTS + agent) must stay
            # strictly below the per-segment pad sentinels at
            # [2^31-1 - nseg, 2^31-2] (_rank_sorted_segments), or a real
            # op's rank collides with a pad's and the arrangement scatter
            # corrupts both.
            assert (
                max_lamport * MAX_AGENTS + MAX_AGENTS - 1
                < (1 << 31) - 1 - len(segments)
            ), "lamport too large for the packed rank key"
            assert self.n_agents < MAX_AGENTS - 1

        log = self._padded(src, multiple=self.batch * epoch)
        state = down_packed_init(n_replicas, self.capacity, self.n_base)
        return merge_oplogs_packed(
            state,
            jnp.asarray(log.lamport),
            jnp.asarray(log.agent),
            jnp.asarray(log.kind),
            jnp.asarray(log.elem),
            jnp.asarray(log.origin),
            jnp.asarray(log.ch),
            batch=self.batch,
            epoch=epoch,
            max_unique=max_unique,
            segments=segments,
        )

    def decode(self, state) -> str:
        from ..ops.apply2 import PackedState, decode_state3
        from .downstream import DownPacked

        if isinstance(state, DownPacked):
            codes, nvis = jax.jit(
                decode_state3, static_argnames=("replica",)
            )(
                PackedState(
                    doc=state.doc, length=state.length, nvis=state.nvis
                ),
                self.chars,
            )
            return "".join(
                map(chr, np.asarray(codes)[: int(nvis)].tolist())
            )
        return decode_to_str(state, self.chars)


# ---- native cross-validation ----------------------------------------------


def to_native_ops(sim: "MergeSimulation", log: OpLog | None = None,
                  base_agent: int = 1_000_000):
    """Translate a (union) op log into the native treap's struct-of-array
    form (backends/native.py NativeMerge): ids become (agent, seq=lamport);
    base slot k maps to (base_agent, k+1) per crdt_new's base assignment;
    origin -1 (document head) maps to the native HEAD (0, 0); DELETE rows
    carry the TARGET's id.  Ops are (lamport, agent)-sorted host-side.
    Returns (type, id_agent, id_seq, org_agent, org_seq, ch) arrays."""
    log = log if log is not None else sim.log
    # slot -> (agent, seq) table
    agent_of = np.zeros(sim.capacity, np.uint32)
    seq_of = np.zeros(sim.capacity, np.uint32)
    nb = sim.n_base
    agent_of[:nb] = base_agent
    seq_of[:nb] = np.arange(1, nb + 1, dtype=np.uint32)
    for l in sim.agent_logs:
        ins = l.kind == INSERT
        agent_of[l.elem[ins]] = l.agent[ins].astype(np.uint32)
        seq_of[l.elem[ins]] = l.lamport[ins].astype(np.uint32)

    live = log.kind != PAD
    order = np.lexsort((log.agent[live], log.lamport[live]))
    k = log.kind[live][order]
    elem = log.elem[live][order]
    origin = log.origin[live][order]
    is_ins = k == INSERT
    type_ = np.where(is_ins, 1, 2).astype(np.uint8)
    id_agent = np.where(
        is_ins, log.agent[live][order].astype(np.uint32),
        agent_of[np.clip(elem, 0, None)],
    ).astype(np.uint32)
    id_seq = np.where(
        is_ins, log.lamport[live][order].astype(np.uint32),
        seq_of[np.clip(elem, 0, None)],
    ).astype(np.uint32)
    head = origin < 0
    org_agent = np.where(
        head, 0, agent_of[np.clip(origin, 0, None)]
    ).astype(np.uint32)
    org_seq = np.where(
        head, 0, seq_of[np.clip(origin, 0, None)]
    ).astype(np.uint32)
    return type_, id_agent, id_seq, org_agent, org_seq, (
        log.ch[live][order].astype(np.int32)
    )


def native_merge_content(sim: "MergeSimulation",
                         log: OpLog | None = None) -> str:
    """Merged document per the independent native RGA treap."""
    from ..backends.native import NativeMerge

    nm = NativeMerge(
        "".join(chr(int(c)) for c in np.asarray(sim.chars)[: sim.n_base])
    )
    nm.integrate(*to_native_ops(sim, log))
    return nm.content()


# ---- pure-Python merge oracle ---------------------------------------------


def merge_oracle(log: OpLog, base: str, chars: np.ndarray) -> str:
    """Sequential reference: sort ops by (lamport, agent), dedup, insert each
    element directly after its origin in a Python list, tombstone deletes.
    Ground truth for the batched kernel (SURVEY.md section 4 rebuild
    implication: differential tests against a trivial oracle)."""
    order = np.argsort(
        log.lamport.astype(np.int64) * (int(log.agent.max(initial=0)) + 2)
        + log.agent,
        kind="stable",
    )
    seen: set[tuple[int, int]] = set()
    doc: list[int] = list(range(len(base)))  # global slots
    visible = {s: True for s in doc}
    for i in order:
        k = int(log.kind[i])
        if k == PAD:
            continue
        key = (int(log.lamport[i]), int(log.agent[i]))
        if key in seen:
            continue
        seen.add(key)
        if k == INSERT:
            org = int(log.origin[i])
            at = doc.index(org) + 1 if org >= 0 else 0
            doc.insert(at, int(log.elem[i]))
            visible[int(log.elem[i])] = True
        else:
            visible[int(log.elem[i])] = False
    return "".join(chr(int(chars[s])) for s in doc if visible[s])
