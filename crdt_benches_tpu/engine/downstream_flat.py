"""One-shot RGA flatten: integrate an entire run-granular wire stream in
a single fused device pass — no sequential batch loop at all.

The batched run merge (engine/merge_range.py merge_runlogs) integrates
the causally-sorted union batch by batch: ~356 sequential kernel launches
for automerge-paper's per-patch wire, each streaming (R, C) arrays, which
capped the reference-granularity downstream cell at ~2M el/s aggregate
(round-4 verdict weak #2).  This module removes the sequential loop
entirely by computing the FINAL document order directly from the wire:

Under ascending-head-key integration with the no-skip precondition
(check_no_skip, engine/merge_range.py module docstring), every run is
placed DIRECTLY after its anchor element.  The end state of that
sequential process is a linked structure whose successor pointers are
fully determined by per-anchor relationships:

- ``next[a]`` = head of the HIGHEST-keyed run anchored at element ``a``
  (it was integrated last, so it sits closest to ``a``), else ``a``'s
  natural within-run successor;
- a run's tail chains to the next-LOWER-keyed sibling at the same
  anchor; the lowest-keyed sibling falls through to the anchor's natural
  successor ("exit" continuation).

Those pointers are computable with ONE segmented sort (runs by (anchor
asc, key desc)) plus vectorized scatters, and the final position of
every element is then a weighted LIST RANK over the pointer graph —
pointer doubling, ceil(log2(M)) rounds of gathers.  Total work is
O(N log N) with zero sequential dependency between updates, the classic
parallel-list-contraction restatement of "apply N updates one after
another" (the reference applies the same updates sequentially,
src/main.rs:65-67, then materializes once via len()'s checkout,
src/rope.rs:135).

The wire shape is untouched: one update per patch (or per run / unit
op), exactly the reference's generation granularity (src/rope.rs:196-220)
— only the APPLY SCHEDULE changes, and every anchor resolution happens
inside the timed region.

Everything here is plain XLA (sorts, scatters, gathers) — no Pallas —
so the same code runs on CPU tests and TPU benches, and capacity is NOT
bound by the 2^20 ddelta-chunk ceiling of the batched path (positions
come from ranks, not painted deltas); the int32 node-id space holds to
C + N + 2 < 2^31.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .downstream import DownPacked


def _rightmost_fill(marks: jax.Array) -> jax.Array:
    """Per-position latest nonnegative value at or before each index
    (segment fill): associative 'rightmost valid' scan."""
    def comb(a, b):
        return jnp.where(b >= 0, b, a)

    return jax.lax.associative_scan(comb, marks)


@partial(
    jax.jit,
    static_argnames=("n_base", "capacity", "n_elems", "n_replicas"),
)
def flatten_runs(
    key, slot0, rlen, origin,
    *, n_base: int, capacity: int, n_elems: int | None = None,
    n_replicas: int = 1,
) -> DownPacked:
    """Integrate the whole insert-run wire in one pass.

    Inputs (int32[N], host-pre-padded; pad rows have ``rlen == 0``):
    - key: head key ``lamport * MAX_AGENTS + agent`` (>= 0 real, BIGKEY pad)
    - slot0: first slot id of the run (runs cover slot-contiguous ranges
      that PARTITION [n_base, capacity) exactly once)
    - rlen: run length in elements (0 = pad)
    - origin: anchor ELEMENT slot of the head (-1 = document head)

    ``n_elems`` = n_base + total insert chars, the number of REAL
    element slots; ``capacity`` may be padded beyond it (lane rounding)
    and the orphan tail [n_elems, capacity) is fenced out of the pointer
    graph entirely.  Returns a :class:`DownPacked` with every real
    element placed (length = n_elems, all visible); fold delete
    intervals afterwards with
    :func:`engine.merge_range.delete_fold`.  Correctness requires the
    no-skip precondition (engine/merge_range.py check_no_skip) — the same
    gate the batched run merge runs behind.
    """
    C = capacity
    if n_elems is None:
        n_elems = C
    NE = n_elems
    N = key.shape[0]
    NR = N + 1  # plus the base pseudo-run at index 0
    root = C + NR
    term = root + 1
    M = term + 1

    def link_and_rank(key, slot0, rlen, origin):
        # ---- base pseudo-run: key -1 sorts below every real key, so the
        # start content ends up LAST among document-head children (it was
        # integrated first — later head-anchored runs land closer to the
        # head), the standard RGA behavior the batched paths share.
        keyb = jnp.concatenate([jnp.full((1,), -1, jnp.int32), key])
        s0b = jnp.concatenate([jnp.zeros((1,), jnp.int32), slot0])
        rlb = jnp.concatenate(
            [jnp.full((1,), n_base, jnp.int32), rlen]
        )
        orb = jnp.concatenate([jnp.full((1,), -1, jnp.int32), origin])
        valid = rlb > 0

        # ---- slot -> (run, offset, tail?) via segment fill over starts
        ridx = jnp.arange(NR, dtype=jnp.int32)
        marks = (
            jnp.full((C,), -1, jnp.int32)
            .at[jnp.where(valid, s0b, C)]
            .set(ridx, mode="drop")
        )
        run_of = _rightmost_fill(marks)
        elem = jnp.arange(C, dtype=jnp.int32)
        off = elem - s0b[run_of]
        is_tail = off == rlb[run_of] - 1

        # ---- order runs by (anchor asc, key desc): stable desc-key
        # argsort, then stable anchor argsort of that arrangement
        # (negate rather than subtract from INT32_MAX: the base pseudo-key
        # -1 would overflow the subtraction)
        p1 = jnp.argsort(jnp.negative(keyb), stable=True)
        anch = jnp.where(valid, orb + 1, jnp.int32(2**31 - 1))[p1]
        p2 = jnp.argsort(anch, stable=True)
        perm = p1[p2]
        o_s = jnp.where(valid, orb, -2)[perm]  # -1 = root, -2 = pad
        head_s = s0b[perm]
        valid_s = valid[perm]
        exit_s = C + perm

        # ---- first child per anchor node (segment firsts)
        seg_first = jnp.concatenate(
            [jnp.ones((1,), bool), o_s[1:] != o_s[:-1]]
        )
        anchor_node = jnp.where(o_s >= 0, o_s, root)
        fc_idx = jnp.where(seg_first & valid_s, anchor_node, M)
        first_child = (
            jnp.full((M,), -1, jnp.int32)
            .at[fc_idx]
            .set(head_s, mode="drop")
        )

        # ---- natural (child-free) successor of each element
        base_next_elem = jnp.where(is_tail, C + run_of, elem + 1)

        # ---- exit pointers: next-lower-keyed sibling, else the anchor's
        # natural successor (root anchor falls through to terminal)
        nxt_head = jnp.concatenate(
            [head_s[1:], jnp.full((1,), -1, jnp.int32)]
        )
        same_seg = jnp.concatenate(
            [o_s[1:] == o_s[:-1], jnp.zeros((1,), bool)]
        ) & jnp.concatenate([valid_s[1:], jnp.zeros((1,), bool)])
        anchor_cont = jnp.where(
            o_s >= 0,
            base_next_elem[jnp.clip(o_s, 0, C - 1)],
            jnp.int32(term),
        )
        exit_ptr = jnp.where(same_seg, nxt_head, anchor_cont)

        # ---- assemble next pointers over [elements | exits | root | term]
        # orphan padding slots [NE, C) must not point into (or be
        # pointed at by) the real graph: fence them to the terminal
        elem_next = jnp.where(
            first_child[:C] >= 0, first_child[:C], base_next_elem
        )
        elem_next = jnp.where(elem < NE, elem_next, term)
        nxt = jnp.concatenate(
            [
                elem_next,
                jnp.full((NR,), term, jnp.int32),
                jnp.full((2,), term, jnp.int32),
            ]
        )
        nxt = nxt.at[jnp.where(valid_s, exit_s, M)].set(
            exit_ptr, mode="drop"
        )
        rc = first_child[root]
        nxt = nxt.at[root].set(jnp.where(rc >= 0, rc, term))

        # ---- predecessor pointers (each reachable node has exactly one;
        # term collects the garbage writes)
        nodes = jnp.arange(M, dtype=jnp.int32)
        prev = (
            jnp.full((M,), root, jnp.int32)
            .at[jnp.where(nodes != term, nxt, M)]
            .set(nodes, mode="drop")
        )
        prev = prev.at[root].set(root)

        # ---- weighted list rank by pointer doubling: rank(v) = number
        # of ELEMENT nodes on root->v inclusive (root weight 0 self-loop)
        w = jnp.concatenate(
            [
                (elem < NE).astype(jnp.int32),
                jnp.zeros((NR + 2,), jnp.int32),
            ]
        )
        rounds = max(1, (M - 1).bit_length())

        def body(_, carry):
            acc, p = carry
            return acc + acc[p], p[p]

        acc, _ = jax.lax.fori_loop(0, rounds, body, (w, prev))
        return acc[:C] - 1  # 0-indexed document position of each element

    # The wire -> position resolution is a pure function of the shared
    # wire, computed ONCE across replicas — the same sharing the batched
    # schedule uses (merge_runlogs's device argsort and the W x W
    # fragment forests are replica-shared; only the state apply is
    # per-replica).  Each replica then materializes ITS document from
    # the resolved positions ((R, C) scatter; the delete fold after is
    # (R, C) too).
    pos = link_and_rank(key, slot0, rlen, origin)
    elem = jnp.arange(C, dtype=jnp.int32)
    fill = jnp.left_shift(elem + 2, 1) | 1
    idx = jnp.where(elem < NE, pos, C)

    def materialize(_):
        return (
            jnp.full((C,), 2, jnp.int32).at[idx].set(fill, mode="drop")
        )

    R = n_replicas
    doc = jax.vmap(materialize)(jnp.arange(R, dtype=jnp.int32))
    return DownPacked(
        doc=doc,
        snap=jnp.broadcast_to(pos, (R, C)),
        length=jnp.full((R,), NE, jnp.int32),
        nvis=jnp.full((R,), NE, jnp.int32),
    )


@partial(
    jax.jit,
    static_argnames=(
        "n_base", "capacity", "n_elems", "max_unique", "n_replicas",
    ),
)
def flatten_unit_log(
    lamport, agent, kind, elem, origin,
    *, n_base: int, capacity: int, n_elems: int, max_unique: int,
    n_replicas: int = 1,
) -> DownPacked:
    """One-shot merge of a DELIVERED unit-op log: dedup + integrate the
    whole stream in one fused pass (the merge-cell analog of
    :func:`flatten_runs`).

    The input is the wire-delivered stream exactly as the fault model
    hands it over — arbitrarily shuffled, every op possibly delivered
    many times (bench/runner.py _delivered_log).  At unit granularity
    every run has length 1, so the run-atomicity precondition of the
    run-granular path is VACUOUS (a single-element run's head is its own
    last element): this path is exact for ANY log, including the
    adversarial duplicated-delivery config the batched run merge must
    refuse.

    Device work, all timed: one descending-key sort of the delivered
    stream (duplicates become adjacent — element keys (lamport, agent)
    are unique per element), first-occurrence compaction into a dense
    ``max_unique``-wide prefix, then the :func:`flatten_runs` pointer
    graph + list rank + per-replica materialization.  Deletes are NOT
    deduped: the delete fold's interval paint is idempotent by
    construction (duplicated starts and stops stay balanced).  Callers
    fold deletes afterwards with ``delete_fold(st, dlo(), dhi())`` where
    dlo = where(kind==DELETE, elem, -1), dhi likewise with -2.

    ``max_unique`` must be >= the number of unique INSERT ops (host
    metadata, same contract as merge_oplogs_packed's max_unique);
    ``n_elems`` = n_base + that count.
    """
    from ..traces.tensorize import INSERT
    from .merge import MAX_AGENTS

    key_raw = jnp.where(
        kind == INSERT,
        lamport * jnp.int32(MAX_AGENTS) + agent,
        jnp.int32(2**31 - 1),
    )
    p1 = jnp.argsort(jnp.negative(key_raw), stable=True)
    key_s = key_raw[p1]
    valid_s = key_s != jnp.int32(2**31 - 1)
    dup = jnp.concatenate(
        [jnp.zeros((1,), bool), key_s[1:] == key_s[:-1]]
    )
    keep = valid_s & ~dup
    urank = jnp.cumsum(keep.astype(jnp.int32)) - 1
    MU = max_unique
    idx = jnp.where(keep & (urank < MU), urank, MU)
    ukey = (
        jnp.full((MU,), 2**31 - 1, jnp.int32)
        .at[idx].set(key_s, mode="drop")
    )
    # (key overflow cannot be checked on traced values — make_flat_merge
    # guards lamport * MAX_AGENTS + MAX_AGENTS < 2^31 - 1 host-side)
    uslot = (
        jnp.full((MU,), -1, jnp.int32)
        .at[idx].set(elem[p1], mode="drop")
    )
    uorig = (
        jnp.full((MU,), -2, jnp.int32)
        .at[idx].set(origin[p1], mode="drop")
    )
    urlen = (
        jnp.zeros((MU,), jnp.int32)
        .at[idx].set(jnp.ones_like(idx), mode="drop")
    )
    return flatten_runs(
        ukey, uslot, urlen, uorig,
        n_base=n_base, capacity=capacity, n_elems=n_elems,
        n_replicas=n_replicas,
    )


def make_flat_merge(sim, delivered, n_replicas: int = 1):
    """ONE construction of the flat merge cell, shared by the timed
    bench (bench/runner.py run_merge), its --verify twin, and the tests —
    a drift between those would let --verify check a different
    computation than the one benchmarked (code-review r5).

    Untimed host work here: device upload of the delivered log, delete-
    interval derivation (wire translation, same contract as the other
    merge cells) and the packed-key range guard.  Returns a zero-arg
    callable whose invocation is the timed region: device dedup +
    one-shot integration + delete fold.
    """
    import numpy as np

    from ..traces.tensorize import DELETE, INSERT
    from .merge import MAX_AGENTS
    from .merge_range import delete_fold

    max_lam = int(delivered.lamport.max(initial=0))
    if max_lam * MAX_AGENTS + MAX_AGENTS >= 2**31 - 1:
        # a wrapped (or sentinel-colliding) key would drop/mis-order
        # inserts IDENTICALLY on every replica — invisible to the
        # convergence digest, so fail loudly host-side (the unit cell
        # asserts the same bound, bench/runner.py)
        raise ValueError(
            f"lamport {max_lam} too large for the packed int32 run key"
            f" (needs lamport * {MAX_AGENTS} + {MAX_AGENTS} < 2^31 - 1)"
        )
    n_uni = int(np.asarray(sim.log.kind == INSERT).sum())
    dev = tuple(
        jnp.asarray(getattr(delivered, f))
        for f in ("lamport", "agent", "kind", "elem", "origin")
    )
    dlo = jnp.asarray(
        np.where(delivered.kind == DELETE, delivered.elem, -1)
    )
    dhi = jnp.asarray(
        np.where(delivered.kind == DELETE, delivered.elem, -2)
    )
    n_base, capacity = sim.n_base, sim.capacity

    def run() -> DownPacked:
        st = flatten_unit_log(
            *dev,
            n_base=n_base, capacity=capacity,
            n_elems=n_base + n_uni, max_unique=n_uni,
            n_replicas=n_replicas,
        )
        return delete_fold(st, dlo, dhi)

    return run
