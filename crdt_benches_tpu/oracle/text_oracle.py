"""Pure-Python ground-truth document replay (the framework's oracle).

The reference's only correctness check is a length-only assert inside the
timed loop (src/main.rs:35,68).  This oracle upgrades that to **byte-identical
final document content**: every other backend (JAX engine, C++ rope, C++ CRDT)
is differentially tested against it (SURVEY.md section 4, rebuild implication).

``OracleDocument`` also implements the Upstream-trait surface of the reference
(``from_str`` / ``insert`` / ``remove`` / ``len`` / ``replace``,
src/rope.rs:6-33) so it can serve as the pure-Python backend in the bench
matrix.
"""

from __future__ import annotations

import numpy as np

from ..traces.loader import TestData
from ..traces.tensorize import DELETE, INSERT


class OracleDocument:
    """A trivial char-list document.  Char (codepoint) offsets."""

    NAME = "python-oracle"
    EDITS_USE_BYTE_OFFSETS = False

    def __init__(self, content: str = ""):
        self._chars: list[str] = list(content)

    @classmethod
    def from_str(cls, s: str) -> "OracleDocument":
        return cls(s)

    def insert(self, at: int, text: str) -> None:
        self._chars[at:at] = list(text)

    def remove(self, start: int, end: int) -> None:
        del self._chars[start:end]

    def replace(self, start: int, end: int, text: str) -> None:
        # remove-then-insert, as the reference's default impl (src/rope.rs:21-32)
        self._chars[start:end] = list(text)

    def __len__(self) -> int:
        return len(self._chars)

    def content(self) -> str:
        return "".join(self._chars)


def replay_trace(trace: TestData) -> str:
    """Replay all patches; return final content (ground truth)."""
    doc = OracleDocument.from_str(trace.start_content)
    for pos, del_count, ins in trace.iter_patches():
        doc.replace(pos, pos + del_count, ins)
    return doc.content()


def replay_unit_ops(
    kind: np.ndarray, pos: np.ndarray, ch: np.ndarray, start: str = ""
) -> str:
    """Replay exploded unit ops (tensorize.py layout); oracle for the engine's
    exact input representation."""
    doc = list(start)
    for k, p, c in zip(kind.tolist(), pos.tolist(), ch.tolist()):
        if k == INSERT:
            doc[max(p, 0) : max(p, 0)] = [chr(c)]  # p > len appends, p < 0 prepends
        elif k == DELETE and 0 <= p < len(doc):  # out-of-range delete: no-op
            del doc[p]
    return "".join(doc)
