from .text_oracle import OracleDocument, replay_trace, replay_unit_ops

__all__ = ["OracleDocument", "replay_trace", "replay_unit_ops"]
