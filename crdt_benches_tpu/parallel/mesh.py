"""Device-mesh scaling: replicas sharded over devices via shard_map.

The reference has no parallelism at all (SURVEY.md section 2.3 — one
synchronous thread); the TPU-native replacement axes are:

- **replica-parallelism** (the DP analog): simulated replicas sharded over a
  ``replicas`` mesh axis, each shard vmapping its local replicas;
- **cross-replica reduction**: convergence checking via ``pmin``/``pmax``/
  ``psum`` over the mesh axis (the downstream/merge analog of reference
  src/main.rs:65-68), riding ICI within a slice / DCN across slices — these
  are XLA collectives, not a hand-rolled comm backend.

Works identically on a real multi-chip mesh and on a virtual
``--xla_force_host_platform_device_count`` CPU mesh (tests/conftest.py).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.8 top-level API (check_vma kwarg); fall back for older
    from jax import shard_map as _shard_map

    def shard_map(f, **kw):
        kw.pop("check_rep", None)
        return _shard_map(f, check_vma=False, **kw)
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from ..ops.apply import DocState, apply_batch, init_state
from ..ops.resolve import resolve_batch
from ..utils.digest import doc_digest

AXIS = "replicas"


def replica_mesh(n_devices: int | None = None) -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (AXIS,))


def device_memory_stats(n_devices: int | None = None) -> list[dict | None]:
    """Per-device allocator stats for the first ``n_devices`` devices
    (the serve fleet's shard order — ``replica_mesh`` takes the same
    prefix).  Real TPU/GPU backends answer ``Device.memory_stats()``
    with ``bytes_in_use`` et al.; backends without allocator telemetry
    (the virtual host-CPU mesh) yield None entries — callers gauge what
    exists and skip the rest.  A local allocator query, not a sync:
    nothing blocks on in-flight dispatches."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    out: list[dict | None] = []
    for d in devs:
        try:
            ms = d.memory_stats()
        except Exception:  # backend without allocator stats
            ms = None
        out.append(ms if isinstance(ms, dict) else None)
    return out


def fleet_sharding(mesh: Mesh) -> NamedSharding:
    """Docs-over-mesh layout for the serve/ document fleet: the leading
    axis of every DocPool bucket array (one lane per *independent
    document*, unlike the replica stacks above) splits over the mesh's
    replica axis.  Resolve/apply are row-local, so the vmapped fleet
    step partitions under jit with zero collectives — the serving analog
    of the replica-parallel sharding this module was built for."""
    return NamedSharding(mesh, P(AXIS))


def _local_replay_step(state: DocState, kind, pos, slot) -> DocState:
    """One op-batch step for a single replica (resolve + apply)."""
    resolved = resolve_batch(kind, pos, state.nvis)
    return apply_batch(state, resolved, slot)


def sharded_replay_and_digest(mesh: Mesh):
    """Build the full sharded step: every shard replays its local replicas
    through all op batches, computes local digests, then the mesh agrees on
    convergence via pmin/pmax collectives.

    Returns (step_fn, state_sharding).  ``step_fn(state, kind_b, pos_b,
    slot_b, chars) -> (state, digests, converged)`` where state/digests are
    sharded over replicas and ``converged`` is a replicated scalar bool.
    """

    def shard_body(state: DocState, kind_b, pos_b, slot_b, chars):
        def batch_step(st, batch):
            k, p, s = batch
            return jax.vmap(_local_replay_step, in_axes=(0, None, None, None))(
                st, k, p, s
            ), None

        state, _ = jax.lax.scan(batch_step, state, (kind_b, pos_b, slot_b))
        digests = jax.vmap(
            lambda st: doc_digest(st.order, st.visible, st.length, chars)
        )(state)
        # Convergence across ALL replicas on ALL devices: every digest equal.
        local_min = jnp.min(digests, axis=0)
        local_max = jnp.max(digests, axis=0)
        gmin = jax.lax.pmin(local_min, AXIS)
        gmax = jax.lax.pmax(local_max, AXIS)
        converged = jnp.all(gmin == gmax)
        return state, digests, converged

    dummy = DocState(0, 0, 0, 0, 0)
    state_spec = jax.tree.map(lambda _: P(AXIS), dummy)
    step = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(state_spec, P(), P(), P(), P()),
        out_specs=(state_spec, P(AXIS), P()),
        check_rep=False,
    )
    state_sharding = jax.tree.map(
        lambda _: NamedSharding(mesh, P(AXIS)), dummy
    )
    return jax.jit(step), state_sharding


def sharded_merge_and_converge(
    mesh: Mesh, capacity: int, n_base: int, batch: int
):
    """Build the distributed update-exchange + merge step (the TPU-native
    replacement for the reference's in-memory ``Vec<Update>`` "network",
    SURVEY.md section 5): every replica's op log is exchanged with
    ``all_gather`` over the replica mesh axis (riding ICI/DCN), then every
    replica independently integrates the union via engine/merge.py's
    sort + batched-integration kernel, and the mesh agrees on convergence by
    comparing digests with pmin/pmax collectives.

    Replicas rebuild from the shared base rather than patching their local
    state: on accelerators recompute-from-sorted-union is one fused scan
    pipeline, while incremental out-of-order integration would reintroduce
    the sequential sibling-scan RGA does per op (see engine/merge.py).

    Returns ``step(logs, chars) -> (states, digests, converged)`` where
    ``logs`` is a dict of int32[R, N] arrays (lamport/agent/kind/elem/
    origin/ch, R = total replicas, N a multiple of ``batch``), sharded over
    the replica axis.  Every replica integrates the full union, so states
    and digests are [R, ...] and converged is a replicated scalar bool.
    """
    from ..engine.downstream import init_down_state
    from ..engine.merge import merge_oplogs

    def body(lam, ag, kind, elem, orig, ch, chars):
        # local shard (r_loc, N) -> exchange -> union (R*N,)
        g = lambda x: jax.lax.all_gather(x, AXIS, tiled=True).reshape(-1)
        union = tuple(map(g, (lam, ag, kind, elem, orig, ch)))

        def integrate(_r):
            st = init_down_state(capacity, n_base)
            return merge_oplogs(st, *union, batch=batch)

        states = jax.vmap(integrate)(
            jnp.arange(lam.shape[0], dtype=jnp.int32)
        )
        digests = jax.vmap(
            lambda st: doc_digest(st.order, st.visible, st.length, chars)
        )(states)
        gmin = jax.lax.pmin(jnp.min(digests, axis=0), AXIS)
        gmax = jax.lax.pmax(jnp.max(digests, axis=0), AXIS)
        return states, digests, jnp.all(gmin == gmax)

    log_spec = tuple(P(AXIS) for _ in range(6))
    state_spec = jax.tree.map(
        lambda _: P(AXIS), init_down_state(1, 0)
    )
    step = shard_map(
        body,
        mesh=mesh,
        in_specs=log_spec + (P(),),
        out_specs=(state_spec, P(AXIS), P()),
        check_rep=False,
    )
    return jax.jit(step)


def sharded_merge_packed(
    mesh: Mesh, capacity: int, n_base: int, batch: int, epoch: int = 4,
    max_unique: int | None = None,
):
    """sharded_merge_and_converge on the packed fast path
    (engine/merge.py merge_oplogs_packed): all_gather the per-replica op
    logs over the mesh axis, every local replica batch integrates the
    union through the id-resolved packed kernels, convergence by
    pmin/pmax digest agreement.  ``step(logs, chars) -> (state, digests,
    converged)`` with state a DownPacked whose leaves are [R, ...]
    sharded over the replica axis.
    """
    from ..engine.downstream import DownPacked, down_packed_init
    from ..engine.merge import merge_oplogs_packed
    from ..utils.digest import doc_digest_packed

    def body(lam, ag, kind, elem, orig, ch, chars):
        g = lambda x: jax.lax.all_gather(x, AXIS, tiled=True).reshape(-1)
        union = tuple(map(g, (lam, ag, kind, elem, orig, ch)))
        state = merge_oplogs_packed(
            down_packed_init(lam.shape[0], capacity, n_base),
            *union,
            batch=batch,
            epoch=epoch,
            max_unique=max_unique,
        )
        digests = jax.vmap(doc_digest_packed, in_axes=(0, 0, None))(
            state.doc, state.length, chars
        )
        gmin = jax.lax.pmin(jnp.min(digests, axis=0), AXIS)
        gmax = jax.lax.pmax(jnp.max(digests, axis=0), AXIS)
        return state, digests, jnp.all(gmin == gmax)

    from ..engine.downstream import DownPacked as _DP

    log_spec = tuple(P(AXIS) for _ in range(6))
    state_spec = _DP(P(AXIS), P(AXIS), P(AXIS), P(AXIS))
    step = shard_map(
        body,
        mesh=mesh,
        in_specs=log_spec + (P(),),
        out_specs=(state_spec, P(AXIS), P()),
        check_rep=False,
    )
    return jax.jit(step)


def _sharded_runs_step(
    mesh: Mesh, capacity: int, n_base: int, batch: int, epoch: int,
    nbits: int, *, gather: bool, r_per_shard: int,
):
    """Shared builder for the two run-granular sharded paths: the
    concurrent MERGE (``gather=True``: each device contributes its wire
    shard, all_gather reassembles the union) and the single-writer
    DOWNSTREAM (``gather=False``: the wire is replicated — the broadcast
    fan-out topology — and only the subscriber replicas shard).  Both
    integrate via merge_runlogs + the one-pass delete fold and agree on
    convergence via pmin/pmax digest collectives."""
    from ..engine.downstream import DownPacked as _DP
    from ..engine.downstream import down_packed_init
    from ..engine.merge_range import delete_fold, merge_runlogs
    from ..utils.digest import doc_digest_packed

    def body(lam, ag, s0, rl, orig, dlo, dhi, chars):
        if gather:
            g = lambda x: jax.lax.all_gather(x, AXIS, tiled=True).reshape(-1)
            lam, ag, s0, rl, orig, dlo, dhi = (
                g(lam), g(ag), g(s0), g(rl), g(orig), g(dlo), g(dhi)
            )
        state = merge_runlogs(
            down_packed_init(r_per_shard, capacity, n_base),
            lam, ag, s0, rl, orig,
            batch=batch, epoch=epoch, nbits=nbits,
        )
        state = delete_fold(state, dlo, dhi)
        digests = jax.vmap(doc_digest_packed, in_axes=(0, 0, None))(
            state.doc, state.length, chars
        )
        gmin = jax.lax.pmin(jnp.min(digests, axis=0), AXIS)
        gmax = jax.lax.pmax(jnp.max(digests, axis=0), AXIS)
        return state, digests, jnp.all(gmin == gmax)

    wire_spec = tuple((P(AXIS) if gather else P()) for _ in range(7))
    state_spec = _DP(P(AXIS), P(AXIS), P(AXIS), P(AXIS))
    step = shard_map(
        body,
        mesh=mesh,
        in_specs=wire_spec + (P(),),
        out_specs=(state_spec, P(AXIS), P()),
        check_rep=False,
    )
    return jax.jit(step)


def sharded_merge_runs(
    mesh: Mesh, capacity: int, n_base: int, batch: int, epoch: int,
    nbits: int,
):
    """sharded_merge_packed at RUN granularity (engine/merge_range.py):
    each device contributes its shard of the run-log wire stream,
    all_gather reassembles the union over the mesh axis, every local
    replica integrates it through merge_runlogs + the one-pass delete
    fold, and convergence is pmin/pmax digest agreement.

    ``step(lam, ag, slot0, rlen, origin, dlo, dhi, chars)`` with the five
    run arrays (N,) and delete intervals (Nd,) sharded over the axis
    (N and Nd divisible by the mesh size; pad runs with rlen == 0 and
    intervals with dlo == -1 — both are no-ops end to end).
    """
    return _sharded_runs_step(
        mesh, capacity, n_base, batch, epoch, nbits,
        gather=True, r_per_shard=1,
    )


def sharded_downstream_runs(
    mesh: Mesh, capacity: int, n_base: int, batch: int, epoch: int,
    nbits: int, r_per_shard: int,
):
    """Single-writer downstream apply sharded over the replica mesh axis
    (VERDICT r3 missing #3).  The downstream topology is a BROADCAST —
    one upstream's wire stream fans out to every subscriber — so the run
    arrays are replicated to all devices (in_specs P(); XLA keeps one
    copy per device, no collective needed) while the subscriber replicas
    are sharded: each shard integrates the full stream into its
    ``r_per_shard`` local replicas via the same merge_runlogs +
    delete_fold machinery the runs downstream bench times
    (engine/merge_range.py JaxRunDownstreamBackend), then the mesh
    agrees on convergence via pmin/pmax digest collectives.

    ``step(lam, ag, slot0, rlen, origin, dlo, dhi, chars) -> (state,
    digests, converged)``: run arrays (N,) replicated, state a DownPacked
    with leaves [n_devices * r_per_shard, ...] sharded over the axis.
    """
    return _sharded_runs_step(
        mesh, capacity, n_base, batch, epoch, nbits,
        gather=False, r_per_shard=r_per_shard,
    )


def make_sharded_state(
    mesh: Mesh, n_replicas: int, capacity: int, n_init: int = 0
) -> DocState:
    """Replica states sharded over the mesh: (R, C) arrays with R split
    across devices."""
    if n_replicas % mesh.devices.size:
        raise ValueError(
            f"n_replicas={n_replicas} not divisible by mesh size {mesh.devices.size}"
        )
    st = init_state(capacity, n_init)
    st = jax.tree.map(
        lambda x: jnp.broadcast_to(x, (n_replicas,) + jnp.shape(x)), st
    )
    sharding = jax.tree.map(
        lambda _: NamedSharding(mesh, P(AXIS)), DocState(0, 0, 0, 0, 0)
    )
    return jax.tree.map(
        lambda x, s: jax.device_put(x, s), st, sharding
    )
