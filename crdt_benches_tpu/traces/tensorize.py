"""Trace tensorization: patches -> padded integer op tensors.

The reference replays patches one at a time through a mutable rope
(src/main.rs:30-34).  The TPU engine instead consumes the trace as fixed-shape
integer arrays: each patch ``(pos, del, ins)`` is *exploded* into unit ops —
``del`` single-char deletes at ``pos`` followed by one single-char insert per
char of ``ins`` (at ``pos``, ``pos+1``, ...).  Unit ops are padded to a
multiple of the scan batch size ``B``; a ``kind == PAD`` op is a no-op.

Each insert unit op is pre-assigned its **slot id** (its index in the
insertion-order physical buffer): slot ids are dense, deterministic, and
computable at tensorize time, which lets the device engine scatter new chars
without dynamic allocation.  Slot ids double as CRDT element ids
(``(agent, seq)`` with ``seq`` = slot) — the analog of diamond-types' agent
ids / op-log times (reference src/rope.rs:117-120).

Pure NumPy; no JAX dependency at this layer (SURVEY.md section 7, layer 1).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .loader import TestData

# Op kinds.
PAD = 0
INSERT = 1
DELETE = 2


@dataclass
class TensorizedTrace:
    """A trace as padded unit-op tensors plus static sizing metadata."""

    kind: np.ndarray  # int32[N_pad]  PAD / INSERT / DELETE
    pos: np.ndarray  # int32[N_pad]  visible char position at op time
    ch: np.ndarray  # int32[N_pad]  codepoint for INSERT, 0 otherwise
    slot: np.ndarray  # int32[N_pad]  preassigned slot id for INSERT, -1 otherwise
    init_chars: np.ndarray  # int32[S] start-content codepoints (slots 0..S-1)
    n_ops: int  # real (unpadded) unit-op count
    n_patches: int  # reference throughput element count (src/main.rs:25)
    n_inserts: int  # INSERT unit-op count
    capacity: int  # S + n_inserts = total slots ever allocated
    batch: int  # scan batch size the padding is aligned to
    end_content: str

    @property
    def n_batches(self) -> int:
        return len(self.kind) // self.batch

    def batched(self) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Reshape the op streams to (n_batches, batch)."""
        nb, b = self.n_batches, self.batch
        return (
            self.kind.reshape(nb, b),
            self.pos.reshape(nb, b),
            self.ch.reshape(nb, b),
            self.slot.reshape(nb, b),
        )


def explode_unit_ops(trace: TestData) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Explode patches into (kind, pos, ch) unit-op arrays (no padding)."""
    kinds: list[int] = []
    poss: list[int] = []
    chs: list[int] = []
    for pos, del_count, ins in trace.iter_patches():
        for _ in range(del_count):
            kinds.append(DELETE)
            poss.append(pos)
            chs.append(0)
        for i, c in enumerate(ins):
            kinds.append(INSERT)
            poss.append(pos + i)
            chs.append(ord(c))
    return (
        np.asarray(kinds, dtype=np.int32),
        np.asarray(poss, dtype=np.int32),
        np.asarray(chs, dtype=np.int32),
    )


@dataclass
class RangeTrace:
    """A trace as padded RANGE-op tensors: one op per patch component
    (delete-range and/or insert-run) instead of one per char.

    The per-char explosion multiplies op counts up to ~24x on block-edit
    traces (SURVEY.md section 6 'per-char-exploded unit ops'); the range
    layout keeps op count ~= patch count, so the sequential resolver does
    O(patches) work instead of O(chars) (SURVEY.md section 7 hard-part 4).
    """

    kind: np.ndarray  # int32[N_pad]  PAD / INSERT / DELETE
    pos: np.ndarray  # int32[N_pad]  visible char position at op time
    rlen: np.ndarray  # int32[N_pad]  run length (chars inserted / deleted)
    slot0: np.ndarray  # int32[N_pad] first slot id for INSERT, -1 otherwise
    init_chars: np.ndarray  # int32[S]
    n_ops: int
    n_patches: int
    n_ins_chars: int  # total inserted chars
    capacity: int  # S + n_ins_chars
    batch: int
    end_content: str
    max_batch_ins: int  # max inserted chars in any one op batch
    chars: np.ndarray  # int32[capacity] slot -> codepoint

    @property
    def n_batches(self) -> int:
        return len(self.kind) // self.batch

    def batched(self):
        nb, b = self.n_batches, self.batch
        return (
            self.kind.reshape(nb, b),
            self.pos.reshape(nb, b),
            self.rlen.reshape(nb, b),
            self.slot0.reshape(nb, b),
        )


def coalesce_patches(trace: TestData):
    """Merge ADJACENT patches whose combined effect is one contiguous run
    into a single (pos, del, ins) patch — run-length encoding of the edit
    stream, the same coalescing diamond-types' op log performs internally
    when the reference feeds it consecutive single-char inserts
    (reference src/rope.rs:119-126; dt stores ops RLE).  Three patterns:

    - typing run: insert at ``prev_pos + len(prev_ins)`` extends the run
      (``ins(p, "a"); ins(p+1, "b") == ins(p, "ab")``);
    - forward delete (Del key): delete at the SAME position extends
      (``del(p, 1); del(p, 1) == del(p, 2)``);
    - backspace run: delete ending where the previous delete began
      (``del(p, 1); del(p-1, 1) == del(p-1, 2)``).

    Order is never changed — only adjacent ops merge — so replaying the
    coalesced stream is byte-identical to the original (asserted against
    the oracle in tests and ``--verify``).  Yields (pos, del, ins).
    """
    pend: list | None = None  # [pos, del_count, ins] — pure del or pure ins

    for pos, del_count, ins in trace.iter_patches():
        if del_count:
            if pend is not None and pend[1] and not pend[2]:
                if pos == pend[0]:  # forward delete continues
                    pend[1] += del_count
                    del_count = 0
                elif pos + del_count == pend[0]:  # backspace grows leftward
                    pend[0] = pos
                    pend[1] += del_count
                    del_count = 0
            if del_count:  # could not merge: flush and start a new delete
                if pend is not None:
                    yield tuple(pend)
                pend = [pos, del_count, ""]
        if ins:
            if (
                pend is not None
                and pend[2]
                and not pend[1]
                and pos == pend[0] + len(pend[2])
            ):
                pend[2] += ins  # typing run continues
            else:
                if pend is not None:
                    yield tuple(pend)
                pend = [pos, 0, ins]
    if pend is not None:
        yield tuple(pend)


def split_insert_runs(
    kind: np.ndarray, pos: np.ndarray, rlen: np.ndarray, slot0: np.ndarray,
    max_ins: int,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Split INSERT runs longer than ``max_ins`` chars into consecutive
    pieces: inserting ``L`` chars at ``p`` equals inserting the first
    ``max_ins`` at ``p``, the next at ``p + max_ins``, ... with slot ids
    advancing in step.  Deletes pass through whole (a delete range of any
    length is one interval clear in the apply — only inserted chars gate
    the expansion's nbits budget).  Lets a scheduler cap per-batch insert
    volume without per-op cursor state (serve/scheduler.py)."""
    if max_ins < 1:
        raise ValueError(f"max_ins must be >= 1, got {max_ins}")
    splits = (kind == INSERT) & (rlen > max_ins)
    if not splits.any():
        return kind, pos, rlen, slot0
    reps = np.where(splits, -(-rlen // max_ins), 1).astype(np.int64)
    idx = np.repeat(np.arange(len(kind)), reps)
    first = np.concatenate([[0], np.cumsum(reps)[:-1]])
    off = (np.arange(len(idx)) - np.repeat(first, reps)).astype(np.int64)
    chars_before = (off * max_ins).astype(np.int32)
    k2 = kind[idx]
    is_ins = k2 == INSERT
    p2 = np.where(is_ins, pos[idx] + chars_before, pos[idx]).astype(np.int32)
    r2 = np.where(
        is_ins, np.minimum(max_ins, rlen[idx] - chars_before), rlen[idx]
    ).astype(np.int32)
    s2 = np.where(is_ins, slot0[idx] + chars_before, slot0[idx]).astype(
        np.int32
    )
    return k2, p2, r2, s2


def tensorize_ranges(
    trace: TestData, batch: int = 512, coalesce: bool = False,
    patches=None,
) -> RangeTrace:
    """Tensorize a trace as range ops (no per-char explosion).  With
    ``coalesce`` the patch stream is first run-length encoded across
    patch boundaries (:func:`coalesce_patches`), shrinking the sequential
    op count a further ~3-24x on keystroke traces.  ``patches`` lets a
    caller that already materialized the (coalesced) patch list pass it
    in instead of re-walking the trace."""
    kinds: list[int] = []
    poss: list[int] = []
    lens: list[int] = []
    slot0s: list[int] = []
    init_chars = np.asarray([ord(c) for c in trace.start_content], np.int32)
    s = len(init_chars)
    next_slot = s
    chars: list[int] = []
    if patches is None:
        patches = (
            coalesce_patches(trace) if coalesce else trace.iter_patches()
        )
    for pos, del_count, ins in patches:
        if del_count:
            kinds.append(DELETE)
            poss.append(pos)
            lens.append(del_count)
            slot0s.append(-1)
        if ins:
            kinds.append(INSERT)
            poss.append(pos)
            lens.append(len(ins))
            slot0s.append(next_slot)
            chars.extend(ord(c) for c in ins)
            next_slot += len(ins)
    n_ops = len(kinds)
    n_pad = (-n_ops) % batch if n_ops else batch
    kind = np.asarray(kinds + [PAD] * n_pad, np.int32)
    pos = np.asarray(poss + [0] * n_pad, np.int32)
    rlen = np.asarray(lens + [0] * n_pad, np.int32)
    slot0 = np.asarray(slot0s + [-1] * n_pad, np.int32)
    n_ins_chars = next_slot - s
    char_table = np.zeros(s + n_ins_chars, np.int32)
    char_table[:s] = init_chars
    char_table[s:] = np.asarray(chars, np.int32)
    nb = len(kind) // batch
    ins_per_batch = (
        np.where(kind == INSERT, rlen, 0).reshape(nb, batch).sum(axis=1)
    )
    return RangeTrace(
        kind=kind,
        pos=pos,
        rlen=rlen,
        slot0=slot0,
        init_chars=init_chars,
        n_ops=n_ops,
        n_patches=len(trace),
        n_ins_chars=int(n_ins_chars),
        capacity=int(s + n_ins_chars),
        batch=batch,
        end_content=trace.end_content,
        max_batch_ins=int(ins_per_batch.max(initial=0)),
        chars=char_table,
    )


def tensorize(trace: TestData, batch: int = 256) -> TensorizedTrace:
    """Tensorize a trace with padding aligned to ``batch`` unit ops."""
    kind, pos, ch = explode_unit_ops(trace)
    n_ops = len(kind)
    n_pad = (-n_ops) % batch if n_ops else batch
    if n_pad:
        kind = np.concatenate([kind, np.zeros(n_pad, np.int32)])
        pos = np.concatenate([pos, np.zeros(n_pad, np.int32)])
        ch = np.concatenate([ch, np.zeros(n_pad, np.int32)])

    init_chars = np.asarray([ord(c) for c in trace.start_content], dtype=np.int32)
    s = len(init_chars)
    is_ins = kind == INSERT
    # slot id = S + (number of inserts strictly before this op)
    slot = np.where(
        is_ins, s + np.cumsum(is_ins, dtype=np.int64) - 1, -1
    ).astype(np.int32)
    n_inserts = int(is_ins.sum())
    return TensorizedTrace(
        kind=kind,
        pos=pos,
        ch=ch,
        slot=slot,
        init_chars=init_chars,
        n_ops=n_ops,
        n_patches=len(trace),
        n_inserts=n_inserts,
        capacity=s + n_inserts,
        batch=batch,
        end_content=trace.end_content,
    )
