"""Trace tensorization: patches -> padded integer op tensors.

The reference replays patches one at a time through a mutable rope
(src/main.rs:30-34).  The TPU engine instead consumes the trace as fixed-shape
integer arrays: each patch ``(pos, del, ins)`` is *exploded* into unit ops —
``del`` single-char deletes at ``pos`` followed by one single-char insert per
char of ``ins`` (at ``pos``, ``pos+1``, ...).  Unit ops are padded to a
multiple of the scan batch size ``B``; a ``kind == PAD`` op is a no-op.

Each insert unit op is pre-assigned its **slot id** (its index in the
insertion-order physical buffer): slot ids are dense, deterministic, and
computable at tensorize time, which lets the device engine scatter new chars
without dynamic allocation.  Slot ids double as CRDT element ids
(``(agent, seq)`` with ``seq`` = slot) — the analog of diamond-types' agent
ids / op-log times (reference src/rope.rs:117-120).

Pure NumPy; no JAX dependency at this layer (SURVEY.md section 7, layer 1).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .loader import TestData

# Op kinds.
PAD = 0
INSERT = 1
DELETE = 2


@dataclass
class TensorizedTrace:
    """A trace as padded unit-op tensors plus static sizing metadata."""

    kind: np.ndarray  # int32[N_pad]  PAD / INSERT / DELETE
    pos: np.ndarray  # int32[N_pad]  visible char position at op time
    ch: np.ndarray  # int32[N_pad]  codepoint for INSERT, 0 otherwise
    slot: np.ndarray  # int32[N_pad]  preassigned slot id for INSERT, -1 otherwise
    init_chars: np.ndarray  # int32[S] start-content codepoints (slots 0..S-1)
    n_ops: int  # real (unpadded) unit-op count
    n_patches: int  # reference throughput element count (src/main.rs:25)
    n_inserts: int  # INSERT unit-op count
    capacity: int  # S + n_inserts = total slots ever allocated
    batch: int  # scan batch size the padding is aligned to
    end_content: str

    @property
    def n_batches(self) -> int:
        return len(self.kind) // self.batch

    def batched(self) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Reshape the op streams to (n_batches, batch)."""
        nb, b = self.n_batches, self.batch
        return (
            self.kind.reshape(nb, b),
            self.pos.reshape(nb, b),
            self.ch.reshape(nb, b),
            self.slot.reshape(nb, b),
        )


def explode_unit_ops(trace: TestData) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Explode patches into (kind, pos, ch) unit-op arrays (no padding)."""
    kinds: list[int] = []
    poss: list[int] = []
    chs: list[int] = []
    for pos, del_count, ins in trace.iter_patches():
        for _ in range(del_count):
            kinds.append(DELETE)
            poss.append(pos)
            chs.append(0)
        for i, c in enumerate(ins):
            kinds.append(INSERT)
            poss.append(pos + i)
            chs.append(ord(c))
    return (
        np.asarray(kinds, dtype=np.int32),
        np.asarray(poss, dtype=np.int32),
        np.asarray(chs, dtype=np.int32),
    )


def tensorize(trace: TestData, batch: int = 256) -> TensorizedTrace:
    """Tensorize a trace with padding aligned to ``batch`` unit ops."""
    kind, pos, ch = explode_unit_ops(trace)
    n_ops = len(kind)
    n_pad = (-n_ops) % batch if n_ops else batch
    if n_pad:
        kind = np.concatenate([kind, np.zeros(n_pad, np.int32)])
        pos = np.concatenate([pos, np.zeros(n_pad, np.int32)])
        ch = np.concatenate([ch, np.zeros(n_pad, np.int32)])

    init_chars = np.asarray([ord(c) for c in trace.start_content], dtype=np.int32)
    s = len(init_chars)
    is_ins = kind == INSERT
    # slot id = S + (number of inserts strictly before this op)
    slot = np.where(
        is_ins, s + np.cumsum(is_ins, dtype=np.int64) - 1, -1
    ).astype(np.int32)
    n_inserts = int(is_ins.sum())
    return TensorizedTrace(
        kind=kind,
        pos=pos,
        ch=ch,
        slot=slot,
        init_chars=init_chars,
        n_ops=n_ops,
        n_patches=len(trace),
        n_inserts=n_inserts,
        capacity=s + n_inserts,
        batch=batch,
        end_content=trace.end_content,
    )
