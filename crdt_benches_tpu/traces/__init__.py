from .loader import TestData, TestTxn, TestPatch, load_testing_data, trace_path, TRACES
from .tensorize import TensorizedTrace, tensorize, explode_unit_ops

__all__ = [
    "TestData",
    "TestTxn",
    "TestPatch",
    "load_testing_data",
    "trace_path",
    "TRACES",
    "TensorizedTrace",
    "tensorize",
    "explode_unit_ops",
]
