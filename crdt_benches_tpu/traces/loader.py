"""Editing-trace loading (L1 of the framework).

Re-provides the capability of the reference's external ``crdt-testdata`` crate
(reference: Cargo.toml:10, used at src/main.rs:19,52): load a gzipped-JSON
editing trace in josephg's ``editing-traces`` format into a ``TestData`` value
with ``start_content`` / ``end_content`` / ``txns``, a ``len()`` equal to the
total patch count (the Criterion throughput element count, src/main.rs:25), and
a ``chars_to_bytes()`` conversion for byte-addressed backends
(src/main.rs:21-23).

Schema (verified against the mounted trace files, SURVEY.md section 3.4)::

    {"startContent": str, "endContent": str,
     "txns": [{"time": ISO8601 str,
               "patches": [[pos: int, delCount: int, insStr: str], ...]}, ...]}

Positions and delete counts are in **character (codepoint) units**;
``chars_to_bytes`` rewrites them into UTF-8 byte units.

Pure Python + stdlib; no JAX dependency at this layer.
"""

from __future__ import annotations

import gzip
import json
import os
from dataclasses import dataclass, field
from typing import Iterator, NamedTuple

#: The four workloads, as in the reference's hardcoded trace table
#: (src/main.rs:10-15).  Overridable via the bench runner's --traces flag —
#: the rebuild replaces the hardcoded const with configuration.
TRACES = (
    "automerge-paper",
    "rustcode",
    "sveltecomponent",
    "seph-blog1",
)

_DEFAULT_TRACE_DIRS = (
    os.path.join(os.path.dirname(__file__), "..", "..", "traces_data"),
    "./traces_data",
    "./traces",
)


class TestPatch(NamedTuple):
    """One edit: replace ``del_count`` chars at ``pos`` with ``ins``.

    Mirrors the reference's ``TestPatch(pos, del, ins)`` tuple
    (destructured at src/main.rs:31).
    """

    pos: int
    del_count: int
    ins: str

    __test__ = False  # "Test*" name; keep pytest collection away


@dataclass
class TestTxn:
    __test__ = False  # "Test*" name; keep pytest collection away
    time: str
    patches: list[TestPatch] = field(default_factory=list)


@dataclass
class TestData:
    __test__ = False  # "Test*" name; keep pytest collection away
    start_content: str
    end_content: str
    txns: list[TestTxn]

    def __len__(self) -> int:
        """Total patch count — the throughput element count (src/main.rs:25)."""
        return sum(len(t.patches) for t in self.txns)

    def iter_patches(self) -> Iterator[TestPatch]:
        for txn in self.txns:
            yield from txn.patches

    def chars_to_bytes(self) -> "TestData":
        """Rewrite char-unit positions/counts into UTF-8 byte units.

        Required for byte-addressed backends (the reference's cola and yrs
        adapters set ``EDITS_USE_BYTE_OFFSETS = true``, src/rope.rs:82,147).

        Only non-ASCII chars make byte offsets differ from char offsets, and
        the traces contain at most a handful at any time (SURVEY.md section
        3.4), so we track just the char positions of multi-byte chars in the
        evolving document — O(#multibyte) per patch instead of replaying the
        whole document.
        """
        # (char_pos, extra_bytes) for each multi-byte char currently in doc.
        extras: list[list[int]] = [
            [i, len(c.encode("utf-8")) - 1]
            for i, c in enumerate(self.start_content)
            if ord(c) >= 128
        ]
        new_txns: list[TestTxn] = []
        for txn in self.txns:
            new_patches: list[TestPatch] = []
            for pos, del_count, ins in txn.patches:
                byte_pos = pos + sum(e for p, e in extras if p < pos)
                byte_del = del_count + sum(
                    e for p, e in extras if pos <= p < pos + del_count
                )
                new_patches.append(TestPatch(byte_pos, byte_del, ins))
                shift = len(ins) - del_count
                extras = [
                    [p + shift if p >= pos + del_count else p, e]
                    for p, e in extras
                    if not (pos <= p < pos + del_count)
                ]
                extras.extend(
                    [pos + i, len(c.encode("utf-8")) - 1]
                    for i, c in enumerate(ins)
                    if ord(c) >= 128
                )
                extras.sort()
            new_txns.append(TestTxn(txn.time, new_patches))
        return TestData(self.start_content, self.end_content, new_txns)

    def stats(self) -> dict:
        """Workload characteristics (the SURVEY.md section 6 table) as a
        self-check for the loader."""
        patches = ins_ops = del_ops = ins_chars = del_chars = 0
        max_ins = max_del = 0
        unit_ops = 0
        for pos, del_count, ins in self.iter_patches():
            patches += 1
            if ins:
                ins_ops += 1
                ins_chars += len(ins)
                max_ins = max(max_ins, len(ins))
            if del_count:
                del_ops += 1
                del_chars += del_count
                max_del = max(max_del, del_count)
            unit_ops += del_count + len(ins)
        return {
            "txns": len(self.txns),
            "patches": patches,
            "ins_ops": ins_ops,
            "del_ops": del_ops,
            "ins_chars": ins_chars,
            "del_chars": del_chars,
            "max_ins": max_ins,
            "max_del": max_del,
            "final_chars": len(self.end_content),
            "unit_ops": unit_ops,
        }


def trace_path(name: str, trace_dir: str | None = None) -> str:
    """Resolve a trace name (e.g. ``"sveltecomponent"``) to a .json.gz path."""
    if name.endswith(".json.gz"):
        if os.path.exists(name):
            return name
        raise FileNotFoundError(f"trace file {name!r} does not exist")
    candidates = [trace_dir] if trace_dir else list(_DEFAULT_TRACE_DIRS)
    for d in candidates:
        if d is None:
            continue
        p = os.path.join(d, f"{name}.json.gz")
        if os.path.exists(p):
            return os.path.normpath(p)
    raise FileNotFoundError(
        f"trace {name!r} not found in {candidates}; "
        "pass trace_dir= or set cwd to the repo root"
    )


def load_testing_data(path_or_name: str, trace_dir: str | None = None) -> TestData:
    """Load a gzipped-JSON editing trace (the ``load_testing_data`` capability,
    reference src/main.rs:19,52)."""
    path = trace_path(path_or_name, trace_dir)
    with gzip.open(path, "rt", encoding="utf-8") as f:
        raw = json.load(f)
    try:
        txns = [
            TestTxn(
                time=t.get("time", ""),
                patches=[TestPatch(p[0], p[1], p[2]) for p in t["patches"]],
            )
            for t in raw["txns"]
        ]
        return TestData(
            start_content=raw["startContent"],
            end_content=raw["endContent"],
            txns=txns,
        )
    except (KeyError, IndexError, TypeError) as e:
        raise ValueError(
            f"{path}: not a valid editing-traces file "
            "(expected startContent/endContent/txns[].patches)"
        ) from e
