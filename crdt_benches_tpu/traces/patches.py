"""Patch-level array layout (un-exploded): one record per trace patch.

The native tier and the bench harness consume patches in the reference's
granularity (one ``(pos, del, ins)`` replace per element, reference
src/main.rs:31-32) rather than the exploded unit ops the JAX engine uses.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .loader import TestData


@dataclass
class PatchArrays:
    pos: np.ndarray  # int32[n]
    del_count: np.ndarray  # int32[n]
    ins_off: np.ndarray  # int32[n+1]  insert text for patch i = flat[off[i]:off[i+1]]
    ins_flat: np.ndarray  # int32[total_ins_chars] codepoints
    init: np.ndarray  # int32[len(start_content)]
    n_patches: int
    end_len: int


def patch_arrays(trace: TestData) -> PatchArrays:
    pos, dels, lens, flat = [], [], [0], []
    for p, d, ins in trace.iter_patches():
        pos.append(p)
        dels.append(d)
        lens.append(lens[-1] + len(ins))
        flat.extend(ord(c) for c in ins)
    return PatchArrays(
        pos=np.asarray(pos, np.int32),
        del_count=np.asarray(dels, np.int32),
        ins_off=np.asarray(lens, np.int32),
        ins_flat=np.asarray(flat, np.int32),
        init=np.asarray([ord(c) for c in trace.start_content], np.int32),
        n_patches=len(pos),
        end_len=len(trace.end_content),
    )
