"""Patch-level array layout (un-exploded): one record per trace patch.

The native tier and the bench harness consume patches in the reference's
granularity (one ``(pos, del, ins)`` replace per element, reference
src/main.rs:31-32) rather than the exploded unit ops the JAX engine uses.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .loader import TestData


@dataclass
class PatchArrays:
    pos: np.ndarray  # int32[n]
    del_count: np.ndarray  # int32[n]
    ins_off: np.ndarray  # int32[n+1]  insert text for patch i = flat[off[i]:off[i+1]]
    ins_flat: np.ndarray  # int32[total_ins_chars] codepoints
    init: np.ndarray  # int32[len(start_content)]
    n_patches: int
    end_len: int


def patch_arrays(
    trace: TestData, bytes_mode: bool = False, patches=None
) -> PatchArrays:
    """``bytes_mode``: encode text as UTF-8 bytes (one int per byte) for
    byte-addressed backends — the trace must already be in byte units
    (``trace.chars_to_bytes()``), matching the reference's byte-offset
    adapters (cola/yrs, src/rope.rs:82,147).

    ``patches``: optional replacement (pos, del, ins) stream (e.g. the
    RLE-coalesced stream from traces/tensorize.py coalesce_patches) —
    used to feed native baselines the SAME coalesced stream the JAX range
    engine replays, making headline ratios stream-symmetric (VERDICT r3
    weak #4).  ``end_len`` still comes from the trace (byte-identity of
    the coalesced replay is oracle-asserted in tests)."""
    enc = (
        (lambda s: list(s.encode("utf-8")))
        if bytes_mode
        else (lambda s: [ord(c) for c in s])
    )
    pos, dels, lens, flat = [], [], [0], []
    for p, d, ins in (
        patches if patches is not None else trace.iter_patches()
    ):
        pos.append(p)
        dels.append(d)
        chunk = enc(ins)
        lens.append(lens[-1] + len(chunk))
        flat.extend(chunk)
    return PatchArrays(
        pos=np.asarray(pos, np.int32),
        del_count=np.asarray(dels, np.int32),
        ins_off=np.asarray(lens, np.int32),
        ins_flat=np.asarray(flat, np.int32),
        init=np.asarray(enc(trace.start_content), np.int32),
        n_patches=len(pos),
        end_len=len(enc(trace.end_content)),
    )
