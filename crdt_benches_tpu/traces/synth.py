"""Synthetic edit-stream generation — the adversarial-interleaving workload
of BASELINE.md config 5 and the shared random-stream helper for tests/dryrun.

The reference has no synthetic workloads (its four fixtures are real traces,
SURVEY.md section 4); convergence under adversarial concurrent interleavings
is a rebuild-only capability, so the generator lives in the library, not in
test helpers.
"""

from __future__ import annotations

import numpy as np

from .loader import TestData, TestPatch, TestTxn


def random_patches(
    rng: np.random.Generator,
    n_ops: int,
    start_len: int = 0,
    p_insert: float = 0.65,
) -> tuple[list[TestPatch], int]:
    """``n_ops`` single-char random edits against a document of
    ``start_len`` chars; returns (patches, final_len)."""
    doc_len = start_len
    patches: list[TestPatch] = []
    for _ in range(n_ops):
        if doc_len == 0 or rng.random() < p_insert:
            pos = int(rng.integers(0, doc_len + 1))
            patches.append(TestPatch(pos, 0, chr(int(rng.integers(97, 123)))))
            doc_len += 1
        else:
            patches.append(TestPatch(int(rng.integers(0, doc_len)), 1, ""))
            doc_len -= 1
    return patches, doc_len


def synth_trace(
    seed: int, n_ops: int, base: str = "", p_insert: float = 0.65
) -> TestData:
    """A synthetic TestData: random unit edits from ``base`` (end_content
    left empty — the oracle defines truth for synthetic streams)."""
    rng = np.random.default_rng(seed)
    patches, _ = random_patches(rng, n_ops, len(base), p_insert)
    return TestData(base, "", [TestTxn("", patches)])


def synth_streams(
    seed: int, n_agents: int, n_ops: int, base: str = "",
    p_insert: float = 0.65,
) -> list[TestData]:
    """One divergent random edit stream per agent from a shared base — the
    concurrent-merge workload (BASELINE.md configs 4-5)."""
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_agents):
        patches, _ = random_patches(rng, n_ops, len(base), p_insert)
        out.append(TestData(base, "", [TestTxn("", patches)]))
    return out
