"""Lifecycle & ownership rules G022-G025: state-machine discipline,
acquire/release pairing, identity/generation hazards, and the runtime
lifecycle-artifact cross-check.

The last three PRs each shipped a latent lifecycle bug no existing
rule could see: a prefetch inflight counter underflowed, an
``id(trace)``-keyed cache was poisoned by id recycling, duplicate GC
enqueues KeyError'd mid-reclaim, and a doc was migrated before its
install was real.  These rules encode that incident class the same
way G014-G021 encoded theirs: a declared static model, enforced
against the AST, with a runtime sanitizer twin
(lint/lifecycle_sanitizer.py) whose counters the artifact-driven G025
cross-checks.

Marker vocabulary (parsed from REAL comments via
``ModuleInfo.comments``; richer than core's ``_MARKER_RE`` — keys
carry ``,``/``->`` payloads):

- class line::

    # graftlint: state=<machine> [field=<attr>] [states=a,b,...]
    #            [edges=a->b,b->c,...]

  declares a state machine (``doc``/``row``/``spool``/``stream``/
  ``session``), optionally naming the guarded instance attribute, the
  state vocabulary, and the legal edge graph.

- def line ``# graftlint: transition=<machine>:<a>-><b>[,<c>-><d>..]``
  declares a transition function and the edges it is allowed to
  traverse.

- def line ``# graftlint: acquire=<resource>`` / ``release=<resource>``
  declares a paired ownership primitive
  (``rows``/``spool``/``stream``/``segment``/``socket``/``thread``).

**G022 — state-machine discipline.**  A direct store to a declared
state field outside a transition function (or ``__init__``) in the
machine's jurisdiction (the modules that declare it or carry its
transitions), a transition marker for a machine nothing declares, a
transition endpoint outside the declared state vocabulary, or a
transition edge missing from the declared graph (the PR 18
same-round-admit migration was exactly an illegal edge out of
GENESIS) are all findings.

**G023 — acquire/release pairing.**  Marked functions are the
primitives; every *unmarked* function is walked statement-ordered and
its resolved calls to primitives (confident edges only, plus a
unique-bare-name fallback) become acquire/release events.  An acquire
whose balance never returns to zero on the fall-off path — with no
release in a covering ``finally`` and no ownership escape (returned,
stored into an attribute/subscript, or handed to another call) — is a
leak-on-path; a release that would drive the balance negative, or a
syntactically identical repeated release, is a double-release; a
resource acquired somewhere but released nowhere (or vice versa) is
unpaired at the marker level.

**G024 — identity/generation hazards.**  An attribute-held map
(``self._cache`` — long-lived state) keyed by ``id(obj)`` (subscript
or ``.get``/``.setdefault``/``.pop``) without a >=2-tuple generation
component is the PR 17 cache-poisoning incident (a function-local
table keyed by id() over pinned objects is the legal identity idiom
and stays out of scope); inside
lifecycle-annotated classes, a paired ``+=``/``-=`` attribute whose
decrement carries no underflow guard (a dominating self-test /
``is``/``in`` filter / ``> 0`` comparison, or an earlier
membership-``continue`` filter in the same function) is the inflight
underflow.

**G025 — lifecycle artifact cross-check** (artifact-driven, mirrors
G011/G017/G021): the serve artifact's ``lifecycle`` block (the
lifecycle sanitizer's transition/acquire counters) is the runtime
ground truth.  A declared machine/resource the run never touched is
DEAD (scoped by armed surface); a runtime machine or resource with no
static declaration, and unattributed runtime transitions, are model
escapes — all findings.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field

from .core import Finding, FuncInfo, ModuleInfo, PackageIndex
from .lifecycle_sanitizer import KNOWN_MACHINES, KNOWN_RESOURCES
from .threads import load_artifact_block

_STATE_RE = re.compile(
    r"#\s*graftlint:\s*state=([a-zA-Z0-9_-]+)([^#]*)"
)
_FIELD_RE = re.compile(r"\bfield=([A-Za-z_][A-Za-z0-9_]*)")
_STATES_RE = re.compile(r"\bstates=([A-Za-z0-9_,]+)")
_EDGES_RE = re.compile(r"\bedges=([A-Za-z0-9_>,\-]+)")
_TRANS_RE = re.compile(
    r"#\s*graftlint:\s*transition=([a-zA-Z0-9_-]+):([A-Za-z0-9_>,\-]+)"
)
_ACQ_RE = re.compile(r"#\s*graftlint:\s*acquire=([a-zA-Z0-9_-]+)")
_REL_RE = re.compile(r"#\s*graftlint:\s*release=([a-zA-Z0-9_-]+)")

#: Armed-surface scoping for the G025 dead checks, the
#: PROTOCOL_SURFACES pattern: a machine/resource is only expected to
#: have runtime entries when the run armed the surface it lives on.
MACHINE_SURFACES = {
    "doc": "pool",
    "spool": "pool",
    "row": "reshard",
    "stream": "stream",
    "session": "ingest",
}
RESOURCE_SURFACES = {
    "rows": "pool",
    "spool": "pool",
    "stream": "stream",
    "segment": "journal",
    "socket": "ingest",
    "thread": "prefetch",
}


def _parse_edges(spec: str) -> tuple[list[tuple[str, str]], list[str]]:
    """``a->b,c->d`` as edge pairs + the malformed chunks."""
    edges, bad = [], []
    for chunk in spec.split(","):
        chunk = chunk.strip()
        if not chunk:
            continue
        parts = chunk.split("->")
        if len(parts) == 2 and parts[0] and parts[1]:
            edges.append((parts[0], parts[1]))
        else:
            bad.append(chunk)
    return edges, bad


@dataclass
class MachineDecl:
    name: str
    module: ModuleInfo
    cls: str | None
    line: int
    col: int
    field_name: str | None = None
    states: frozenset | None = None
    edges: frozenset | None = None


@dataclass
class TransitionDecl:
    machine: str
    edges: list
    fi: FuncInfo
    line: int


@dataclass
class LifecycleModel:
    machines: dict = field(default_factory=dict)  # name -> MachineDecl
    transitions: list = field(default_factory=list)
    acquires: dict = field(default_factory=dict)  # res -> [FuncInfo]
    releases: dict = field(default_factory=dict)
    #: (module path, class name) pairs carrying ANY lifecycle marker —
    #: the G024 pair-counter jurisdiction.
    marked_classes: set = field(default_factory=set)
    #: findings produced during parsing (malformed specs, unknown
    #: vocabulary) — surfaced by G022.
    parse_findings: list = field(default_factory=list)


def _class_decls(m: ModuleInfo):
    """Every ClassDef in the module (nested included), in order."""
    out = []

    def visit(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                out.append(child)
                visit(child)
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                visit(child)

    visit(m.tree)
    return out


def build_model(index: PackageIndex) -> LifecycleModel:
    # G022/G023/G024 all start from the same marker scan; memoize it
    # on the index (one lint run = one index) so the gate pays for the
    # comment sweep once, not once per rule.
    cached = getattr(index, "_lifecycle_model", None)
    if cached is not None:
        return cached
    model = LifecycleModel()
    for m in index.modules:
        cls_lines = {c.lineno: c for c in _class_decls(m)}
        for lineno, text in sorted(m.comments.items()):
            for sm in _STATE_RE.finditer(text):
                name, tail = sm.group(1), sm.group(2)
                node = cls_lines.get(lineno)
                cls = node.name if node is not None else None
                col = node.col_offset if node is not None else 0
                decl = MachineDecl(
                    name=name, module=m, cls=cls, line=lineno, col=col,
                )
                if name not in KNOWN_MACHINES:
                    model.parse_findings.append(Finding(
                        rule="G022", path=m.path, line=lineno, col=col,
                        msg=(
                            f"unknown state machine `{name}` — the "
                            "lifecycle model only knows "
                            f"{'/'.join(KNOWN_MACHINES)}; a typo'd "
                            "machine silently detaches every "
                            "transition declared for it"
                        ),
                    ))
                fm = _FIELD_RE.search(tail)
                if fm:
                    decl.field_name = fm.group(1)
                stm = _STATES_RE.search(tail)
                if stm:
                    decl.states = frozenset(
                        s for s in stm.group(1).split(",") if s
                    )
                em = _EDGES_RE.search(tail)
                if em:
                    edges, bad = _parse_edges(em.group(1))
                    decl.edges = frozenset(edges)
                    for b in bad:
                        model.parse_findings.append(Finding(
                            rule="G022", path=m.path, line=lineno,
                            col=col,
                            msg=(
                                f"malformed edge `{b}` in machine "
                                f"`{name}`'s declared graph (want "
                                "`from->to`)"
                            ),
                        ))
                if name not in model.machines:
                    model.machines[name] = decl
                if cls is not None:
                    model.marked_classes.add((m.path, cls))
        for fi in m.functions.values():
            text = m.comments.get(fi.node.lineno, "")
            if not text:
                continue
            for tm in _TRANS_RE.finditer(text):
                machine, spec = tm.group(1), tm.group(2)
                edges, bad = _parse_edges(spec)
                for b in bad:
                    model.parse_findings.append(Finding(
                        rule="G022", path=m.path, line=fi.node.lineno,
                        col=fi.node.col_offset,
                        msg=(
                            f"malformed transition edge `{b}` on "
                            f"`{fi.qualname}` (want `from->to`)"
                        ),
                    ))
                model.transitions.append(TransitionDecl(
                    machine=machine, edges=edges, fi=fi,
                    line=fi.node.lineno,
                ))
                if fi.cls is not None:
                    model.marked_classes.add((m.path, fi.cls))
            for am in _ACQ_RE.finditer(text):
                res = am.group(1)
                model.acquires.setdefault(res, []).append(fi)
                if res not in KNOWN_RESOURCES:
                    model.parse_findings.append(Finding(
                        rule="G023", path=m.path, line=fi.node.lineno,
                        col=fi.node.col_offset,
                        msg=(
                            f"unknown resource `{res}` in acquire "
                            "marker — the ownership model only knows "
                            f"{'/'.join(KNOWN_RESOURCES)}"
                        ),
                    ))
                if fi.cls is not None:
                    model.marked_classes.add((m.path, fi.cls))
            for rm in _REL_RE.finditer(text):
                res = rm.group(1)
                model.releases.setdefault(res, []).append(fi)
                if res not in KNOWN_RESOURCES:
                    model.parse_findings.append(Finding(
                        rule="G023", path=m.path, line=fi.node.lineno,
                        col=fi.node.col_offset,
                        msg=(
                            f"unknown resource `{res}` in release "
                            "marker — the ownership model only knows "
                            f"{'/'.join(KNOWN_RESOURCES)}"
                        ),
                    ))
                if fi.cls is not None:
                    model.marked_classes.add((m.path, fi.cls))
    index._lifecycle_model = model
    return model


# ---------------------------------------------------------------------------
# G022 — state-machine discipline
# ---------------------------------------------------------------------------


def g022_state_discipline(index: PackageIndex) -> list[Finding]:
    model = build_model(index)
    out = [f for f in model.parse_findings if f.rule == "G022"]
    by_machine: dict[str, list[TransitionDecl]] = {}
    for t in model.transitions:
        by_machine.setdefault(t.machine, []).append(t)

    for t in model.transitions:
        decl = model.machines.get(t.machine)
        if decl is None:
            out.append(Finding(
                rule="G022", path=t.fi.module.path, line=t.line,
                col=t.fi.node.col_offset,
                msg=(
                    f"transition marker on `{t.fi.qualname}` names "
                    f"machine `{t.machine}` but no class declares it "
                    "(`# graftlint: state=...`) — orphaned transition"
                ),
            ))
            continue
        for frm, to in t.edges:
            if decl.states is not None:
                for endpoint in (frm, to):
                    if endpoint not in decl.states:
                        out.append(Finding(
                            rule="G022", path=t.fi.module.path,
                            line=t.line, col=t.fi.node.col_offset,
                            msg=(
                                f"transition `{frm}->{to}` on "
                                f"`{t.fi.qualname}` uses state "
                                f"`{endpoint}` outside machine "
                                f"`{t.machine}`'s declared vocabulary "
                                f"{sorted(decl.states)}"
                            ),
                        ))
            if decl.edges is not None and (frm, to) not in decl.edges:
                out.append(Finding(
                    rule="G022", path=t.fi.module.path, line=t.line,
                    col=t.fi.node.col_offset,
                    msg=(
                        f"illegal `{t.machine}` transition "
                        f"`{frm}->{to}` on `{t.fi.qualname}`: not an "
                        "edge of the declared graph "
                        f"{sorted('->'.join(e) for e in decl.edges)} — "
                        "an undeclared edge is how a doc got migrated "
                        "straight out of GENESIS"
                    ),
                ))

    # direct writes to a declared state field outside its transition
    # functions, within the machine's jurisdiction
    for name, decl in sorted(model.machines.items()):
        if decl.field_name is None:
            continue
        jurisdiction = {decl.module.path}
        allowed: set[int] = set()
        for t in by_machine.get(name, ()):
            jurisdiction.add(t.fi.module.path)
            allowed.add(id(t.fi.node))
        for m in index.modules:
            if m.path not in jurisdiction:
                continue
            for fi in m.functions.values():
                if id(fi.node) in allowed:
                    continue
                if fi.qualname.split(".")[-1] == "__init__":
                    continue
                for node in ast.walk(fi.node):
                    targets = ()
                    if isinstance(node, ast.Assign):
                        targets = node.targets
                    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                        targets = (node.target,)
                    for tgt in targets:
                        for leaf in ast.walk(tgt):
                            if (
                                isinstance(leaf, ast.Attribute)
                                and leaf.attr == decl.field_name
                            ):
                                out.append(Finding(
                                    rule="G022", path=m.path,
                                    line=node.lineno,
                                    col=node.col_offset,
                                    msg=(
                                        "direct write to state field "
                                        f"`.{decl.field_name}` of "
                                        f"machine `{name}` outside a "
                                        "declared transition function "
                                        f"(`{fi.qualname}`) — route it "
                                        "through a `# graftlint: "
                                        f"transition={name}:...` "
                                        "function so the edge is "
                                        "declared and counted"
                                    ),
                                ))
    return out


# ---------------------------------------------------------------------------
# G023 — acquire/release pairing
# ---------------------------------------------------------------------------


def _marker_map(model: LifecycleModel) -> dict[int, list]:
    """id(FuncInfo.node) -> [("acq"|"rel", resource)] for primitives."""
    marks: dict[int, list] = {}
    for res, fis in model.acquires.items():
        for fi in fis:
            marks.setdefault(id(fi.node), []).append(("acq", res))
    for res, fis in model.releases.items():
        for fi in fis:
            marks.setdefault(id(fi.node), []).append(("rel", res))
    return marks


def _bare_name_fallback(model: LifecycleModel) -> dict[str, tuple]:
    """bare function name -> its unique ("acq"|"rel", resource), for
    attribute calls the strict resolver cannot see through
    (``self.prefetcher.stop()``).  Ambiguous names resolve to
    nothing — precision over recall, same reasoning as strict
    resolve_call."""
    seen: dict[str, set] = {}
    for kind, table in (("acq", model.acquires),
                        ("rel", model.releases)):
        for res, fis in table.items():
            for fi in fis:
                bare = fi.qualname.split(".")[-1]
                seen.setdefault(bare, set()).add((kind, res))
    return {
        name: next(iter(kinds))
        for name, kinds in seen.items() if len(kinds) == 1
    }


@dataclass
class _Event:
    kind: str  # "acq" | "rel"
    resource: str
    call: ast.Call
    stmt: ast.stmt
    in_finally: bool


def _collect_events(fi: FuncInfo, index: PackageIndex,
                    marks: dict[int, list],
                    fallback: dict[str, tuple],
                    candidates: frozenset) -> list[_Event]:
    events: list[_Event] = []

    def calls_of(stmt: ast.stmt):
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                yield node

    def classify(call: ast.Call) -> list[tuple]:
        # cheap bare-name prefilter: resolve_call only when the callee
        # name could possibly be a marked primitive
        f = call.func
        name = (
            f.id if isinstance(f, ast.Name)
            else f.attr if isinstance(f, ast.Attribute) else None
        )
        if name is None or name not in candidates:
            return []
        hits = []
        for callee in index.resolve_call(call, fi, strict=True):
            hits.extend(marks.get(id(callee.node), ()))
        if not hits and isinstance(call.func, ast.Attribute):
            fb = fallback.get(call.func.attr)
            if fb is not None:
                hits.append(fb)
        return list(dict.fromkeys(hits))

    def calls_of_shallow(s):
        """Calls in a control statement's own header (test / iter /
        with-items), not its body — bodies recurse separately so Try
        nesting keeps its finally tagging."""
        headers = []
        if isinstance(s, (ast.If, ast.While)):
            headers.append(s.test)
        elif isinstance(s, ast.For):
            headers.extend([s.target, s.iter])
        elif isinstance(s, ast.With):
            for item in s.items:
                headers.append(item.context_expr)
        for h in headers:
            for node in ast.walk(h):
                if isinstance(node, ast.Call):
                    yield node

    def ordered(stmts, in_finally: bool, sink: list[_Event]):
        for s in stmts:
            if isinstance(s, ast.Try):
                # handlers are the crash paths — G023 checks the
                # non-crash paths (the crash windows belong to the fs
                # crash-enumeration harness); finally-releases cover
                # every exit, so they are tagged
                ordered(s.body, in_finally, sink)
                ordered(s.orelse, in_finally, sink)
                ordered(s.finalbody, True, sink)
            elif isinstance(s, ast.If) and s.orelse:
                for call in calls_of_shallow(s):
                    for kind, res in classify(call):
                        sink.append(
                            _Event(kind, res, call, s, in_finally)
                        )
                # if/else are ALTERNATIVE paths: linearizing both
                # would double-count an either-way release (a migrate
                # batch that releases the source row on both the
                # row-to-row and the demote branch is balanced, not a
                # double release).  Keep the heavier branch — ties go
                # to the if-body, so a branch-local acquire stays
                # visible to the leak check.
                body_ev: list[_Event] = []
                else_ev: list[_Event] = []
                ordered(s.body, in_finally, body_ev)
                ordered(s.orelse, in_finally, else_ev)
                sink.extend(
                    body_ev if len(body_ev) >= len(else_ev) else else_ev
                )
            elif isinstance(s, (ast.If, ast.For, ast.While, ast.With)):
                for call in calls_of_shallow(s):
                    for kind, res in classify(call):
                        sink.append(
                            _Event(kind, res, call, s, in_finally)
                        )
                ordered(s.body, in_finally, sink)
                ordered(getattr(s, "orelse", []) or [], in_finally, sink)
            else:
                for call in calls_of(s):
                    for kind, res in classify(call):
                        sink.append(
                            _Event(kind, res, call, s, in_finally)
                        )

    ordered(fi.node.body, False, events)
    return events


def _escape_names(fi: FuncInfo) -> set[str]:
    """Names whose value leaves the function's ownership: returned,
    stored into an attribute/subscript, or passed to another call."""
    out: set[str] = set()
    for node in ast.walk(fi.node):
        if isinstance(node, ast.Return) and node.value is not None:
            for leaf in ast.walk(node.value):
                if isinstance(leaf, ast.Name):
                    out.add(leaf.id)
        elif isinstance(node, ast.Assign):
            if any(
                isinstance(t, (ast.Attribute, ast.Subscript))
                or any(
                    isinstance(e, (ast.Attribute, ast.Subscript))
                    for e in ast.walk(t)
                )
                for t in node.targets
            ):
                for leaf in ast.walk(node.value):
                    if isinstance(leaf, ast.Name):
                        out.add(leaf.id)
        elif isinstance(node, ast.Call):
            for a in list(node.args) + [kw.value for kw in node.keywords]:
                for leaf in ast.walk(a):
                    if isinstance(leaf, ast.Name):
                        out.add(leaf.id)
    return out


def _acquire_escapes(ev: _Event, fi: FuncInfo,
                     escaped: set[str]) -> bool:
    # handle-by-argument acquire (``take_row(row)``): the resource's
    # identity is an argument the caller's bookkeeping chose, so when
    # that handle is itself stored beyond the frame (or IS an attribute
    # load) the ownership record outlives the function — the release
    # lives wherever the record does
    for a in list(ev.call.args) + [kw.value for kw in ev.call.keywords]:
        for leaf in ast.walk(a):
            if isinstance(leaf, ast.Attribute):
                return True
            if isinstance(leaf, ast.Name) and leaf.id in escaped:
                return True
    stmt = ev.stmt
    if isinstance(stmt, ast.Return):
        return True
    if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
        targets = (
            stmt.targets if isinstance(stmt, ast.Assign)
            else [stmt.target]
        )
        for t in targets:
            for leaf in ast.walk(t):
                if isinstance(leaf, (ast.Attribute, ast.Subscript)):
                    return True  # stored beyond the frame
                if isinstance(leaf, ast.Name) and leaf.id in escaped:
                    return True
        return False
    if isinstance(stmt, ast.Expr) and stmt.value is ev.call:
        return False  # bare call, result dropped on the floor
    # the acquire feeds a larger expression (wrapped in another call,
    # a condition, a comprehension) — ownership moved, stay silent
    return True


def g023_acquire_release(index: PackageIndex) -> list[Finding]:
    model = build_model(index)
    out = [f for f in model.parse_findings if f.rule == "G023"]
    for res, fis in sorted(model.acquires.items()):
        if res in KNOWN_RESOURCES and res not in model.releases:
            fi = fis[0]
            out.append(Finding(
                rule="G023", path=fi.module.path, line=fi.node.lineno,
                col=fi.node.col_offset,
                msg=(
                    f"resource `{res}` has an acquire marker but no "
                    "release marker anywhere in the lint scope — an "
                    "unpaired acquire is a leak by construction"
                ),
            ))
    for res, fis in sorted(model.releases.items()):
        if res in KNOWN_RESOURCES and res not in model.acquires:
            fi = fis[0]
            out.append(Finding(
                rule="G023", path=fi.module.path, line=fi.node.lineno,
                col=fi.node.col_offset,
                msg=(
                    f"resource `{res}` has a release marker but no "
                    "acquire marker anywhere in the lint scope — a "
                    "release without a matching acquire protocol"
                ),
            ))
    marks = _marker_map(model)
    fallback = _bare_name_fallback(model)
    if not marks:
        return out
    candidates = frozenset(
        fi.qualname.split(".")[-1]
        for table in (model.acquires, model.releases)
        for fis in table.values() for fi in fis
    )
    for m in index.modules:
        for fi in m.functions.values():
            if id(fi.node) in marks:
                continue  # primitives are trusted, not analyzed
            events = _collect_events(fi, index, marks, fallback,
                                     candidates)
            if not events:
                continue
            escaped = _escape_names(fi)
            resources = sorted({e.resource for e in events})
            for res in resources:
                evs = [e for e in events if e.resource == res]
                acqs = [e for e in evs if e.kind == "acq"]
                if not acqs:
                    # release-only function: legal cleanup — unless
                    # the SAME release is issued twice verbatim (the
                    # duplicate-GC-enqueue shape)
                    seen_dumps: dict[str, _Event] = {}
                    for e in evs:
                        d = ast.dump(e.call)
                        if d in seen_dumps:
                            out.append(Finding(
                                rule="G023", path=m.path,
                                line=e.call.lineno,
                                col=e.call.col_offset,
                                msg=(
                                    f"double release of `{res}`: this "
                                    "call repeats an identical release "
                                    f"on line "
                                    f"{seen_dumps[d].call.lineno} — "
                                    "the second one fires on an "
                                    "already-dead resource"
                                ),
                            ))
                        else:
                            seen_dumps[d] = e
                    continue
                balance = 0
                finally_covered = any(
                    e.kind == "rel" and e.in_finally for e in evs
                )
                for e in evs:
                    if e.kind == "acq":
                        balance += 1
                    else:
                        if balance == 0 and any(
                            isinstance(leaf, ast.Attribute)
                            for a in (list(e.call.args)
                                      + [kw.value for kw in e.call.keywords])
                            for leaf in ast.walk(a)
                        ):
                            # the handle is an attribute load (a record
                            # field, not a local this frame acquired):
                            # cross-frame ownership release, legal
                            # without a local dominating acquire
                            continue
                        balance -= 1
                        if balance < 0:
                            out.append(Finding(
                                rule="G023", path=m.path,
                                line=e.call.lineno,
                                col=e.call.col_offset,
                                msg=(
                                    f"release of `{res}` without a "
                                    "dominating acquire in "
                                    f"`{fi.qualname}` — on the path "
                                    "walked this is a double release"
                                ),
                            ))
                            balance = 0
                if balance > 0 and not finally_covered:
                    if not any(
                        _acquire_escapes(e, fi, escaped) for e in acqs
                    ):
                        e = acqs[0]
                        out.append(Finding(
                            rule="G023", path=m.path,
                            line=e.call.lineno, col=e.call.col_offset,
                            msg=(
                                f"`{res}` acquired in "
                                f"`{fi.qualname}` is never released "
                                "on the fall-off path and never "
                                "escapes the frame (not returned, "
                                "stored, or handed off) — leaked on "
                                "every non-crash exit"
                            ),
                        ))
    return out


# ---------------------------------------------------------------------------
# G024 — identity/generation hazards
# ---------------------------------------------------------------------------

_KEYED_METHODS = ("get", "setdefault", "pop")

#: Text prefilter for the id-key scan: a module with no ``id(`` call
#: anywhere cannot hold the hazard, and skipping its AST walk keeps
#: the tier-1 stage-0 gate fast.
_ID_CALL_RE = re.compile(r"\bid\(")


def _is_id_call(node) -> bool:
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Name)
        and node.func.id == "id"
        and len(node.args) == 1
    )


def _id_key_hazard(key: ast.expr) -> ast.Call | None:
    """The bare ``id(...)`` call used as a map key, or None when the
    key is safe (no id() at all, or id() inside a >=2-element tuple —
    the generation component defeats recycling)."""
    if _is_id_call(key):
        return key
    if isinstance(key, ast.Tuple):
        if len(key.elts) >= 2:
            return None  # (id(x), gen) carries a generation component
        for e in key.elts:
            if _is_id_call(e):
                return e
    return None


def g024_identity_hazards(index: PackageIndex) -> list[Finding]:
    out: list[Finding] = []
    for m in index.modules:
        if not _ID_CALL_RE.search(m.src):
            continue
        for node in ast.walk(m.tree):
            # jurisdiction: maps held in ATTRIBUTES (self._cache /
            # obj.table) — the long-lived caches id recycling poisons.
            # A function-local table keyed by id() while its objects
            # are pinned for one pass (the linter's own walk sets) is
            # the legal identity idiom and stays out of scope.
            hazard = None
            if isinstance(node, ast.Subscript) and isinstance(
                node.value, ast.Attribute
            ):
                hazard = _id_key_hazard(node.slice)
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _KEYED_METHODS
                and isinstance(node.func.value, ast.Attribute)
                and node.args
            ):
                hazard = _id_key_hazard(node.args[0])
            if hazard is not None:
                out.append(Finding(
                    rule="G024", path=m.path, line=hazard.lineno,
                    col=hazard.col_offset,
                    msg=(
                        "map keyed by bare `id(...)`: CPython recycles "
                        "a freed object's id, so a later allocation "
                        "can silently hit the dead entry (the PR 17 "
                        "cache poisoning) — key by identity that "
                        "cannot recycle, or add a generation "
                        "component (`(id(x), gen)`)"
                    ),
                ))
    model = build_model(index)
    out.extend(_pair_counter_hazards(index, model))
    return out


def _guarding_test(test: ast.expr) -> bool:
    """A conditional test that plausibly protects a decrement under
    it: a membership / identity / positivity comparison (`in`, `not
    in`, `is`, `is not`, `>`, `>=`) or any attribute read (the
    `if self.x:` truthiness shape) — the guard classes the prefetch
    fix used.  A plain boolean flag or `==` test does not count."""
    for leaf in ast.walk(test):
        if isinstance(leaf, ast.Compare) and any(
            isinstance(op, (ast.In, ast.NotIn, ast.Is, ast.IsNot,
                            ast.Gt, ast.GtE))
            for op in leaf.ops
        ):
            return True
        if isinstance(leaf, ast.Attribute):
            return True
    return False


def _membership_filter_line(fi: FuncInfo) -> int | None:
    """The line of an `if x in ...: ... continue/return` filter — the
    prefetch drain's reaped-seq dedup — which guards every later
    decrement in the same function."""
    for node in ast.walk(fi.node):
        if not isinstance(node, ast.If):
            continue
        has_membership = any(
            isinstance(op, (ast.In, ast.NotIn))
            for leaf in ast.walk(node.test)
            if isinstance(leaf, ast.Compare)
            for op in leaf.ops
        )
        bails = any(
            isinstance(b, (ast.Continue, ast.Return))
            for b in ast.walk(node)
        )
        if has_membership and bails:
            return node.lineno
    return None


def _pair_counter_hazards(index: PackageIndex,
                          model: LifecycleModel) -> list[Finding]:
    out: list[Finding] = []
    for m in index.modules:
        classes = {
            cls for path, cls in model.marked_classes if path == m.path
        }
        if not classes:
            continue
        incs: dict[str, list] = {}  # attr -> inc sites
        decs: dict[str, list] = {}  # attr -> (site, guarded, fi)
        for fi in m.functions.values():
            if fi.cls not in classes:
                continue
            filter_line = _membership_filter_line(fi)

            def scan(stmts, guarded: bool):
                for s in stmts:
                    if isinstance(s, ast.AugAssign) and isinstance(
                        s.target, ast.Attribute
                    ) and isinstance(s.target.value, ast.Name) \
                            and s.target.value.id == "self":
                        attr = s.target.attr
                        if isinstance(s.op, ast.Add):
                            incs.setdefault(attr, []).append(s)
                        elif isinstance(s.op, ast.Sub):
                            g = guarded or (
                                filter_line is not None
                                and filter_line < s.lineno
                            )
                            decs.setdefault(attr, []).append(
                                (s, g, fi)
                            )
                    if isinstance(s, ast.If):
                        scan(s.body,
                             guarded or _guarding_test(s.test))
                        scan(s.orelse, guarded)
                    elif isinstance(s, (ast.For, ast.While, ast.With)):
                        scan(s.body, guarded)
                        scan(getattr(s, "orelse", []) or [], guarded)
                    elif isinstance(s, ast.Try):
                        scan(s.body, guarded)
                        for h in s.handlers:
                            scan(h.body, guarded)
                        scan(s.orelse, guarded)
                        scan(s.finalbody, guarded)

            scan(fi.node.body, False)
        for attr in sorted(set(incs) & set(decs)):
            for s, guarded, fi in decs[attr]:
                if not guarded:
                    out.append(Finding(
                        rule="G024", path=m.path, line=s.lineno,
                        col=s.col_offset,
                        msg=(
                            f"paired counter `self.{attr}` is "
                            "decremented without an underflow guard "
                            f"in `{fi.qualname}` — an inc/dec "
                            "imbalance drives it negative (the "
                            "prefetch inflight underflow); clamp with "
                            "max(0, ...), test positivity, or filter "
                            "duplicates before the decrement"
                        ),
                    ))
    return out


# ---------------------------------------------------------------------------
# G025 — lifecycle artifact cross-check
# ---------------------------------------------------------------------------


def g025_lifecycle_artifact(index: PackageIndex, artifact_path: str
                            ) -> list[Finding]:
    """Cross-validate the declared lifecycle model against a serve
    run's ``lifecycle`` counters (the lifecycle sanitizer's ground
    truth): a declared machine/resource the run never touched is DEAD
    — the annotation is stale or the transition path moved; a runtime
    machine/resource (or an unattributed transition) with no matching
    static declaration is lifecycle activity the model does not know
    about.  Dead-checking is scoped by armed surface exactly like
    G011 fence tags and G021 protocol surfaces."""
    block, err = load_artifact_block(artifact_path, "lifecycle")
    if block is None:
        return [Finding(
            rule="G025", path=artifact_path, line=0, col=0, msg=err,
        )]
    out: list[Finding] = []
    version = block.get("version")
    if version != 1:
        out.append(Finding(
            rule="G025", path=artifact_path, line=0, col=0,
            msg=(
                f"lifecycle block version {version!r} is not the "
                "schema this rule validates (want 1) — regenerate the "
                "artifact or update the cross-check together with the "
                "schema"
            ),
        ))
        return out
    machines = block.get("machines") or {}
    resources = block.get("resources") or {}
    unattributed = block.get("unattributed") or []
    model = build_model(index)
    base = os.path.basename(artifact_path)
    for name, decl in sorted(model.machines.items()):
        surface = MACHINE_SURFACES.get(name)
        if surface is None:
            continue  # unknown machine: G022's finding, not G025's
        if surface not in block:
            out.append(Finding(
                rule="G025", path=decl.module.path, line=decl.line,
                col=decl.col,
                msg=(
                    f"machine `{name}` is scoped to surface "
                    f"`{surface}` but {base} records no such surface "
                    "— stale lifecycle schema or typo'd surface map; "
                    "an unmatchable surface silently disables the "
                    "dead-machine check"
                ),
            ))
            continue
        if not block.get(surface):
            continue  # surface not armed in this run
        if not machines.get(name):
            out.append(Finding(
                rule="G025", path=decl.module.path, line=decl.line,
                col=decl.col,
                msg=(
                    f"declared machine `{name}` recorded zero "
                    f"transitions in {base} (surface `{surface}` "
                    "armed) — dead machine: delete the stale "
                    "declaration or route the real state writes "
                    "through its transition functions"
                ),
            ))
    declared_res = {
        r for r in set(model.acquires) | set(model.releases)
        if r in KNOWN_RESOURCES
    }
    for res in sorted(declared_res):
        fis = model.acquires.get(res) or model.releases.get(res)
        fi = fis[0]
        surface = RESOURCE_SURFACES[res]
        if surface not in block:
            out.append(Finding(
                rule="G025", path=fi.module.path, line=fi.node.lineno,
                col=fi.node.col_offset,
                msg=(
                    f"resource `{res}` is scoped to surface "
                    f"`{surface}` but {base} records no such surface "
                    "— stale lifecycle schema or typo'd surface map"
                ),
            ))
            continue
        if not block.get(surface):
            continue
        if not resources.get(res):
            out.append(Finding(
                rule="G025", path=fi.module.path, line=fi.node.lineno,
                col=fi.node.col_offset,
                msg=(
                    f"declared resource `{res}` recorded zero "
                    f"acquire/release events in {base} (surface "
                    f"`{surface}` armed) — dead ownership protocol: "
                    "delete the stale markers or route the real "
                    "alloc/free path through them"
                ),
            ))
    for name in sorted(machines):
        if name not in model.machines:
            out.append(Finding(
                rule="G025", path=artifact_path, line=0, col=0,
                msg=(
                    f"runtime machine `{name}` has no matching "
                    "`# graftlint: state=` declaration — state "
                    "activity the static lifecycle model does not "
                    "know about"
                ),
            ))
    for res in sorted(resources):
        if res not in set(model.acquires) | set(model.releases):
            out.append(Finding(
                rule="G025", path=artifact_path, line=0, col=0,
                msg=(
                    f"runtime resource `{res}` has no matching "
                    "`# graftlint: acquire=`/`release=` marker — "
                    "ownership activity the static model does not "
                    "know about"
                ),
            ))
    for entry in sorted(set(unattributed)):
        out.append(Finding(
            rule="G025", path=artifact_path, line=0, col=0,
            msg=(
                f"unattributed runtime transition `{entry}` — the "
                "sanitizer saw an edge on a machine no "
                "declare_machine() registered; declare the machine "
                "or remove the stray transition call"
            ),
        ))
    return out
