"""graftlint core: file model, suppressions, rule driver, reporters.

graftlint is an AST-based JAX-hygiene linter for this repository (stdlib
``ast`` only — it must run in CI before anything heavy imports).  The
design is deliberately small:

- every ``.py`` file is parsed once into a :class:`ModuleInfo` (AST +
  per-function facts: jit decoration, donation, boundary contracts,
  hot-path / fence markers, suppression comments);
- the :class:`PackageIndex` aggregates modules so rules can resolve
  cross-module calls by name (best-effort, the repo's idiom is flat
  enough for this to work);
- each rule in :mod:`crdt_benches_tpu.lint.rules` is a function
  ``rule(index) -> list[Finding]``;
- findings carrying a same-line ``# graftlint: disable=G00X`` (or a
  file-level ``# graftlint: disable-file=G00X``) are dropped.

Marker comments (on the ``def`` line):

- ``# graftlint: hot-path`` — the function is a serving hot-path root:
  G002 walks its call graph for host syncs;
- ``# graftlint: fence`` — the function is a DECLARED sync boundary
  (e.g. the scheduler's boundary bucket pulls): G002 does not descend
  into it.  Fences are the allowlist — a new sync belongs behind one, or
  it is a bug.
- ``# graftlint: thread=<name>`` — the function (or, on a ``class``
  line, every method of the class) is OWNED by that host thread
  (``hot`` / ``status`` / ``bus`` / ``journal`` are the canonical
  roots).  The thread-confinement rules (G014/G015, lint/threads.py)
  propagate ownership along the call graph from these declarations;
  a mutable object shared across two owners must cross at a publish
  point.
- ``# graftlint: publish`` (optionally ``publish=<tag>``) — the
  function is a DECLARED cross-thread publish point: an atomic
  reference swap (or lock-guarded section) that hands an object from
  its owning thread to a reader thread.  The runtime twin
  (lint/race_sanitizer.py ``@published``) counts its entries; G017
  cross-validates the two like G011 does for fences.  A tag names the
  armed surface the point rides (``publish=status`` crosses only when
  the live status server runs) and scopes the dead-point accounting
  to artifacts whose run armed it.
- ``# graftlint: durable=<protocol>`` — the function is a DECLARED
  member of a multi-step durable commit protocol (``snapshot`` / ``gc``
  / ``wal`` / ``spool`` / ``flight``).  The crash-consistency rules
  (G018-G020, lint/fsops.py) build a per-protocol filesystem-effect
  sequence (write/fsync/replace/link/unlink over path-role symbols)
  from these declarations and check atomic-commit discipline, durable
  ordering, and verify-before-trust; the runtime twin
  (lint/fs_sanitizer.py ``fs_protocol``) counts entries and records
  the real op sequences, and G021 cross-validates the two like G011
  does for fences.

Fence tags (``# graftlint: fence=<tag>``) scope the G011 dead-fence
accounting against serve bench artifacts:

- bare ``fence`` — expected to cross in EVERY serve drain; a zero
  counter in a ``boundary_syncs`` artifact block is a G011 finding;
- ``fence=chaos`` — crosses only under fault injection; accounted only
  against chaos artifacts;
- ``fence=journal`` — crosses only with the write-ahead journal on;
  accounted only against journaled artifacts;
- ``fence=flight`` — crosses only when the flight recorder DUMPED
  (``boundary_syncs.flight``); even an armed recorder on a clean
  chaos run never enters it, so chaos-scoping would false-positive;
- ``fence=reshard`` — crosses only with a live-reshard coordinator
  bound (``boundary_syncs.reshard``); the per-round tick and the
  end-of-drain finalize are the two declared boundaries;
- ``fence=cold`` — an off-drain API boundary (direct pool calls from
  tests/tools): still a G002 barrier, never dead-fence accounted.
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass

# ---------------------------------------------------------------------------
# configuration

#: G002 hot-path roots that hold even on an unannotated tree (qualnames).
DEFAULT_HOT_ROOTS = {
    "fleet_step",
    "DocPool.step",
    "DocPool.macro_step",
    "FleetScheduler.run_round",
}

#: Method names never linked by the bare-name call resolver (container /
#: stdlib traffic would otherwise swamp the call graph).
_GENERIC_METHODS = {
    "append", "add", "get", "pop", "popleft", "items", "keys", "values",
    "update", "extend", "sort", "clear", "copy", "discard", "remove",
    "insert", "index", "count", "join", "split", "strip", "format",
    "startswith", "endswith", "setdefault", "write", "read", "close",
    "open", "mkdir", "exists", "unlink", "encode", "decode", "flush",
    "reshape", "astype", "sum", "max", "min", "mean", "all", "any",
    "fire", "pick", "event", "describe", "bit_length", "put", "take",
    "dump", "dumps", "load", "loads",
}

#: Directories whose modules are in scope for G005 (implicit dtype) and
#: G006 (nondeterminism in journaled paths).
G005_DIRS = ("ops", "engine", "serve", "parallel", "traces")
G006_DIRS = ("serve",)
G006_FILES = ("tensorize.py",)

#: Recognized dtype spellings for "an explicit dtype was passed".
DTYPE_NAMES = {
    "int8", "int16", "int32", "int64", "uint8", "uint16", "uint32",
    "uint64", "float16", "float32", "float64", "bfloat16", "bool_",
    "complex64", "complex128",
}

_SUPPRESS_RE = re.compile(r"#\s*graftlint:\s*disable=([A-Z0-9,\s]+)")
_SUPPRESS_FILE_RE = re.compile(
    r"#\s*graftlint:\s*disable-file=([A-Z0-9,\s]+)"
)
_MARKER_RE = re.compile(
    r"#\s*graftlint:\s*(hot-path|fence|publish|thread|durable)"
    r"(?:=([a-zA-Z0-9_-]+))?\b"
)

#: Recognized ``fence=<tag>`` spellings (see module docstring).
FENCE_TAGS = ("chaos", "journal", "flight", "reshard", "cold")


def dotted(e: ast.expr) -> str | None:
    """``a.b.c`` as a string, or None for non-trivial expressions."""
    parts = []
    while isinstance(e, ast.Attribute):
        parts.append(e.attr)
        e = e.value
    if isinstance(e, ast.Name):
        parts.append(e.id)
        return ".".join(reversed(parts))
    return None


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    col: int
    msg: str

    def key(self):
        return (self.path, self.line, self.rule, self.msg)


@dataclass
class FuncInfo:
    """Per-function facts extracted from the decorator stack + markers."""

    qualname: str  # "func" or "Class.method"
    node: ast.AST  # FunctionDef / AsyncFunctionDef
    module: "ModuleInfo"
    cls: str | None = None
    jitted: bool = False
    donate_argnums: tuple | None = None  # statically parsed, else None
    static_argnames: tuple = ()
    boundary: dict | None = None  # parsed @boundary(...) kwargs
    boundary_line: int = 0
    hot: bool = False
    fence: bool = False
    fence_tag: str | None = None  # None|"chaos"|"journal"|"flight"|"cold"
    publish: bool = False  # declared cross-thread publish point
    publish_tag: str | None = None  # armed-surface tag (e.g. "status")
    thread: str | None = None  # declared owning thread (or class's)
    durable: bool = False  # declared durable-commit-protocol member
    protocol: str | None = None  # snapshot|gc|wal|spool|flight

    @property
    def params(self) -> list[str]:
        a = self.node.args
        return [p.arg for p in (a.posonlyargs + a.args)]


class ModuleInfo:
    def __init__(self, path: str, src: str):
        self.path = path
        self.src = src
        self.lines = src.splitlines()
        self.tree = ast.parse(src, filename=path)
        self.suppress: dict[int, set[str]] = {}
        self.suppress_file: set[str] = set()
        self.jnp_aliases: set[str] = set()  # names bound to jax.numpy
        self.np_aliases: set[str] = set()  # names bound to numpy
        self.time_aliases: set[str] = set()  # names bound to time
        self.random_aliases: set[str] = set()  # stdlib random module
        self.imports: dict[str, str] = {}  # local name -> dotted source
        self.functions: dict[str, FuncInfo] = {}
        self.class_threads: dict[str, str] = {}  # class -> thread marker
        self.class_bases: dict[str, list[str]] = {}  # class -> base names
        self._scan_comments()
        self._scan_imports()
        self._scan_functions()

    # -- comments ----------------------------------------------------------

    def _scan_comments(self) -> None:
        """Directives live in REAL comments only (tokenize, not line
        regex): a docstring that *documents* the escape hatch must not
        trigger it."""
        self.comments: dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(
                io.StringIO(self.src).readline
            ):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string
        except (tokenize.TokenError, IndentationError):
            pass  # ast.parse already surfaced the syntax problem
        for i, text in self.comments.items():
            m = _SUPPRESS_RE.search(text)
            if m:
                self.suppress.setdefault(i, set()).update(
                    r.strip() for r in m.group(1).split(",") if r.strip()
                )
            m = _SUPPRESS_FILE_RE.search(text)
            if m:
                self.suppress_file.update(
                    r.strip() for r in m.group(1).split(",") if r.strip()
                )

    def _markers(self, lineno: int) -> list[tuple[str, str | None]]:
        """All ``# graftlint: <marker>`` directives on one line (a def
        line may carry several, e.g. ``publish=status`` + ``thread=hot``
        — each with its own ``graftlint:`` prefix)."""
        return [
            (m.group(1), m.group(2))
            for m in _MARKER_RE.finditer(self.comments.get(lineno, ""))
        ]

    # -- imports -----------------------------------------------------------

    def _scan_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for al in node.names:
                    name = al.asname or al.name.split(".")[0]
                    self.imports[name] = al.name
                    if al.name == "jax.numpy":
                        self.jnp_aliases.add(al.asname or "jax.numpy")
                    elif al.name == "numpy":
                        self.np_aliases.add(al.asname or "numpy")
                    elif al.name == "time":
                        self.time_aliases.add(al.asname or "time")
                    elif al.name == "random":
                        self.random_aliases.add(al.asname or "random")
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                for al in node.names:
                    local = al.asname or al.name
                    self.imports[local] = f"{mod}.{al.name}"
                    if mod == "jax" and al.name == "numpy":
                        self.jnp_aliases.add(local)

    # -- functions ---------------------------------------------------------

    def _scan_functions(self) -> None:
        def visit(node, cls: str | None):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    self.class_bases[child.name] = [
                        b for b in (dotted(e) for e in child.bases)
                        if b is not None
                    ]
                    for kind, tag in self._markers(child.lineno):
                        if kind == "thread" and tag:
                            self.class_threads[child.name] = tag
                    visit(child, child.name)
                elif isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    qual = (
                        f"{cls}.{child.name}" if cls else child.name
                    )
                    self.functions[qual] = self._func_info(
                        child, qual, cls
                    )
                    # nested defs are part of the enclosing body for
                    # sync scanning; they are not indexed separately.

        visit(self.tree, None)

    def _func_info(self, node, qual: str, cls: str | None) -> FuncInfo:
        fi = FuncInfo(qualname=qual, node=node, module=self, cls=cls)
        for kind, tag in self._markers(node.lineno):
            if kind == "hot-path":
                fi.hot = True
            elif kind == "fence":
                fi.fence = True
                fi.fence_tag = tag
            elif kind == "publish":
                fi.publish = True
                fi.publish_tag = tag
            elif kind == "thread" and tag:
                fi.thread = tag
            elif kind == "durable":
                fi.durable = True
                fi.protocol = tag
        if fi.thread is None and cls is not None:
            fi.thread = self.class_threads.get(cls)
        for dec in node.decorator_list:
            self._parse_decorator(fi, dec)
        return fi

    def _parse_decorator(self, fi: FuncInfo, dec: ast.expr) -> None:
        # @jax.jit / @jit
        if self._is_jit_expr(dec):
            fi.jitted = True
            if fi.donate_argnums is None:
                fi.donate_argnums = ()
            return
        if not isinstance(dec, ast.Call):
            return
        # @partial(jax.jit, ...) or @functools.partial(jax.jit, ...)
        f = dec.func
        fname = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None
        )
        if fname == "partial" and dec.args and self._is_jit_expr(
            dec.args[0]
        ):
            fi.jitted = True
            fi.donate_argnums = ()
            for kw in dec.keywords:
                if kw.arg == "donate_argnums":
                    fi.donate_argnums = self._literal_tuple(kw.value)
                elif kw.arg == "static_argnames":
                    v = self._literal_tuple(kw.value)
                    fi.static_argnames = v or ()
            return
        # @jax.jit(...) used directly as a decorator factory
        if self._is_jit_expr(f):
            fi.jitted = True
            fi.donate_argnums = ()
            for kw in dec.keywords:
                if kw.arg == "donate_argnums":
                    fi.donate_argnums = self._literal_tuple(kw.value)
                elif kw.arg == "static_argnames":
                    fi.static_argnames = self._literal_tuple(kw.value) or ()
            return
        # @boundary(...)
        if fname == "boundary":
            spec: dict = {}
            for kw in dec.keywords:
                if kw.arg in ("dtypes", "shapes", "donates"):
                    spec[kw.arg] = self._literal_tuple(kw.value)
            fi.boundary = spec
            fi.boundary_line = dec.lineno

    @staticmethod
    def _is_jit_expr(e: ast.expr) -> bool:
        if isinstance(e, ast.Name):
            return e.id == "jit"
        return (
            isinstance(e, ast.Attribute)
            and e.attr == "jit"
            and isinstance(e.value, ast.Name)
            and e.value.id == "jax"
        )

    @staticmethod
    def _literal_tuple(e: ast.expr):
        """A decorator kwarg as a tuple of literals, or None when it is
        not statically evaluable (rules then skip the comparison)."""
        try:
            v = ast.literal_eval(e)
        except (ValueError, TypeError, SyntaxError):
            return None
        if isinstance(v, (list, tuple)):
            return tuple(v)
        return (v,)

    # -- helpers for rules -------------------------------------------------

    def is_jnp_attr(self, e: ast.expr) -> str | None:
        """'zeros' for an expression like ``jnp.zeros`` (any alias)."""
        if isinstance(e, ast.Attribute) and isinstance(e.value, ast.Name):
            if e.value.id in self.jnp_aliases:
                return e.attr
        return None

    def is_np_attr(self, e: ast.expr) -> str | None:
        if isinstance(e, ast.Attribute) and isinstance(e.value, ast.Name):
            if e.value.id in self.np_aliases:
                return e.attr
        return None

    def dotted(self, e: ast.expr) -> str | None:
        """``a.b.c`` as a string, or None for non-trivial expressions."""
        return dotted(e)


class PackageIndex:
    """All parsed modules + name-based cross-module call resolution."""

    def __init__(self, modules: list[ModuleInfo]):
        self.modules = modules
        self.by_name: dict[str, list[FuncInfo]] = {}
        self.methods: dict[str, dict[str, list[FuncInfo]]] = {}
        # subclass edges by bare class name (suffix-matched bases, so
        # `scheduler.FleetScheduler` links like `FleetScheduler`)
        self.subclasses: dict[str, set[str]] = {}
        self.bases: dict[str, set[str]] = {}  # reverse: class -> bases
        for m in modules:
            for fi in m.functions.values():
                bare = fi.qualname.split(".")[-1]
                self.by_name.setdefault(bare, []).append(fi)
                if fi.cls:
                    self.methods.setdefault(fi.cls, {}).setdefault(
                        bare, []
                    ).append(fi)
            for cls, bases in m.class_bases.items():
                for b in bases:
                    self.subclasses.setdefault(
                        b.split(".")[-1], set()
                    ).add(cls)
                    self.bases.setdefault(cls, set()).add(
                        b.split(".")[-1]
                    )

    def _descendants(self, cls: str) -> set[str]:
        out: set[str] = set()
        queue = [cls]
        while queue:
            c = queue.pop()
            for sub in self.subclasses.get(c, ()):
                if sub not in out:
                    out.add(sub)
                    queue.append(sub)
        return out

    def _ancestors(self, cls: str) -> list[str]:
        out: list[str] = []
        seen = {cls}
        queue = [cls]
        while queue:
            for b in sorted(self.bases.get(queue.pop(), ())):
                if b not in seen:
                    seen.add(b)
                    out.append(b)
                    queue.append(b)
        return out

    def override_methods(self, cls: str, name: str) -> list[FuncInfo]:
        """Every subclass override of ``cls.name`` in the index — a
        ``self.m()`` call in a hot-path root dispatches to the override
        when the subclass runs (ReplicatedScheduler's ``_plan`` /
        ``_deliver`` bus tick), so the hot-path walks must cover them,
        not just the statically enclosing class."""
        out = []
        for sub in sorted(self._descendants(cls)):
            out.extend(self.methods.get(sub, {}).get(name, []))
        return out

    def resolve_call(self, call: ast.Call, fi: FuncInfo,
                     strict: bool = False) -> list[FuncInfo]:
        """Best-effort callee resolution (see module docstring).

        ``strict=True`` keeps only the confident edges — same-module /
        named-import functions and ``self.m()`` dispatch (subclass
        overrides included) — and drops the any-receiver bare-name
        fan-out.  The fan-out is tuned for recall (a missed host sync
        is a silent stall, so G002 wants every plausible edge); thread-
        ownership propagation needs precision instead — one generic
        method name shared between a status handler and the scheduler
        would fuse the two thread roots and mark half the package
        bilaterally owned."""
        f = call.func
        if isinstance(f, ast.Name):
            m = fi.module
            if f.id in m.functions:
                return [m.functions[f.id]]
            # from .sibling import helper
            src = m.imports.get(f.id)
            if src is not None:
                bare = src.split(".")[-1]
                return [
                    g for g in self.by_name.get(bare, [])
                    if g.cls is None
                ]
            return []
        if isinstance(f, ast.Attribute):
            name = f.attr
            if isinstance(f.value, ast.Name) and f.value.id == "self":
                if fi.cls:
                    own = fi.module.functions.get(f"{fi.cls}.{name}")
                    if own is not None:
                        # the defining method PLUS every subclass
                        # override virtual dispatch could select
                        return [own] + self.override_methods(
                            fi.cls, name
                        )
                    # inherited: `self.m()` where m lives on an
                    # ancestor class — dispatch UP the hierarchy to
                    # the defining method, then back down through the
                    # overrides of the CALLING class (still a
                    # confident edge: the receiver is self)
                    for anc in self._ancestors(fi.cls):
                        inherited = self.methods.get(anc, {}).get(name)
                        if inherited:
                            return list(inherited) + \
                                self.override_methods(fi.cls, name)
            if strict or name in _GENERIC_METHODS:
                return []
            # obj.method(...): link every same-named package function —
            # conservative, fences/suppressions handle the rare FP.
            return self.by_name.get(name, [])
        return []


def hot_roots(index: PackageIndex) -> list[FuncInfo]:
    """The serving hot-path roots: ``# graftlint: hot-path`` markers
    plus the built-in qualname set."""
    return [
        fi for m in index.modules for fi in m.functions.values()
        if fi.hot or fi.qualname in DEFAULT_HOT_ROOTS
    ]


def walk_hot_scope(index: PackageIndex, *, descend_fences: bool):
    """THE hot-path call-graph walker shared by G002/G012/G013/G016:
    yields ``(fi, chain)`` for every function reachable from the hot
    roots via :meth:`PackageIndex.resolve_call` (subclass overrides of
    ``self.m()`` dispatches included).  ``descend_fences=False`` is the
    G002 shape (fences are declared sync boundaries, the walk stops at
    them); the hygiene rules (G012/G013/G016) descend — being behind a
    sync boundary does not make a mid-drain socket, a per-round series
    registration, or a blocking wait acceptable."""
    seen: set[int] = set()
    queue: list[tuple[FuncInfo, str]] = [
        (r, f"reached from {r.qualname}") for r in hot_roots(index)
    ]
    while queue:
        fi, chain = queue.pop()
        if id(fi) in seen:
            continue
        seen.add(id(fi))
        if not descend_fences and fi.fence:
            continue
        yield fi, chain
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Call):
                for callee in index.resolve_call(node, fi):
                    if id(callee) not in seen:
                        queue.append(
                            (callee, f"{chain} -> {callee.qualname}")
                        )


# ---------------------------------------------------------------------------
# driver

#: Directory names pruned from directory walks: the fixture corpus is
#: INTENTIONALLY dirty (linting ``tests/`` must not fail on it).  A
#: fixture file passed as an explicit path still lints.
_WALK_PRUNE = ("__pycache__", "lint_fixtures")


def collect_files(paths: list[str]) -> tuple[list[str], list[Finding]]:
    """Expand paths to .py files.  A target that does not exist (or
    names no Python file at all) is a G000 finding, NOT a silent skip —
    a typo'd path in a CI script must fail the gate, never turn it
    permanently green."""
    out, errors = [], []
    for p in paths:
        if os.path.isdir(p):
            n0 = len(out)
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs
                    if not d.startswith(".") and d not in _WALK_PRUNE
                )
                for f in sorted(files):
                    if f.endswith(".py"):
                        out.append(os.path.join(root, f))
            if len(out) == n0:
                errors.append(Finding(
                    rule="G000", path=p, line=0, col=0,
                    msg=(
                        "lint target directory contains no .py files — "
                        "refusing to report a clean run on nothing"
                    ),
                ))
        elif os.path.isfile(p) and p.endswith(".py"):
            out.append(p)
        else:
            errors.append(Finding(
                rule="G000", path=p, line=0, col=0,
                msg=(
                    "lint target does not exist or is not a .py "
                    "file/directory — refusing to report a clean run "
                    "on nothing"
                ),
            ))
    return out, errors


def build_index(paths: list[str]) -> tuple[PackageIndex, list[Finding]]:
    files, errors = collect_files(paths)
    modules = []
    for path in files:
        try:
            with open(path, encoding="utf-8") as fh:
                src = fh.read()
            modules.append(ModuleInfo(path, src))
        except SyntaxError as e:
            errors.append(Finding(
                rule="G000", path=path, line=e.lineno or 0, col=0,
                msg=f"syntax error: {e.msg}",
            ))
        except OSError as e:
            errors.append(Finding(
                rule="G000", path=path, line=0, col=0,
                msg=f"unreadable: {e}",
            ))
    return PackageIndex(modules), errors


#: Artifact-driven rules: rule id -> (keyword, CLI flag) of the runtime
#: ground truth it cross-checks; without an artifact the rule is
#: skipped (nothing to validate against), and explicitly selecting it
#: without one is a G000 failure, never a silent no-op.
ARTIFACT_RULES = {
    "G011": ("sync_artifact", "--sync-artifact"),
    "G017": ("thread_artifact", "--thread-artifact"),
    "G021": ("fs_artifact", "--fs-artifact"),
    "G025": ("lifecycle_artifact", "--lifecycle-artifact"),
    "G029": ("ranges_artifact", "--ranges-artifact"),
}


def run_lint(paths: list[str], select: set[str] | None = None,
             sync_artifact: str | None = None,
             thread_artifact: str | None = None,
             fs_artifact: str | None = None,
             lifecycle_artifact: str | None = None,
             ranges_artifact: str | None = None) -> list[Finding]:
    """Run the rule suite over ``paths``.  ``sync_artifact`` names a
    serve bench artifact (or raw ``boundary_syncs`` JSON) to enable the
    G011 fence-cost cross-check — without it G011 is skipped (it has no
    runtime ground truth to compare the static fence graph against).
    ``thread_artifact`` is the same for G017's ``thread_crossings``
    publish-point cross-check (usually the same artifact file);
    ``fs_artifact`` for G021's ``fs_ops`` durable-protocol cross-check
    (the fs sanitizer's per-protocol op counters);
    ``lifecycle_artifact`` for G025's ``lifecycle`` machine/resource
    cross-check (the lifecycle sanitizer's transition and
    acquire/release counters); ``ranges_artifact`` for G029's
    ``ranges`` bounds cross-check (the range sanitizer's index-check
    and clamp-mask dispatch counters)."""
    from . import rules as _rules

    artifacts = {
        "sync_artifact": sync_artifact,
        "thread_artifact": thread_artifact,
        "fs_artifact": fs_artifact,
        "lifecycle_artifact": lifecycle_artifact,
        "ranges_artifact": ranges_artifact,
    }
    index, findings = build_index(paths)
    for rule_id, fn in _rules.RULES.items():
        if select and rule_id not in select:
            continue
        if rule_id in ARTIFACT_RULES:
            kw, flag = ARTIFACT_RULES[rule_id]
            artifact = artifacts[kw]
            if artifact is not None:
                findings.extend(fn(index, artifact))
            elif select and rule_id in select:
                # explicitly selecting the rule with no ground truth
                # must FAIL, not no-op: a dropped artifact flag in a CI
                # script would otherwise turn the gate permanently green
                findings.append(Finding(
                    rule="G000", path=f"<{rule_id}>", line=0, col=0,
                    msg=(
                        f"{rule_id} selected but no {flag} given — "
                        "the cross-check has no runtime counters to "
                        "validate against"
                    ),
                ))
            continue
        findings.extend(fn(index))
    # apply suppressions
    by_path = {m.path: m for m in index.modules}
    out = []
    for f in findings:
        if select and f.rule not in select and f.rule != "G000":
            continue
        m = by_path.get(f.path)
        if m is not None:
            if f.rule in m.suppress_file:
                continue
            if f.rule in m.suppress.get(f.line, ()):
                continue
        out.append(f)
    out.sort(key=Finding.key)
    # de-dup (the bare-name resolver can reach a function twice)
    seen, uniq = set(), []
    for f in out:
        if f.key() not in seen:
            seen.add(f.key())
            uniq.append(f)
    return uniq


# ---------------------------------------------------------------------------
# reporters

def format_text(findings: list[Finding]) -> str:
    lines = [
        f"{f.path}:{f.line}:{f.col}: {f.rule} {f.msg}" for f in findings
    ]
    lines.append(
        f"graftlint: {len(findings)} finding(s)"
        if findings else "graftlint: clean"
    )
    return "\n".join(lines)


def format_json(findings: list[Finding]) -> str:
    return json.dumps(
        {
            "findings": [
                {
                    "rule": f.rule, "path": f.path, "line": f.line,
                    "col": f.col, "message": f.msg,
                }
                for f in findings
            ],
            "count": len(findings),
        },
        indent=2,
    )


def format_sarif(findings: list[Finding]) -> str:
    """SARIF 2.1.0 (the schema CI annotation surfaces ingest).  One
    run, one result per finding; ``level`` is always ``error`` — the
    exit-code gate treats every finding as fatal, SARIF must not paint
    a softer picture.  Artifact-level findings carry line 0; SARIF
    regions are 1-based, so those clamp to line 1."""
    rules = sorted({f.rule for f in findings})
    return json.dumps(
        {
            "$schema": (
                "https://raw.githubusercontent.com/oasis-tcs/"
                "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"
            ),
            "version": "2.1.0",
            "runs": [{
                "tool": {"driver": {
                    "name": "graftlint",
                    "rules": [{"id": r} for r in rules],
                }},
                "results": [
                    {
                        "ruleId": f.rule,
                        "level": "error",
                        "message": {"text": f.msg},
                        "locations": [{
                            "physicalLocation": {
                                "artifactLocation": {"uri": f.path},
                                "region": {
                                    "startLine": max(1, f.line),
                                    "startColumn": max(1, f.col + 1),
                                },
                            },
                        }],
                    }
                    for f in findings
                ],
            }],
        },
        indent=2,
    )
