"""Runtime lifecycle sanitizer: the dynamic half of the G022-G025
lifecycle & ownership model (lint/lifecycle.py), and the leak oracle
behind the churn-drain harness (serve/lifecheck.py).

graftlint's lifecycle rules prove *statically* that every declared
state machine (``# graftlint: state=<machine>``) only moves along its
declared edge graph through its declared transition functions, and
that every declared resource acquisition (``# graftlint:
acquire=<resource>``) is dominated by a release — but the static model
trusts the annotations and the call-graph walk.  This module supplies
the runtime evidence, the same architecture as the sync, race and fs
sanitizers:

- every declared transition function routes through
  :func:`transition` (keyed ``machine, frm, to`` so runtime counters
  line up with the static ``transition=`` markers) and counts its
  **edges** — always, in every mode, one lock-guarded dict increment
  per transition; likewise :func:`acquire`/:func:`release` count per
  resource.  These counters are the ground truth the serve artifact
  exports as its ``lifecycle`` block (lint G025 cross-validates dead
  declared machines and unattributed runtime transitions against it,
  G011/G017/G021's mirror);
- with ``CRDT_BENCH_SANITIZE_LIFECYCLE=1`` the model is enforced
  **live**: a transition along an edge missing from the declared
  graph (:func:`declare_machine`) raises
  :class:`UndeclaredTransitionError` at the callsite; releasing a
  ``(resource, key)`` that is not live raises
  :class:`DoubleReleaseError`; touching a released key
  (:func:`touch` — e.g. reading a released stream's arrays) raises
  :class:`UseAfterReleaseError`; a gauge observed below zero
  (:func:`gauge` — the PR 17 prefetch-inflight underflow) raises
  :class:`NegativeGaugeError`.  Live keys carry a **generation**
  bumped on every re-acquire, so an id recycled by the allocator (the
  PR 17 ``id(trace)`` cache poisoning) is a *different* live object,
  never a stale hit;
- :func:`assert_all_released` is the drain-end leak gate: any
  ``(resource, key)`` still live raises :class:`LifecycleLeakError`
  naming every leaked key — zero unreleased acquisitions is the
  lifecheck harness's acceptance criterion.

Disarmed (the default), nothing is enforced and nothing is tracked —
the only cost anywhere is the counter bump, exactly the zero-overhead
contract every sanitizer in this repo keeps.
"""

from __future__ import annotations

import os
import threading

_ENV = "CRDT_BENCH_SANITIZE_LIFECYCLE"

#: The machine vocabulary (the static rules reject any other tag).
KNOWN_MACHINES = ("doc", "row", "spool", "stream", "session")

#: The resource vocabulary for acquire/release pairing.
KNOWN_RESOURCES = ("rows", "spool", "stream", "segment", "socket",
                   "thread")


class LifecycleError(RuntimeError):
    """Base class for every armed lifecycle violation."""


class UndeclaredTransitionError(LifecycleError):
    """A runtime transition along an edge missing from the declared
    state-machine graph — the static G022 model just met a
    counterexample (the PR 18 same-round-admit migration shape)."""


class DoubleReleaseError(LifecycleError):
    """A release of a ``(resource, key)`` that is not live: either it
    was already released (the duplicate-GC-enqueue shape) or it was
    never acquired at all."""


class UseAfterReleaseError(LifecycleError):
    """A touch of a ``(resource, key)`` after its release — reading a
    released stream's arrays is reading freed memory in spirit."""


class NegativeGaugeError(LifecycleError):
    """A paired inc/dec counter observed below zero — the PR 17
    prefetch inflight underflow as a typed error."""


class LifecycleLeakError(LifecycleError):
    """Drain ended with live acquisitions: the leak the G023 static
    pairing rule exists to prevent, caught at runtime."""


#: Transition/acquire counts come from whatever thread runs the
#: protocol (the prefetch worker releases off-thread), so the counter
#: tables take a real mutex — same reasoning as fs_sanitizer._mu.
_mu = threading.Lock()
_machines: dict[str, dict[str, int]] = {}  # machine -> edge -> count
_resources: dict[str, dict[str, int]] = {}  # resource -> acq/rel count
_unattributed: list[str] = []  # transitions on undeclared machines
_gauges: dict[str, int] = {}  # gauge -> last observed value

_decls: dict[str, dict] = {}  # machine -> {"states": set, "edges": set}
_live: dict[tuple[str, object], int] = {}  # (resource, key) -> gen
_released: dict[tuple[str, object], int] = {}  # last released gen
_gens: dict[tuple[str, object], int] = {}  # next generation per key

_armed = False
_forced = False  # armed explicitly (lifecheck harness), not via env

_UNATTRIBUTED_CAP = 256  # bounded: a hot loop must not grow a list


def sanitizing() -> bool:
    """True when ``CRDT_BENCH_SANITIZE_LIFECYCLE`` arms the sanitizer.
    Read at reset (not at import) so tests can flip it."""
    return os.environ.get(_ENV, "") not in ("", "0")


def _sync_armed() -> None:
    global _armed
    if not _forced:
        _armed = sanitizing()


def armed() -> bool:
    return _armed


def arm() -> None:
    """Force-arm (the lifecheck harness; tests), independent of the
    env flag."""
    global _armed, _forced
    _armed = True
    _forced = True


def disarm() -> None:
    global _armed, _forced
    _armed = False
    _forced = False


def reset_counters() -> None:
    """Zero the counter tables and the live-object model (each bench
    run owns its window).  Machine declarations survive — they
    describe the code, not the run's history.  When the env flag is
    set the sanitizer arms HERE, eagerly, so acquisitions before the
    first transition are tracked too."""
    _sync_armed()
    with _mu:
        _machines.clear()
        _resources.clear()
        _unattributed.clear()
        _gauges.clear()
        _live.clear()
        _released.clear()
        _gens.clear()
        _states.clear()


def declare_machine(name: str, states, edges) -> None:
    """Register a state machine's legal graph: ``states`` an iterable
    of state names, ``edges`` an iterable of ``(frm, to)`` pairs.
    Idempotent per name; the declaration mirrors the static
    ``# graftlint: state=<name> states=... edges=...`` marker so the
    runtime model and the G022 model enforce the same graph."""
    with _mu:
        _decls[name] = {
            "states": frozenset(states),
            "edges": frozenset(tuple(e) for e in edges),
        }


def transition(machine: str, frm: str, to: str, key=None) -> None:
    """One state-machine edge traversal.  Counted in EVERY mode (the
    G025 ground truth); armed, the edge must be in the declared graph
    and — when ``key`` identifies the instance — must depart from the
    instance's actual current state."""
    edge = f"{frm}->{to}"
    decl = _decls.get(machine)
    with _mu:
        if decl is None:
            if len(_unattributed) < _UNATTRIBUTED_CAP:
                _unattributed.append(f"{machine}:{edge}")
        else:
            t = _machines.setdefault(machine, {})
            t[edge] = t.get(edge, 0) + 1
    if not _armed:
        return
    if decl is None:
        raise UndeclaredTransitionError(
            f"transition `{edge}` on undeclared machine `{machine}` — "
            f"declare_machine() it (and mirror the static "
            f"`# graftlint: state={machine}` marker) ({_ENV}=1)"
        )
    if (frm, to) not in decl["edges"]:
        raise UndeclaredTransitionError(
            f"illegal `{machine}` transition `{edge}`: not in the "
            f"declared edge graph "
            f"{sorted('->'.join(e) for e in decl['edges'])} ({_ENV}=1)"
        )
    if key is not None:
        k = (machine, key)
        with _mu:
            cur = _states.get(k)
            if cur is not None and cur != frm:
                raise UndeclaredTransitionError(
                    f"`{machine}` instance {key!r} is in state "
                    f"`{cur}`, not `{frm}` — transition `{edge}` "
                    f"departs from a state the instance never reached "
                    f"({_ENV}=1)"
                )
            _states[k] = to


_states: dict[tuple[str, object], str] = {}  # (machine, key) -> state


def acquire(resource: str, key) -> None:
    """One resource acquisition.  Counted in EVERY mode; armed, the
    ``(resource, key)`` pair becomes live under a fresh generation
    (re-acquiring a recycled key is a NEW object, never a stale
    hit)."""
    with _mu:
        t = _resources.setdefault(resource, {})
        t["acquire"] = t.get("acquire", 0) + 1
        if _armed:
            k = (resource, key)
            gen = _gens.get(k, 0) + 1
            _gens[k] = gen
            _live[k] = gen
            _released.pop(k, None)


def release(resource: str, key) -> None:
    """One resource release.  Counted in EVERY mode; armed, releasing
    a key that is not live is a typed error at the callsite."""
    with _mu:
        t = _resources.setdefault(resource, {})
        t["release"] = t.get("release", 0) + 1
        if not _armed:
            return
        k = (resource, key)
        gen = _live.pop(k, None)
        if gen is not None:
            _released[k] = gen
            return
        prior = _released.get(k)
    if prior is not None:
        raise DoubleReleaseError(
            f"double release of {resource} key {key!r} "
            f"(generation {prior} already released) ({_ENV}=1)"
        )
    raise DoubleReleaseError(
        f"release of {resource} key {key!r} that was never acquired "
        f"({_ENV}=1)"
    )


def touch(resource: str, key) -> None:
    """Assert a resource is live before use — armed, touching a
    released key raises at the callsite (use-after-release); a key the
    model has never seen is out of jurisdiction and passes."""
    if not _armed:
        return
    k = (resource, key)
    with _mu:
        live = k in _live
        was_released = _released.get(k)
    if not live and was_released is not None:
        raise UseAfterReleaseError(
            f"use of {resource} key {key!r} after its release "
            f"(generation {was_released}) ({_ENV}=1)"
        )


def generation(resource: str, key) -> int | None:
    """The live generation of ``(resource, key)``, or None — cache
    layers key entries as ``(key, generation(...))`` so a recycled id
    can never alias a dead object's entry."""
    with _mu:
        return _live.get((resource, key))


def gauge(name: str, value: int) -> None:
    """Observe a paired inc/dec counter.  Recorded in every mode;
    armed, a negative observation is the PR 17 underflow as a typed
    error."""
    with _mu:
        _gauges[name] = value
    if _armed and value < 0:
        raise NegativeGaugeError(
            f"gauge `{name}` observed at {value} — an inc/dec "
            f"imbalance drove a paired counter negative ({_ENV}=1)"
        )


def live_count(resource: str | None = None) -> int:
    """Live (unreleased) acquisitions, optionally for one resource —
    only meaningful armed (disarmed, nothing is tracked)."""
    with _mu:
        if resource is None:
            return len(_live)
        return sum(1 for (r, _k) in _live if r == resource)


def live_keys() -> list[tuple[str, object]]:
    with _mu:
        return sorted(_live, key=repr)


def assert_all_released() -> None:
    """The drain-end leak gate: every acquisition released, or a
    :class:`LifecycleLeakError` naming the leaked keys."""
    with _mu:
        leaked = sorted(_live, key=repr)
    if leaked:
        raise LifecycleLeakError(
            f"{len(leaked)} unreleased acquisition(s) at drain end: "
            + ", ".join(f"{r}:{k!r}" for r, k in leaked[:20])
            + (" ..." if len(leaked) > 20 else "")
        )


def counters() -> dict:
    """Snapshot: ``{"machines": {m: {edge: n}}, "resources": {r:
    {"acquire": n, "release": n}}, "gauges": {name: last},
    "unattributed": [...]}``.  Machine/resource tables are populated
    in every mode (the G025 ground truth)."""
    with _mu:
        return {
            "machines": {
                m: dict(sorted(t.items()))
                for m, t in sorted(_machines.items())
            },
            "resources": {
                r: dict(sorted(t.items()))
                for r, t in sorted(_resources.items())
            },
            "gauges": dict(sorted(_gauges.items())),
            "unattributed": sorted(set(_unattributed)),
        }
