"""Interprocedural constant/shape dataflow + rule G008 (shape drift).

PR 4's rules are all module-local; the incident class they cannot see is
a *dimension constant* drifting between the module that defines it and
the modules that consume it — ``ops/apply2.py LANE`` tiling every packed
kernel, the capacity-class tuples that ``serve/pool.py`` buckets by, the
``Rt``/``B`` tile sizes baked into BlockSpecs.  This module adds the
missing half: a package-wide **constant environment** that resolves
module-level constants *across imports* (fixpoint over literal folding:
ints, tuples, arithmetic on already-resolved names, ``len`` of resolved
tuples), plus rule G008 which cross-checks producers and consumers of
the same symbolic dimension:

- **shared-constant drift**: a constant name that some module imports
  cross-module (it has a *producer*) independently redefined elsewhere
  with a different value — two copies of the same symbolic dimension
  that can now diverge silently;
- **import shadowing**: a module that imports NAME and also assigns a
  module-level NAME with a different resolved value (the imported
  binding is dead, the local fork wins);
- **capacity classes vs LANE**: every literal/default capacity-class
  tuple (``classes=...`` parameter defaults and call-site keywords) must
  hold multiples of the *resolved* ``LANE`` — the packed kernels tile by
  it, and ``DocPool`` only catches this at runtime;
- **classes/slots pairing**: ``classes`` and ``slots`` tuples declared
  together must agree on length (one bucket row-count per class).

The environment is also the shared resolver for the Pallas rules
(:mod:`crdt_benches_tpu.lint.pallas_rules`): block shapes written as
``(Rt, nt, LANE)`` resolve their ``LANE`` through the same import chain
the runtime uses.
"""

from __future__ import annotations

import ast
import re

from .core import Finding, ModuleInfo, PackageIndex

#: Module-level constant names eligible for drift tracking: the
#: screaming-case convention this repo uses for dimension constants.
_CONST_NAME = re.compile(r"^[A-Z][A-Z0-9_]{2,}$")

#: Parameter names whose tuple values are capacity-class lists (checked
#: against LANE divisibility and against their paired row-count tuple).
_CLASS_PARAMS = ("classes",)
_SLOT_PARAMS = ("slots",)

_FOLD_BINOPS = {
    ast.Add: lambda a, b: a + b,
    ast.Sub: lambda a, b: a - b,
    ast.Mult: lambda a, b: a * b,
    ast.FloorDiv: lambda a, b: a // b if b else None,
    ast.Mod: lambda a, b: a % b if b else None,
    ast.Pow: lambda a, b: a ** b if abs(b) < 64 else None,
    ast.LShift: lambda a, b: a << b if 0 <= b < 64 else None,
    ast.RShift: lambda a, b: a >> b if 0 <= b < 64 else None,
}


class ConstEnv:
    """Package-wide module-constant resolution (best-effort, pure AST).

    ``values[(module_path, name)]`` holds the resolved constant — int,
    float, str, bool, or tuple of those — for every module-level
    single-target assignment the fixpoint could fold.  Imports resolve
    through :meth:`resolve_module` (suffix match on the dotted source,
    the same flat-package assumption as ``PackageIndex.resolve_call``).
    """

    @classmethod
    def of(cls, index: PackageIndex) -> "ConstEnv":
        """The memoized environment for this index (rules share it)."""
        env = getattr(index, "_const_env", None)
        if env is None:
            env = index._const_env = cls(index)
        return env

    def __init__(self, index: PackageIndex):
        self.index = index
        self.values: dict[tuple[str, str], object] = {}
        self.def_lines: dict[tuple[str, str], int] = {}
        self._exprs: dict[tuple[str, str], tuple[ModuleInfo, ast.expr]] = {}
        self._mod_index: dict[str, list[ModuleInfo]] = {}
        for m in index.modules:
            parts = m.path.replace("\\", "/").split("/")
            stem = parts[-1][:-3] if parts[-1].endswith(".py") else parts[-1]
            names = parts[:-1] + [stem]
            # register every dotted suffix: "apply2", "ops.apply2", ...
            for i in range(len(names)):
                key = ".".join(names[i:])
                self._mod_index.setdefault(key, []).append(m)
            self._scan_module(m)
        self._fixpoint()

    # -- collection --------------------------------------------------------

    def _scan_module(self, m: ModuleInfo) -> None:
        dead: set[tuple[str, str]] = set()  # rebound names STAY dropped
        for node in ast.iter_child_nodes(m.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                value = node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                t = node.target
                value = node.value
            else:
                continue
            if not isinstance(t, ast.Name):
                continue
            key = (m.path, t.id)
            if key in dead or key in self._exprs:
                # rebound at module level: ambiguous, drop from the env
                # for good (a third assignment must not resurrect it)
                dead.add(key)
                self.values.pop(key, None)
                self._exprs.pop(key, None)
                self.def_lines.pop(key, None)
                continue
            self._exprs[key] = (m, value)
            self.def_lines[key] = node.lineno

    # -- resolution --------------------------------------------------------

    def resolve_module(self, dotted: str) -> ModuleInfo | None:
        """The index module a dotted import source names, or None when
        the suffix is missing or ambiguous."""
        hits = self._mod_index.get(dotted, ())
        return hits[0] if len(hits) == 1 else None

    def lookup(self, m: ModuleInfo, name: str):
        """Resolve ``name`` as seen from module ``m``: a local module
        constant, or an imported one followed to its defining module.
        Returns the value or None."""
        v = self.values.get((m.path, name))
        if v is not None:
            return v
        src = m.imports.get(name)
        if src is None:
            return None
        mod, _, attr = src.rpartition(".")
        if not mod:
            return None
        target = self.resolve_module(mod)
        if target is None or target.path == m.path:
            return None
        return self.values.get((target.path, attr))

    def producer_of(self, m: ModuleInfo, name: str) -> ModuleInfo | None:
        """The module an import of ``name`` in ``m`` resolves to."""
        src = m.imports.get(name)
        if src is None:
            return None
        mod, _, attr = src.rpartition(".")
        if not mod or attr != name:
            return None
        return self.resolve_module(mod)

    def fold(self, m: ModuleInfo, e: ast.expr, depth: int = 0):
        """Fold ``e`` to a literal using ``m``'s constant view, or None."""
        if depth > 24:
            return None
        if isinstance(e, ast.Constant):
            v = e.value
            return v if isinstance(v, (int, float, str, bool)) else None
        if isinstance(e, ast.Name):
            return self.lookup(m, e.id)
        if isinstance(e, (ast.Tuple, ast.List)):
            out = []
            for el in e.elts:
                v = self.fold(m, el, depth + 1)
                if v is None:
                    return None
                out.append(v)
            return tuple(out)
        if isinstance(e, ast.UnaryOp) and isinstance(e.op, ast.USub):
            v = self.fold(m, e.operand, depth + 1)
            return -v if isinstance(v, (int, float)) else None
        if isinstance(e, ast.BinOp):
            op = _FOLD_BINOPS.get(type(e.op))
            if op is None:
                return None
            a = self.fold(m, e.left, depth + 1)
            b = self.fold(m, e.right, depth + 1)
            if isinstance(a, (int, float)) and isinstance(b, (int, float)):
                try:
                    return op(a, b)
                except (ZeroDivisionError, OverflowError, ValueError):
                    return None
            return None
        if (
            isinstance(e, ast.Call)
            and isinstance(e.func, ast.Name)
            and e.func.id == "len"
            and len(e.args) == 1
            and not e.keywords
        ):
            v = self.fold(m, e.args[0], depth + 1)
            return len(v) if isinstance(v, tuple) else None
        if isinstance(e, ast.Subscript):
            base = self.fold(m, e.value, depth + 1)
            idx = self.fold(m, e.slice, depth + 1)
            if isinstance(base, tuple) and isinstance(idx, int):
                try:
                    return base[idx]
                except IndexError:
                    return None
        return None

    def _fixpoint(self) -> None:
        pending = dict(self._exprs)
        for _ in range(12):  # import chains in this repo are shallow
            progressed = False
            for key, (m, expr) in list(pending.items()):
                v = self.fold(m, expr)
                if v is not None:
                    self.values[key] = v
                    del pending[key]
                    progressed = True
            if not progressed:
                break

    def lane_for(self, m: ModuleInfo) -> int | None:
        """The LANE value as module ``m`` sees it: its own resolved
        binding when present, otherwise the package's unique module-level
        ``LANE`` definition (every kernel module imports exactly that)."""
        v = self.lookup(m, "LANE")
        if isinstance(v, int):
            return v
        defs = {
            val for (_, name), val in self.values.items()
            if name == "LANE" and isinstance(val, int)
        }
        return defs.pop() if len(defs) == 1 else None


def _const_defs(env: ConstEnv) -> dict[str, list[tuple[ModuleInfo, object, int]]]:
    """name -> [(module, value, line)] for tracked module constants."""
    by_path = {m.path: m for m in env.index.modules}
    out: dict[str, list] = {}
    for (path, name), v in env.values.items():
        if not _CONST_NAME.match(name):
            continue
        m = by_path.get(path)
        if m is None:
            continue
        out.setdefault(name, []).append(
            (m, v, env.def_lines.get((path, name), 0))
        )
    return out


def _imported_producers(env: ConstEnv, name: str) -> dict[str, ModuleInfo]:
    """Modules whose constant ``name`` is imported by someone else in the
    package: path -> producer ModuleInfo."""
    out: dict[str, ModuleInfo] = {}
    for m in env.index.modules:
        p = env.producer_of(m, name)
        if p is not None and p.path != m.path:
            if (p.path, name) in env.values:
                out[p.path] = p
    return out


def _class_tuple_findings(env: ConstEnv, m: ModuleInfo, node: ast.expr,
                          values, lane: int | None, where: str
                          ) -> list[Finding]:
    out = []
    if lane and isinstance(values, tuple):
        bad = [v for v in values if isinstance(v, int) and v % lane]
        if bad:
            out.append(Finding(
                rule="G008", path=m.path, line=node.lineno,
                col=node.col_offset,
                msg=(
                    f"capacity class(es) {bad} in {where} are not "
                    f"multiples of LANE={lane} (ops/apply2.py) — the "
                    "packed kernels tile the capacity axis by LANE and "
                    "DocPool only rejects this at runtime"
                ),
            ))
    return out


def g008_shape_drift(index: PackageIndex) -> list[Finding]:
    """Cross-module constant/shape drift (see module docstring)."""
    env = ConstEnv.of(index)
    out: list[Finding] = []

    # ---- (a) import shadowing: local NAME forks an imported NAME ----
    shadowed: set[tuple[str, str]] = set()  # (path, name) already flagged
    for m in index.modules:
        for (path, name), v in list(env.values.items()):
            if path != m.path or not _CONST_NAME.match(name):
                continue
            p = env.producer_of(m, name)
            if p is None or p.path == m.path:
                continue
            pv = env.values.get((p.path, name))
            if pv is not None and pv != v:
                shadowed.add((path, name))
                out.append(Finding(
                    rule="G008", path=m.path,
                    line=env.def_lines[(path, name)], col=0,
                    msg=(
                        f"`{name} = {v!r}` shadows the imported "
                        f"`{name} = {pv!r}` from {p.path} — the local "
                        "fork silently drifts from the producer"
                    ),
                ))

    # ---- (b) shared-constant drift across independent definitions ----
    defs = _const_defs(env)
    for name, sites in defs.items():
        if len(sites) < 2:
            continue
        producers = _imported_producers(env, name)
        if not producers:
            continue  # never imported cross-module: not a shared symbol
        # canonical value: the producer(s) everyone imports from
        canon_vals = {
            env.values[(p.path, name)] for p in producers.values()
        }
        if len(canon_vals) != 1:
            canon_vals = {sites[0][1]}
        canon = canon_vals.pop()
        canon_paths = set(producers)
        for m, v, line in sites:
            if m.path in canon_paths or v == canon:
                continue
            if (m.path, name) in shadowed:
                continue  # already reported as an import shadow
            src = sorted(canon_paths)[0]
            out.append(Finding(
                rule="G008", path=m.path, line=line, col=0,
                msg=(
                    f"`{name} = {v!r}` drifts from `{name} = {canon!r}` "
                    f"defined in {src} (imported cross-module as the "
                    "shared dimension) — one symbolic dimension now has "
                    "two values"
                ),
            ))

    # ---- (c)/(d) capacity-class tuples: LANE multiples + slot pairing --
    def sig_params(fi):
        a = fi.node.args
        params = [p.arg for p in (a.posonlyargs + a.args)]
        defaults = list(a.defaults)
        # align defaults to the tail of params
        pairs = dict(zip(params[len(params) - len(defaults):], defaults))
        for p, d in zip(a.kwonlyargs, a.kw_defaults):
            if d is not None:
                pairs[p.arg] = d
        return pairs

    for m in index.modules:
        lane = env.lane_for(m)
        for fi in m.functions.values():
            pairs = sig_params(fi)
            cls_vals = slot_vals = None
            slot_node = None
            for pname, dnode in pairs.items():
                if pname in _CLASS_PARAMS:
                    cls_vals = env.fold(m, dnode)
                    out.extend(_class_tuple_findings(
                        env, m, dnode, cls_vals, lane,
                        f"`{fi.qualname}` default `{pname}=`",
                    ))
                elif pname in _SLOT_PARAMS:
                    slot_vals = env.fold(m, dnode)
                    slot_node = dnode
            if (
                isinstance(cls_vals, tuple)
                and isinstance(slot_vals, tuple)
                and len(cls_vals) != len(slot_vals)
            ):
                out.append(Finding(
                    rule="G008", path=m.path, line=slot_node.lineno,
                    col=slot_node.col_offset,
                    msg=(
                        f"`{fi.qualname}`: {len(cls_vals)} capacity "
                        f"classes but {len(slot_vals)} slot counts — "
                        "every class needs exactly one bucket row count"
                    ),
                ))
        # call sites passing literal class/slot tuples by keyword
        for fi in m.functions.values():
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                kw = {k.arg: k.value for k in node.keywords if k.arg}
                cv = sv = None
                for pname in _CLASS_PARAMS:
                    if pname in kw:
                        cv = env.fold(m, kw[pname])
                        out.extend(_class_tuple_findings(
                            env, m, kw[pname], cv, lane,
                            f"call-site `{pname}=`",
                        ))
                for pname in _SLOT_PARAMS:
                    if pname in kw:
                        sv = env.fold(m, kw[pname])
                if (
                    isinstance(cv, tuple) and isinstance(sv, tuple)
                    and len(cv) != len(sv)
                ):
                    out.append(Finding(
                        rule="G008", path=m.path,
                        line=kw[_SLOT_PARAMS[0]].lineno,
                        col=kw[_SLOT_PARAMS[0]].col_offset,
                        msg=(
                            f"call passes {len(cv)} capacity classes "
                            f"but {len(sv)} slot counts — every class "
                            "needs exactly one bucket row count"
                        ),
                    ))
    return out
