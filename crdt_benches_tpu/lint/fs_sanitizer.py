"""Runtime fs sanitizer: the dynamic half of the G018-G020 model, and
the crash-point injection engine behind the durability stack's
exhaustive crash-enumeration harness (serve/fscrash.py).

graftlint's crash-consistency rules (lint/fsops.py) prove *statically*
that every declared durable commit protocol (``# graftlint:
durable=<protocol>``) follows atomic-commit discipline and durable
ordering — but the static model trusts the annotations and the
call-graph walk.  This module supplies the runtime evidence, the same
architecture as the sync and race sanitizers:

- every declared protocol function routes through :func:`fs_protocol`
  (keyed by the protocol tag, so runtime counters line up with the
  static ``durable=`` markers) and counts its **entries** — always, in
  every mode, one lock-guarded dict increment per protocol run;
- with ``CRDT_BENCH_SANITIZE_FS=1`` the filesystem surface the static
  model reasons about is interposed — ``os.replace`` / ``os.rename`` /
  ``os.link`` / ``os.unlink`` / ``os.fsync`` / ``shutil.rmtree`` plus
  write-mode ``open`` — and every op touching a **watched root** (the
  journal + spool directories, registered via :func:`watch_root`) is
  attributed to the innermost active protocol, building the
  per-protocol op sequences the serve artifact exports as its
  ``fs_ops`` block (lint G021 cross-validates that ground truth
  against the static ``durable=`` markers — dead protocols and
  unattributed mutating ops both findings, G011's mirror);
- armed, the G019 durable-ordering invariant is enforced **live**: a
  destructive op (unlink / rmtree) on a durable path-role (non-``.tmp``
  under a watched root) inside a protocol entry must be dominated by a
  committed install (``os.replace``/``os.rename`` to a durable target)
  or a read of the committed record (the torn-pass-completion form) —
  anything else raises :class:`DurableOrderingError` at the callsite;
- :func:`crash_at` injects a **crash** at any mutating-op boundary:
  the ``i``-th mutating op on a watched root raises
  :class:`InjectedCrash` *instead of executing*, and every later
  mutating op is frozen to a silent no-op (a dead process writes
  nothing — in particular, ``except``/``finally`` cleanup handlers
  must not get to tidy up the crash window they are being tested on).
  The harness enumerates ``i`` over the whole recorded sequence and
  requires byte-verified recovery at every single point.

Disarmed (the default), nothing is interposed — ``os.replace`` is the
real ``os.replace``, ``open`` is the builtin — and the only cost
anywhere is the protocol-entry counter bump, exactly the zero-overhead
contract every sanitizer in this repo keeps.
"""

from __future__ import annotations

import builtins
import functools
import os
import shutil
import threading
from contextlib import contextmanager

_ENV = "CRDT_BENCH_SANITIZE_FS"

#: The protocol vocabulary (the static rules reject any other tag).
KNOWN_PROTOCOLS = ("snapshot", "gc", "wal", "spool", "flight",
                   "reshard")

#: Ops that change the filesystem — the crash-point boundaries.
#: ``update`` is an ``r+``-mode open (the WAL torn-tail truncate
#: repair): it mutates in place, so it is a boundary and frozen
#: post-crash, and it is NOT a read for G019's witness rule.
MUTATING_OPS = frozenset(
    {"write", "append", "update", "replace", "rename", "link",
     "unlink", "rmtree"}
)
#: Ops that destroy a copy (G019's live jurisdiction).
DESTRUCTIVE_OPS = frozenset({"unlink", "rmtree"})
#: Ops that commit a staged replacement into its final name.
COMMIT_OPS = frozenset({"replace", "rename"})

#: Bounded in-memory op log (tests assert exact sequences off it).
_OP_LOG_CAP = 8192


class DurableOrderingError(RuntimeError):
    """A destructive fs op on a durable path-role fired inside a
    declared protocol entry before the committed install of its
    replacement — the static G019 model just met a counterexample."""


class InjectedCrash(BaseException):
    """The simulated kill at one fs-op boundary.  A ``BaseException``
    on purpose: recovery-relevant cleanup handlers catch ``OSError`` /
    ``Exception``, and a crash must not be swallowed by the very code
    whose crash window is under test."""


_tls = threading.local()
#: Crossing counts come from whatever thread runs the protocol (the
#: prefetch worker rehydrates spools off-thread), so the counter tables
#: take a real mutex — same reasoning as race_sanitizer._mu.
_mu = threading.Lock()
_protocols: dict[str, int] = {}  # entries, counted in EVERY mode
_ops: dict[str, dict[str, int]] = {}  # tag -> op -> count (armed)
_unattributed: dict[str, int] = {}  # mutating ops outside any protocol
_op_log: list[tuple[str | None, str, str]] = []  # (tag, op, basename)
_op_log_dropped = 0

_watch: list[str] = []
_installed = False
_armed = False
_forced = False  # armed explicitly (crash harness), not via the env

_crash_point: int | None = None
_mutations = 0
_crashed = False


def sanitizing() -> bool:
    """True when ``CRDT_BENCH_SANITIZE_FS`` arms the sanitizer.  Read
    at every protocol entry (not at import) so tests can flip it."""
    return os.environ.get(_ENV, "") not in ("", "0")


def watch_root(path: str) -> None:
    """Register a directory as durable territory: ops on paths under it
    are attributed (and, armed, enforced + crash-enumerable).  The
    bench registers the journal dir and the pool's spool dir."""
    root = os.path.abspath(path)
    if root not in _watch:
        _watch.append(root)


def clear_watch_roots() -> None:
    _watch.clear()


def _watched(path) -> bool:
    if not _watch or not isinstance(path, str):
        return False
    p = os.path.abspath(path)
    for root in _watch:
        if p == root or p.startswith(root + os.sep):
            return True
    return False


def _durable(path) -> bool:
    """Path-role classifier, matching the static model: a ``.tmp``
    anywhere in the path — basename OR any ancestor component (files
    inside a ``snap_*.tmp`` staging directory are staging too) — is
    never committed and ignorable after a crash; anything else under a
    watched root is a durable role."""
    s = str(path).replace("\\", "/")
    return not any(".tmp" in part for part in s.split("/"))


def reset_counters() -> None:
    """Zero the counter tables and the op log (each bench run owns its
    window).  Watch roots survive — they describe the run's layout,
    not its history.  When the env flag is set, the interposition is
    installed and armed HERE, eagerly: arming only at the first
    protocol entry would leave any mutating op on a watched root
    *before* that entry invisible to the unattributed-op accounting —
    exactly the op class G021 exists to catch."""
    global _op_log_dropped, _mutations, _armed
    if not _forced:
        if sanitizing():
            _install()
            _armed = True
        else:
            _armed = False
    with _mu:
        _protocols.clear()
        _ops.clear()
        _unattributed.clear()
        _op_log.clear()
        _op_log_dropped = 0
        _mutations = 0


def counters() -> dict:
    """Snapshot: ``{"protocols": {tag: entries}, "ops": {tag: {op:
    n}}, "unattributed": {op: n}}``.  ``protocols`` is populated in
    every mode (the G021 ground truth); the op tables only while the
    sanitizer is armed (the interposed surface is what observes
    individual ops)."""
    with _mu:
        return {
            "protocols": dict(sorted(_protocols.items())),
            "ops": {
                tag: dict(sorted(t.items()))
                for tag, t in sorted(_ops.items())
            },
            "unattributed": dict(sorted(_unattributed.items())),
        }


def op_log() -> list[tuple[str | None, str, str]]:
    """The armed run's ``(protocol, op, basename)`` sequence, bounded
    at ``_OP_LOG_CAP`` entries (tests assert orderings off it, e.g.
    fsync-before-replace in the spool protocol)."""
    with _mu:
        return list(_op_log)


def mutation_count() -> int:
    """Mutating ops observed on watched roots since the last reset —
    the crash-enumeration domain size."""
    with _mu:
        return _mutations


def crashed() -> bool:
    return _crashed


# ---------------------------------------------------------------------------
# protocol entries
# ---------------------------------------------------------------------------


def _stack() -> list:
    s = getattr(_tls, "protocols", None)
    if s is None:
        s = _tls.protocols = []
    return s


@contextmanager
def fs_protocol(tag: str):
    """One declared durable-protocol entry: count it (always — the
    G021 ground truth), and while inside, every interposed fs op on a
    watched root is attributed to ``tag`` (innermost wins, like
    fences).  Arms/disarms the interposition lazily off the env flag
    so tests can flip it without an import dance."""
    global _armed
    if not _forced:
        if sanitizing():
            if not _armed:
                _install()
                _armed = True
        elif _armed:
            _armed = False
    with _mu:
        _protocols[tag] = _protocols.get(tag, 0) + 1
    stack = _stack()
    stack.append({"tag": tag, "ops": []})
    try:
        yield
    finally:
        stack.pop()


def durable_protocol(tag: str):
    """Decorator form of :func:`fs_protocol` (the ``@published``
    pattern): goes on exactly the functions carrying ``# graftlint:
    durable=<tag>`` markers so the runtime protocol entries line up
    with the static declarations — G021 cross-checks that the two sets
    agree."""
    def deco(fn):
        @functools.wraps(fn)
        def run(*args, **kwargs):
            with fs_protocol(tag):
                return fn(*args, **kwargs)

        run.__graft_protocol__ = tag
        return run

    return deco


@contextmanager
def crash_at(point: int):
    """Arm the sanitizer and kill the run at mutating-op boundary
    ``point`` (0-based): ops ``[0, point)`` execute, op ``point``
    raises :class:`InjectedCrash` without executing, and everything
    after is frozen to a no-op until the context exits.  Resets the
    counters on entry so ``point`` indexes the same sequence a
    recording pass observed."""
    global _crash_point, _crashed
    _arm()
    reset_counters()
    _crash_point = point
    _crashed = False
    try:
        yield
    finally:
        _crash_point = None
        _crashed = False
        if not sanitizing():
            disarm()


def _arm() -> None:
    global _armed, _forced
    _install()
    _armed = True
    _forced = True


def disarm() -> None:
    """Passthrough mode: hooks stay installed (interposition cannot be
    safely unwound mid-process) but become identity."""
    global _armed, _forced
    _armed = False
    _forced = False


# ---------------------------------------------------------------------------
# the interposed surface
# ---------------------------------------------------------------------------


def _observe(op: str, path, durable_hint: bool | None = None) -> bool:
    """Record one fs op.  Returns False when the op must NOT execute
    (frozen post-crash).  Raises :class:`InjectedCrash` at the armed
    crash boundary and :class:`DurableOrderingError` on a live G019
    violation."""
    global _mutations, _crashed, _op_log_dropped
    if not _armed:
        return True
    watched = _watched(path) if path is not None else bool(_stack())
    if not watched:
        return True
    durable = _durable(path) if durable_hint is None else durable_hint
    mutating = op in MUTATING_OPS
    if mutating and _crashed:
        return False  # the process is dead: nothing lands on disk
    stack = _stack()
    entry = stack[-1] if stack else None
    tag = entry["tag"] if entry else None
    if mutating:
        with _mu:
            idx = _mutations
            _mutations += 1
        if _crash_point is not None and idx == _crash_point:
            _crashed = True
            raise InjectedCrash(
                f"injected crash before fs op #{idx} "
                f"({op} {os.path.basename(str(path))!r}, "
                f"protocol {tag or 'unattributed'})"
            )
    if op in DESTRUCTIVE_OPS and durable and entry is not None \
            and not _crashed:
        # live G019: destruction of a durable copy must be dominated by
        # the committed install of its replacement — or by a read of
        # the committed record (completing a torn pass)
        ok = any(
            (o in COMMIT_OPS and dur) or o == "read"
            for o, dur in entry["ops"]
        )
        if not ok:
            raise DurableOrderingError(
                f"{op} of durable `{os.path.basename(str(path))}` "
                f"inside protocol `{tag}` before any committed install "
                "(os.replace/os.rename to a durable target) or read of "
                "the committed record — a crash here loses the only "
                f"copy ({_ENV}=1); install the replacement first"
            )
    with _mu:
        if tag is not None:
            t = _ops.setdefault(tag, {})
            t[op] = t.get(op, 0) + 1
        elif mutating:
            _unattributed[op] = _unattributed.get(op, 0) + 1
        if len(_op_log) < _OP_LOG_CAP:
            _op_log.append(
                (tag, op, os.path.basename(str(path)) if path else "")
            )
        else:
            _op_log_dropped += 1
    if entry is not None:
        entry["ops"].append((op, durable))
    return True


_orig_open = builtins.open
_orig_replace = os.replace
_orig_rename = os.rename
_orig_link = os.link
_orig_unlink = os.unlink
_orig_fsync = os.fsync
_orig_rmtree = shutil.rmtree


def _fs_open(file, mode="r", *args, **kwargs):
    if _armed:
        try:
            path = os.fspath(file)
        except TypeError:
            path = None  # raw fd / file-like: out of model
        if isinstance(path, str) and _watched(path):
            if any(c in mode for c in "wx"):
                op = "write"
            elif "a" in mode:
                op = "append"
            elif "+" in mode:
                op = "update"  # r+: in-place edit (torn-tail truncate)
            else:
                op = "read"
            if not _observe(op, path):
                # frozen: give the unwinding caller a harmless sink so
                # cleanup code cannot touch the crash window
                return _orig_open(os.devnull,
                                  mode.replace("x", "w"), *args, **kwargs)
    return _orig_open(file, mode, *args, **kwargs)


def _fs_replace(src, dst, *args, **kwargs):
    if _observe("replace", dst):
        return _orig_replace(src, dst, *args, **kwargs)


def _fs_rename(src, dst, *args, **kwargs):
    if _observe("rename", dst):
        return _orig_rename(src, dst, *args, **kwargs)


def _fs_link(src, dst, *args, **kwargs):
    if _observe("link", dst):
        return _orig_link(src, dst, *args, **kwargs)


def _fs_unlink(path, *args, **kwargs):
    if _observe("unlink", path):
        return _orig_unlink(path, *args, **kwargs)


def _fs_fsync(fd):
    # fd-keyed: no path to watch-filter, so attribution rides the
    # active protocol entry (nothing outside the durability stack
    # fsyncs in this codebase); never a crash boundary — a crash
    # "before the fsync" is indistinguishable from one before the next
    # mutating op, and the enumeration already covers that point.
    if _armed and _stack():
        _observe("fsync", None)
    return _orig_fsync(fd)


def _fs_rmtree(path, *args, **kwargs):
    if _observe("rmtree", path):
        return _orig_rmtree(path, *args, **kwargs)


def _install() -> None:
    global _installed
    if _installed:
        return
    builtins.open = _fs_open
    os.replace = _fs_replace
    os.rename = _fs_rename
    os.link = _fs_link
    os.unlink = _fs_unlink
    os.remove = _fs_unlink  # the same syscall, both spellings
    os.fsync = _fs_fsync
    shutil.rmtree = _fs_rmtree
    _installed = True
