"""Crash-consistency analysis: rules G018-G021.

Every real durability bug shipped-then-fixed in PRs 12-13 was a
filesystem-*ordering* bug found by hand or by the oracle, never by
tooling: the spool unlink-before-install crash window, the torn GC pass
between manifest write and unlinks, the bit-flipped-but-parseable
manifest that escaped the CRC catch.  The static model here is the
G002/G011/G014 architecture applied to filesystem effects:

- **protocols are declared**, not inferred: ``# graftlint:
  durable=<protocol>`` on a def line pins the function into one of the
  durability stack's multi-step commit protocols (``snapshot`` / ``gc``
  / ``wal`` / ``spool`` / ``flight``).  The analyzer builds a
  per-function **effect sequence** — write/read/fsync/replace/link/
  unlink/rmtree/truncate over *path-role symbols* — walking the body in
  statement order and inlining the CONFIDENT call edges
  (``resolve_call(strict=True)``), descending into undeclared helpers
  and same-protocol members but stopping at functions declared under a
  DIFFERENT protocol (a declared boundary, exactly like pinned thread
  roots).
- **path roles** are ``staging`` vs ``durable``: a name bound from an
  expression carrying a ``.tmp`` literal (or ``tempfile.mkstemp``), or
  tested with ``endswith(".tmp")``, is staging — free to write, free to
  destroy; everything else a protocol touches is a durable role.
- **G018 atomic-commit discipline**: a durable artifact reaches its
  final name only via tmp + ``os.replace``/``os.rename`` — an in-place
  write-mode ``open`` of a durable role is a finding (append mode is
  exempt: the WAL's contract is append-only + CRC framing, and an
  append never destroys committed bytes).  A commit (replace/rename to
  a durable target) with NO fsync effect anywhere earlier in the
  protocol sequence is also a finding: rename durability does not
  imply content durability — the committed name can point at
  never-flushed pages after a power cut.
- **G019 durable-ordering**: destruction of a durable copy (unlink,
  rmtree, truncation) must be dominated by the committed install of
  its replacement (an earlier replace/rename to a durable target) or
  by a read of the committed record (the torn-pass-completion form,
  e.g. ``finish_torn_gc`` re-reading the GC manifest).  This is the
  exact PR 13 spool-unlink-before-install and PR 12 torn-GC incident
  class, as a rule.
- **G020 verify-before-trust**: reads of durable artifacts must flow
  through CRC verification (``np.load`` in a function that never
  computes ``zlib.crc32`` is a trusted read), and a fallback handler
  in a protocol function whose try-body indexes into parsed manifest
  data must catch the parseable-garbage set (KeyError / IndexError /
  TypeError) — a bit-flipped manifest can stay PARSEABLE json with
  garbled values, and a designed-recoverable corruption must degrade
  to the next candidate, never crash the recovery itself (the
  ``_read_manifest`` incident).
- **G021 fs-protocol cross-check** (artifact-driven, G011/G017's
  mirror): the runtime fs sanitizer (lint/fs_sanitizer.py) counts
  every declared protocol entry and attributes every observed fs op to
  the protocol that ran it, exported as the serve artifact's
  ``fs_ops`` block.  A declared protocol the run never entered is DEAD
  (scoped by armed surface: ``snapshot``/``gc``/``wal`` ride the
  journal, ``spool`` rides pool spool traffic, ``flight`` a dump); a
  runtime protocol tag or mutating op with no matching ``durable=``
  marker is UNATTRIBUTED — fs activity the static model does not know
  about.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass

from .core import Finding, FuncInfo, PackageIndex, dotted
from .threads import load_artifact_block

#: The declared-protocol vocabulary (shared with the runtime twin).
KNOWN_PROTOCOLS = ("snapshot", "gc", "wal", "spool", "flight",
                   "reshard")

#: Armed-surface scoping for the G021 dead-protocol accounting: a tag
#: is only dead-checked against artifacts whose run armed its surface
#: (``journal`` = the WAL + barriers ran; ``spool`` = the pool actually
#: spooled; ``flight`` = a dump fired this drain; ``reshard`` = a live
#: shard-map change committed its migration manifest).
PROTOCOL_SURFACES = {
    "snapshot": "journal",
    "gc": "journal",
    "wal": "journal",
    "spool": "spool",
    "flight": "flight",
    "reshard": "reshard",
}

_COMMIT_OPS = ("replace", "rename")
_DESTRUCTIVE_OPS = ("unlink", "rmtree", "truncate")

#: The parseable-garbage error set a recovery fallback must cover: a
#: bit-flipped manifest that still parses surfaces as one of these
#: deep in the restore, not as a corruption error.
_GARBAGE_ERRORS = frozenset({"KeyError", "IndexError", "TypeError"})


@dataclass
class Effect:
    op: str  # write|append|read|fsync|replace|rename|link|unlink|rmtree|truncate|copy|npload
    role: str  # role of the affected/destination path: staging|durable
    fi: FuncInfo  # function whose body contains the op (for location)
    line: int
    col: int
    reportable: bool = True  # False for effects inlined from a
    # DECLARED callee (it gets its own standalone analysis — findings
    # there would duplicate)


# ---------------------------------------------------------------------------
# path-role inference
# ---------------------------------------------------------------------------


def _walk_skip_defs(node: ast.AST):
    """ast.walk that does not descend into nested function bodies (a
    nested def's effects happen at its CALL sites, not its def site)."""
    queue = [node]
    while queue:
        n = queue.pop(0)
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child,
                          (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.Lambda)):
                continue
            queue.append(child)


def _has_tmp_literal(e: ast.AST) -> bool:
    for n in ast.walk(e):
        if isinstance(n, ast.Constant) and isinstance(n.value, str) \
                and ".tmp" in n.value:
            return True
    return False


def _staging_names(fnode: ast.AST, seed: set[str] | None = None
                   ) -> set[str]:
    """Names bound to staging paths inside one function body: assigned
    from an expression carrying a ``.tmp`` literal or a
    ``tempfile.mkstemp`` call (both unpacked names — the fd rides the
    same temp file), tested with ``endswith(".tmp")`` anywhere, or
    derived from another staging name (run to a fixpoint — staging-ness
    propagates through ``os.path.join(tmp, fname)``)."""
    staging: set[str] = set(seed or ())
    assigns: list[tuple[list[str], ast.expr]] = []
    for n in _walk_skip_defs(fnode):
        if isinstance(n, ast.Assign):
            for t in n.targets:
                names = [e.id for e in ast.walk(t)
                         if isinstance(e, ast.Name)]
                if names:
                    assigns.append((names, n.value))
        elif isinstance(n, ast.AnnAssign) and n.value is not None \
                and isinstance(n.target, ast.Name):
            assigns.append(([n.target.id], n.value))
        elif isinstance(n, ast.Call):
            f = n.func
            if isinstance(f, ast.Attribute) and f.attr == "endswith" \
                    and isinstance(f.value, ast.Name) and n.args \
                    and _has_tmp_literal(n.args[0]):
                staging.add(f.value.id)
    for names, value in assigns:
        d = dotted(getattr(value, "func", value)) or ""
        if _has_tmp_literal(value) or d.split(".")[-1] == "mkstemp":
            staging.update(names)
    changed = True
    while changed:
        changed = False
        for names, value in assigns:
            if any(n in staging for n in names):
                continue
            if any(isinstance(e, ast.Name) and e.id in staging
                   for e in ast.walk(value)):
                staging.update(names)
                changed = True
    return staging


def _role(e: ast.expr | None, staging: set[str]) -> str:
    """'staging' | 'durable' for a path expression.  Durable is the
    default: inside a declared protocol, any path not provably staged
    is somebody's committed artifact."""
    if e is None:
        return "durable"
    if _has_tmp_literal(e):
        return "staging"
    for n in ast.walk(e):
        if isinstance(n, ast.Name) and n.id in staging:
            return "staging"
    return "durable"


# ---------------------------------------------------------------------------
# effect-sequence extraction (with confident-call inlining)
# ---------------------------------------------------------------------------

_MAX_INLINE_DEPTH = 8


def _function_effects(index: PackageIndex, fi: FuncInfo, proto: str | None,
                      *, seen: set[int] | None = None, depth: int = 0,
                      staging_seed: set[str] | None = None,
                      reportable: bool = True) -> list[Effect]:
    """The protocol effect sequence of ``fi``: its own fs ops in
    statement order, with confident callees inlined at their call
    sites — undeclared helpers and same-protocol members descend,
    functions declared under a different protocol are boundaries."""
    seen = set() if seen is None else seen
    seen.add(id(fi))
    staging = _staging_names(fi.node, staging_seed)
    nested: dict[str, ast.AST] = {}
    handles: dict[str, str] = {}  # file-handle var -> path role
    out: list[Effect] = []

    def note(op: str, role: str, node: ast.AST) -> None:
        out.append(Effect(op=op, role=role, fi=fi, line=node.lineno,
                          col=node.col_offset, reportable=reportable))

    def handle_open(call: ast.Call, target: str | None) -> None:
        mode = "r"
        if len(call.args) > 1 and isinstance(call.args[1], ast.Constant):
            mode = str(call.args[1].value)
        for kw in call.keywords:
            if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                mode = str(kw.value.value)
        path = call.args[0] if call.args else None
        role = _role(path, staging)
        if any(c in mode for c in "wx"):
            note("write", role, call)
        elif "a" in mode:
            note("append", role, call)
        elif "+" in mode:
            note("update", role, call)  # r+: in-place edit, not a
            # G019 read-witness (the torn-tail truncate repair shape)
        else:
            note("read", role, call)
        if target is not None:
            handles[target] = role

    def visit_call(call: ast.Call) -> None:
        f = call.func
        d = dotted(f) or ""
        tail = d.split(".")[-1]
        args = call.args
        if d in ("open", "io.open"):
            handle_open(call, None)
            return
        if tail == "fdopen":
            mode = "r"
            if len(args) > 1 and isinstance(args[1], ast.Constant):
                mode = str(args[1].value)
            role = _role(args[0] if args else None, staging)
            if any(c in mode for c in "wxa"):
                note("write", role, call)
            return
        if d == "os.replace" or d == "os.rename":
            op = "replace" if d.endswith("replace") else "rename"
            note(op, _role(args[1] if len(args) > 1 else None, staging),
                 call)
            return
        if d == "os.link":
            note("link",
                 _role(args[1] if len(args) > 1 else None, staging), call)
            return
        if d in ("os.unlink", "os.remove"):
            note("unlink", _role(args[0] if args else None, staging),
                 call)
            return
        if d in ("os.fsync", "os.fdatasync"):
            note("fsync", "durable", call)
            return
        if d == "shutil.rmtree":
            note("rmtree", _role(args[0] if args else None, staging),
                 call)
            return
        if tail in ("copy2", "copy", "copyfile") and d.startswith(
                "shutil."):
            note("copy",
                 _role(args[1] if len(args) > 1 else None, staging), call)
            return
        if d in ("os.truncate", "os.ftruncate"):
            note("truncate", _role(args[0] if args else None, staging),
                 call)
            return
        if isinstance(f, ast.Attribute) and f.attr == "truncate" \
                and isinstance(f.value, ast.Name):
            note("truncate", handles.get(f.value.id, "durable"), call)
            return
        if fi.module.is_np_attr(f) == "load":
            note("npload", _role(args[0] if args else None, staging),
                 call)
            return
        # nested defs inline at their call sites, under the caller's
        # staging environment (a closure sees the enclosing temps)
        if isinstance(f, ast.Name) and f.id in nested:
            sub = nested[f.id]
            sub_staging = _staging_names(sub, staging)
            saved = dict(handles)
            for n in _walk_skip_defs(sub):
                if isinstance(n, ast.Call):
                    _dispatch(n, sub_staging)
            handles.update(saved)
            return
        for callee in index.resolve_call(call, fi, strict=True):
            if id(callee) in seen or depth >= _MAX_INLINE_DEPTH:
                continue
            if callee.protocol is not None and callee.protocol != proto:
                continue  # a different declared protocol: boundary
            out.extend(_function_effects(
                index, callee, proto, seen=seen, depth=depth + 1,
                reportable=reportable and not callee.durable,
            ))

    def _dispatch(call: ast.Call, env: set[str]) -> None:
        nonlocal staging
        saved = staging
        staging = env
        try:
            visit_call(call)
        finally:
            staging = saved

    def scan_stmt(stmt: ast.AST) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            nested[stmt.name] = stmt
            return
        # file-handle role bindings (for `f.truncate(...)`)
        if isinstance(stmt, ast.Assign) and isinstance(
                stmt.value, ast.Call):
            d = dotted(stmt.value.func) or ""
            if d in ("open", "io.open") and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                handle_open(stmt.value, stmt.targets[0].id)
                return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                if isinstance(item.context_expr, ast.Call):
                    d = dotted(item.context_expr.func) or ""
                    if d in ("open", "io.open"):
                        tgt = (item.optional_vars.id
                               if isinstance(item.optional_vars, ast.Name)
                               else None)
                        handle_open(item.context_expr, tgt)
                    else:
                        visit_call(item.context_expr)
            for sub in stmt.body:
                scan_stmt(sub)
            return
        if isinstance(stmt, (ast.If, ast.For, ast.AsyncFor, ast.While)):
            guard = getattr(stmt, "test", None) or getattr(
                stmt, "iter", None)
            if guard is not None:
                for n in _walk_skip_defs(guard):
                    if isinstance(n, ast.Call):
                        visit_call(n)
            for sub in stmt.body + getattr(stmt, "orelse", []):
                scan_stmt(sub)
            return
        if isinstance(stmt, ast.Try):
            for sub in stmt.body:
                scan_stmt(sub)
            for h in stmt.handlers:
                for sub in h.body:
                    scan_stmt(sub)
            for sub in stmt.orelse + stmt.finalbody:
                scan_stmt(sub)
            return
        for n in _walk_skip_defs(stmt):
            if isinstance(n, ast.Call):
                visit_call(n)

    for stmt in fi.node.body:
        scan_stmt(stmt)
    return out


def _declared(index: PackageIndex) -> list[FuncInfo]:
    return [
        fi for m in index.modules for fi in m.functions.values()
        if fi.durable
    ]


# ---------------------------------------------------------------------------
# G018 — atomic-commit discipline
# ---------------------------------------------------------------------------


def g018_atomic_commit(index: PackageIndex) -> list[Finding]:
    """Durable artifacts reach their final name only via tmp +
    ``os.replace`` inside a declared protocol — and a commit is only a
    commit when the staged bytes were fsynced first (see module
    docstring)."""
    out: list[Finding] = []
    for fi in sorted(_declared(index),
                     key=lambda f: (f.module.path, f.node.lineno)):
        if fi.protocol is not None and fi.protocol not in KNOWN_PROTOCOLS:
            out.append(Finding(
                rule="G018", path=fi.module.path, line=fi.node.lineno,
                col=fi.node.col_offset,
                msg=(
                    f"`{fi.qualname}` declares unknown durable protocol "
                    f"`{fi.protocol}` (known: "
                    f"{', '.join(KNOWN_PROTOCOLS)}) — a typo'd tag "
                    "silently exempts the function from the fs-protocol "
                    "accounting forever"
                ),
            ))
            continue
        effects = _function_effects(index, fi, fi.protocol)
        fsync_seen = False
        for e in effects:
            if e.op == "fsync":
                fsync_seen = True
            elif e.op == "write" and e.role == "durable" and e.reportable:
                out.append(Finding(
                    rule="G018", path=e.fi.module.path, line=e.line,
                    col=e.col,
                    msg=(
                        "in-place write-mode open of a durable path "
                        f"role in protocol `{fi.protocol}` — a crash "
                        "mid-write leaves a torn artifact under its "
                        "committed name; write to a `.tmp` sibling and "
                        "commit it with os.replace"
                    ),
                ))
            elif e.op in _COMMIT_OPS and e.role == "durable" \
                    and not fsync_seen and e.reportable:
                out.append(Finding(
                    rule="G018", path=e.fi.module.path, line=e.line,
                    col=e.col,
                    msg=(
                        f"committed {e.op} in protocol `{fi.protocol}` "
                        "with no fsync anywhere earlier in the effect "
                        "sequence — rename durability does not imply "
                        "content durability; fsync the staged file "
                        "(and the parent directory) before the commit"
                    ),
                ))
    return out


# ---------------------------------------------------------------------------
# G019 — durable ordering
# ---------------------------------------------------------------------------


def g019_durable_ordering(index: PackageIndex) -> list[Finding]:
    """Destruction of a durable copy must be dominated by the committed
    install of its replacement — or by a read of the committed record
    (completing a torn pass).  Unlink-before-install is the PR 13
    spool crash window; rmtree-before-commit is the PR 12 torn-GC
    class."""
    out: list[Finding] = []
    for fi in sorted(_declared(index),
                     key=lambda f: (f.module.path, f.node.lineno)):
        if fi.protocol is not None and fi.protocol not in KNOWN_PROTOCOLS:
            continue  # G018 already flagged the typo
        effects = _function_effects(index, fi, fi.protocol)
        dominated = False
        for e in effects:
            if (e.op in _COMMIT_OPS and e.role == "durable") \
                    or e.op in ("read", "npload"):
                dominated = True
            elif e.op in _DESTRUCTIVE_OPS and e.role == "durable" \
                    and not dominated and e.reportable:
                out.append(Finding(
                    rule="G019", path=e.fi.module.path, line=e.line,
                    col=e.col,
                    msg=(
                        f"{e.op} of a durable path role in protocol "
                        f"`{fi.protocol}` before any committed install "
                        "(os.replace/os.rename to a durable target) or "
                        "read of the committed record — a crash at "
                        "this boundary destroys the only copy; install "
                        "the replacement first, destroy second"
                    ),
                ))
    return out


# ---------------------------------------------------------------------------
# G020 — verify-before-trust
# ---------------------------------------------------------------------------


def _resolve_catch(handler_type: ast.expr | None, module
                   ) -> set[str] | None:
    """The exception-name set an ``except`` clause catches, resolving
    a bare Name through module-level tuple assignments (the
    ``_RECOVER_ERRORS`` idiom).  None = unresolvable or bare except
    (trust it — a bare except already covers the garbage set)."""
    if handler_type is None:
        return None
    if isinstance(handler_type, ast.Tuple):
        names: set[str] = set()
        for el in handler_type.elts:
            got = _resolve_catch(el, module)
            if got is None:
                return None
            names |= got
        return names
    if isinstance(handler_type, ast.Attribute):
        return {handler_type.attr}
    if isinstance(handler_type, ast.Name):
        name = handler_type.id
        for node in ast.iter_child_nodes(module.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id == name \
                    and isinstance(node.value, ast.Tuple):
                return _resolve_catch(node.value, module)
        return {name}
    return None


def g020_verify_before_trust(index: PackageIndex) -> list[Finding]:
    """(a) a ``np.load`` of a durable artifact in a function that never
    computes ``zlib.crc32`` is a TRUSTED read — damage flows into field
    access far from the load site; route it through the verifying
    reader.  (b) a fallback handler (no re-raise) in a declared
    protocol function whose try-body indexes into parsed data must
    catch the parseable-garbage set {KeyError, IndexError, TypeError}:
    a bit-flipped manifest can stay parseable json with garbled values,
    and designed-recoverable corruption must degrade to the next
    candidate, never crash the recovery (the ``_read_manifest``
    incident class)."""
    out: list[Finding] = []
    crc_cache: dict[int, bool] = {}

    def has_crc(fi: FuncInfo) -> bool:
        if id(fi) not in crc_cache:
            crc_cache[id(fi)] = any(
                isinstance(n, ast.Call)
                and (dotted(n.func) or "").endswith("crc32")
                for n in _walk_skip_defs(fi.node)
            )
        return crc_cache[id(fi)]

    for fi in sorted(_declared(index),
                     key=lambda f: (f.module.path, f.node.lineno)):
        if fi.protocol is not None and fi.protocol not in KNOWN_PROTOCOLS:
            continue
        for e in _function_effects(index, fi, fi.protocol):
            if e.op == "npload" and e.reportable and not has_crc(e.fi):
                out.append(Finding(
                    rule="G020", path=e.fi.module.path, line=e.line,
                    col=e.col,
                    msg=(
                        "trusted np.load of a durable artifact in "
                        f"protocol `{fi.protocol}` — no CRC "
                        "verification in this function; bit flips "
                        "surface as field-access crashes far from the "
                        "load site, route the read through the "
                        "verifying loader (utils/checkpoint.load_state)"
                    ),
                ))
        for node in _walk_skip_defs(fi.node):
            if not isinstance(node, ast.Try):
                continue
            body_subscripts = any(
                isinstance(n, ast.Subscript)
                for stmt in node.body for n in ast.walk(stmt)
            )
            if not body_subscripts:
                continue
            for handler in node.handlers:
                caught = _resolve_catch(handler.type, fi.module)
                if caught is None:
                    continue
                if {"Exception", "BaseException"} & caught:
                    continue
                if any(isinstance(n, ast.Raise)
                       for stmt in handler.body
                       for n in ast.walk(stmt)):
                    continue  # re-raise: not a fallback
                missing = _GARBAGE_ERRORS - caught
                if missing:
                    out.append(Finding(
                        rule="G020", path=fi.module.path,
                        line=handler.lineno, col=handler.col_offset,
                        msg=(
                            "recovery fallback in protocol "
                            f"`{fi.protocol}` catches "
                            f"{{{', '.join(sorted(caught))}}} but the "
                            "try-body indexes into parsed data — a "
                            "bit-flipped manifest stays PARSEABLE with "
                            "garbled values and escapes as "
                            f"{{{', '.join(sorted(missing))}}}; widen "
                            "the catch to the parseable-garbage set so "
                            "damage degrades to the next candidate "
                            "instead of crashing the recovery"
                        ),
                    ))
    return out


# ---------------------------------------------------------------------------
# G021 — fs-protocol cross-check (static markers vs runtime fs_ops)
# ---------------------------------------------------------------------------


def g021_fs_protocols(index: PackageIndex, artifact_path: str
                      ) -> list[Finding]:
    """Cross-validate the declared ``durable=`` protocols against a
    serve run's ``fs_ops`` counters (the fs sanitizer's ground truth):
    a declared protocol the run never entered is DEAD — the annotation
    is stale or the commit path moved; a runtime protocol tag (or an
    unattributed mutating op) with no matching static declaration is
    fs activity the crash-consistency model does not know about.
    Dead-checking is scoped by armed surface exactly like G011 fence
    tags: ``snapshot``/``gc``/``wal`` are only expected in journaled
    runs, ``spool`` when the pool actually spooled, ``flight`` when a
    dump fired."""
    block, err = load_artifact_block(artifact_path, "fs_ops")
    if block is None:
        return [Finding(
            rule="G021", path=artifact_path, line=0, col=0, msg=err,
        )]
    entries = block.get("protocols") or {}
    ops = block.get("ops") or {}
    unattributed = block.get("unattributed") or {}
    declared: dict[str, FuncInfo] = {}
    for fi in sorted(_declared(index),
                     key=lambda f: (f.module.path, f.node.lineno)):
        if fi.protocol in KNOWN_PROTOCOLS:
            declared.setdefault(fi.protocol, fi)
    out: list[Finding] = []
    for tag, fi in sorted(declared.items()):
        surface = PROTOCOL_SURFACES[tag]
        if surface not in block:
            out.append(Finding(
                rule="G021", path=fi.module.path, line=fi.node.lineno,
                col=fi.node.col_offset,
                msg=(
                    f"durable protocol `{tag}` is scoped to surface "
                    f"`{surface}` but "
                    f"{os.path.basename(artifact_path)} records no "
                    "such surface — stale fs_ops schema or typo'd "
                    "surface map; an unmatchable surface silently "
                    "disables the dead-protocol check"
                ),
            ))
            continue
        if not block.get(surface):
            continue  # surface not armed in this run
        if not entries.get(tag):
            out.append(Finding(
                rule="G021", path=fi.module.path, line=fi.node.lineno,
                col=fi.node.col_offset,
                msg=(
                    f"declared durable protocol `{tag}` never entered "
                    f"in {os.path.basename(artifact_path)} (surface "
                    f"`{surface}` armed) — dead protocol: delete the "
                    "stale annotation or route the real commit path "
                    "through its fs_protocol context"
                ),
            ))
    for tag in sorted(set(entries) | set(ops)):
        if tag not in declared:
            out.append(Finding(
                rule="G021", path=artifact_path, line=0, col=0,
                msg=(
                    f"runtime fs protocol `{tag}` has no matching "
                    "`# graftlint: durable=` marker — fs activity the "
                    "static crash-consistency model does not know about"
                ),
            ))
    for op, n in sorted(unattributed.items()):
        out.append(Finding(
            rule="G021", path=artifact_path, line=0, col=0,
            msg=(
                f"{n} unattributed runtime `{op}` op(s) on watched "
                "durable roots outside every declared protocol — "
                "either declare the owning protocol or move the op "
                "out of durable territory"
            ),
        ))
    return out
