"""graftlint — JAX-hygiene static analysis + jit-boundary contracts.

Run it: ``python -m crdt_benches_tpu.lint <paths>`` (or ``tools/lint.sh``).
Suppress a finding: trailing ``# graftlint: disable=G00X`` on the line,
or ``# graftlint: disable-file=G00X`` anywhere in the file.
"""

from .boundary import (  # noqa: F401
    REGISTRY,
    BoundaryContract,
    BoundaryError,
    boundary,
    boundary_table,
    checks_enabled,
)
from .core import (  # noqa: F401
    Finding,
    format_json,
    format_sarif,
    format_text,
    run_lint,
)
from .fs_sanitizer import (  # noqa: F401
    DurableOrderingError,
    InjectedCrash,
    crash_at,
    durable_protocol,
    fs_protocol,
    watch_root,
)
from .race_sanitizer import (  # noqa: F401
    SharedProxy,
    UndeclaredCrossThreadAccess,
    publish_point,
    published,
    reveal,
    share,
)
from .sanitizer import (  # noqa: F401
    UndeclaredSyncError,
    fence,
    fenced,
    hot_path,
    sanitizing,
)

__all__ = [
    "REGISTRY",
    "BoundaryContract",
    "BoundaryError",
    "DurableOrderingError",
    "InjectedCrash",
    "SharedProxy",
    "UndeclaredCrossThreadAccess",
    "UndeclaredSyncError",
    "crash_at",
    "durable_protocol",
    "fs_protocol",
    "watch_root",
    "boundary",
    "boundary_table",
    "checks_enabled",
    "fence",
    "fenced",
    "hot_path",
    "publish_point",
    "published",
    "reveal",
    "sanitizing",
    "share",
    "Finding",
    "format_json",
    "format_sarif",
    "format_text",
    "run_lint",
]
