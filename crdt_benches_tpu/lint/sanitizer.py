"""Runtime sync sanitizer: the dynamic half of the G002 fence model.

graftlint's G002 proves *statically* that no host sync is reachable from
the serving hot path outside a ``# graftlint: fence`` function — but the
static model trusts the annotations.  This module supplies the runtime
evidence:

- every declared fence routes through :func:`fence` (usually via the
  :func:`fenced` decorator, keyed by the function's ``__qualname__`` so
  runtime counters line up with the static fence graph) and counts its
  **entries** — always, in every mode, a dict increment per boundary
  crossing (nanoseconds against a multi-ms macro-round);
- with ``CRDT_BENCH_SANITIZE_SYNCS=1``, :func:`hot_path` (wrapped around
  ``FleetScheduler.run_round``) arms the sanitizer: the exact host-sync
  surface G002 models (``Array.__array__`` — the ``np.asarray``/
  ``device_get`` funnel — ``.item()``, ``.tolist()``,
  ``block_until_ready``, ``__int__``/``__float__``/``__bool__``/
  ``__index__``) is interposed, and any such call OUTSIDE an active
  fence raises :class:`UndeclaredSyncError` **at the offending
  callsite**.  Inside a fence the sync is allowed and counted against
  that fence (innermost wins), giving per-fence **sync** counters.
  ``jax.transfer_guard_device_to_host("disallow")`` is entered too —
  a no-op on the zero-copy CPU backend (which is exactly why the
  interposition exists) but a second, independent tripwire on real
  accelerators, re-allowed inside fences;
- the serve bench snapshots :func:`counters` into its artifact as the
  ``boundary_syncs`` block, and lint rule G011 cross-validates that
  ground truth against the static fence graph (dead declared fences,
  unattributed runtime fences).

Everything here is import-light on purpose: jax is imported lazily and
only once the sanitizer actually arms, so the serve modules can import
:func:`fenced` without changing cold-start, and with the flag unset the
only cost anywhere is the per-entry counter bump.
"""

from __future__ import annotations

import functools
import os
import threading
from contextlib import contextmanager

_ENV = "CRDT_BENCH_SANITIZE_SYNCS"

#: Host-sync surface interposed on the jax Array type — the runtime
#: twin of rules.py G002's ``_SYNC_METHODS`` model.
_SYNC_SURFACE = (
    "__array__", "item", "tolist", "block_until_ready",
    "__int__", "__float__", "__bool__", "__index__", "__complex__",
)

#: numpy module-level converters interposed for CONCRETE jax arrays:
#: the CPU backend satisfies ``np.asarray`` through the zero-copy C
#: buffer protocol, never calling ``__array__`` — the exact reason the
#: native transfer guard is silent on CPU and these wrappers exist.
#: This is G002's ``_NP_SYNC_FUNCS`` surface plus ``ascontiguousarray``.
_NP_SURFACE = ("asarray", "array", "copy", "ascontiguousarray")


class UndeclaredSyncError(RuntimeError):
    """A host sync fired on the serving hot path outside every declared
    fence — the static G002 model just met a counterexample."""


_tls = threading.local()
_entries: dict[str, int] = {}
_syncs: dict[str, int] = {}
_hooks_installed = False
#: Fence-entry observers (obs/trace.py plants one when span tracing is
#: armed, so every boundary crossing lands on the timeline).  Empty in
#: normal runs: the per-crossing cost stays one truthiness test.
_fence_observers: list = []


def add_fence_observer(cb) -> None:
    """Register ``cb(qualname)`` to run at every fence entry."""
    if cb not in _fence_observers:
        _fence_observers.append(cb)


def remove_fence_observer(cb) -> None:
    if cb in _fence_observers:
        _fence_observers.remove(cb)


def sanitizing() -> bool:
    """True when ``CRDT_BENCH_SANITIZE_SYNCS`` arms the sanitizer.
    Read per hot-scope entry (not at import) so tests can flip it."""
    return os.environ.get(_ENV, "") not in ("", "0")


def _fence_stack() -> list:
    s = getattr(_tls, "fences", None)
    if s is None:
        s = _tls.fences = []
    return s


def _hot_depth() -> int:
    return getattr(_tls, "hot", 0)


def reset_counters() -> None:
    """Zero both counter tables (each bench run owns its window)."""
    _entries.clear()
    _syncs.clear()


def counters() -> dict[str, dict[str, int]]:
    """Snapshot: ``{"entries": {fence: n}, "syncs": {fence: n}}``.
    ``syncs`` is only populated while the sanitizer is armed (the
    interposition is what attributes individual host syncs)."""
    return {
        "entries": dict(sorted(_entries.items())),
        "syncs": dict(sorted(_syncs.items())),
    }


def entries_total() -> int:
    """Sum of every fence-entry counter — the per-round accessor the
    time-series recorder samples (``counters()`` builds fresh sorted
    dicts; this is one pass over a handful of ints)."""
    return sum(_entries.values())


def _note_sync(label: str) -> None:
    stack = _fence_stack()
    if stack:
        _syncs[stack[-1]] = _syncs.get(stack[-1], 0) + 1
        return
    if _hot_depth() > 0:
        raise UndeclaredSyncError(
            f"undeclared host sync `{label}` on the serving hot path "
            "(CRDT_BENCH_SANITIZE_SYNCS=1): no `# graftlint: fence` "
            "scope is active here — move the sync behind a declared "
            "fence or declare this boundary"
        )


def _install_hooks() -> None:
    global _hooks_installed
    if _hooks_installed:
        return
    from jax._src.array import ArrayImpl

    def wrap(orig, label):
        # NOT functools.wraps: several of these are pybind11-level
        # methods whose metadata attributes reject copying
        def hooked(self, *args, **kwargs):
            _note_sync(label)
            return orig(self, *args, **kwargs)

        hooked.__name__ = label
        hooked.__graft_sanitizer__ = True
        return hooked

    for name in _SYNC_SURFACE:
        orig = getattr(ArrayImpl, name, None)
        if orig is None or getattr(orig, "__graft_sanitizer__", False):
            continue
        setattr(ArrayImpl, name, wrap(orig, name))

    import numpy as np

    def wrap_np(orig, label):
        def hooked(*args, **kwargs):
            # the data operand may arrive by keyword (np.asarray(a=...),
            # np.array(object=...)) — never constrain the signature
            probe = args[0] if args else kwargs.get(
                "a", kwargs.get("object")
            )
            if isinstance(probe, ArrayImpl):
                _note_sync(f"np.{label}")
            return orig(*args, **kwargs)

        hooked.__name__ = label
        hooked.__graft_sanitizer__ = True
        return hooked

    for name in _NP_SURFACE:
        orig = getattr(np, name, None)
        if orig is None or getattr(orig, "__graft_sanitizer__", False):
            continue
        setattr(np, name, wrap_np(orig, name))
    _hooks_installed = True


@contextmanager
def hot_path():
    """Arm the sanitizer for one hot-path scope (no-op unless the env
    flag is set).  Inside: any interposed host sync outside a fence
    raises; ``transfer_guard_device_to_host`` is set to disallow for
    backends that enforce it."""
    if not sanitizing():
        yield
        return
    _install_hooks()
    import jax

    _tls.hot = _hot_depth() + 1
    try:
        with jax.transfer_guard_device_to_host("disallow"):
            yield
    finally:
        _tls.hot -= 1


@contextmanager
def fence(name: str):
    """One declared-boundary crossing: count the entry, allow (and
    attribute) host syncs within."""
    _entries[name] = _entries.get(name, 0) + 1
    if _fence_observers:
        for cb in _fence_observers:
            cb(name)
    stack = _fence_stack()
    stack.append(name)
    try:
        if _hot_depth() > 0:
            import jax

            with jax.transfer_guard_device_to_host("allow"):
                yield
        else:
            yield
    finally:
        stack.pop()


def fenced(fn):
    """Decorator form of :func:`fence`, keyed by ``__qualname__`` so the
    runtime counter name equals the static fence graph's qualname.  Goes
    on exactly the functions carrying ``# graftlint: fence`` markers —
    G011 cross-checks that the two sets agree."""
    name = fn.__qualname__

    @functools.wraps(fn)
    def crossing(*args, **kwargs):
        with fence(name):
            return fn(*args, **kwargs)

    crossing.__graft_fence__ = name
    return crossing
