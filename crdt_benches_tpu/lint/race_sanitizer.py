"""Runtime race sanitizer: the dynamic half of the G014/G015 model.

graftlint's thread-confinement rules (lint/threads.py) prove
*statically* that every mutable object crossing host threads passes
through a declared ``# graftlint: publish`` point — but the static
model trusts the annotations.  This module supplies the runtime
evidence, the same architecture as the G002 sync sanitizer:

- every declared publish point routes through :func:`publish_point`
  (usually via the :func:`published` decorator, keyed by the
  function's ``__qualname__`` so runtime counters line up with the
  static publish markers) and counts its **entries** — always, in
  every mode, one lock-guarded dict increment per handoff;
- with ``CRDT_BENCH_SANITIZE_RACES=1``, :func:`share` wraps the object
  being handed over in a :class:`SharedProxy` — an ownership cell
  remembering its **owner thread id**, its **publish generation**
  (bumped at each declared publish), and the publish point that last
  released it.  An access from another thread while the object is
  UNPUBLISHED raises :class:`UndeclaredCrossThreadAccess` **at the
  callsite**; so does any in-place mutation after publish THROUGH the
  shared reference (owner or reader side — a published snapshot is
  frozen by contract, exactly G015's two halves).  A mutation through
  a bare alias the publisher retained is invisible to the proxy, so
  each publish also fingerprints the snapshot (they are
  JSON-serializable by contract — /status.json renders them) and every
  legal cross-thread read re-verifies it: a torn publish raises at the
  READ that observes it, attributed to its publish point.  Legal
  cross-thread reads are counted against the publish point that made
  them legal, giving per-point **crossing** counters;
- the serve bench snapshots :func:`counters` into its artifact as the
  ``thread_crossings`` block, and lint rule G017 cross-validates that
  ground truth against the static publish markers (dead publish
  points, unattributed crossings) — G011's mirror.

Disarmed (the default), :func:`share` and :func:`reveal` return their
argument unchanged — identity, asserted by tests like the ``@fenced``
and span no-op paths — so the only cost anywhere is the publish-entry
counter bump (a mutex-guarded dict store, gated <=5% by the smoke's
race-sanitized leg).
"""

from __future__ import annotations

import functools
import json
import os
import threading
from contextlib import contextmanager

_ENV = "CRDT_BENCH_SANITIZE_RACES"


class UndeclaredCrossThreadAccess(RuntimeError):
    """An object crossed host threads outside every declared publish
    point (or was mutated after publish) — the static G014/G015
    confinement model just met a counterexample."""


_tls = threading.local()
#: Publish-point entry counts — bumped in EVERY run (G017's ground
#: truth), exactly like the sync sanitizer's fence entries.
_publishes: dict[str, int] = {}
#: Cross-thread accesses attributed to the publish point that made
#: them legal — only populated while the sanitizer is armed (the
#: proxies are what observe individual accesses).
_crossings: dict[str, int] = {}
#: Crossing bumps come from reader threads (the status server's
#: handler pool), so unlike every other counter in lint/ they need a
#: real mutex.  Publish bumps take it too: today one thread publishes,
#: but the ROADMAP's prefetch/bus work adds publisher threads, and an
#: uncounted bump (or a dict resize racing ``counters()``) would
#: corrupt the very G017 ground truth this module exists to record.
#: The critical section is one dict store — the race-sanitized smoke
#: leg's <=5% overhead gate holds with it in place.
_mu = threading.Lock()

#: Publish-entry observers (the sanitizer's fence-observer pattern,
#: lint/sanitizer.py): each is called with the point's qualname at
#: every publish-point entry, OUTSIDE the counter mutex.  The request
#: tracer (obs/reqtrace.py) hooks here so every trace-context
#: propagation edge IS a declared publish point — the crossing
#: counters and the request trace stay one causal picture.  Observers
#: run on the publishing thread (for every point in this stack, the
#: hot thread); an observer that needs cross-thread safety brings its
#: own.
_publish_observers: list = []


def add_publish_observer(fn) -> None:
    if fn not in _publish_observers:
        _publish_observers.append(fn)


def remove_publish_observer(fn) -> None:
    try:
        _publish_observers.remove(fn)
    except ValueError:
        pass


def sanitizing() -> bool:
    """True when ``CRDT_BENCH_SANITIZE_RACES`` arms the sanitizer.
    Read at every :func:`share` (not at import) so tests can flip it."""
    return os.environ.get(_ENV, "") not in ("", "0")


def reset_counters() -> None:
    """Zero both counter tables (each bench run owns its window)."""
    with _mu:
        _publishes.clear()
        _crossings.clear()


def counters() -> dict[str, dict[str, int]]:
    """Snapshot: ``{"publishes": {point: n}, "crossings": {point: n}}``.
    ``crossings`` is only populated while the sanitizer is armed."""
    with _mu:
        return {
            "publishes": dict(sorted(_publishes.items())),
            "crossings": dict(sorted(_crossings.items())),
        }


def _point_stack() -> list:
    s = getattr(_tls, "points", None)
    if s is None:
        s = _tls.points = []
    return s


#: Receiver-mutating method names the proxy treats as writes.  This is
#: THE canonical set: the static model (lint/threads.py
#: MUTATOR_METHODS) derives from it, so the two halves of the
#: G014/G015 model cannot drift apart.
MUTATOR_METHODS = frozenset({
    "append", "extend", "insert", "update", "setdefault", "pop",
    "popitem", "popleft", "appendleft", "clear", "add", "discard",
    "remove", "sort", "reverse",
})

_SLOTS = ("_graft_target", "_graft_label", "_graft_owner",
          "_graft_gen", "_graft_point", "_graft_fp")


def _fingerprint(obj) -> str | None:
    """Content fingerprint of a published snapshot, or None when the
    object is not canonically serializable.  The snapshots this module
    guards are JSON-serializable by contract (/status.json and the
    Prometheus renderer consume them), so in practice every publish
    gets one."""
    try:
        return json.dumps(obj, sort_keys=True, default=repr)
    except Exception:
        return None


class SharedProxy:
    """Ownership-tracking wrapper around one shared object.

    Owner-thread accesses are free until the object is published; a
    publish (inside a declared publish point) freezes it — further
    in-place mutation from ANY thread raises — and licenses
    cross-thread reads, each counted against the publish point.  An
    unpublished cross-thread access raises at the callsite."""

    __slots__ = _SLOTS

    def __init__(self, target, label: str):
        object.__setattr__(self, "_graft_target", target)
        object.__setattr__(self, "_graft_label", label)
        object.__setattr__(self, "_graft_owner", threading.get_ident())
        object.__setattr__(self, "_graft_gen", 0)
        object.__setattr__(self, "_graft_point", None)
        object.__setattr__(self, "_graft_fp", None)

    # -- the access rule --

    def _graft_check(self, mutate: bool, what: str) -> None:
        tid = threading.get_ident()
        gen = self._graft_gen
        if tid == self._graft_owner:
            if mutate and gen:
                raise UndeclaredCrossThreadAccess(
                    f"owner mutation `{what}` of `{self._graft_label}` "
                    f"AFTER publish (generation {gen}, via "
                    f"`{self._graft_point}`): a published object is "
                    "frozen — readers on other threads may hold it; "
                    "build a fresh object and publish that instead "
                    f"({_ENV}=1)"
                )
            return
        if gen == 0:
            raise UndeclaredCrossThreadAccess(
                f"undeclared cross-thread access `{what}` to "
                f"`{self._graft_label}` (owner thread "
                f"{self._graft_owner}, reader thread {tid}): the "
                "object never passed a declared publish point "
                f"({_ENV}=1) — hand it over inside a "
                "`# graftlint: publish` function"
            )
        if mutate:
            raise UndeclaredCrossThreadAccess(
                f"reader-side mutation `{what}` of published "
                f"`{self._graft_label}` (thread {tid}): what crosses "
                "a publish point is read-only on the far side — copy "
                f"before mutating ({_ENV}=1)"
            )
        # torn-publish detection: the proxy cannot see a mutation made
        # through a bare alias the publisher retained, but the
        # fingerprint taken at publish can — verify it at every legal
        # cross-thread read, so the tear raises at the read that would
        # have observed it.
        fp = self._graft_fp
        if fp is not None and _fingerprint(self._graft_target) != fp:
            raise UndeclaredCrossThreadAccess(
                f"torn publish of `{self._graft_label}` observed at "
                f"read `{what}` (thread {tid}, via "
                f"`{self._graft_point}`): the snapshot changed after "
                "its publish — the publisher mutated a retained bare "
                "reference; a published object is frozen, build a "
                f"fresh one and publish that instead ({_ENV}=1)"
            )
        point = self._graft_point
        with _mu:
            _crossings[point] = _crossings.get(point, 0) + 1

    def _graft_publish(self, point: str) -> None:
        object.__setattr__(self, "_graft_gen", self._graft_gen + 1)
        object.__setattr__(self, "_graft_point", point)
        object.__setattr__(self, "_graft_fp",
                           _fingerprint(self._graft_target))

    # -- forwarding surface --

    def __getattr__(self, name):
        self._graft_check(name in MUTATOR_METHODS, name)
        return getattr(self._graft_target, name)

    def __setattr__(self, name, value):
        self._graft_check(True, f"set {name}")
        setattr(self._graft_target, name, value)

    def __getitem__(self, k):
        self._graft_check(False, f"[{k!r}]")
        return self._graft_target[k]

    def __setitem__(self, k, v):
        self._graft_check(True, f"[{k!r}] = ...")
        self._graft_target[k] = v

    def __delitem__(self, k):
        self._graft_check(True, f"del [{k!r}]")
        del self._graft_target[k]

    def __iter__(self):
        self._graft_check(False, "iter")
        return iter(self._graft_target)

    def __len__(self):
        self._graft_check(False, "len")
        return len(self._graft_target)

    def __contains__(self, k):
        self._graft_check(False, "in")
        return k in self._graft_target

    def __bool__(self):
        self._graft_check(False, "bool")
        return bool(self._graft_target)

    def __repr__(self):
        return (
            f"SharedProxy({self._graft_label!r}, "
            f"gen={self._graft_gen}, via={self._graft_point!r})"
        )


def share(obj, label: str | None = None):
    """Wrap ``obj`` for cross-thread handoff.  Disarmed: returns
    ``obj`` unchanged (identity — the zero-overhead contract).  Armed:
    returns (or re-publishes) a :class:`SharedProxy`; when called
    inside an active publish point the proxy's generation bumps and
    the handoff is attributed to that point, otherwise the object
    stays owner-confined until a publish releases it."""
    if not sanitizing():
        return obj
    if isinstance(obj, SharedProxy):
        proxy = obj
    else:
        proxy = SharedProxy(obj, label or type(obj).__name__)
    stack = _point_stack()
    if stack:
        proxy._graft_publish(stack[-1])
    return proxy


def reveal(obj):
    """The reader-side gate: check the cross-thread access (counted
    against the licensing publish point; raises if unpublished) and
    return the BARE object — callers hand it to code that needs the
    real type (``json.dumps``, the Prometheus renderer).  Identity on
    non-proxies, so disarmed paths pass straight through."""
    if isinstance(obj, SharedProxy):
        obj._graft_check(False, "reveal")
        return obj._graft_target
    return obj


def generation(obj) -> int | None:
    """The proxy's publish generation (None for bare objects)."""
    if isinstance(obj, SharedProxy):
        return obj._graft_gen
    return None


@contextmanager
def publish_point(name: str):
    """One declared publish-point entry: count it (always — the G017
    ground truth), and while inside, every :func:`share` call is a
    publish attributed to ``name``."""
    with _mu:
        _publishes[name] = _publishes.get(name, 0) + 1
    if _publish_observers:  # disarmed runs keep the entry allocation-free
        for fn in list(_publish_observers):
            fn(name)
    stack = _point_stack()
    stack.append(name)
    try:
        yield
    finally:
        stack.pop()


def published(fn):
    """Decorator form of :func:`publish_point`, keyed by
    ``__qualname__`` so the runtime counter name equals the static
    publish marker's qualname.  Goes on exactly the functions carrying
    ``# graftlint: publish`` markers — G017 cross-checks that the two
    sets agree."""
    name = fn.__qualname__

    @functools.wraps(fn)
    def handoff(*args, **kwargs):
        with publish_point(name):
            return fn(*args, **kwargs)

    return handoff
