"""jit-boundary contract registry: declared shapes/dtypes/donation.

Every public jitted entry point in this repo is a *boundary*: host-built
tensors cross into a traced region, and the two historical classes of
silent breakage (a wrong dtype causing an unplanned recompile, a donated
buffer read after the donating call) both happen exactly there.  The
``@boundary`` decorator records each entry point's contract in a
machine-readable table (:data:`REGISTRY`):

    @boundary(dtypes=(None, "int32", "int32"), shapes=(None, "R B", "R B"),
              donates=(0,))
    @partial(jax.jit, donate_argnums=(0,))
    def fleet_step(state, kind, pos): ...

- ``dtypes``: per-positional-arg dtype name (``"int32"``), applied to
  every array leaf of that argument; ``None`` = unchecked.
- ``shapes``: per-arg symbolic dim spec (``"K R B"``); letters must bind
  consistently across the call's arguments, integer tokens are exact.
  Only checked for single-array arguments; ``None`` = unchecked.
- ``donates``: positions whose buffers the jitted callee donates.  The
  runtime check rejects *aliased donation* — a donated argument sharing
  an array object with any other argument (XLA would read a freed
  buffer, or silently copy).

The table is consumed three ways:

1. **statically** — graftlint rule G007 cross-checks the declared
   ``donates`` against the ``jax.jit(donate_argnums=...)`` in the same
   decorator stack, and call sites against declared dtypes;
2. **at runtime** — with ``CRDT_BENCH_CHECK_BOUNDARIES=1`` in the
   environment at import time, every decorated call validates its
   arguments (works on tracers too: checks read only ``.dtype``/
   ``.shape``);
3. **zero-overhead default** — with the variable unset the decorator
   returns the function object *unchanged* (identity), so production
   dispatch pays nothing (asserted by tests/test_boundary.py).

This module is stdlib-only on purpose: the hot modules import it, and it
must never drag jax into import-time of the lint CLI.
"""

from __future__ import annotations

import functools
import inspect
import os
from dataclasses import dataclass

_ENV = "CRDT_BENCH_CHECK_BOUNDARIES"


def checks_enabled() -> bool:
    """True when the debug enforcement mode is switched on.  Read at
    DECORATION time (module import), not per call — the off switch must
    cost zero, so there is no per-call branch to mispredict."""
    return os.environ.get(_ENV, "") not in ("", "0")


class BoundaryError(TypeError):
    """A call violated its declared jit-boundary contract."""


@dataclass(frozen=True)
class BoundaryContract:
    name: str  # "module.qualname" — the registry key
    dtypes: tuple  # per-positional-arg dtype name or None
    shapes: tuple  # per-positional-arg "K R B" spec or None
    donates: tuple  # donated positional indices

    def describe(self) -> dict:
        return {
            "dtypes": list(self.dtypes),
            "shapes": list(self.shapes),
            "donates": list(self.donates),
        }


#: The machine-readable contract table, keyed by "module.qualname".
REGISTRY: dict[str, BoundaryContract] = {}


def boundary_table() -> dict[str, dict]:
    """The registry as plain JSON-ready data (``--boundaries`` dump)."""
    return {name: c.describe() for name, c in sorted(REGISTRY.items())}


def _leaves(x):
    """Array leaves of a minimal pytree (NamedTuple / tuple / list /
    dict) — no jax import; anything with a ``.dtype`` is a leaf."""
    if hasattr(x, "_fields"):  # NamedTuple state pytrees
        for f in x._fields:
            yield from _leaves(getattr(x, f))
    elif isinstance(x, (tuple, list)):
        for v in x:
            yield from _leaves(v)
    elif isinstance(x, dict):
        for v in x.values():
            yield from _leaves(v)
    elif hasattr(x, "dtype"):
        yield x


def _check_call(c: BoundaryContract, args: tuple) -> None:
    # dtypes: every array leaf of arg i must match the declared name
    for i, want in enumerate(c.dtypes):
        if want is None or i >= len(args):
            continue
        for leaf in _leaves(args[i]):
            got = str(leaf.dtype)
            if got != want:
                raise BoundaryError(
                    f"{c.name}: arg {i} dtype {got!r} != declared {want!r}"
                )
    # shapes: symbolic dims bind consistently across the call
    env: dict[str, int] = {}
    for i, spec in enumerate(c.shapes):
        if spec is None or i >= len(args):
            continue
        leaves = list(_leaves(args[i]))
        if len(leaves) != 1:  # pytree arg: spec applies to arrays only
            continue
        shape = tuple(leaves[0].shape)
        toks = spec.split()
        if len(shape) != len(toks):
            raise BoundaryError(
                f"{c.name}: arg {i} rank {len(shape)} != declared "
                f"{spec!r}"
            )
        for tok, dim in zip(toks, shape):
            if tok.isdigit():
                if int(tok) != dim:
                    raise BoundaryError(
                        f"{c.name}: arg {i} dim {dim} != declared {tok} "
                        f"in {spec!r}"
                    )
            elif env.setdefault(tok, dim) != dim:
                raise BoundaryError(
                    f"{c.name}: arg {i} dim {tok}={dim} contradicts "
                    f"{tok}={env[tok]} bound earlier in the call"
                )
    # donation: a donated buffer must not alias any other argument
    for i in c.donates:
        if i >= len(args):
            continue
        donated = {id(leaf) for leaf in _leaves(args[i])}
        for j, other in enumerate(args):
            if j == i:
                continue
            for leaf in _leaves(other):
                if id(leaf) in donated:
                    raise BoundaryError(
                        f"{c.name}: arg {j} aliases donated arg {i} — "
                        "the donated buffer would be read after free"
                    )


def boundary(*, dtypes=(), shapes=(), donates=(), check=None):
    """Declare a jit-boundary contract (see module docstring).

    ``check`` overrides the environment switch (tests use it to build
    enforced wrappers without re-importing the world)."""

    def deco(fn):
        c = BoundaryContract(
            name=f"{fn.__module__}.{fn.__qualname__}",
            dtypes=tuple(dtypes),
            shapes=tuple(shapes),
            donates=tuple(donates),
        )
        REGISTRY[c.name] = c
        enabled = checks_enabled() if check is None else check
        if not enabled:
            try:
                fn.__boundary__ = c  # discoverable, still the bare fn
            except (AttributeError, TypeError):  # pragma: no cover
                pass
            return fn

        # positional parameter names, so keyword call sites are bound
        # back to their contract positions — `f(state, kind=k)` must be
        # checked exactly like `f(state, k)`
        try:
            pos_params = [
                p.name
                for p in inspect.signature(fn).parameters.values()
                if p.kind in (
                    inspect.Parameter.POSITIONAL_ONLY,
                    inspect.Parameter.POSITIONAL_OR_KEYWORD,
                )
            ]
        except (ValueError, TypeError):  # pragma: no cover
            pos_params = []

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            full = list(args)
            for name in pos_params[len(args):]:
                if name not in kwargs:
                    break
                full.append(kwargs[name])
            _check_call(c, tuple(full))
            return fn(*args, **kwargs)

        wrapper.__boundary__ = c
        return wrapper

    return deco
