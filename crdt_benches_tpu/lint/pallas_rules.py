"""Pallas kernel-launch sanity: rules G009 (grid/spec consistency) and
G010 (VMEM block lane alignment).

``ops/apply_range_fused.py`` alone carries ~20 ``BlockSpec``s feeding
three ``pl.pallas_call`` launches; nothing type-checks that the spec
list, the kernel signature, the grid rank and the block shapes agree —
a dropped spec or a stale index-map arity compiles into garbage reads
(or a Mosaic error naming none of this).  These rules parse every
``pl.pallas_call`` statically (resolving spec/kernel locals within the
enclosing function and dimension names like ``LANE`` through
:class:`crdt_benches_tpu.lint.flow.ConstEnv`) and check what is
decidable without running anything:

G009 — launch-geometry consistency:

- kernel positional arity == len(in_specs) + len(out_specs) +
  len(scratch_shapes) (``functools.partial``-bound positionals are
  discounted; kernels with ``*args`` are skipped);
- len(out_specs) == len(out_shape);
- the immediate call's argument count == len(in_specs);
- every BlockSpec index map takes exactly ``len(grid)`` parameters and
  returns one coordinate per block-shape dimension;
- where both a block-shape dim and the matching ``out_shape`` extent
  resolve to ints, the block must divide the extent it tiles (the
  "non-dividing grid" class: a partial edge block silently reads and
  writes out-of-tile data in interpret mode and miscompiles on Mosaic).

G010 — VMEM lane alignment: a resolved block-shape *minor* dimension
must be a multiple of ``LANE`` (128).  A minor dim of 1 is exempt — the
``(Rt, nt, 1)`` per-tile-scalar blocks this repo uses are padded to a
full lane by Mosaic, while an unaligned 8/64/96 silently serializes
every VMEM copy.  Symbolic dims that do not resolve are left alone:
the rule never guesses.
"""

from __future__ import annotations

import ast

from .core import Finding, FuncInfo, ModuleInfo, PackageIndex
from .flow import ConstEnv

_PALLAS_MODULE = "jax.experimental.pallas"


def _pallas_alias(m: ModuleInfo) -> str | None:
    for local, src in m.imports.items():
        if src == _PALLAS_MODULE:
            return local
    return None


def _is_pl_attr(m: ModuleInfo, e: ast.expr, attr: str,
                alias: str | None) -> bool:
    return (
        alias is not None
        and isinstance(e, ast.Attribute)
        and e.attr == attr
        and isinstance(e.value, ast.Name)
        and e.value.id == alias
    )


class _Spec:
    """One statically-parsed BlockSpec."""

    def __init__(self, node: ast.Call, shape_node: ast.expr | None,
                 shape: tuple | None, map_params: int | None,
                 map_rank: int | None):
        self.node = node
        self.shape_node = shape_node
        self.shape = shape  # tuple of int|None, or None when unknown
        self.map_params = map_params
        self.map_rank = map_rank


def _local_env(fn_node: ast.AST) -> dict[str, ast.expr]:
    """Single-assignment locals of the enclosing function (a name bound
    more than once is dropped — resolution must never guess)."""
    env: dict[str, ast.expr] = {}
    dead: set[str] = set()
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name):
                if t.id in env or t.id in dead:
                    env.pop(t.id, None)
                    dead.add(t.id)
                else:
                    env[t.id] = node.value
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign, ast.For)):
            t = node.target
            if isinstance(t, ast.Name):
                env.pop(t.id, None)
                dead.add(t.id)
    return env


def _deref(e: ast.expr, env: dict[str, ast.expr],
           depth: int = 0) -> ast.expr:
    while isinstance(e, ast.Name) and e.id in env and depth < 8:
        e = env[e.id]
        depth += 1
    return e


def _parse_spec(m: ModuleInfo, e: ast.expr, env: dict, cenv: ConstEnv,
                alias: str | None) -> _Spec | None:
    e = _deref(e, env)
    if not (isinstance(e, ast.Call)
            and _is_pl_attr(m, e.func, "BlockSpec", alias)):
        return None
    kw = {k.arg: k.value for k in e.keywords if k.arg}
    shape_node = e.args[0] if e.args else kw.get("block_shape")
    map_node = e.args[1] if len(e.args) > 1 else kw.get("index_map")
    shape = None
    if isinstance(shape_node, (ast.Tuple, ast.List)):
        shape = tuple(
            v if isinstance(v, int) else None
            for v in (cenv.fold(m, el) for el in shape_node.elts)
        )
    map_params = map_rank = None
    map_node = _deref(map_node, env) if map_node is not None else None
    if isinstance(map_node, ast.Lambda):
        a = map_node.args
        if not (a.vararg or a.kwarg):
            map_params = len(a.posonlyargs + a.args)
        body = map_node.body
        map_rank = len(body.elts) if isinstance(
            body, (ast.Tuple, ast.List)
        ) else 1
    return _Spec(e, shape_node, shape, map_params, map_rank)


def _spec_list(m: ModuleInfo, e: ast.expr | None, env: dict,
               cenv: ConstEnv, alias: str | None
               ) -> tuple[int | None, list[_Spec | None]]:
    """(count, parsed elements).  Count folds ``[x]*k`` and ``a + b``;
    a single BlockSpec counts as one.  (None, []) = undecidable."""
    if e is None:
        return None, []
    e = _deref(e, env)
    if isinstance(e, (ast.List, ast.Tuple)):
        specs = [_parse_spec(m, el, env, cenv, alias) for el in e.elts]
        return len(e.elts), specs
    if isinstance(e, ast.BinOp) and isinstance(e.op, ast.Add):
        nl, sl = _spec_list(m, e.left, env, cenv, alias)
        nr, sr = _spec_list(m, e.right, env, cenv, alias)
        if nl is None or nr is None:
            return None, []
        return nl + nr, sl + sr
    if isinstance(e, ast.BinOp) and isinstance(e.op, ast.Mult):
        base, mult = e.left, e.right
        if isinstance(base, ast.Constant):
            base, mult = mult, base
        n = cenv.fold(m, mult)
        nb, sb = _spec_list(m, base, env, cenv, alias)
        if isinstance(n, int) and nb is not None and 0 <= n < 1024:
            return nb * n, sb * n
        return None, []
    spec = _parse_spec(m, e, env, cenv, alias)
    if spec is not None:
        return 1, [spec]
    # anything else (a factory call, an unresolvable name) could hide
    # any number of specs — undecidable, never guess
    return None, []


def _sds_shapes(m: ModuleInfo, e: ast.expr | None, env: dict,
                cenv: ConstEnv) -> tuple[int | None, list[tuple | None]]:
    """out_shape as (count, per-entry resolved shape tuples)."""
    if e is None:
        return None, []
    e = _deref(e, env)
    if isinstance(e, (ast.List, ast.Tuple)):
        shapes = []
        for el in e.elts:
            shapes.append(_one_sds(m, el, env, cenv))
        return len(e.elts), shapes
    if isinstance(e, ast.BinOp) and isinstance(e.op, ast.Add):
        nl, sl = _sds_shapes(m, e.left, env, cenv)
        nr, sr = _sds_shapes(m, e.right, env, cenv)
        if nl is None or nr is None:
            return None, []
        return nl + nr, sl + sr
    if isinstance(e, ast.BinOp) and isinstance(e.op, ast.Mult):
        base, mult = e.left, e.right
        if isinstance(base, ast.Constant):
            base, mult = mult, base
        n = cenv.fold(m, mult)
        nb, sb = _sds_shapes(m, base, env, cenv)
        if isinstance(n, int) and nb is not None and 0 <= n < 1024:
            return nb * n, sb * n
        return None, []
    if (
        isinstance(e, ast.Call)
        and isinstance(e.func, ast.Attribute)
        and e.func.attr == "ShapeDtypeStruct"
    ):
        return 1, [_one_sds(m, e, env, cenv)]
    return None, []  # opaque expression: undecidable, never guess


def _one_sds(m: ModuleInfo, e: ast.expr, env: dict,
             cenv: ConstEnv) -> tuple | None:
    e = _deref(e, env)
    if not (isinstance(e, ast.Call) and isinstance(e.func, ast.Attribute)
            and e.func.attr == "ShapeDtypeStruct"):
        return None
    kw = {k.arg: k.value for k in e.keywords if k.arg}
    shape_node = e.args[0] if e.args else kw.get("shape")
    if not isinstance(shape_node, (ast.Tuple, ast.List)):
        return None
    return tuple(
        v if isinstance(v, int) else None
        for v in (cenv.fold(m, el) for el in shape_node.elts)
    )


def _kernel_arity(m: ModuleInfo, e: ast.expr, env: dict,
                  index: PackageIndex, fi: FuncInfo) -> int | None:
    """Positional-ref count of the kernel argument, or None (varargs,
    unresolvable, or positionally-bound partials)."""
    e = _deref(e, env)
    bound = 0
    if isinstance(e, ast.Call):
        f = e.func
        fname = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None
        )
        if fname != "partial" or not e.args:
            return None
        bound = len(e.args) - 1
        e = _deref(e.args[0], env)
    if not isinstance(e, ast.Name):
        return None
    target = m.functions.get(e.id)
    if target is None:
        cands = [
            g for g in index.by_name.get(e.id, ()) if g.cls is None
        ]
        if len(cands) != 1:
            return None
        target = cands[0]
    a = target.node.args
    if a.vararg is not None:
        return None
    return len(a.posonlyargs + a.args) - bound


def _grid_len(m: ModuleInfo, e: ast.expr | None, env: dict) -> int | None:
    if e is None:
        return None
    e = _deref(e, env)
    if isinstance(e, (ast.Tuple, ast.List)):
        return len(e.elts)
    if isinstance(e, ast.Constant) and isinstance(e.value, int):
        return 1
    return None


def _pallas_calls(m: ModuleInfo, fi: FuncInfo, alias: str):
    for node in ast.walk(fi.node):
        if isinstance(node, ast.Call) and _is_pl_attr(
            m, node.func, "pallas_call", alias
        ):
            yield node


def g009_g010_pallas(index: PackageIndex) -> list[Finding]:
    cached = getattr(index, "_pallas_findings", None)
    if cached is not None:
        return cached
    _annotate_parents(index)
    cenv = ConstEnv.of(index)
    g9: list[Finding] = []
    g10: list[Finding] = []
    for m in index.modules:
        alias = _pallas_alias(m)
        if alias is None:
            continue
        lane = cenv.lane_for(m) or 128
        for fi in m.functions.values():
            env = _local_env(fi.node)
            for call in _pallas_calls(m, fi, alias):
                kw = {k.arg: k.value for k in call.keywords if k.arg}
                n_in, in_specs = _spec_list(
                    m, kw.get("in_specs"), env, cenv, alias
                )
                n_out, out_specs = _spec_list(
                    m, kw.get("out_specs"), env, cenv, alias
                )
                n_oshape, oshapes = _sds_shapes(
                    m, kw.get("out_shape"), env, cenv
                )
                n_scratch, _ = _spec_list(
                    m, kw.get("scratch_shapes"), env, cenv, alias
                )
                if "scratch_shapes" not in kw:
                    n_scratch = 0
                glen = _grid_len(m, kw.get("grid"), env)

                # ---- out_specs vs out_shape count ----
                if (
                    n_out is not None and n_oshape is not None
                    and n_out != n_oshape
                ):
                    g9.append(Finding(
                        rule="G009", path=m.path, line=call.lineno,
                        col=call.col_offset,
                        msg=(
                            f"pallas_call declares {n_out} out_specs but "
                            f"{n_oshape} out_shape entries — every output "
                            "needs exactly one block spec"
                        ),
                    ))

                # ---- kernel arity vs spec list ----
                karity = _kernel_arity(
                    m, call.args[0], env, index, fi
                ) if call.args else None
                if (
                    karity is not None
                    and None not in (n_in, n_out, n_scratch)
                ):
                    want = n_in + n_out + n_scratch
                    if karity != want:
                        g9.append(Finding(
                            rule="G009", path=m.path, line=call.lineno,
                            col=call.col_offset,
                            msg=(
                                f"kernel takes {karity} positional refs "
                                f"but the spec lists supply {want} "
                                f"({n_in} in + {n_out} out + "
                                f"{n_scratch} scratch) — refs and specs "
                                "pair positionally"
                            ),
                        ))

                # ---- immediate invocation arity vs in_specs ----
                parent = getattr(call, "_graft_parent_call", None)
                if (
                    parent is not None and n_in is not None
                    and not any(
                        isinstance(a, ast.Starred) for a in parent.args
                    )
                    and len(parent.args) != n_in
                ):
                    g9.append(Finding(
                        rule="G009", path=m.path, line=parent.lineno,
                        col=parent.col_offset,
                        msg=(
                            f"pallas_call invoked with "
                            f"{len(parent.args)} arrays but declares "
                            f"{n_in} in_specs"
                        ),
                    ))

                # ---- per-spec checks ----
                for si, (spec, where) in enumerate(
                    [(s, "in") for s in in_specs]
                    + [(s, "out") for s in out_specs]
                ):
                    if spec is None:
                        continue
                    oi = si - len(in_specs)
                    if glen is not None and spec.map_params is not None \
                            and spec.map_params != glen:
                        g9.append(Finding(
                            rule="G009", path=m.path,
                            line=spec.node.lineno,
                            col=spec.node.col_offset,
                            msg=(
                                f"BlockSpec index map takes "
                                f"{spec.map_params} grid indices but the "
                                f"grid has {glen} dimension(s)"
                            ),
                        ))
                    if (
                        spec.shape is not None
                        and spec.map_rank is not None
                        and spec.map_rank != len(spec.shape)
                    ):
                        g9.append(Finding(
                            rule="G009", path=m.path,
                            line=spec.node.lineno,
                            col=spec.node.col_offset,
                            msg=(
                                f"BlockSpec block shape has "
                                f"{len(spec.shape)} dims but its index "
                                f"map returns {spec.map_rank} "
                                "coordinate(s)"
                            ),
                        ))
                    # divisibility: out blocks vs declared out extents
                    if (
                        where == "out" and spec.shape is not None
                        and 0 <= oi < len(oshapes)
                        and oshapes[oi] is not None
                        and len(oshapes[oi]) == len(spec.shape)
                    ):
                        for d, (blk, ext) in enumerate(
                            zip(spec.shape, oshapes[oi])
                        ):
                            if (
                                isinstance(blk, int)
                                and isinstance(ext, int)
                                and blk > 0 and ext % blk
                            ):
                                g9.append(Finding(
                                    rule="G009", path=m.path,
                                    line=spec.node.lineno,
                                    col=spec.node.col_offset,
                                    msg=(
                                        f"block dim {d} = {blk} does "
                                        f"not divide the output extent "
                                        f"{ext} it tiles — the edge "
                                        "block reads/writes out of "
                                        "bounds"
                                    ),
                                ))
                    # G010: VMEM minor-dim lane alignment
                    if spec.shape:
                        minor = spec.shape[-1]
                        if (
                            isinstance(minor, int)
                            and minor != 1 and minor % lane
                        ):
                            g10.append(Finding(
                                rule="G010", path=m.path,
                                line=spec.node.lineno,
                                col=spec.node.col_offset,
                                msg=(
                                    f"VMEM block minor dim {minor} is "
                                    f"not a multiple of LANE={lane} — "
                                    "unaligned blocks serialize every "
                                    "VMEM copy on TPU (minor dim 1 is "
                                    "the padded-scalar exemption)"
                                ),
                            ))
    index._pallas_findings = g9 + g10
    return index._pallas_findings


def _annotate_parents(index: PackageIndex) -> None:
    """Mark pallas_call nodes that are immediately invoked:
    ``pl.pallas_call(...)(args)`` — the outer Call is stashed on the
    inner one for the invocation-arity check."""
    for m in index.modules:
        for node in ast.walk(m.tree):
            if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Call
            ):
                node.func._graft_parent_call = node


def g009_pallas_grid(index: PackageIndex) -> list[Finding]:
    return [f for f in g009_g010_pallas(index) if f.rule == "G009"]


def g010_block_lane(index: PackageIndex) -> list[Finding]:
    return [f for f in g009_g010_pallas(index) if f.rule == "G010"]
