"""graftlint rules G001-G025.

Each rule is ``fn(index: PackageIndex) -> list[Finding]`` and is
registered in :data:`RULES`.  Every rule is motivated by a real hazard
this repository has already hit (see README "Static analysis" for the
rule table and the incident each one encodes).  G008 lives in
:mod:`crdt_benches_tpu.lint.flow` (the interprocedural constant pass),
G009/G010 in :mod:`crdt_benches_tpu.lint.pallas_rules`, the
thread-confinement suite G014-G017 in
:mod:`crdt_benches_tpu.lint.threads`, the lifecycle & ownership suite
G022-G025 in :mod:`crdt_benches_tpu.lint.lifecycle`; G011 (below)
cross-validates the
static fence graph against a serve bench artifact's ``boundary_syncs``
counters and only runs when the driver hands it one (G017 does the
same for the ``thread_crossings`` publish-point counters).
"""

from __future__ import annotations

import ast
import json
import os

from .core import (
    DTYPE_NAMES,
    G005_DIRS,
    G006_DIRS,
    G006_FILES,
    Finding,
    FuncInfo,
    PackageIndex,
    dotted,
    walk_hot_scope,
)
from .flow import g008_shape_drift
from .fsops import (
    g018_atomic_commit,
    g019_durable_ordering,
    g020_verify_before_trust,
    g021_fs_protocols,
)
from .lifecycle import (
    g022_state_discipline,
    g023_acquire_release,
    g024_identity_hazards,
    g025_lifecycle_artifact,
)
from .pallas_rules import g009_pallas_grid, g010_block_lane
from .ranges import (
    g026_index_guard,
    g027_narrow_overflow,
    g028_pad_flow,
    g029_ranges_artifact,
)
from .threads import (
    g014_shared_escape,
    g015_publish_discipline,
    g016_blocking_hot_thread,
    g017_thread_crossings,
)

_JNP_CREATORS = {
    "array", "zeros", "ones", "empty", "full", "arange", "linspace",
    "eye",
}

_NP_LEGACY_RANDOM = {
    "seed", "rand", "randn", "randint", "random", "choice", "shuffle",
    "permutation", "uniform", "normal", "sample",
}

_JOURNAL_SINKS = {
    "round_record", "event", "write_snapshot", "tensorize_ranges",
}


def _in_dirs(path: str, dirs: tuple, files: tuple = ()) -> bool:
    parts = path.replace("\\", "/").split("/")
    return any(d in parts for d in dirs) or any(
        path.endswith(f) for f in files
    )


def _has_dtype_arg(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "dtype":
            return True
    for a in call.args:
        if isinstance(a, ast.Name) and a.id in ("bool", "int", "float"):
            return True
        if isinstance(a, ast.Attribute) and (
            a.attr in DTYPE_NAMES or a.attr == "dtype"
        ):
            return True  # jnp.int32 / arr.dtype passed positionally
        if isinstance(a, ast.Constant) and isinstance(a.value, str):
            return True
    return False


def _explicit_dtype_name(call: ast.Call) -> str | None:
    """The dtype NAME a creation call passes explicitly, if literal."""
    for kw in call.keywords:
        if kw.arg == "dtype":
            if isinstance(kw.value, ast.Attribute):
                return kw.value.attr
            if isinstance(kw.value, ast.Constant) and isinstance(
                kw.value.value, str
            ):
                return kw.value.value
            return None
    for a in call.args:
        if isinstance(a, ast.Attribute) and a.attr in DTYPE_NAMES:
            return a.attr
    return None


# ---------------------------------------------------------------------------
# G001 — tracer leak: module-level device constants

def g001_tracer_leak(index: PackageIndex) -> list[Finding]:
    """A module-scope ``jnp.*`` constant is a DEVICE value created in
    whatever trace context is live at first import — the historical
    ``ops/idpos.py BIG`` bug leaked a tracer into
    ``__graft_entry__.dryrun_multichip``; a committed module constant
    also forces the slow dispatch path per executable launch.  Use a
    host-side ``np.*`` scalar (identical arithmetic under jit)."""
    out = []
    for m in index.modules:
        for node in ast.iter_child_nodes(m.tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            value = node.value
            if value is None:
                continue
            hit = None
            for sub in ast.walk(value):
                if isinstance(sub, ast.Call):
                    if m.is_jnp_attr(sub.func) is not None:
                        hit = m.dotted(sub.func)
                        break
                    if m.dotted(sub.func) == "jax.device_put":
                        hit = "jax.device_put"
                        break
            if hit is None:
                continue
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            names = [
                t.id for t in targets if isinstance(t, ast.Name)
            ]
            used_in = sorted({
                fi.qualname
                for fi in m.functions.values() if fi.jitted
                for sub in ast.walk(fi.node)
                if isinstance(sub, ast.Name)
                and isinstance(sub.ctx, ast.Load) and sub.id in names
            })
            closure = (
                f"; closed over by jitted {', '.join(used_in)}"
                if used_in else ""
            )
            out.append(Finding(
                rule="G001", path=m.path, line=node.lineno,
                col=node.col_offset,
                msg=(
                    f"module-level device constant `{' = '.join(names) or '<target>'}"
                    f" = {hit}(...)` — created inside whatever trace "
                    f"context is live at import (the idpos.py BIG tracer "
                    f"leak){closure}; use a host-side np.* value"
                ),
            ))
    return out


# ---------------------------------------------------------------------------
# G002 — host sync reachable from the serving hot path

_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_NP_SYNC_FUNCS = {"asarray", "array", "copy"}


def _sync_findings(fi: FuncInfo, index: PackageIndex, chain: str
                   ) -> list[Finding]:
    m = fi.module
    out = []
    for node in ast.walk(fi.node):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in _SYNC_METHODS:
            out.append(Finding(
                rule="G002", path=m.path, line=node.lineno,
                col=node.col_offset,
                msg=(
                    f"host sync `.{f.attr}()` on the serving hot path "
                    f"({chain}); move it behind a declared fence "
                    "(# graftlint: fence)"
                ),
            ))
            continue
        np_attr = m.is_np_attr(f)
        if np_attr in _NP_SYNC_FUNCS:
            out.append(Finding(
                rule="G002", path=m.path, line=node.lineno,
                col=node.col_offset,
                msg=(
                    f"`np.{np_attr}(...)` device->host transfer on the "
                    f"serving hot path ({chain}); stage with jnp/"
                    "device_put or move behind a fence"
                ),
            ))
            continue
        if m.dotted(f) == "jax.device_get":
            out.append(Finding(
                rule="G002", path=m.path, line=node.lineno,
                col=node.col_offset,
                msg=f"`jax.device_get` on the serving hot path ({chain})",
            ))
            continue
        if (
            isinstance(f, ast.Name)
            and f.id in ("int", "float", "bool")
            and len(node.args) == 1
        ):
            arg = node.args[0]
            device_like = any(
                (isinstance(s, ast.Attribute) and s.attr == "state")
                for s in ast.walk(arg)
            ) or any(
                isinstance(s, ast.Call)
                and any(
                    g.jitted for g in index.resolve_call(s, fi)
                )
                for s in ast.walk(arg)
            )
            if device_like:
                out.append(Finding(
                    rule="G002", path=m.path, line=node.lineno,
                    col=node.col_offset,
                    msg=(
                        f"`{f.id}(...)` forces a device sync on the "
                        f"serving hot path ({chain})"
                    ),
                ))
    return out


def g002_host_sync(index: PackageIndex) -> list[Finding]:
    """Walk the call graph from the serving hot-path roots
    (``# graftlint: hot-path`` markers + the built-in root set, with
    ``self.m()`` dispatches covering subclass overrides — the
    ReplicatedScheduler bus tick, not just the base planner) and flag
    host-synchronizing calls.  Functions marked ``# graftlint: fence``
    are DECLARED sync boundaries (the scheduler's bucket pulls, the
    drain fence): the walk does not descend into them."""
    out: list[Finding] = []
    for fi, chain in walk_hot_scope(index, descend_fences=False):
        out.extend(_sync_findings(fi, index, chain))
    return out


# ---------------------------------------------------------------------------
# G003 — recompile / version-drift hazards

def g003_recompile_hazard(index: PackageIndex) -> list[Finding]:
    """Three recompile/drift hazards: (a) ``print``/f-strings on traced
    parameters inside a jitted body (retrace side effects, tracer
    formatting); (b) importing ``jax.experimental.pallas.tpu`` outside
    ``ops/pallas_compat.py`` — the jax-0.4 ``CompilerParams`` rename is
    papered over in exactly one shim, a direct import reintroduces the
    drift; (c) list/dict/set literals passed for a declared
    ``static_argnames`` kwarg (unhashable statics fail or retrace)."""
    out = []
    for m in index.modules:
        # (b) pre-shim pallas-TPU import
        if not m.path.endswith("pallas_compat.py"):
            for node in ast.walk(m.tree):
                bad = None
                if isinstance(node, ast.ImportFrom):
                    if node.module == "jax.experimental.pallas" and any(
                        al.name == "tpu" for al in node.names
                    ):
                        bad = "from jax.experimental.pallas import tpu"
                    elif node.module == "jax.experimental.pallas.tpu":
                        bad = "from jax.experimental.pallas.tpu import ..."
                elif isinstance(node, ast.Import):
                    if any(
                        al.name.startswith("jax.experimental.pallas.tpu")
                        for al in node.names
                    ):
                        bad = "import jax.experimental.pallas.tpu"
                if bad:
                    out.append(Finding(
                        rule="G003", path=m.path, line=node.lineno,
                        col=node.col_offset,
                        msg=(
                            f"`{bad}` bypasses ops/pallas_compat.py — "
                            "the CompilerParams jax-0.4 rename shim "
                            "lives there; import `pltpu` from the shim"
                        ),
                    ))
        for fi in m.functions.values():
            # (a) print / f-string on traced params
            if fi.jitted:
                params = set(fi.params) - set(
                    fi.static_argnames or ()
                ) - {"self"}
                for node in ast.walk(fi.node):
                    traced = None
                    if (
                        isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)
                        and node.func.id == "print"
                    ):
                        traced = [
                            s.id for a in node.args
                            for s in ast.walk(a)
                            if isinstance(s, ast.Name) and s.id in params
                        ]
                    elif isinstance(node, ast.JoinedStr):
                        traced = [
                            s.id for v in node.values
                            if isinstance(v, ast.FormattedValue)
                            for s in ast.walk(v.value)
                            if isinstance(s, ast.Name) and s.id in params
                        ]
                    if traced:
                        out.append(Finding(
                            rule="G003", path=m.path, line=node.lineno,
                            col=node.col_offset,
                            msg=(
                                f"formatting traced value(s) "
                                f"{sorted(set(traced))} inside jitted "
                                f"`{fi.qualname}` — runs at trace time "
                                "only (or leaks a tracer repr); use "
                                "jax.debug.print"
                            ),
                        ))
            # (c) unhashable literals for static kwargs at call sites
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                for callee in index.resolve_call(node, fi):
                    statics = set(callee.static_argnames or ())
                    if not statics:
                        continue
                    for kw in node.keywords:
                        if kw.arg in statics and isinstance(
                            kw.value, (ast.List, ast.Dict, ast.Set)
                        ):
                            out.append(Finding(
                                rule="G003", path=m.path,
                                line=kw.value.lineno,
                                col=kw.value.col_offset,
                                msg=(
                                    f"unhashable literal for static arg "
                                    f"`{kw.arg}` of `{callee.qualname}` "
                                    "— statics must hash stably or "
                                    "every call recompiles/fails"
                                ),
                            ))
    return out


# ---------------------------------------------------------------------------
# G004 — donated buffer referenced after the donating call

def _collect_assign_lines(fn_node: ast.AST) -> dict[str, list[int]]:
    lines: dict[str, list[int]] = {}
    for node in ast.walk(fn_node):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            targets = [node.target]
        elif isinstance(node, ast.For):
            targets = [node.target]
        for t in targets:
            for leaf in ast.walk(t):
                if isinstance(leaf, (ast.Name, ast.Attribute)):
                    s = dotted(leaf)
                    if s:
                        lines.setdefault(s, []).append(node.lineno)
    return lines


def g004_donation_misuse(index: PackageIndex) -> list[Finding]:
    """A buffer passed at a donated position is dead after the call —
    XLA may have reused its memory.  Flag any later read of the donated
    variable in the same function body (unless rebound first).  Donation
    positions come from ``jax.jit(donate_argnums=...)`` and
    ``@boundary(donates=...)``."""
    out = []
    for m in index.modules:
        for fi in m.functions.values():
            assigns = None
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                callees = index.resolve_call(node, fi)
                for callee in callees:
                    donated = set(callee.donate_argnums or ())
                    if callee.boundary and callee.boundary.get("donates"):
                        donated |= set(callee.boundary["donates"])
                    if not donated:
                        continue
                    offset = 0
                    if (
                        callee.cls
                        and callee.params
                        and callee.params[0] == "self"
                        and isinstance(node.func, ast.Attribute)
                    ):
                        offset = 1
                    for d in sorted(donated):
                        i = d - offset
                        if not 0 <= i < len(node.args):
                            continue
                        expr = m.dotted(node.args[i])
                        if expr is None:
                            continue
                        if assigns is None:
                            assigns = _collect_assign_lines(fi.node)
                        rebinds = [
                            ln for ln in assigns.get(expr, ())
                            if ln >= node.lineno
                        ]
                        for read in ast.walk(fi.node):
                            if not isinstance(
                                read, (ast.Name, ast.Attribute)
                            ):
                                continue
                            if not isinstance(
                                getattr(read, "ctx", None), ast.Load
                            ):
                                continue
                            # the donating call may span lines; its own
                            # argument expressions are not "later" reads
                            call_end = getattr(
                                node, "end_lineno", node.lineno
                            )
                            if read.lineno <= call_end:
                                continue
                            if m.dotted(read) != expr:
                                continue
                            if any(
                                node.lineno <= ln <= read.lineno
                                for ln in rebinds
                            ):
                                continue
                            out.append(Finding(
                                rule="G004", path=m.path,
                                line=read.lineno, col=read.col_offset,
                                msg=(
                                    f"`{expr}` read after being donated "
                                    f"to `{callee.qualname}` (line "
                                    f"{node.lineno}) — the buffer may "
                                    "already be reused; rebind or copy"
                                ),
                            ))
                            break  # one finding per donated arg
    return out


# ---------------------------------------------------------------------------
# G005 — implicit dtype at array creation

def g005_implicit_dtype(index: PackageIndex) -> list[Finding]:
    """``jnp.zeros/array/arange/...`` without an explicit dtype follows
    the x64 flag and weak-type promotion — an int32-keyed kernel fed an
    accidental int64 recompiles (or worse, silently widens a packed
    layout).  Everything in ops/engine/serve/parallel/traces states its
    dtype."""
    out = []
    for m in index.modules:
        if not _in_dirs(m.path, G005_DIRS):
            continue
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call):
                continue
            attr = m.is_jnp_attr(node.func)
            if attr not in _JNP_CREATORS:
                continue
            if _has_dtype_arg(node):
                continue
            out.append(Finding(
                rule="G005", path=m.path, line=node.lineno,
                col=node.col_offset,
                msg=(
                    f"`jnp.{attr}(...)` without an explicit dtype — "
                    "dtype follows the x64 flag / promotion rules and "
                    "can silently recompile int32-shaped kernels"
                ),
            ))
    return out


# ---------------------------------------------------------------------------
# G006 — nondeterminism feeding journaled paths

def g006_nondeterminism(index: PackageIndex) -> list[Finding]:
    """The write-ahead journal assumes replay parity: the same streams
    re-produce the same tensors.  Wall-clock or unseeded randomness
    feeding tensorization/journal records, and set-order iteration,
    break that parity (a recovered fleet diverges byte-wise)."""
    out = []
    for m in index.modules:
        if not _in_dirs(m.path, G006_DIRS, G006_FILES):
            continue
        for node in ast.walk(m.tree):
            if isinstance(node, ast.Call):
                f = node.func
                d = m.dotted(f) or ""
                root = d.split(".")[0] if d else ""
                # stdlib random module (always unseeded-global here)
                if root in m.random_aliases:
                    out.append(Finding(
                        rule="G006", path=m.path, line=node.lineno,
                        col=node.col_offset,
                        msg=(
                            f"stdlib `{d}(...)` in a journaled path — "
                            "global unseeded RNG breaks replay parity; "
                            "use np.random.default_rng(seed)"
                        ),
                    ))
                # numpy legacy global RNG / unseeded default_rng
                elif (
                    root in m.np_aliases
                    and d.split(".")[1:2] == ["random"]
                ):
                    tail = d.split(".")[-1]
                    if tail in _NP_LEGACY_RANDOM:
                        out.append(Finding(
                            rule="G006", path=m.path, line=node.lineno,
                            col=node.col_offset,
                            msg=(
                                f"`{d}(...)` uses numpy's GLOBAL RNG — "
                                "journal replay parity needs a seeded "
                                "default_rng instance"
                            ),
                        ))
                    elif tail == "default_rng" and not (
                        node.args or node.keywords
                    ):
                        out.append(Finding(
                            rule="G006", path=m.path, line=node.lineno,
                            col=node.col_offset,
                            msg=(
                                "`default_rng()` without a seed in a "
                                "journaled path — recovery replay "
                                "cannot reproduce it"
                            ),
                        ))
                # wall-clock feeding a journal/tensorize sink
                sink = (
                    f.attr if isinstance(f, ast.Attribute)
                    else (f.id if isinstance(f, ast.Name) else "")
                )
                if sink in _JOURNAL_SINKS:
                    for a in list(node.args) + [
                        kw.value for kw in node.keywords
                    ]:
                        for s in ast.walk(a):
                            if (
                                isinstance(s, ast.Call)
                                and isinstance(s.func, ast.Attribute)
                                and isinstance(s.func.value, ast.Name)
                                and s.func.value.id in m.time_aliases
                            ):
                                out.append(Finding(
                                    rule="G006", path=m.path,
                                    line=s.lineno, col=s.col_offset,
                                    msg=(
                                        f"wall-clock `{m.dotted(s.func)}"
                                        f"()` feeds journaled sink "
                                        f"`{sink}` — replay cannot "
                                        "reproduce it; journal round "
                                        "counters instead"
                                    ),
                                ))
            elif isinstance(node, ast.For):
                it = node.iter
                is_set = isinstance(it, ast.Set) or (
                    isinstance(it, ast.Call)
                    and isinstance(it.func, ast.Name)
                    and it.func.id in ("set", "frozenset")
                )
                if is_set:
                    out.append(Finding(
                        rule="G006", path=m.path, line=it.lineno,
                        col=it.col_offset,
                        msg=(
                            "iteration over a set in a journaled path — "
                            "order is salted per process; wrap in "
                            "sorted(...)"
                        ),
                    ))
    return out


# ---------------------------------------------------------------------------
# G007 — boundary contract cross-check

def g007_boundary_contract(index: PackageIndex) -> list[Finding]:
    """Static cross-checks of the ``@boundary`` registry: the declared
    ``donates`` must equal the ``donate_argnums`` of the jit wrapper in
    the same decorator stack, and call sites passing an explicit literal
    dtype must match the declared one."""
    out = []
    for m in index.modules:
        for fi in m.functions.values():
            if fi.boundary is None:
                continue
            declared = fi.boundary.get("donates")
            if (
                fi.jitted
                and declared is not None
                and fi.donate_argnums is not None
                and set(declared) != set(fi.donate_argnums)
            ):
                out.append(Finding(
                    rule="G007", path=m.path, line=fi.boundary_line,
                    col=0,
                    msg=(
                        f"`{fi.qualname}`: @boundary donates="
                        f"{tuple(declared)} but jax.jit donate_argnums="
                        f"{tuple(fi.donate_argnums)} — the contract "
                        "table lies about buffer lifetime"
                    ),
                ))
    # call-site dtype literals vs declared contract
    for m in index.modules:
        for fi in m.functions.values():
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                for callee in index.resolve_call(node, fi):
                    spec = callee.boundary
                    if not spec or not spec.get("dtypes"):
                        continue
                    dtypes = spec["dtypes"]
                    offset = 1 if (
                        callee.cls
                        and callee.params
                        and callee.params[0] == "self"
                        and isinstance(node.func, ast.Attribute)
                    ) else 0
                    for j, a in enumerate(node.args):
                        k = j + offset
                        if k >= len(dtypes) or dtypes[k] is None:
                            continue
                        if not isinstance(a, ast.Call):
                            continue
                        if m.is_jnp_attr(a.func) is None and (
                            m.is_np_attr(a.func) is None
                        ):
                            continue
                        got = _explicit_dtype_name(a)
                        if got is not None and got != dtypes[k]:
                            out.append(Finding(
                                rule="G007", path=m.path,
                                line=a.lineno, col=a.col_offset,
                                msg=(
                                    f"arg {k} of `{callee.qualname}` "
                                    f"built as {got} but the boundary "
                                    f"contract declares {dtypes[k]}"
                                ),
                            ))
    return out


# ---------------------------------------------------------------------------
# G011 — fence-cost cross-check (static fence graph vs runtime counters)

def _load_boundary_syncs(path: str) -> tuple[dict | None, str | None]:
    """The ``boundary_syncs`` block of a serve bench artifact (a
    ``save_results`` list of BenchResult dicts) or of a raw JSON fixture.
    Returns (block, error)."""
    from .threads import load_artifact_block

    return load_artifact_block(path, "boundary_syncs")


def g011_fence_cost(index: PackageIndex, artifact_path: str
                    ) -> list[Finding]:
    """Cross-validate the static fence model against a serve run's
    ``boundary_syncs`` counters (the runtime ground truth the sanitizer
    records): a declared fence the run never crossed is DEAD — either
    the annotation is stale (delete it) or the boundary moved (re-fence
    the real one); a runtime counter with no matching ``# graftlint:
    fence`` marker is an UNATTRIBUTED sync boundary the static model
    does not know about.  ``fence=chaos`` / ``fence=journal`` /
    ``fence=flight`` / ``fence=reshard`` fences are accounted only
    against artifacts whose run had faults / a journal / a
    flight-recorder dump / a live-reshard coordinator;
    ``fence=cold`` fences (off-drain APIs) are never dead-checked."""
    block, err = _load_boundary_syncs(artifact_path)
    if block is None:
        return [Finding(
            rule="G011", path=artifact_path, line=0, col=0, msg=err,
        )]
    entries = block.get("entries") or {}
    chaos = bool(block.get("chaos"))
    journal = bool(block.get("journal"))
    flight = bool(block.get("flight"))
    reshard = bool(block.get("reshard"))
    out = []
    fences = {
        fi.qualname: fi
        for m in index.modules for fi in m.functions.values() if fi.fence
    }
    for qual, fi in sorted(fences.items()):
        tag = fi.fence_tag
        if tag == "cold":
            continue
        if tag == "chaos" and not chaos:
            continue
        if tag == "journal" and not journal:
            continue
        if tag == "flight" and not flight:
            continue
        if tag == "reshard" and not reshard:
            continue
        if not entries.get(qual):
            out.append(Finding(
                rule="G011", path=fi.module.path, line=fi.node.lineno,
                col=fi.node.col_offset,
                msg=(
                    f"declared fence `{qual}` never crossed in "
                    f"{os.path.basename(artifact_path)} — dead fence: "
                    "delete the stale annotation or re-fence the real "
                    "boundary (tag it fence=chaos/journal/cold if it is "
                    "only reachable there)"
                ),
            ))
    for qual in sorted(entries):
        if qual not in fences:
            out.append(Finding(
                rule="G011", path=artifact_path, line=0, col=0,
                msg=(
                    f"runtime fence counter `{qual}` has no matching "
                    "`# graftlint: fence` marker — an unattributed sync "
                    "boundary the static G002 model does not know about"
                ),
            ))
    return out


# ---------------------------------------------------------------------------
# G012 — observability hygiene in hot-path scopes

#: obs-API calls that take a series NAME as their first argument.
#: ``segment`` is the obs/reqtrace.py per-phase timer — its names are
#: registered constants exactly like span/metric names.
_OBS_NAME_CALLS = {"span", "instant", "counter", "gauge", "histogram",
                   "segment"}

#: obs/reqtrace.py admission/drain-EDGE calls: opening a request
#: context or sampling an exemplar allocates and (for exemplars) grows
#: per-bucket state — legal once per admitted doc at the selection/
#: close edges (loop depth <= 1), banned in per-op inner loops.
_REQTRACE_EDGE_CALLS = {"open_request", "sample_exemplar",
                        "RequestContext"}

#: Tracer lifecycle — never legal in a hot scope (arming inside the
#: drain voids the disarmed-tracer no-op contract and skews timing).
_OBS_LIFECYCLE = {"arm", "disarm", "write_trace", "SpanTracer"}


def _is_obs_name(m, f: ast.expr) -> bool:
    """Does this call expression denote the obs span/metric API?
    Attribute calls (``registry.counter``, ``tracer.span``) match by
    attr name; bare names must be imported from an obs module."""
    if isinstance(f, ast.Attribute):
        return f.attr in _OBS_NAME_CALLS
    if isinstance(f, ast.Name) and f.id in _OBS_NAME_CALLS:
        src = m.imports.get(f.id, "")
        return "obs.trace" in src or "obs.metrics" in src
    return False


def _is_obs_lifecycle(m, f: ast.expr) -> str | None:
    d = dotted(f)
    if d is None:
        return None
    tail = d.split(".")[-1]
    if tail not in _OBS_LIFECYCLE:
        return None
    if isinstance(f, ast.Name):
        src = m.imports.get(f.id, "")
        return tail if ("obs.trace" in src or tail == "SpanTracer") \
            else None
    root = d.split(".")[0]
    src = m.imports.get(root, "")
    return tail if "obs" in src else None


def _obs_findings(fi: FuncInfo, chain: str) -> list[Finding]:
    m = fi.module
    out = []
    for node in ast.walk(fi.node):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        life = _is_obs_lifecycle(m, f)
        if life is not None:
            out.append(Finding(
                rule="G012", path=m.path, line=node.lineno,
                col=node.col_offset,
                msg=(
                    f"tracer lifecycle `{life}(...)` in a hot-path "
                    f"scope ({chain}) — arming/writing belongs to the "
                    "bench driver; inside the drain the tracer must "
                    "stay a no-op when disarmed"
                ),
            ))
            continue
        if not _is_obs_name(m, f):
            continue
        name_arg = node.args[0] if node.args else next(
            (kw.value for kw in node.keywords if kw.arg == "name"), None
        )
        if name_arg is None:
            continue
        if isinstance(name_arg, ast.Constant):
            # a constant str name is the contract; a constant NON-str
            # first arg means this is some other API sharing the method
            # name (re.Match.span(1)) — not an obs callsite at all
            continue
        what = (
            f.attr if isinstance(f, ast.Attribute) else f.id
        )
        out.append(Finding(
            rule="G012", path=m.path, line=node.lineno,
            col=node.col_offset,
            msg=(
                f"non-constant name passed to `{what}(...)` in a "
                f"hot-path scope ({chain}) — span/metric names are "
                "registered constants (f-strings allocate per round "
                "and explode series cardinality); put dynamic context "
                "in the args/tag payload"
            ),
        ))
    return out


def _reqtrace_call_name(m, f: ast.expr) -> str | None:
    """The reqtrace edge-call name this expression denotes, or None.
    Attribute calls (``tracker.open_request``) match by attr name —
    the method names are distinctive; bare names must be imported from
    ``obs.reqtrace``."""
    d = dotted(f)
    if d is None:
        return None
    tail = d.split(".")[-1]
    if tail not in _REQTRACE_EDGE_CALLS:
        return None
    if isinstance(f, ast.Name):
        src = m.imports.get(f.id, "")
        return tail if "reqtrace" in src else None
    return tail


def _reqtrace_loop_findings(fi: FuncInfo, chain: str) -> list[Finding]:
    """Request-context creation / exemplar sampling inside per-op
    INNER loops (loop depth >= 2) of a hot-path scope.  Depth 1 is the
    admission edge — the scheduler's per-DOC selection loop opens one
    context per admitted doc there, which is the sanctioned pattern."""
    m = fi.module
    out: list[Finding] = []

    def walk(node: ast.AST, depth: int) -> None:
        for child in ast.iter_child_nodes(node):
            d = depth
            if isinstance(child, (ast.For, ast.AsyncFor, ast.While)):
                d = depth + 1
            elif isinstance(child, (ast.ListComp, ast.SetComp,
                                    ast.DictComp, ast.GeneratorExp)):
                d = depth + len(child.generators)
            if isinstance(child, ast.Call) and depth >= 2:
                name = _reqtrace_call_name(m, child.func)
                if name is not None:
                    what = ("request-context creation"
                            if name in ("open_request", "RequestContext")
                            else "exemplar sampling")
                    out.append(Finding(
                        rule="G012", path=m.path, line=child.lineno,
                        col=child.col_offset,
                        msg=(
                            f"{what} `{name}(...)` inside a per-op "
                            f"inner loop (depth {depth}) in a hot-path "
                            f"scope ({chain}) — contexts and exemplars "
                            "are admission/drain-edge work: open once "
                            "per admitted doc in the selection loop, "
                            "sample once per request close"
                        ),
                    ))
            walk(child, d)

    walk(fi.node, 0)
    return out


def g012_obs_hygiene(index: PackageIndex) -> list[Finding]:
    """Observability discipline on the serving hot path: every
    ``obs/trace.py`` span, ``obs/metrics.py`` series, and
    ``obs/reqtrace.py`` segment created in a hot-path scope must use a
    registered CONSTANT name (dynamic context goes in args /
    pre-registered cause tags), the tracer lifecycle (arm / disarm /
    write) must never run there — the disarmed tracer is a shared
    no-op and arming mid-drain would void that contract — and request
    contexts / exemplars are opened at admission/drain EDGES only,
    never in per-op inner loops.  Unlike G002 the walk DESCENDS into
    declared fences: naming discipline applies behind sync boundaries
    too."""
    out: list[Finding] = []
    for fi, chain in walk_hot_scope(index, descend_fences=True):
        out.extend(_obs_findings(fi, chain))
        out.extend(_reqtrace_loop_findings(fi, chain))
    return out


# ---------------------------------------------------------------------------
# G013 — status/telemetry isolation in hot-path scopes

#: Server/socket constructor names (with their import-source checks
#: below): binding a port or accepting connections belongs to the bench
#: driver, never the serving hot path.
_G013_SERVER_CTORS = {
    "HTTPServer", "ThreadingHTTPServer", "TCPServer",
    "ThreadingTCPServer", "UDPServer", "ThreadingUDPServer",
    "StatusServer", "IngestFront",
}
_G013_SERVER_SOURCES = ("http.server", "socketserver", "obs.status",
                        "serve.ingest")

#: obs/ v3 lifecycle constructors: the flight recorder and the request
#: tracker are built (and armed — the tracker installs a global
#: publish observer) by the bench DRIVER; constructing either mid-
#: drain re-arms tracing under the hot path and leaks observers.
_G013_OBS_LIFECYCLE_CTORS = {"FlightRecorder", "RequestTracker"}

#: ``socket``-module entry points that create/bind network endpoints.
_G013_SOCKET_FUNCS = {"socket", "create_server", "create_connection"}

#: Registry-shape mutators: get-or-create and adoption.  The hot path
#: holds pre-registered references; creating series mid-drain races the
#: status server's snapshot reads and allocates per round.
_G013_REG_MUTATORS = {"counter", "gauge", "histogram", "attach"}


def _g013_call_finding(fi: FuncInfo, node: ast.Call, chain: str
                       ) -> Finding | None:
    m = fi.module
    f = node.func
    d = dotted(f)
    # (a) HTTP/TCP server construction (http.server / socketserver /
    # obs.status classes, by import source)
    tail = d.split(".")[-1] if d else None
    if tail in _G013_SERVER_CTORS:
        root = d.split(".")[0]
        src = m.imports.get(root, "")
        if tail in ("StatusServer", "IngestFront") or any(
            s in src for s in _G013_SERVER_SOURCES
        ):
            return Finding(
                rule="G013", path=m.path, line=node.lineno,
                col=node.col_offset,
                msg=(
                    f"`{tail}(...)` constructed in a hot-path scope "
                    f"({chain}) — servers are thread-confined and "
                    "driver-owned (status AND the ingest front); the "
                    "drain only swaps snapshot references in"
                ),
            )
    # (a') obs/ v3 lifecycle construction (flight recorder / request
    # tracker) — driver-side work, like the status server above
    if tail in _G013_OBS_LIFECYCLE_CTORS:
        return Finding(
            rule="G013", path=m.path, line=node.lineno,
            col=node.col_offset,
            msg=(
                f"`{tail}(...)` constructed in a hot-path scope "
                f"({chain}) — flight-recorder / request-tracker "
                "lifecycle belongs to the bench driver (the tracker "
                "installs a global publish observer when armed); the "
                "drain holds pre-built references"
            ),
        )
    # (b) raw socket creation
    if d is not None and len(d.split(".")) == 2:
        root, attr = d.split(".")
        if attr in _G013_SOCKET_FUNCS and m.imports.get(root) == "socket":
            return Finding(
                rule="G013", path=m.path, line=node.lineno,
                col=node.col_offset,
                msg=(
                    f"`{d}(...)` in a hot-path scope ({chain}) — no "
                    "network endpoints on the serving hot path"
                ),
            )
    # (c) serving a socket from the hot path
    if isinstance(f, ast.Attribute) and f.attr == "serve_forever":
        return Finding(
            rule="G013", path=m.path, line=node.lineno,
            col=node.col_offset,
            msg=(
                f"`.serve_forever()` in a hot-path scope ({chain}) — "
                "the status server loops on its own daemon thread"
            ),
        )
    # (d) registry mutation (get-or-create / attach), even with a
    # constant name — G012 polices naming, this polices WHEN: series
    # are pre-registered at bind time, the hot path holds references
    is_mutator = False
    if isinstance(f, ast.Attribute) and f.attr in _G013_REG_MUTATORS:
        is_mutator = True
        if (isinstance(f.value, ast.Name)
                and "sanitizer" in m.imports.get(f.value.id, "")):
            # runtime-sanitizer record calls (fs/race/lifecycle) share
            # the metric verbs but mutate no registry shape: a
            # fixed-key dict write the status server never snapshots
            is_mutator = False
    elif isinstance(f, ast.Name) and f.id in _G013_REG_MUTATORS:
        is_mutator = "obs.metrics" in m.imports.get(f.id, "")
    if is_mutator:
        what = f.attr if isinstance(f, ast.Attribute) else f.id
        return Finding(
            rule="G013", path=m.path, line=node.lineno,
            col=node.col_offset,
            msg=(
                f"registry mutation `{what}(...)` in a hot-path scope "
                f"({chain}) — get-or-create/attach races the status "
                "server's snapshot reads and allocates per round; "
                "pre-register at bind time and hold the reference "
                "(.inc()/.set()/.observe() stay legal)"
            ),
        )
    return None


def g013_status_isolation(index: PackageIndex) -> list[Finding]:
    """The live-telemetry isolation contract: the serving hot path
    never constructs sockets or HTTP servers, never serves them, and
    never mutates the metric registry's shape — the status endpoint is
    read-only over published snapshots on its own thread, and every
    series the hot path touches was pre-registered at bind time.  Like
    G012 (and unlike G002) the walk DESCENDS into declared fences:
    being behind a sync boundary does not make a mid-drain socket or a
    per-round series registration acceptable."""
    out: list[Finding] = []
    for fi, chain in walk_hot_scope(index, descend_fences=True):
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Call):
                finding = _g013_call_finding(fi, node, chain)
                if finding is not None:
                    out.append(finding)
    return out


RULES = {
    "G001": g001_tracer_leak,
    "G002": g002_host_sync,
    "G003": g003_recompile_hazard,
    "G004": g004_donation_misuse,
    "G005": g005_implicit_dtype,
    "G006": g006_nondeterminism,
    "G007": g007_boundary_contract,
    "G008": g008_shape_drift,
    "G009": g009_pallas_grid,
    "G010": g010_block_lane,
    "G011": g011_fence_cost,  # artifact-driven; see run_lint
    "G012": g012_obs_hygiene,
    "G013": g013_status_isolation,
    "G014": g014_shared_escape,
    "G015": g015_publish_discipline,
    "G016": g016_blocking_hot_thread,
    "G017": g017_thread_crossings,  # artifact-driven; see run_lint
    "G018": g018_atomic_commit,
    "G019": g019_durable_ordering,
    "G020": g020_verify_before_trust,
    "G021": g021_fs_protocols,  # artifact-driven; see run_lint
    "G022": g022_state_discipline,
    "G023": g023_acquire_release,
    "G024": g024_identity_hazards,
    "G025": g025_lifecycle_artifact,  # artifact-driven; see run_lint
    "G026": g026_index_guard,
    "G027": g027_narrow_overflow,
    "G028": g028_pad_flow,
    "G029": g029_ranges_artifact,  # artifact-driven; see run_lint
}
