"""Thread-confinement analysis: rules G014-G017.

The serving stack is concurrent on the host side: the drain runs on the
**hot** thread, the live status endpoint renders on its own **status**
threads, and the broadcast bus and journal writer are their own logical
roots (today co-scheduled on the hot thread; the tiered-residency
prefetch work moves them off it).  The static model here is the
G002/G011 architecture applied to threads instead of device syncs:

- **ownership is declared**, not inferred: ``# graftlint: thread=<t>``
  on a def (or a class) line pins the function (or every method) to a
  thread root; ownership then propagates along the call graph — the
  same best-effort resolver the hot-path walks use, including subclass
  overrides of ``self.m()`` dispatches — into unmarked functions.  A
  function reachable from two roots is owned by both.
- **publish points are declared like fences**: ``# graftlint: publish``
  marks the one legal way a mutable object crosses threads — an atomic
  single-assignment reference swap (or a lock-guarded section).
  ``publish=<tag>`` scopes the G017 dead-point accounting to artifacts
  whose run armed that surface (``publish=status`` = the live status
  server).
- **G014 shared-mutable escape**: a mutable class attribute written on
  one thread and touched on another, with no write ever passing
  through a declared publish point, is a data race waiting for the
  second thread to actually exist.  Immutable single-assignment swaps
  (bools, strs, tuples of scalars — CPython makes the store atomic)
  are legal without a publish point; ``__init__`` writes precede
  thread handoff and are exempt.
- **G015 publish-point discipline**: inside a publish function the
  shared attribute may only be *swapped* (``self.x = fresh``), never
  mutated in place (``self.x[k] = v`` / ``self.x.append(...)`` — a
  reader on the other thread can observe the half-applied mutation);
  and a reader-thread function may not mutate an object it received
  through a publish point (the published snapshot contract is
  read-only).
- **G016 blocking call in the hot thread**: locks acquired, bare
  thread ``join()``s, socket waits (``recv``/``accept``/``select``)
  and unbounded stdlib-queue ``get``/``put`` inside the hot-path walk.
  Like G012/G013 (and unlike G002) the walk DESCENDS into declared
  fences: a fence declares a device sync, not a license to wedge the
  drain behind a lock.
- **G017 publish-point cross-check** (artifact-driven, G011's mirror):
  the runtime race sanitizer (lint/race_sanitizer.py) counts every
  declared publish-point entry and attributes every observed
  cross-thread access to the publish that made it legal, exported as
  the serve artifact's ``thread_crossings`` block.  A declared publish
  point the run never entered is DEAD; a runtime counter with no
  matching ``# graftlint: publish`` marker is an UNATTRIBUTED handoff
  the static model does not know about.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field

from .core import (
    DEFAULT_HOT_ROOTS,
    Finding,
    FuncInfo,
    PackageIndex,
    dotted,
    walk_hot_scope,
)
from .race_sanitizer import MUTATOR_METHODS as _RUNTIME_MUTATORS

# ---------------------------------------------------------------------------
# ownership propagation
# ---------------------------------------------------------------------------


def thread_labels(index: PackageIndex) -> dict[int, set[str]]:
    """``id(FuncInfo) -> set of owning thread roots``.  Explicitly
    marked functions are PINNED to their declared root (propagation
    neither relabels them nor descends through them under a different
    label — the marker is a declared ownership boundary); hot-path
    roots (G002's set) count as ``thread=hot``.  Unmarked functions
    accumulate every root that reaches them.  Propagation follows only
    the CONFIDENT call edges (``resolve_call(strict=True)``: same-
    module / named-import functions, ``self.m()`` dispatch with
    subclass overrides) — the any-receiver bare-name fan-out the sync
    rules use for recall would fuse thread roots through every shared
    method name and label half the package bilaterally owned.

    Memoized on the index: G014 and G015 both need the full labeling
    (a per-root BFS over every function body) and run back-to-back in
    one gate pass over one immutable index."""
    cached = getattr(index, "_thread_labels", None)
    if cached is not None:
        return cached
    labels: dict[int, set[str]] = {}
    roots: list[tuple[FuncInfo, str]] = []
    for m in index.modules:
        for fi in m.functions.values():
            if fi.thread:
                roots.append((fi, fi.thread))
            elif fi.hot or fi.qualname in DEFAULT_HOT_ROOTS:
                roots.append((fi, "hot"))
    for root, label in roots:
        queue = [root]
        while queue:
            fi = queue.pop()
            got = labels.setdefault(id(fi), set())
            if label in got:
                continue
            got.add(label)
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                for callee in index.resolve_call(node, fi, strict=True):
                    if callee.thread and callee.thread != label:
                        continue  # pinned to another thread: boundary
                    if label not in labels.get(id(callee), ()):
                        queue.append(callee)
    index._thread_labels = labels
    return labels


# ---------------------------------------------------------------------------
# per-class attribute access model
# ---------------------------------------------------------------------------

#: Method names that mutate their receiver in place.  Derived from the
#: runtime proxy's canonical set (race_sanitizer.MUTATOR_METHODS) plus
#: the subscript dunders only the AST sees spelled out — the static
#: and runtime halves of the model judge mutation identically by
#: construction.
MUTATOR_METHODS = _RUNTIME_MUTATORS | frozenset(
    {"__setitem__", "__delitem__"}
)

#: Constructors whose result is a shared-mutable container.
_MUTABLE_CTORS = {
    "list", "dict", "set", "deque", "defaultdict", "bytearray",
    "OrderedDict",
}

#: Calls safely returning immutables (atomic to swap by reference).
_IMMUTABLE_CALLS = {
    "int", "float", "bool", "str", "bytes", "tuple", "frozenset",
    "len", "min", "max", "sum", "round", "id",
}
_IMMUTABLE_DOTTED = {
    "time.time", "time.monotonic", "time.perf_counter",
    "os.getpid", "threading.get_ident",
}


def _value_kind(e: ast.expr | None) -> str:
    """'immutable' | 'mutable' | 'unknown' for an assigned value.  A
    tuple literal of scalars/names counts as immutable: the reference
    swap is atomic and tuples cannot be mutated in place — the legal
    no-publish-point pattern for multi-field state (see
    ``StatusServer._health``)."""
    if e is None:
        return "unknown"
    if isinstance(e, ast.Constant):
        return "immutable"
    if isinstance(e, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                      ast.DictComp, ast.SetComp)):
        return "mutable"
    if isinstance(e, ast.Tuple):
        kinds = {_value_kind(el) for el in e.elts}
        if kinds <= {"immutable"} or all(
            isinstance(el, (ast.Constant, ast.Name)) for el in e.elts
        ):
            return "immutable"
        return "unknown"
    if isinstance(e, (ast.UnaryOp, ast.BinOp, ast.BoolOp, ast.Compare,
                      ast.IfExp)):
        return "unknown"  # usually scalar, but not provably
    if isinstance(e, ast.Call):
        f = e.func
        name = f.id if isinstance(f, ast.Name) else None
        if name in _MUTABLE_CTORS:
            return "mutable"
        if name in _IMMUTABLE_CALLS:
            return "immutable"
        if dotted(f) in _IMMUTABLE_DOTTED:
            return "immutable"
    return "unknown"


@dataclass
class _Access:
    fi: FuncInfo
    line: int
    col: int
    write: bool  # any store/mutation (False = plain read)
    inplace: bool  # subscript/aug/mutator-call (never an atomic swap)
    value_kind: str = "unknown"  # for plain assigns
    locked: bool = False  # textually inside a `with <...lock...>:`


@dataclass
class _AttrTable:
    accesses: dict[str, list[_Access]] = field(default_factory=dict)

    def note(self, attr: str, acc: _Access) -> None:
        self.accesses.setdefault(attr, []).append(acc)


#: Name tokens (``.``/``_``-separated segments of a dotted receiver)
#: that identify a mutual-exclusion primitive.  Token-exact on purpose:
#: a bare substring test would classify every ``block``/``block_span``
#: receiver — pervasive domain terms here — as a lock, flagging G016 on
#: non-locks and (worse) silently lock-exempting unguarded shared
#: writes from G014/G015.
_LOCK_TOKENS = frozenset({"lock", "rlock", "mutex", "semaphore"})


def _is_lockish(e: ast.expr) -> bool:
    d = dotted(e)
    if d is None:
        return False
    for tok in re.split(r"[._]", d.lower()):
        if tok in _LOCK_TOKENS or (
            tok.endswith("lock") and not tok.endswith("block")
        ):
            return True
    return False


class _AttrScanner(ast.NodeVisitor):
    """Collect every ``self.X`` access (and one-hop local aliases of
    ``self.X`` that are later mutated) in one method body."""

    def __init__(self, fi: FuncInfo, table: _AttrTable):
        self.fi = fi
        self.table = table
        self._lock_depth = 0
        self.aliases: dict[str, str] = {}  # local name -> attr

    # -- helpers --

    def _self_attr(self, e: ast.expr) -> str | None:
        if (isinstance(e, ast.Attribute)
                and isinstance(e.value, ast.Name)
                and e.value.id == "self"):
            return e.attr
        return None

    def _note(self, node: ast.AST, attr: str, *, write: bool,
              inplace: bool = False, value: ast.expr | None = None
              ) -> None:
        self.table.note(attr, _Access(
            fi=self.fi, line=node.lineno, col=node.col_offset,
            write=write, inplace=inplace,
            value_kind=_value_kind(value) if write else "unknown",
            locked=self._lock_depth > 0,
        ))

    def _target_attr(self, t: ast.expr) -> tuple[str, bool] | None:
        """(attr, inplace) for a store target touching ``self.X`` (or a
        tracked alias), else None."""
        a = self._self_attr(t)
        if a is not None:
            return a, False
        if isinstance(t, ast.Subscript):
            base = t.value
            a = self._self_attr(base)
            if a is not None:
                return a, True
            if isinstance(base, ast.Name) and base.id in self.aliases:
                return self.aliases[base.id], True
        return None

    # -- visitors --

    def visit_With(self, node: ast.With) -> None:
        lockish = any(_is_lockish(it.context_expr) for it in node.items)
        if lockish:
            self._lock_depth += 1
        self.generic_visit(node)
        if lockish:
            self._lock_depth -= 1

    visit_AsyncWith = visit_With

    def _visit_store(self, node: ast.Assign, t: ast.expr,
                     value: ast.expr | None) -> None:
        # Tuple/list unpacking: `self._a, x = {}, y` stores into
        # self._a just as surely as the single-target form — pair each
        # element with its RHS element when the shapes line up, else
        # fall through with an unknown value.
        if isinstance(t, (ast.Tuple, ast.List)):
            elts = (value.elts if isinstance(value, (ast.Tuple, ast.List))
                    and len(value.elts) == len(t.elts) else None)
            for i, sub in enumerate(t.elts):
                if isinstance(sub, ast.Starred):
                    self._visit_store(node, sub.value, None)
                else:
                    self._visit_store(node, sub,
                                      elts[i] if elts is not None else None)
            return
        hit = self._target_attr(t)
        if hit is not None:
            attr, inplace = hit
            self._note(node, attr, write=True, inplace=inplace, value=value)
        # alias tracking: y = self.X
        if isinstance(t, ast.Name):
            src = self._self_attr(value) if value is not None else None
            if src is not None:
                self.aliases[t.id] = src
            else:
                self.aliases.pop(t.id, None)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._visit_store(node, t, node.value)
        self.visit(node.value)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        hit = self._target_attr(node.target)
        if hit is not None:
            self._note(node, hit[0], write=True, inplace=True)
        self.visit(node.value)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            hit = self._target_attr(node.target)
            if hit is not None:
                attr, inplace = hit
                self._note(node, attr, write=True, inplace=inplace,
                           value=node.value)
            self.visit(node.value)

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in MUTATOR_METHODS:
            attr = self._self_attr(f.value)
            if attr is None and isinstance(f.value, ast.Name):
                attr = self.aliases.get(f.value.id)
            if attr is not None:
                self._note(node, attr, write=True, inplace=True)
                for a in node.args:
                    self.visit(a)
                for kw in node.keywords:
                    self.visit(kw.value)
                return
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = self._self_attr(node)
        if attr is not None and isinstance(node.ctx, ast.Load):
            self._note(node, attr, write=False)
        self.generic_visit(node)


def _class_tables(index: PackageIndex
                  ) -> dict[tuple[str, str], dict[str, list[_Access]]]:
    """(module path | '<hierarchy>', class) -> attr -> accesses, across
    the index.  A subclass instance is ONE object at runtime — a base
    method and a subclass method touch the same ``self.X`` storage —
    so classes connected by LOCAL inheritance edges (the base has
    methods in the index; external bases merge nothing real) share one
    table, keyed by the component root.  Memoized on the index (G014 +
    G015 share one scan)."""
    cached = getattr(index, "_class_tables", None)
    if cached is not None:
        return cached
    parent: dict[str, str] = {}

    def find(c: str) -> str:
        parent.setdefault(c, c)
        while parent[c] != c:
            parent[c] = parent[parent[c]]
            c = parent[c]
        return c

    for cls, bases in index.bases.items():
        for b in bases:
            if b in index.methods:
                ra, rb = find(cls), find(b)
                if ra != rb:
                    parent[max(ra, rb)] = min(ra, rb)
    merged = {c for c in parent if find(c) != c} | {
        find(c) for c in parent if find(c) != c
    }
    out: dict[tuple[str, str], _AttrTable] = {}
    for m in index.modules:
        for fi in m.functions.values():
            if fi.cls is None:
                continue
            key = (("<hierarchy>", find(fi.cls)) if fi.cls in merged
                   else (m.path, fi.cls))
            table = out.setdefault(key, _AttrTable())
            _AttrScanner(fi, table).visit(fi.node)
    tables = {k: t.accesses for k, t in out.items()}
    index._class_tables = tables
    return tables


def _is_init(fi: FuncInfo) -> bool:
    return fi.qualname.endswith(".__init__") or fi.qualname.endswith(
        ".__post_init__"
    )


# ---------------------------------------------------------------------------
# G014 — shared-mutable escape
# ---------------------------------------------------------------------------


def g014_shared_escape(index: PackageIndex) -> list[Finding]:
    """A mutable class attribute reachable from two declared thread
    roots with no write ever passing through a declared publish point
    (or a lock-guarded section).  Immutable reference swaps and
    ``__init__``-time construction are exempt; attributes that DO cross
    a publish point are G015's jurisdiction (discipline, not escape)."""
    labels = thread_labels(index)
    out: list[Finding] = []
    for (path, cls), attrs in sorted(_class_tables(index).items()):
        for attr, accesses in sorted(attrs.items()):
            threads: set[str] = set()
            for a in accesses:
                threads |= labels.get(id(a.fi), set())
            if len(threads) < 2:
                continue
            writes = [a for a in accesses if a.write]
            if any(a.fi.publish for a in writes):
                continue  # published attr: G015 territory
            suspects = [
                a for a in writes
                if not _is_init(a.fi) and not a.locked
                and labels.get(id(a.fi))
                and (a.inplace or a.value_kind != "immutable")
            ]
            for a in suspects:
                out.append(Finding(
                    rule="G014", path=a.fi.module.path, line=a.line, col=a.col,
                    msg=(
                        f"`self.{attr}` is shared across threads "
                        f"{{{', '.join(sorted(threads))}}} but this "
                        "write is not a declared publish point — a "
                        "mutable object escaping its owning thread "
                        "without an atomic handoff races its readers; "
                        "swap it in via a `# graftlint: publish` "
                        "function (or guard both sides with one lock)"
                    ),
                ))
    return out


# ---------------------------------------------------------------------------
# G015 — publish-point discipline
# ---------------------------------------------------------------------------


def g015_publish_discipline(index: PackageIndex) -> list[Finding]:
    """The publish contract: (a) a publish function may only SWAP the
    shared attribute (one atomic reference store) — an in-place
    mutation (``self.x[k] = v``, ``self.x += ...``,
    ``self.x.append(...)``) outside a lock publishes a half-applied
    state; (b) a reader-thread function may not mutate an attribute it
    received through a publish point — published snapshots are
    read-only on the far side; (c) the OWNER may not mutate a
    published attribute in place outside the publish point either —
    readers may already hold the reference (the armed sanitizer's
    owner-mutation-after-publish raise, statically); (d) a non-writer
    thread may not REASSIGN a published attribute — even an atomic
    swap races the publisher's swap when it comes from the far side;
    (e) the owner may not reassign a published attribute to a fresh
    MUTABLE object outside the publish point — the swap itself is
    atomic, but the new object crosses threads with no publish
    generation, so the armed sanitizer cannot track it and G017's
    accounting misses the handoff (immutable swaps stay legal: atomic
    and frozen by construction)."""
    labels = thread_labels(index)
    out: list[Finding] = []
    for (path, cls), attrs in sorted(_class_tables(index).items()):
        # published attrs of this class and their writer-side threads
        published: dict[str, set[str]] = {}
        for attr, accesses in attrs.items():
            for a in accesses:
                if a.write and a.fi.publish:
                    published.setdefault(attr, set()).update(
                        labels.get(id(a.fi), set())
                    )
        for attr, accesses in sorted(attrs.items()):
            for a in accesses:
                if not a.write or a.locked:
                    continue
                if not a.inplace:
                    # plain reference swap: the legal form inside a
                    # publish point (and during construction) — but a
                    # NON-writer thread clobbering the published
                    # reference races the publisher's swap
                    if a.fi.publish or _is_init(a.fi):
                        continue
                    writer_threads = published.get(attr)
                    if writer_threads is None:
                        continue
                    mine = labels.get(id(a.fi), set())
                    if mine and not (mine <= writer_threads):
                        out.append(Finding(
                            rule="G015", path=a.fi.module.path, line=a.line,
                            col=a.col,
                            msg=(
                                f"`self.{attr}` is published from "
                                "thread(s) "
                                f"{{{', '.join(sorted(writer_threads))}}}"
                                f" but reassigned here on thread(s) "
                                f"{{{', '.join(sorted(mine))}}} outside "
                                "any publish point — the swap races the "
                                "publisher; route it through a declared "
                                "publish point on the owning thread"
                            ),
                        ))
                    elif mine and a.value_kind != "immutable":
                        # owner-side swap of a fresh mutable object
                        # OUTSIDE the publish point: the store is
                        # atomic, but the new object never gets a
                        # publish generation — the armed sanitizer
                        # cannot track it and the reader thread races
                        # whatever the owner does to it next
                        out.append(Finding(
                            rule="G015", path=a.fi.module.path, line=a.line,
                            col=a.col,
                            msg=(
                                f"`self.{attr}` is a published "
                                "attribute but is reassigned to a "
                                "non-immutable object here outside any "
                                "publish point — the replacement "
                                "crosses threads with no publish "
                                "generation (the race sanitizer cannot "
                                "track it); route every mutable swap "
                                "through the declared publish point"
                            ),
                        ))
                    continue
                if a.fi.publish:
                    out.append(Finding(
                        rule="G015", path=a.fi.module.path,
                        line=a.line, col=a.col,
                        msg=(
                            f"in-place mutation of `self.{attr}` inside "
                            f"publish point `{a.fi.qualname}` — a "
                            "publish must be ONE atomic reference swap "
                            "(build the new object first, then "
                            f"`self.{attr} = fresh`) or lock-guarded; "
                            "readers on the other thread can observe "
                            "this half-applied"
                        ),
                    ))
                    continue
                writer_threads = published.get(attr)
                if writer_threads is None or _is_init(a.fi):
                    continue
                mine = labels.get(id(a.fi), set())
                if mine and not (mine <= writer_threads):
                    out.append(Finding(
                        rule="G015", path=a.fi.module.path,
                        line=a.line, col=a.col,
                        msg=(
                            f"`self.{attr}` is published from thread(s) "
                            f"{{{', '.join(sorted(writer_threads))}}} "
                            f"but mutated here on thread(s) "
                            f"{{{', '.join(sorted(mine))}}} — what a "
                            "reader receives through a publish point "
                            "is read-only; copy before mutating"
                        ),
                    ))
                else:
                    # owner-side: once published, readers may already
                    # hold the reference — mutating it anywhere outside
                    # the publish point tears the snapshot under them
                    # (the armed sanitizer raises for exactly this)
                    out.append(Finding(
                        rule="G015", path=a.fi.module.path,
                        line=a.line, col=a.col,
                        msg=(
                            f"in-place mutation of published "
                            f"`self.{attr}` outside its publish point "
                            f"(`{a.fi.qualname}` is not one) — readers "
                            "on the other thread may already hold this "
                            "reference; build a fresh object and swap "
                            "it in through the publish point"
                        ),
                    ))
    return out


# ---------------------------------------------------------------------------
# G016 — blocking calls in the hot thread
# ---------------------------------------------------------------------------

#: ``queue`` module constructors whose instances block on get/put.
_QUEUE_CTORS = {"Queue", "LifoQueue", "PriorityQueue", "SimpleQueue"}


def _queue_names(m) -> set[str]:
    """Dotted receiver names bound to stdlib ``queue`` constructions in
    this module (``self.inbox = queue.Queue()`` / ``q = Queue()``)."""
    if not any(src == "queue" or src.startswith("queue.")
               for src in m.imports.values()):
        return set()
    out: set[str] = set()
    for node in ast.walk(m.tree):
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]  # self.inbox: queue.Queue = Queue()
        else:
            continue
        v = node.value
        if not isinstance(v, ast.Call):
            continue
        d = dotted(v.func) or ""
        tail = d.split(".")[-1]
        if tail not in _QUEUE_CTORS:
            continue
        root = d.split(".")[0]
        src = m.imports.get(root, "")
        if not (src == "queue" or src.startswith("queue.")):
            continue
        for t in targets:
            td = dotted(t)
            if td:
                out.add(td)
    return out


def _call_arg(node: ast.Call, pos: int, kw: str) -> ast.expr | None:
    """Argument ``kw`` of ``node`` whether passed by keyword or at
    positional index ``pos`` (None when absent or behind ``*args``)."""
    for k in node.keywords:
        if k.arg == kw:
            return k.value
    if len(node.args) > pos and not any(
        isinstance(a, ast.Starred) for a in node.args[: pos + 1]
    ):
        return node.args[pos]
    return None


def _is_false(e: ast.expr | None) -> bool:
    return isinstance(e, ast.Constant) and e.value is False


def _blocking_findings(fi: FuncInfo, chain: str, queues: set[str]
                       ) -> list[Finding]:
    m = fi.module
    out = []

    def hit(node, what, why):
        out.append(Finding(
            rule="G016", path=m.path, line=node.lineno,
            col=node.col_offset,
            msg=(
                f"blocking `{what}` on the serving hot thread "
                f"({chain}) — {why}; hand the wait to its owning "
                "thread and cross back over a publish point"
            ),
        ))

    for node in ast.walk(fi.node):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if _is_lockish(item.context_expr):
                    hit(item.context_expr,
                        f"with {dotted(item.context_expr)}:",
                        "a lock acquisition stalls the drain behind "
                        "whatever thread holds it")
            continue
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not isinstance(f, ast.Attribute):
            continue
        if f.attr == "acquire":
            # acquire(blocking=False) polls; acquire(timeout=t) bounds
            # the stall — only the bare unbounded form wedges the drain
            if not _is_false(_call_arg(node, 0, "blocking")) and (
                _call_arg(node, 1, "timeout") is None
            ):
                hit(node, f"{dotted(f) or f.attr}()",
                    "a lock acquisition stalls the drain behind "
                    "whatever thread holds it")
        elif (f.attr == "join" and not node.args
                and _call_arg(node, 0, "timeout") is None):
            # str.join / os.path.join always take a positional
            # argument; a no-positional-arg join is a thread join —
            # and join(timeout=t) bounds the park, like wait/acquire
            hit(node, f"{dotted(f) or f.attr}()",
                "joining a thread parks the drain for the thread's "
                "whole remaining lifetime")
        elif f.attr == "wait" and _call_arg(node, 0, "timeout") is None:
            hit(node, f"{dotted(f) or f.attr}()",
                "an unbounded event/condition wait wedges the drain "
                "until another thread signals")
        elif f.attr in ("recv", "accept"):
            hit(node, f".{f.attr}()",
                "a socket wait belongs to the status/bus threads, "
                "never the drain")
        elif dotted(f) == "select.select":
            hit(node, "select.select()",
                "a readiness wait belongs to the I/O-owning thread")
        elif f.attr in ("get", "put"):
            recv = dotted(f.value)
            # get/put take (block, timeout) positionally for get and
            # (item, block, timeout) for put — non-blocking or bounded
            # either way stays legal
            pos0 = 1 if f.attr == "put" else 0
            if (recv in queues
                    and not _is_false(_call_arg(node, pos0, "block"))
                    and _call_arg(node, pos0 + 1, "timeout") is None):
                hit(node, f"{recv}.{f.attr}()",
                    "an unbounded stdlib-queue op blocks until the "
                    "other end moves; use put_nowait/get_nowait or a "
                    "timeout and surface the backpressure")
    return out


def g016_blocking_hot_thread(index: PackageIndex) -> list[Finding]:
    """Blocking host primitives reachable from the serving hot path —
    the same walker as G002/G013, DESCENDING into declared fences (a
    fence declares a device sync; wedging the drain behind a lock,
    thread join, socket wait or unbounded queue op is a stall hazard
    anywhere inside the round)."""
    out: list[Finding] = []
    qcache: dict[int, set[str]] = {}
    for fi, chain in walk_hot_scope(index, descend_fences=True):
        m = fi.module
        queues = qcache.get(id(m))
        if queues is None:
            queues = qcache[id(m)] = _queue_names(m)
        out.extend(_blocking_findings(fi, chain, queues))
    return out


# ---------------------------------------------------------------------------
# G017 — publish-point cross-check (static markers vs runtime counters)
# ---------------------------------------------------------------------------


def load_artifact_block(path: str, key: str
                        ) -> tuple[dict | None, str | None]:
    """Block ``key`` from a serve bench artifact (a ``save_results``
    list of BenchResult dicts) or from a raw JSON fixture dict.
    Returns (block, error)."""
    try:
        with open(path, encoding="utf-8") as fh:
            data = json.load(fh)
    except (OSError, ValueError) as e:
        return None, f"unreadable artifact: {e}"
    if isinstance(data, dict):
        block = data.get(key)
        return (block, None) if isinstance(block, dict) else (
            None, f"artifact has no {key} block"
        )
    if isinstance(data, list):
        for entry in data:
            extra = entry.get("extra") if isinstance(entry, dict) else None
            if isinstance(extra, dict) and isinstance(
                extra.get(key), dict
            ):
                return extra[key], None
        return None, f"artifact has no {key} block"
    return None, "artifact is neither a result list nor a dict"


def g017_thread_crossings(index: PackageIndex, artifact_path: str
                          ) -> list[Finding]:
    """Cross-validate the declared publish points against a serve run's
    ``thread_crossings`` counters (the race sanitizer's ground truth):
    a declared publish point the run never entered is DEAD — the
    annotation is stale or the handoff moved; a runtime publish or
    crossing counter with no matching ``# graftlint: publish`` marker
    is an UNATTRIBUTED cross-thread handoff the static confinement
    model does not know about.  ``publish=<tag>`` points are only
    dead-checked against artifacts whose run armed that surface (the
    block carries one boolean per surface, e.g. ``status``); a tag the
    artifact records NO surface for is itself a finding — an
    unmatchable tag would exempt its point from the accounting
    forever."""
    block, err = load_artifact_block(artifact_path, "thread_crossings")
    if block is None:
        return [Finding(
            rule="G017", path=artifact_path, line=0, col=0, msg=err,
        )]
    publishes = block.get("publishes") or {}
    crossings = block.get("crossings") or {}
    declared = {
        fi.qualname: fi
        for m in index.modules for fi in m.functions.values()
        if fi.publish
    }
    out = []
    for qual, fi in sorted(declared.items()):
        tag = fi.publish_tag
        if tag and tag not in block:
            # a tag naming no surface the artifact records would
            # otherwise exempt this point from dead-point accounting
            # FOREVER (a typo'd tag never matches an armed surface)
            out.append(Finding(
                rule="G017", path=fi.module.path, line=fi.node.lineno,
                col=fi.node.col_offset,
                msg=(
                    f"publish point `{qual}` is tagged "
                    f"`publish={tag}` but "
                    f"{os.path.basename(artifact_path)} records no "
                    f"`{tag}` surface — typo'd or stale tag; an "
                    "unmatchable tag silently disables the dead-point "
                    "check for this point"
                ),
            ))
            continue
        if tag and not block.get(tag):
            continue  # surface not armed in this run
        if not publishes.get(qual):
            out.append(Finding(
                rule="G017", path=fi.module.path, line=fi.node.lineno,
                col=fi.node.col_offset,
                msg=(
                    f"declared publish point `{qual}` never entered in "
                    f"{os.path.basename(artifact_path)} — dead publish "
                    "point: delete the stale annotation or re-declare "
                    "the real handoff (tag it publish=<surface> if it "
                    "only crosses when that surface is armed)"
                ),
            ))
    for qual in sorted(set(publishes) | set(crossings)):
        if qual not in declared:
            out.append(Finding(
                rule="G017", path=artifact_path, line=0, col=0,
                msg=(
                    f"runtime publish/crossing counter `{qual}` has no "
                    "matching `# graftlint: publish` marker — an "
                    "unattributed cross-thread handoff the static "
                    "confinement model does not know about"
                ),
            ))
    return out
