"""Runtime value-range sanitizer: the dynamic half of the G026-G029
value-range & index-space model (lint/ranges.py), and the bounds oracle
behind the dtype-edge harness (serve/edgecheck.py).

graftlint's range rules prove *statically* that every dynamic gather /
scatter / Pallas-ref index is dominated by a clamp, a mod, or a
declared ``# graftlint: inrange=`` fact, that clamped gathers feed a
declared mask, and that narrow uint16/int8 op lanes widen before
arithmetic — but the static model trusts the declarations.  XLA makes
the runtime half mandatory in a way no other rule family is: an
out-of-range index does not crash, it CLAMPS, and a wrapped narrow
lane does not overflow, it aliases another slot id — both corrupt
bytes silently.  This module supplies the runtime evidence, the same
architecture as the sync, race, fs and lifecycle sanitizers:

- every declared index check routes through :func:`check_index` (keyed
  by the ``check=<name>`` payload of its static ``inrange=`` marker so
  runtime counters line up with the declarations) and counts its
  dispatches — always, in every mode, one lock-guarded dict increment
  per staged macro.  Likewise :func:`check_narrow` per narrow lane and
  :func:`note_mask` per declared clamp-mask region.  These counters
  are the ground truth the serve artifact exports as its ``ranges``
  block (lint G029 cross-validates dead declared facts and
  unattributed runtime counters against it, G011/G017/G021/G025's
  mirror);
- with ``CRDT_BENCH_SANITIZE_RANGES=1`` the bounds are enforced
  **live, on the staged host tensors pre-dispatch** — the op arrays
  are host-side numpy at the staging boundary already, so validation
  costs zero hot-path device syncs.  An index operand outside
  ``[lo, bound)`` raises :class:`IndexOutOfBoundsError` at the
  callsite with doc/class/round attribution (the value XLA would have
  silently clamped); a narrow-lane value past its headroom ceiling
  raises :class:`NarrowOverflowError` (the value a uint16 repack
  would wrap); a PAD/sentinel value on a lane that must be
  sentinel-free post-masking raises :class:`PadLeakError`.

Disarmed (the default), nothing is validated — the only cost anywhere
is the counter bump, exactly the zero-overhead contract every
sanitizer in this repo keeps.
"""

from __future__ import annotations

import os
import threading

import numpy as np

_ENV = "CRDT_BENCH_SANITIZE_RANGES"

#: The armed-surface vocabulary for the ``ranges`` artifact block.
#: ``staging`` is armed on every drain (the host staging boundary is
#: always crossed); ``fused``/``scan`` track which resolve kernel the
#: run dispatched, so a kernel-scoped mask declared for the fused
#: gather is only dead-checked against runs that ran the fused path.
KNOWN_SURFACES = ("staging", "fused", "scan")


class RangeSanitizerError(RuntimeError):
    """Base class for every armed value-range violation."""


class IndexOutOfBoundsError(RangeSanitizerError):
    """A staged index operand outside its declared ``[lo, bound)``
    range — the value XLA's gather/scatter would clamp (or drop)
    silently instead of faulting."""


class NarrowOverflowError(RangeSanitizerError):
    """A staged narrow-lane value past its dtype headroom — the value
    a uint16/int8 repack would wrap into an aliased slot id."""


class PadLeakError(RangeSanitizerError):
    """A PAD/sentinel value on a lane declared sentinel-free — the
    sentinel escaped its mask and is about to enter arithmetic."""


#: Checks fire from whatever thread stages the macro (the prefetch
#: worker stages off-thread), so the counter tables take a real mutex
#: — same reasoning as lifecycle_sanitizer._mu.
_mu = threading.Lock()
_checks: dict[str, int] = {}  # check name -> staged-dispatch count
_masks: dict[str, int] = {}  # mask tag -> masked-region dispatch count

_armed = False
_forced = False  # armed explicitly (edgecheck harness), not via env


def sanitizing() -> bool:
    """True when ``CRDT_BENCH_SANITIZE_RANGES`` arms the sanitizer.
    Read at reset (not at import) so tests can flip it."""
    return os.environ.get(_ENV, "") not in ("", "0")


def _sync_armed() -> None:
    global _armed
    if not _forced:
        _armed = sanitizing()


def armed() -> bool:
    return _armed


def arm() -> None:
    """Force-arm (the edgecheck harness; tests), independent of the
    env flag."""
    global _armed, _forced
    _armed = True
    _forced = True


def disarm() -> None:
    global _armed, _forced
    _armed = False
    _forced = False


def reset_counters() -> None:
    """Zero the counter tables (each bench run owns its window).  When
    the env flag is set the sanitizer arms HERE, eagerly, so the very
    first staged macro is validated too."""
    _sync_armed()
    with _mu:
        _checks.clear()
        _masks.clear()


def _where(doc=None, cls=None, rnd=None) -> str:
    parts = []
    if doc is not None:
        parts.append(f"doc={doc}")
    if cls is not None:
        parts.append(f"class={cls}")
    if rnd is not None:
        parts.append(f"round={rnd}")
    return f" [{', '.join(parts)}]" if parts else ""


def check_index(name: str, arr, bound, *, lo: int = 0,
                doc=None, cls=None, rnd=None) -> None:
    """One staged index-operand validation.  Counted in EVERY mode
    under ``name`` (the G029 ground truth, matching the static
    ``inrange=... check=<name>`` marker); armed, every element of
    ``arr`` must lie in ``[lo, bound)`` or the out-of-range value is a
    typed error at the callsite — BEFORE dispatch, while the tensor is
    still host-side numpy (zero device syncs).

    ``arr`` may be a zero-arg callable (e.g. a lambda masking out PAD
    lanes) — it is only evaluated when armed, so the disarmed cost
    stays exactly one counter bump."""
    with _mu:
        _checks[name] = _checks.get(name, 0) + 1
    if not _armed:
        return
    # the staged lanes are host numpy ALREADY (pre-dispatch staging
    # boundary): this asarray is a no-copy view, never a device sync
    a = np.asarray(arr() if callable(arr) else arr)  # graftlint: disable=G002
    if a.size == 0:
        return
    amin = int(a.min())
    amax = int(a.max())
    b = int(bound)
    if amin < lo or amax >= b:
        bad = amin if amin < lo else amax
        raise IndexOutOfBoundsError(
            f"index check `{name}`: value {bad} outside [{lo}, {b}) "
            f"on the staged host tensor{_where(doc, cls, rnd)} — XLA "
            f"would clamp this silently, never fault ({_ENV}=1)"
        )


def check_narrow(name: str, arr, bound, *,
                 doc=None, cls=None, rnd=None) -> None:
    """One narrow-lane headroom validation.  Counted in EVERY mode;
    armed, every element must fit ``[0, bound]`` — the ceiling a
    narrow (uint16/int8) repack of this lane can carry losslessly.  A
    value past it is the silent-wrap corruption ``pack_ops`` exists to
    refuse, caught even on paths that skip the pack (the same-dtype
    passthrough)."""
    with _mu:
        _checks[name] = _checks.get(name, 0) + 1
    if not _armed:
        return
    # host numpy already, same as check_index
    a = np.asarray(arr() if callable(arr) else arr)  # graftlint: disable=G002
    if a.size == 0:
        return
    amin = int(a.min())
    amax = int(a.max())
    b = int(bound)
    if amin < 0 or amax > b:
        bad = amin if amin < 0 else amax
        raise NarrowOverflowError(
            f"narrow lane `{name}`: value {bad} outside [0, {b}] "
            f"headroom{_where(doc, cls, rnd)} — a narrow repack would "
            f"wrap it into an aliased id ({_ENV}=1)"
        )


def check_no_pad(name: str, arr, pad, *,
                 doc=None, cls=None, rnd=None) -> None:
    """One sentinel-free-lane validation.  Counted in EVERY mode;
    armed, no element may equal the ``pad`` sentinel — a surviving
    sentinel here escaped its mask and is headed into arithmetic."""
    with _mu:
        _checks[name] = _checks.get(name, 0) + 1
    if not _armed:
        return
    a = np.asarray(arr() if callable(arr) else arr)
    if a.size and bool((a == pad).any()):
        raise PadLeakError(
            f"lane `{name}`: PAD/sentinel value {pad} present on a "
            f"lane declared sentinel-free{_where(doc, cls, rnd)} — "
            f"the mask upstream leaked it ({_ENV}=1)"
        )


def note_mask(tag: str, n: int = 1) -> None:
    """One dispatch through a declared clamp-mask region (the
    ``# graftlint: mask=<tag>`` pair).  Counted in EVERY mode — the
    G029 dead-mask ground truth: a declared mask whose region no
    armed-surface run ever dispatched is stale."""
    with _mu:
        _masks[tag] = _masks.get(tag, 0) + n


def counters() -> dict:
    """Snapshot: ``{"checks": {name: n}, "masks": {tag: n}}`` —
    populated in every mode (the G029 ground truth)."""
    with _mu:
        return {
            "checks": dict(sorted(_checks.items())),
            "masks": dict(sorted(_masks.items())),
        }
