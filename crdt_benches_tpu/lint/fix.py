"""``--fix`` autofixer for G005 (implicit dtype at array creation).

Mechanically rewrites ``jnp.arange(...)`` / ``jnp.zeros(...)`` / ... to
state the dtype they ALREADY produce under today's default config (x64
off) — making the implicit explicit is semantics-preserving by
construction, which is the only kind of rewrite a linter may apply
unattended.  The inference is deliberately narrow:

- ``arange``/``linspace``: every bound/step must be a numeric literal —
  all-int ``arange`` is ``jnp.int32``, anything float (or ``linspace``)
  is ``jnp.float32``.  A non-literal bound is REFUSED: the result dtype
  follows the runtime type of the argument, which the AST cannot know;
- ``zeros``/``ones``/``empty``/``eye``: always ``jnp.float32`` (the JAX
  default — shape arguments never influence dtype);
- ``full``: dtype of the literal fill value (int -> int32, float ->
  float32, bool -> bool_); non-literal fills are refused;
- ``array``: a literal (nested) list/tuple of numbers — int -> int32,
  any float -> float32, all-bool -> bool_; anything else refused.

Refused sites stay G005 findings; the fixer reports them with the
reason.  Fixes are applied right-to-left per file (positions stay
valid), and a second run is a no-op: the rewritten call now has an
explicit dtype, so G005 no longer selects it (idempotence is asserted
by tests/test_lint.py).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

from .core import ModuleInfo, build_index
from .rules import g005_implicit_dtype


@dataclass
class FixResult:
    path: str
    line: int
    applied: bool
    detail: str  # inserted text, or the refusal reason


def _literal_num(e: ast.expr):
    if isinstance(e, ast.Constant) and isinstance(
        e.value, (int, float, bool)
    ):
        return e.value
    if isinstance(e, ast.UnaryOp) and isinstance(e.op, ast.USub):
        v = _literal_num(e.operand)
        return -v if isinstance(v, (int, float)) else None
    return None


def _flat_literals(e: ast.expr):
    """Every scalar literal of a nested list/tuple, or None."""
    if isinstance(e, (ast.List, ast.Tuple)):
        out = []
        for el in e.elts:
            sub = _flat_literals(el)
            if sub is None:
                return None
            out.extend(sub)
        return out
    v = _literal_num(e)
    return None if v is None else [v]


def infer_dtype(call: ast.Call, creator: str) -> tuple[str | None, str]:
    """(dtype name, reason) — dtype None means REFUSED."""
    if any(isinstance(a, ast.Starred) for a in call.args) or any(
        kw.arg is None for kw in call.keywords
    ):
        return None, "star-args hide the argument types"
    if creator in ("zeros", "ones", "empty", "eye"):
        return "float32", "JAX default for value-less creators"
    if creator == "linspace":
        return "float32", "linspace is always inexact"
    if creator == "arange":
        vals = [_literal_num(a) for a in call.args]
        vals += [
            _literal_num(kw.value) for kw in call.keywords
            if kw.arg in ("start", "stop", "step")
        ]
        if not vals or any(v is None for v in vals):
            return None, (
                "non-literal bound: the result dtype follows the "
                "runtime argument type"
            )
        if any(isinstance(v, float) for v in vals):
            return "float32", "float bound"
        return "int32", "all-int bounds"
    if creator == "full":
        if len(call.args) < 2:
            return None, "fill value not positional"
        v = _literal_num(call.args[1])
        if v is None:
            return None, "non-literal fill value"
        if isinstance(v, bool):
            return "bool_", "bool fill"
        if isinstance(v, float):
            return "float32", "float fill"
        return "int32", "int fill"
    if creator == "array":
        if not call.args:
            return None, "no data argument"
        vals = _flat_literals(call.args[0])
        if vals is None:
            return None, "non-literal data: dtype follows runtime values"
        if vals and all(isinstance(v, bool) for v in vals):
            return "bool_", "all-bool data"
        if any(isinstance(v, float) for v in vals):
            return "float32", "float data"
        return "int32", "all-int data"
    return None, f"no inference rule for jnp.{creator}"


def _insertion(src_lines: list[str], call: ast.Call,
               dtype_expr: str) -> tuple[int, int, str] | None:
    """(line0, col, text) inserting ``dtype=...`` before the closing
    paren — or None when the span is unavailable."""
    end_ln = getattr(call, "end_lineno", None)
    end_col = getattr(call, "end_col_offset", None)
    if end_ln is None or end_col is None or end_col < 1:
        return None
    line0 = end_ln - 1
    if line0 >= len(src_lines):
        return None
    close = end_col - 1
    if src_lines[line0][close:close + 1] != ")":
        return None
    # trailing comma? walk back over whitespace (possibly across lines)
    ln, col = line0, close
    while True:
        seg = src_lines[ln][:col].rstrip()
        if seg:
            last = seg[-1]
            break
        if ln == 0:
            last = ""
            break
        ln -= 1
        col = len(src_lines[ln])
    sep = "" if last in (",", "(") else ", "
    return line0, close, f"{sep}dtype={dtype_expr}"


def fix_g005(paths: list[str]) -> list[FixResult]:
    """Apply the G005 autofix to every finding under ``paths``."""
    index, _errors = build_index(paths)
    findings = g005_implicit_dtype(index)
    by_path: dict[str, ModuleInfo] = {m.path: m for m in index.modules}
    per_file: dict[str, list] = {}
    results: list[FixResult] = []
    for f in findings:
        m = by_path.get(f.path)
        if m is None:
            continue
        if f.rule in m.suppress_file or f.rule in m.suppress.get(
            f.line, ()
        ):
            continue
        # locate the exact call node this finding anchored
        call = creator = None
        for node in ast.walk(m.tree):
            if (
                isinstance(node, ast.Call)
                and node.lineno == f.line
                and node.col_offset == f.col
            ):
                attr = m.is_jnp_attr(node.func)
                if attr:
                    call, creator = node, attr
                    break
        if call is None:
            results.append(FixResult(
                f.path, f.line, False, "could not re-locate the call"
            ))
            continue
        alias = call.func.value.id  # the module's own jnp spelling
        dtype, reason = infer_dtype(call, creator)
        if dtype is None:
            results.append(FixResult(
                f.path, f.line, False, f"refused ({reason})"
            ))
            continue
        ins = _insertion(
            m.src.splitlines(), call, f"{alias}.{dtype}"
        )
        if ins is None:
            results.append(FixResult(
                f.path, f.line, False, "call span not rewritable"
            ))
            continue
        per_file.setdefault(f.path, []).append((ins, f.line))
    for path, edits in per_file.items():
        lines = by_path[path].src.splitlines(keepends=True)
        # right-to-left so earlier positions stay valid
        for (line0, col, text), src_line in sorted(
            edits, key=lambda e: (e[0][0], e[0][1]), reverse=True
        ):
            ln = lines[line0]
            lines[line0] = ln[:col] + text + ln[col:]
            results.append(FixResult(path, src_line, True, text))
        with open(path, "w", encoding="utf-8") as fh:
            fh.write("".join(lines))
    results.sort(key=lambda r: (r.path, r.line))
    return results
