"""Value-range & index-space rules G026-G029: guarded dynamic
indexing, narrow-lane overflow, PAD-sentinel flow, and the runtime
ranges-artifact cross-check.

XLA's failure mode for a bad index is unique among this repo's bug
classes: it does not crash, it CLAMPS — a gather with an out-of-range
operand silently reads the wrong row, a scatter drops the update, and
byte-verify only notices once the corrupted row is decoded.  The
serving stack is built on exactly these operations (the serve_fused
clamped gather whose garbage "is masked" was a prose claim; the
uint16 op lanes whose ``OpRangeError`` ceiling guards one entry point
of several).  These rules encode that incident class the same way
G014-G021 and G022-G025 encoded theirs: a declared static model
enforced against the AST, with a runtime sanitizer twin
(lint/range_sanitizer.py) whose counters the artifact-driven G029
cross-checks.

Marker vocabulary (parsed from REAL comments via
``ModuleInfo.comments``):

- ``# graftlint: inrange=<sym><op><bound> [check=<name>]
  [surface=<staging|fused|scan>]`` — declares that local ``<sym>`` is
  in-range (``<`` or ``<=`` the bound) in the enclosing function.
  The bound is an int literal, a SCREAMING_CASE constant resolved
  through the G008 constant environment (``LANE``, class capacities —
  an unresolvable constant is a finding), or a lowercase local whose
  value only the runtime twin can check.  ``check=<name>`` pairs the
  fact with a :func:`range_sanitizer.check_index` counter so G029 can
  dead-check it against a serve artifact.

- ``# graftlint: mask=<tag>`` — one half of a clamp/mask pair: on the
  clamped-gather line it declares "the clamp region's garbage is
  consumed by mask ``<tag>``"; on the masking ``jnp.where`` line it
  declares the consumer.  G026 requires both halves — an undeclared
  clamp-and-hope is a finding — and G029 dead-checks the tag against
  the runtime :func:`range_sanitizer.note_mask` counters.

- ``# graftlint: narrow=<name>`` — declares local ``<name>`` a narrow
  (uint16/int8) op lane for G027 (lanes assigned via an explicit
  ``.astype(uint16/int8)`` are inferred without a marker).

**G026 — unguarded dynamic index.**  Every ``take_along_axis`` /
``jnp.take`` / ``.at[...]`` scatter / Pallas ``*_ref`` subscript whose
index operand is not dominated by a clip/maximum/minimum/mod/``where``
selection, an ``arange``-family constructor, a ``mode="drop"/"fill"``
keyword, or a declared ``inrange=`` fact is a finding.  Guardedness
propagates through local assignment chains and interprocedurally
along the CONFIDENT call edges (``resolve_call(strict=True)``, the
thread-labeling resolver): a bare-parameter index is guarded only
when every confident caller passes a guarded value.  A *clamped*
gather (clip/maximum/minimum or ``mode="clip"``) additionally
requires a declared ``mask=`` consumer for the clamp region.

**G027 — narrow-lane overflow.**  Arithmetic (``+ - * <<``) on a lane
declared (or inferred) uint16/int8 before a widen
(``.astype(int32)`` / ``widen_ops`` unpack) can exceed the dtype and
wrap — unless the function is dominated by the ``OpRangeError``
staging bound check (``pack_ops``'s refusal path).

**G028 — PAD-sentinel flow.**  A PAD/sentinel constant (``PAD``,
``*_PAD``, ``*_SENTINEL``, ``_BIG`` — local or imported, resolved
cross-module) reaching arithmetic, or a sentinel-carrying local
(assigned from a ``where``/``full`` that plants the sentinel)
reaching arithmetic or an ordering comparison against anything other
than the sentinel itself, without an intervening mask (a ``where``
whose condition tests the sentinel, or a ``mask=`` tag on the line).
Comparisons AGAINST the sentinel are the masking idiom and are legal.

**G029 — ranges artifact cross-check** (artifact-driven, mirrors
G011/G017/G021/G025): the serve artifact's ``ranges`` block (the
range sanitizer's check/mask counters) is the runtime ground truth.
A ``check=``-paired fact or declared mask tag the run never counted
is DEAD (scoped by armed surface: staging/fused/scan); a runtime
counter with no matching declaration is a model escape.

Jurisdiction: the serving stack (``ops/``, ``serve/``) plus the
``ranges`` fixture corpus — the engine's merge/replay kernels predate
the model and land under it with the ROADMAP compaction work.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

from .core import Finding, FuncInfo, ModuleInfo, PackageIndex
from .flow import ConstEnv
from .range_sanitizer import KNOWN_SURFACES
from .threads import load_artifact_block

#: Directory scope (path components): the serving stack, plus the
#: fixture corpus directory so seeded violations fire under test.
_RANGE_DIRS = ("ops", "serve", "ranges")

_INRANGE_RE = re.compile(
    r"#\s*graftlint:\s*inrange=([A-Za-z_][A-Za-z0-9_]*)"
    r"(<=|<)([A-Za-z0-9_\-]+)"
)
_CHECK_RE = re.compile(r"\bcheck=([A-Za-z0-9_.\-]+)")
_SURFACE_RE = re.compile(r"\bsurface=([A-Za-z0-9_-]+)")
_MASK_RE = re.compile(r"#\s*graftlint:\s*mask=([A-Za-z0-9_-]+)")
_NARROW_RE = re.compile(
    r"#\s*graftlint:\s*narrow=([A-Za-z_][A-Za-z0-9_]*)"
)

#: Module-constant names treated as PAD/sentinel values by convention.
_PAD_NAME_RE = re.compile(r"^(_?(PAD|SENTINEL|BIG)|.*_(PAD|SENTINEL))$")

#: SCREAMING_CASE bound symbols must resolve through the constant
#: environment (same convention as flow._CONST_NAME).
_CONST_BOUND_RE = re.compile(r"^[A-Z][A-Z0-9_]{2,}$")

#: Index-producing calls that CLAMP their operand into range — guarded,
#: but the clamp region's garbage needs a declared mask consumer when
#: the result feeds a gather.
_CLAMP_FUNCS = frozenset({"clip", "maximum", "minimum"})

#: Index-producing calls whose result is in-range (or out-of-range-safe)
#: by construction: `where` selection (the drop-sentinel scatter idiom),
#: iota/arange/argsort families, zero/full constructors.
_SAFE_FUNCS = frozenset({
    "where", "arange", "argsort", "argmax", "argmin", "iota",
    "broadcasted_iota", "zeros", "zeros_like", "ones", "full",
    "mod", "remainder",
})

#: Receiver methods transparent to guardedness (shape-only).
_TRANSPARENT_METHODS = frozenset({
    "astype", "reshape", "squeeze", "ravel", "flatten", "transpose",
})

#: Out-of-bounds-safe `mode=` spellings on gather/scatter calls.
_SAFE_MODES = frozenset({"drop", "fill", "promise_in_bounds"})

#: Narrow dtype attribute spellings for G027 inference.
_NARROW_DTYPE_ATTRS = frozenset({"uint16", "int8"})

_ARITH_OPS = (ast.Add, ast.Sub, ast.Mult, ast.LShift, ast.Pow)
_ORDER_OPS = (ast.Lt, ast.LtE, ast.Gt, ast.GtE)


def _in_scope(path: str) -> bool:
    parts = path.replace("\\", "/").split("/")
    return any(d in parts for d in _RANGE_DIRS)


# ---------------------------------------------------------------------------
# marker model
# ---------------------------------------------------------------------------


@dataclass
class RangeFact:
    sym: str
    op: str  # "<" | "<="
    bound: str  # raw token
    bound_val: int | None
    check: str | None
    surface: str
    module: ModuleInfo
    line: int
    fi: FuncInfo | None


@dataclass
class MaskDecl:
    tag: str
    surface: str
    module: ModuleInfo
    line: int
    fi: FuncInfo | None


@dataclass
class NarrowDecl:
    name: str
    module: ModuleInfo
    line: int
    fi: FuncInfo | None


@dataclass
class RangeModel:
    facts: list = field(default_factory=list)
    masks: list = field(default_factory=list)
    narrows: list = field(default_factory=list)
    parse_findings: list = field(default_factory=list)

    def facts_for(self, fi: FuncInfo) -> dict:
        return {
            f.sym: f for f in self.facts
            if f.fi is not None and f.fi.node is fi.node
        }

    def mask_lines(self, m: ModuleInfo) -> dict:
        """tag -> sorted distinct declaration lines in module ``m``."""
        out: dict[str, set] = {}
        for mk in self.masks:
            if mk.module.path == m.path:
                out.setdefault(mk.tag, set()).add(mk.line)
        return {t: sorted(ls) for t, ls in out.items()}


def _enclosing_fn(m: ModuleInfo, line: int) -> FuncInfo | None:
    """The innermost function whose span contains ``line``."""
    best = None
    best_span = None
    for fi in m.functions.values():
        lo = fi.node.lineno
        hi = getattr(fi.node, "end_lineno", lo) or lo
        if lo <= line <= hi:
            span = hi - lo
            if best_span is None or span < best_span:
                best, best_span = fi, span
    return best


def build_range_model(index: PackageIndex) -> RangeModel:
    cached = getattr(index, "_range_model", None)
    if cached is not None:
        return cached
    model = RangeModel()
    env = ConstEnv.of(index)
    for m in index.modules:
        for lineno, text in sorted(m.comments.items()):
            for im in _INRANGE_RE.finditer(text):
                sym, op, bound = im.group(1), im.group(2), im.group(3)
                fi = _enclosing_fn(m, lineno)
                bound_val: int | None = None
                if re.fullmatch(r"-?\d+", bound):
                    bound_val = int(bound)
                elif _CONST_BOUND_RE.match(bound):
                    v = env.lookup(m, bound)
                    if isinstance(v, int):
                        bound_val = v
                    else:
                        model.parse_findings.append(Finding(
                            rule="G026", path=m.path, line=lineno,
                            col=0,
                            msg=(
                                f"inrange bound `{bound}` looks like a "
                                "module constant but the constant "
                                "environment cannot resolve it — a "
                                "typo'd bound symbol declares a fact "
                                "about nothing"
                            ),
                        ))
                cm = _CHECK_RE.search(text)
                sm = _SURFACE_RE.search(text)
                surface = sm.group(1) if sm else "staging"
                if surface not in KNOWN_SURFACES:
                    model.parse_findings.append(Finding(
                        rule="G026", path=m.path, line=lineno, col=0,
                        msg=(
                            f"unknown range surface `{surface}` — the "
                            "ranges model only knows "
                            f"{'/'.join(KNOWN_SURFACES)}; an "
                            "unmatchable surface silently disables "
                            "the G029 dead-fact check"
                        ),
                    ))
                if fi is None:
                    model.parse_findings.append(Finding(
                        rule="G026", path=m.path, line=lineno, col=0,
                        msg=(
                            f"inrange fact for `{sym}` outside any "
                            "function — range facts describe a local "
                            "operand, not the module"
                        ),
                    ))
                model.facts.append(RangeFact(
                    sym=sym, op=op, bound=bound, bound_val=bound_val,
                    check=cm.group(1) if cm else None,
                    surface=surface, module=m, line=lineno, fi=fi,
                ))
            for mm in _MASK_RE.finditer(text):
                sm = _SURFACE_RE.search(text)
                surface = sm.group(1) if sm else "staging"
                if surface not in KNOWN_SURFACES:
                    model.parse_findings.append(Finding(
                        rule="G026", path=m.path, line=lineno, col=0,
                        msg=(
                            f"unknown range surface `{surface}` on "
                            f"mask `{mm.group(1)}` — want "
                            f"{'/'.join(KNOWN_SURFACES)}"
                        ),
                    ))
                model.masks.append(MaskDecl(
                    tag=mm.group(1), surface=surface, module=m,
                    line=lineno, fi=_enclosing_fn(m, lineno),
                ))
            for nm in _NARROW_RE.finditer(text):
                model.narrows.append(NarrowDecl(
                    name=nm.group(1), module=m, line=lineno,
                    fi=_enclosing_fn(m, lineno),
                ))
    index._range_model = model
    return model


# ---------------------------------------------------------------------------
# guardedness analysis (G026)
# ---------------------------------------------------------------------------


def _call_sites(index: PackageIndex) -> dict:
    """id(callee FuncInfo node) -> [(caller FuncInfo, Call)] along the
    CONFIDENT edges only — the same resolver thread_labels trusts."""
    cached = getattr(index, "_range_call_sites", None)
    if cached is not None:
        return cached
    sites: dict[ast.AST, list] = {}
    for m in index.modules:
        for fi in m.functions.values():
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                for callee in index.resolve_call(node, fi, strict=True):
                    sites.setdefault(callee.node, []).append(
                        (fi, node)
                    )
    index._range_call_sites = sites
    return sites


class _FnGuards:
    """Per-function guardedness state: declared facts, range-loop
    variables, and locals assigned from guarded expressions (a small
    fixpoint so assignment chains converge)."""

    def __init__(self, fi: FuncInfo, model: RangeModel):
        self.fi = fi
        self.facts = model.facts_for(fi)
        self.loopvars: set[str] = set()
        self.guarded: dict[str, bool] = {}  # name -> clamped
        for node in ast.walk(fi.node):
            if isinstance(node, ast.For) and isinstance(
                node.target, ast.Name
            ):
                it = node.iter
                if (
                    isinstance(it, ast.Call)
                    and isinstance(it.func, ast.Name)
                    and it.func.id == "range"
                ):
                    self.loopvars.add(node.target.id)

    def populate(self, an: "_Analyzer") -> None:
        for _ in range(4):  # assignment chains are shallow
            changed = False
            for node in ast.walk(self.fi.node):
                if not (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                ):
                    continue
                name = node.targets[0].id
                if name in self.guarded:
                    continue
                g, c = an.guard(node.value, self, set())
                if g:
                    self.guarded[name] = c
                    changed = True
            if not changed:
                break


class _Analyzer:
    def __init__(self, index: PackageIndex, model: RangeModel):
        self.index = index
        self.model = model
        # keyed by the node OBJECT (never a bare id(): the dict keeps
        # the node alive, so the key cannot recycle — G024's contract)
        self._states: dict[ast.AST, _FnGuards] = {}

    def state(self, fi: FuncInfo) -> _FnGuards:
        st = self._states.get(fi.node)
        if st is None:
            # store BEFORE populating: guardedness can re-enter this
            # function's state through a call cycle, and the partially
            # built (conservative) view must answer, not recurse
            st = self._states[fi.node] = _FnGuards(fi, self.model)
            st.populate(self)
        return st

    # -- expression guardedness -------------------------------------------

    def guard(self, e: ast.expr, st: _FnGuards,
              visited: set) -> tuple[bool, bool]:
        """(guarded, clamped) for an index expression in ``st``'s
        function."""
        if isinstance(e, ast.Constant):
            return isinstance(e.value, (int, bool)), False
        if isinstance(e, ast.Slice):
            return True, False  # python slice semantics clamp safely
        if isinstance(e, ast.Tuple):
            clamped = False
            for el in e.elts:
                g, c = self.guard(el, st, visited)
                if not g:
                    return False, False
                clamped |= c
            return True, clamped
        if isinstance(e, ast.UnaryOp) and isinstance(e.op, ast.USub):
            return self.guard(e.operand, st, visited)
        if isinstance(e, ast.Name):
            if e.id in st.facts or e.id in st.loopvars:
                return True, False
            if e.id in st.guarded:
                return True, st.guarded[e.id]
            if e.id in st.fi.params:
                return self._param_guard(st.fi, e.id, visited)
            return False, False
        if isinstance(e, ast.BinOp):
            if isinstance(e.op, ast.Mod):
                return True, False  # wraps into range by construction
            return False, False
        if isinstance(e, ast.Subscript):
            # pure reshape subscripts (`sq[:, :, None]`) are
            # transparent: the values are the receiver's
            parts = (
                e.slice.elts if isinstance(e.slice, ast.Tuple)
                else [e.slice]
            )
            if all(
                isinstance(p, ast.Slice)
                or (isinstance(p, ast.Constant) and p.value is None)
                for p in parts
            ):
                return self.guard(e.value, st, visited)
            return False, False
        if isinstance(e, ast.Call):
            f = e.func
            attr = f.attr if isinstance(f, ast.Attribute) else None
            if attr in _CLAMP_FUNCS:
                return True, True
            if attr in _SAFE_FUNCS:
                return True, False
            if attr in _TRANSPARENT_METHODS and isinstance(
                f, ast.Attribute
            ):
                return self.guard(f.value, st, visited)
            return False, False
        return False, False

    def _param_guard(self, fi: FuncInfo, pname: str,
                     visited: set) -> tuple[bool, bool]:
        """A bare-parameter index is guarded iff EVERY confident call
        site passes a guarded value (and at least one exists) — the
        interprocedural propagation along thread_labels' edges."""
        key = (fi.node, pname)
        if key in visited:
            return False, False  # recursion: nothing proven
        visited = visited | {key}
        sites = _call_sites(self.index).get(fi.node)
        if not sites:
            return False, False
        clamped = False
        try:
            pos = fi.params.index(pname)
        except ValueError:
            return False, False
        for caller, call in sites:
            arg = None
            offset = (
                1 if fi.cls is not None
                and isinstance(call.func, ast.Attribute) else 0
            )
            idx = pos - offset
            if 0 <= idx < len(call.args):
                arg = call.args[idx]
            else:
                for kw in call.keywords:
                    if kw.arg == pname:
                        arg = kw.value
                        break
            if arg is None:
                arg = self._default_for(fi, pname)
            if arg is None:
                return False, False
            g, c = self.guard(arg, self.state(caller), visited)
            if not g:
                return False, False
            clamped |= c
        return True, clamped

    @staticmethod
    def _default_for(fi: FuncInfo, pname: str) -> ast.expr | None:
        a = fi.node.args
        names = [p.arg for p in (a.posonlyargs + a.args)]
        defaults = a.defaults
        if not defaults:
            return None
        tail = names[-len(defaults):]
        if pname in tail:
            return defaults[tail.index(pname)]
        return None


@dataclass
class _Site:
    idx: ast.expr
    line: int
    col: int
    kind: str  # "gather" | "scatter" | "ref"
    mode: str | None
    desc: str


def _gather_mode(call: ast.Call) -> str | None:
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            return kw.value.value
    return None


def _index_sites(m: ModuleInfo, fi: FuncInfo) -> list[_Site]:
    sites: list[_Site] = []
    for node in ast.walk(fi.node):
        if isinstance(node, ast.Call) and isinstance(
            node.func, ast.Attribute
        ):
            f = node.func
            if (
                f.attr in ("take_along_axis", "take")
                and isinstance(f.value, ast.Name)
                and f.value.id in m.jnp_aliases
            ):
                idx = None
                if len(node.args) >= 2:
                    idx = node.args[1]
                else:
                    for kw in node.keywords:
                        if kw.arg == "indices":
                            idx = kw.value
                if idx is not None:
                    sites.append(_Site(
                        idx=idx, line=node.lineno,
                        col=node.col_offset, kind="gather",
                        mode=_gather_mode(node),
                        desc=f"jnp.{f.attr} gather",
                    ))
            elif isinstance(f.value, ast.Subscript) and isinstance(
                f.value.value, ast.Attribute
            ) and f.value.value.attr == "at":
                sub = f.value
                sites.append(_Site(
                    idx=sub.slice, line=sub.lineno,
                    col=sub.col_offset, kind="scatter",
                    mode=_gather_mode(node),
                    desc=f".at[...].{f.attr} scatter",
                ))
        elif isinstance(node, ast.Subscript) and isinstance(
            node.value, ast.Name
        ) and (
            node.value.id.endswith("_ref") or node.value.id == "ref"
        ):
            sites.append(_Site(
                idx=node.slice, line=node.lineno,
                col=node.col_offset, kind="ref", mode=None,
                desc=f"Pallas ref `{node.value.id}[...]` index",
            ))
    return sites


def g026_index_guard(index: PackageIndex) -> list[Finding]:
    model = build_range_model(index)
    out = list(model.parse_findings)
    an = _Analyzer(index, model)
    for m in index.modules:
        if not _in_scope(m.path):
            continue
        mask_lines = model.mask_lines(m)
        for fi in m.functions.values():
            sites = _index_sites(m, fi)
            if not sites:
                continue
            st = an.state(fi)
            for s in sites:
                if s.mode in _SAFE_MODES:
                    continue  # out-of-bounds behavior is declared
                guarded, clamped = an.guard(s.idx, st, set())
                clamped |= s.mode == "clip"
                if not guarded and s.mode != "clip":
                    out.append(Finding(
                        rule="G026", path=m.path, line=s.line,
                        col=s.col,
                        msg=(
                            f"unguarded dynamic index into {s.desc} "
                            f"in `{fi.qualname}`: the operand is not "
                            "dominated by a clip/maximum/mod/where "
                            "guard or a declared `# graftlint: "
                            "inrange=` fact on any confident call "
                            "path — XLA clamps out-of-range indices "
                            "silently instead of faulting"
                        ),
                    ))
                    continue
                if clamped and s.kind == "gather":
                    tags = [
                        t for t, lines in mask_lines.items()
                        if s.line in lines
                    ]
                    if not tags:
                        out.append(Finding(
                            rule="G026", path=m.path, line=s.line,
                            col=s.col,
                            msg=(
                                f"clamped gather in `{fi.qualname}` "
                                "with no declared mask consumer — the "
                                "clamp region reads garbage by "
                                "construction; declare the consuming "
                                "mask with `# graftlint: mask=<tag>` "
                                "on BOTH the gather and the masking "
                                "`where` (undeclared clamp-and-hope)"
                            ),
                        ))
                        continue
                    for t in tags:
                        if len(mask_lines.get(t, [])) < 2:
                            out.append(Finding(
                                rule="G026", path=m.path, line=s.line,
                                col=s.col,
                                msg=(
                                    f"mask tag `{t}` on this clamped "
                                    "gather has no paired consumer "
                                    "site in the module — the clamp "
                                    "region's garbage is read "
                                    "unmasked"
                                ),
                            ))
    return out


# ---------------------------------------------------------------------------
# G027 — narrow-lane overflow
# ---------------------------------------------------------------------------


def _is_narrow_dtype_attr(e: ast.expr, m: ModuleInfo) -> bool:
    return (
        isinstance(e, ast.Attribute)
        and e.attr in _NARROW_DTYPE_ATTRS
        and isinstance(e.value, ast.Name)
        and e.value.id in (m.jnp_aliases | m.np_aliases)
    )


def _narrow_inferred(node: ast.Assign, m: ModuleInfo) -> bool:
    """True when the assignment's value casts to a narrow dtype
    (``x.astype(np.uint16)`` / ``np.asarray(x, np.int8)``)."""
    for leaf in ast.walk(node.value):
        if not isinstance(leaf, ast.Call):
            continue
        f = leaf.func
        if isinstance(f, ast.Attribute) and f.attr in (
            "astype", "asarray", "array", "full", "zeros", "ones",
        ):
            for a in list(leaf.args) + [kw.value for kw in leaf.keywords]:
                if _is_narrow_dtype_attr(a, m):
                    return True
        if _is_narrow_dtype_attr(f, m):  # np.uint16(x) constructor
            return True
    return False


def _widen_lines(fi: FuncInfo) -> dict[str, int]:
    """name -> line where the local is widened back to int32: an
    ``.astype(int32)``-style reassignment or a ``widen_ops`` unpack."""
    out: dict[str, int] = {}
    for node in ast.walk(fi.node):
        if not isinstance(node, ast.Assign):
            continue
        widens = False
        for leaf in ast.walk(node.value):
            if isinstance(leaf, ast.Call):
                f = leaf.func
                if isinstance(f, ast.Name) and f.id == "widen_ops":
                    widens = True
                elif isinstance(f, ast.Attribute) and f.attr in (
                    "astype", "asarray",
                ):
                    for a in (
                        list(leaf.args)
                        + [kw.value for kw in leaf.keywords]
                    ):
                        if (
                            isinstance(a, ast.Attribute)
                            and a.attr in ("int32", "int64")
                        ):
                            widens = True
        if not widens:
            continue
        for t in node.targets:
            elts = t.elts if isinstance(t, ast.Tuple) else [t]
            for el in elts:
                if isinstance(el, ast.Name):
                    line = out.get(el.id)
                    if line is None or node.lineno < line:
                        out[el.id] = node.lineno
    return out


def _range_check_line(fi: FuncInfo) -> int | None:
    """The line of an ``OpRangeError`` raise (or a ``pack_ops`` /
    ``_check_range`` call) dominating later narrow arithmetic — the
    staging bound check the packing module keeps."""
    best = None
    for node in ast.walk(fi.node):
        line = None
        if isinstance(node, ast.Raise) and node.exc is not None:
            for leaf in ast.walk(node.exc):
                if (
                    isinstance(leaf, ast.Name)
                    and leaf.id == "OpRangeError"
                ):
                    line = node.lineno
        elif isinstance(node, ast.Call):
            f = node.func
            name = (
                f.id if isinstance(f, ast.Name)
                else f.attr if isinstance(f, ast.Attribute) else None
            )
            if name in ("pack_ops", "_check_range"):
                line = node.lineno
        if line is not None and (best is None or line < best):
            best = line
    return best


def g027_narrow_overflow(index: PackageIndex) -> list[Finding]:
    model = build_range_model(index)
    out: list[Finding] = []
    for m in index.modules:
        if not _in_scope(m.path):
            continue
        for fi in m.functions.values():
            narrow: dict[str, int] = {}
            for nd in model.narrows:
                if nd.fi is not None and nd.fi.node is fi.node:
                    narrow[nd.name] = nd.line
            for node in ast.walk(fi.node):
                if (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and _narrow_inferred(node, m)
                ):
                    name = node.targets[0].id
                    if name not in narrow:
                        narrow[name] = node.lineno
            if not narrow:
                continue
            widened = _widen_lines(fi)
            checked = _range_check_line(fi)
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.BinOp) or not isinstance(
                    node.op, _ARITH_OPS
                ):
                    continue
                for side in (node.left, node.right):
                    if not isinstance(side, ast.Name):
                        continue
                    name = side.id
                    if name not in narrow:
                        continue
                    if node.lineno < narrow[name]:
                        continue  # arithmetic before it went narrow
                    w = widened.get(name)
                    if w is not None and w <= node.lineno:
                        continue  # widened first — the legal order
                    if checked is not None and checked <= node.lineno:
                        continue  # dominated by the OpRangeError check
                    out.append(Finding(
                        rule="G027", path=m.path, line=node.lineno,
                        col=node.col_offset,
                        msg=(
                            f"arithmetic on narrow lane `{name}` "
                            f"(uint16/int8) in `{fi.qualname}` before "
                            "a widen — the sum can exceed the dtype "
                            "and WRAP into an aliased value; widen "
                            "first (`.astype(int32)` / `widen_ops`) "
                            "or dominate with the `OpRangeError` "
                            "staging bound check"
                        ),
                    ))
    return out


# ---------------------------------------------------------------------------
# G028 — PAD-sentinel flow
# ---------------------------------------------------------------------------


def _pad_consts(m: ModuleInfo) -> set[str]:
    """Local names bound to PAD/sentinel constants: module-level
    definitions matching the naming convention, plus imports whose
    source ends with one (cross-module tracking)."""
    out = set()
    for node in ast.iter_child_nodes(m.tree):
        targets = ()
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = (node.target,)
        for t in targets:
            if isinstance(t, ast.Name) and _PAD_NAME_RE.match(t.id):
                out.add(t.id)
    for local, src in m.imports.items():
        leaf = src.rpartition(".")[2]
        if _PAD_NAME_RE.match(leaf) and _PAD_NAME_RE.match(local):
            out.add(local)
    return out


def _compares_pad(e: ast.expr, pads: set, carrying: set) -> bool:
    """True when ``e`` contains a comparison against the sentinel —
    the masking idiom (``x == PAD`` / ``nxt >= _BIG``)."""
    for leaf in ast.walk(e):
        if isinstance(leaf, ast.Compare):
            for side in [leaf.left] + list(leaf.comparators):
                if isinstance(side, ast.Name) and side.id in pads:
                    return True
    return False


def _carry_names(e: ast.expr) -> list[str]:
    """Names contributing VALUE to ``e`` — Compare subtrees are pruned
    (a comparison yields a boolean mask, never the sentinel value, so
    ``before = sum(where(d < d', L, 0))`` does not carry ``d``'s
    sentinel even though ``d`` appears in it)."""
    out: list[str] = []
    stack = [e]
    while stack:
        n = stack.pop()
        if isinstance(n, ast.Compare):
            continue
        if isinstance(n, ast.Name):
            out.append(n.id)
        stack.extend(ast.iter_child_nodes(n))
    return out


def _replant_exempt(fi: FuncInfo, pads: set) -> set:
    """ids of nodes inside a ``where`` branch whose OTHER branch (or
    the same one) re-plants the sentinel constant — the self-masking
    idiom ``where(live, d - before, BIG)``: whatever garbage the
    sentinel-carrying operand produces on dead lanes is overwritten by
    the sentinel in the same select, so the arithmetic never leaks."""
    out: set[int] = set()
    for node in ast.walk(fi.node):
        if not (isinstance(node, ast.Call) and len(node.args) == 3):
            continue
        f = node.func
        fname = (
            f.attr if isinstance(f, ast.Attribute)
            else f.id if isinstance(f, ast.Name) else None
        )
        if fname != "where":
            continue
        if any(
            isinstance(a, ast.Name) and a.id in pads
            for a in node.args[1:3]
        ):
            for a in node.args[1:3]:
                for leaf in ast.walk(a):
                    out.add(id(leaf))
    return out


def g028_pad_flow(index: PackageIndex) -> list[Finding]:
    model = build_range_model(index)
    out: list[Finding] = []
    for m in index.modules:
        if not _in_scope(m.path):
            continue
        pads = _pad_consts(m)
        if not pads:
            continue
        masked_lines = {
            mk.line for mk in model.masks if mk.module.path == m.path
        }
        for fi in m.functions.values():
            carrying: set[str] = set()
            # sentinel-carrying locals, small fixpoint for chains;
            # a `where` whose condition tests the sentinel MASKS it
            # (the reassigned value is clean), as does any value
            # containing a sentinel comparison (it is a boolean mask)
            for _ in range(4):
                changed = False
                for node in ast.walk(fi.node):
                    if not (
                        isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                    ):
                        continue
                    name = node.targets[0].id
                    carries = any(
                        nm in pads or nm in carrying
                        for nm in _carry_names(node.value)
                    )
                    masked = _compares_pad(node.value, pads, carrying)
                    if carries and not masked:
                        if name not in carrying:
                            carrying.add(name)
                            changed = True
                    elif masked and name in carrying:
                        carrying.discard(name)
                        changed = True
                if not changed:
                    break
            replant = _replant_exempt(fi, pads)
            for node in ast.walk(fi.node):
                if isinstance(node, ast.BinOp) and isinstance(
                    node.op, _ARITH_OPS + (ast.FloorDiv, ast.Mod)
                ):
                    for side in (node.left, node.right):
                        if not isinstance(side, ast.Name):
                            continue
                        if side.id in pads:
                            out.append(Finding(
                                rule="G028", path=m.path,
                                line=node.lineno,
                                col=node.col_offset,
                                msg=(
                                    f"PAD/sentinel constant "
                                    f"`{side.id}` used directly in "
                                    f"arithmetic in `{fi.qualname}` — "
                                    "a sentinel is an out-of-band "
                                    "marker, not a number; mask it "
                                    "out first"
                                ),
                            ))
                        elif (
                            side.id in carrying
                            and node.lineno not in masked_lines
                            and id(node) not in replant
                        ):
                            out.append(Finding(
                                rule="G028", path=m.path,
                                line=node.lineno,
                                col=node.col_offset,
                                msg=(
                                    f"`{side.id}` may carry the PAD/"
                                    "sentinel value into arithmetic "
                                    f"in `{fi.qualname}` with no "
                                    "intervening mask — a surviving "
                                    "sentinel poisons every "
                                    "downstream sum; mask with a "
                                    "`where` testing the sentinel "
                                    "first"
                                ),
                            ))
                elif isinstance(node, ast.Compare) and any(
                    isinstance(op, _ORDER_OPS) for op in node.ops
                ):
                    operands = [node.left] + list(node.comparators)
                    if any(
                        isinstance(s, ast.Name) and s.id in pads
                        for s in operands
                    ):
                        continue  # comparison AGAINST the sentinel:
                        # the masking idiom itself
                    for side in operands:
                        if (
                            isinstance(side, ast.Name)
                            and side.id in carrying
                            and node.lineno not in masked_lines
                        ):
                            out.append(Finding(
                                rule="G028", path=m.path,
                                line=node.lineno,
                                col=node.col_offset,
                                msg=(
                                    f"`{side.id}` may carry the PAD/"
                                    "sentinel value into an ordering "
                                    f"comparison in `{fi.qualname}` — "
                                    "the sentinel orders arbitrarily; "
                                    "mask it out (or compare against "
                                    "the sentinel itself) first"
                                ),
                            ))
    return out


# ---------------------------------------------------------------------------
# G029 — ranges artifact cross-check
# ---------------------------------------------------------------------------


def g029_ranges_artifact(index: PackageIndex, artifact_path: str
                         ) -> list[Finding]:
    """Cross-validate the declared range model against a serve run's
    ``ranges`` counters (the range sanitizer's ground truth): a
    ``check=``-paired inrange fact or declared mask tag the run never
    counted is DEAD — the declaration is stale or the staging path
    moved; a runtime counter with no matching declaration is bounds
    activity the static model does not know about.  Dead-checking is
    scoped by armed surface (staging/fused/scan) exactly like G011
    fence tags and G025 machine surfaces."""
    block, err = load_artifact_block(artifact_path, "ranges")
    if block is None:
        return [Finding(
            rule="G029", path=artifact_path, line=0, col=0, msg=err,
        )]
    out: list[Finding] = []
    version = block.get("version")
    if version != 1:
        out.append(Finding(
            rule="G029", path=artifact_path, line=0, col=0,
            msg=(
                f"ranges block version {version!r} is not the schema "
                "this rule validates (want 1) — regenerate the "
                "artifact or update the cross-check together with "
                "the schema"
            ),
        ))
        return out
    checks = block.get("checks") or {}
    masks = block.get("masks") or {}
    model = build_range_model(index)
    base = artifact_path.replace("\\", "/").rpartition("/")[2]
    declared_checks: dict[str, RangeFact] = {}
    for fact in model.facts:
        if fact.check is not None and fact.check not in declared_checks:
            declared_checks[fact.check] = fact
    for name, fact in sorted(declared_checks.items()):
        if fact.surface not in block:
            out.append(Finding(
                rule="G029", path=fact.module.path, line=fact.line,
                col=0,
                msg=(
                    f"range check `{name}` is scoped to surface "
                    f"`{fact.surface}` but {base} records no such "
                    "surface — stale ranges schema or typo'd "
                    "surface; an unmatchable surface silently "
                    "disables the dead-fact check"
                ),
            ))
            continue
        if not block.get(fact.surface):
            continue  # surface not armed in this run
        if not checks.get(name):
            out.append(Finding(
                rule="G029", path=fact.module.path, line=fact.line,
                col=0,
                msg=(
                    f"declared range check `{name}` recorded zero "
                    f"dispatches in {base} (surface "
                    f"`{fact.surface}` armed) — dead fact: delete "
                    "the stale declaration or route the staging "
                    "path through its check_index() twin"
                ),
            ))
    declared_masks: dict[str, MaskDecl] = {}
    for mk in model.masks:
        if mk.tag not in declared_masks:
            declared_masks[mk.tag] = mk
    for tag, mk in sorted(declared_masks.items()):
        if mk.surface not in block:
            out.append(Finding(
                rule="G029", path=mk.module.path, line=mk.line, col=0,
                msg=(
                    f"mask `{tag}` is scoped to surface "
                    f"`{mk.surface}` but {base} records no such "
                    "surface — stale ranges schema or typo'd surface"
                ),
            ))
            continue
        if not block.get(mk.surface):
            continue
        if not masks.get(tag):
            out.append(Finding(
                rule="G029", path=mk.module.path, line=mk.line, col=0,
                msg=(
                    f"declared mask `{tag}` recorded zero dispatches "
                    f"in {base} (surface `{mk.surface}` armed) — "
                    "dead mask: the clamp region it consumes never "
                    "dispatched; delete the stale tag or note_mask() "
                    "the region"
                ),
            ))
    for name in sorted(checks):
        if name not in declared_checks:
            out.append(Finding(
                rule="G029", path=artifact_path, line=0, col=0,
                msg=(
                    f"runtime range check `{name}` has no matching "
                    "`# graftlint: inrange=... check=` declaration — "
                    "bounds activity the static model does not know "
                    "about"
                ),
            ))
    for tag in sorted(masks):
        if tag not in declared_masks:
            out.append(Finding(
                rule="G029", path=artifact_path, line=0, col=0,
                msg=(
                    f"runtime mask counter `{tag}` has no matching "
                    "`# graftlint: mask=` declaration — a masked "
                    "clamp region the static model does not know "
                    "about"
                ),
            ))
    return out
