"""graftlint CLI.

    python -m crdt_benches_tpu.lint [paths...] [--format text|json|sarif]
                                    [--select G001,G002] [--boundaries]
                                    [--changed] [--fix]
                                    [--sync-artifact bench.json]
                                    [--thread-artifact bench.json]
                                    [--fs-artifact bench.json]
                                    [--lifecycle-artifact bench.json]
                                    [--ranges-artifact bench.json]

Exits nonzero when any finding survives suppression (CI gates on this);
``--format sarif`` emits SARIF 2.1.0 for CI annotation surfaces with
the SAME exit-code semantics (a reporter changes the rendering, never
the gate).

``--changed`` lints only the .py files touched in the working tree
(``git diff --name-only HEAD`` + untracked), the pre-commit fast path —
no changed Python files is a clean exit, not a G000 (nothing was
skipped, there was nothing to check).

``--fix`` applies the G005 implicit-dtype autofixer (lint/fix.py) to
the targets, then lints what remains; refused sites are reported and
still fail the gate.

``--sync-artifact`` hands G011 a serve bench artifact whose
``boundary_syncs`` block is the runtime fence ground truth (dead
declared fences / unattributed runtime fences become findings).

``--thread-artifact`` is G017's twin: the artifact's
``thread_crossings`` block (the race sanitizer's publish-point and
cross-thread-access counters) is cross-checked against the static
``# graftlint: publish`` markers — usually the same artifact file as
``--sync-artifact``.

``--fs-artifact`` is G021's: the artifact's ``fs_ops`` block (the fs
sanitizer's per-protocol entry and op counters) is cross-checked
against the static ``# graftlint: durable=`` protocol markers — dead
declared protocols and unattributed runtime fs ops both fail.

``--lifecycle-artifact`` is G025's: the artifact's ``lifecycle`` block
(the lifecycle sanitizer's state-machine transition and resource
acquire/release counters) is cross-checked against the static
``# graftlint: state=`` / ``acquire=`` / ``release=`` markers — dead
declared machines/resources and unattributed runtime transitions both
fail.

``--ranges-artifact`` is G029's: the artifact's ``ranges`` block (the
range sanitizer's index-check and clamp-mask dispatch counters) is
cross-checked against the static ``# graftlint: inrange=... check=`` /
``mask=`` declarations — dead declared facts/masks and unattributed
runtime counters both fail.

``--boundaries`` dumps the jit-boundary contract registry as JSON by
importing the package modules that declare them (the only mode that
imports anything heavy; plain linting is pure-AST and jax-free).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

from .core import format_json, format_sarif, format_text, run_lint


def changed_py_files() -> list[str] | None:
    """Working-tree .py changes vs HEAD (tracked mods + untracked), with
    the intentionally-dirty fixture corpus excluded.  None = git failed
    (not a repo / no HEAD) — the caller falls back to a full lint rather
    than silently checking nothing.  git emits TOPLEVEL-relative names,
    so they are resolved against the toplevel — running from a
    subdirectory must not silently drop (and skip linting) every file
    outside it."""

    def git(*args) -> subprocess.CompletedProcess | None:
        try:
            proc = subprocess.run(
                ["git", *args], capture_output=True, text=True,
                timeout=30,
            )
        except (OSError, subprocess.TimeoutExpired):
            return None
        return proc if proc.returncode == 0 else None

    top = git("rev-parse", "--show-toplevel")
    if top is None or not top.stdout.strip():
        return None
    root = top.stdout.strip()
    files: list[str] = []
    for cmd in (
        ("diff", "--name-only", "HEAD", "--"),
        ("ls-files", "--others", "--exclude-standard"),
    ):
        proc = git(*cmd)
        if proc is None:
            return None
        files.extend(
            ln.strip() for ln in proc.stdout.splitlines() if ln.strip()
        )
    out = []
    for f in dict.fromkeys(files):  # de-dup, keep order
        if not f.endswith(".py"):
            continue
        if "lint_fixtures" in f.replace("\\", "/").split("/"):
            continue
        path = os.path.join(root, f)
        if os.path.isfile(path):  # deleted files have nothing to lint
            out.append(path)
    return out


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="graftlint")
    ap.add_argument(
        "paths", nargs="*", default=["crdt_benches_tpu"],
        help="files or directories to lint (default: the package)",
    )
    ap.add_argument(
        "--format", choices=("text", "json", "sarif"), default="text",
    )
    ap.add_argument(
        "--select", default="",
        help="comma-separated rule ids to run (default: all)",
    )
    ap.add_argument(
        "--changed", action="store_true",
        help="lint only .py files changed vs HEAD (plus untracked)",
    )
    ap.add_argument(
        "--fix", action="store_true",
        help="apply the G005 implicit-dtype autofixer, then lint",
    )
    ap.add_argument(
        "--sync-artifact", default=None, metavar="JSON",
        help="serve bench artifact for the G011 fence-cost cross-check",
    )
    ap.add_argument(
        "--thread-artifact", default=None, metavar="JSON",
        help="serve bench artifact for the G017 publish-point "
             "cross-check (thread_crossings block)",
    )
    ap.add_argument(
        "--fs-artifact", default=None, metavar="JSON",
        help="serve bench artifact for the G021 durable-protocol "
             "cross-check (fs_ops block)",
    )
    ap.add_argument(
        "--lifecycle-artifact", default=None, metavar="JSON",
        help="serve bench artifact for the G025 lifecycle machine/"
             "resource cross-check (lifecycle block)",
    )
    ap.add_argument(
        "--ranges-artifact", default=None, metavar="JSON",
        help="serve bench artifact for the G029 value-range "
             "cross-check (ranges block)",
    )
    ap.add_argument(
        "--boundaries", action="store_true",
        help="dump the jit-boundary contract registry as JSON and exit",
    )
    args = ap.parse_args(argv)

    if args.boundaries:
        # importing serve/engine registers every @boundary contract
        import importlib

        for mod in (
            "crdt_benches_tpu.serve.pool",
            "crdt_benches_tpu.engine.replay",
            "crdt_benches_tpu.engine.replay_range",
            "crdt_benches_tpu.engine.merge",
            "crdt_benches_tpu.engine.merge_range",
            "crdt_benches_tpu.engine.downstream",
            "crdt_benches_tpu.engine.downstream_range",
        ):
            importlib.import_module(mod)
        from .boundary import boundary_table

        print(json.dumps(boundary_table(), indent=2))
        return 0

    paths = args.paths
    if args.changed:
        changed = changed_py_files()
        if changed is None:
            print(
                "graftlint: --changed needs a git worktree; "
                "linting the full targets instead",
                file=sys.stderr,
            )
        elif not changed:
            print("graftlint: no changed python files")
            return 0
        else:
            paths = changed

    if args.fix:
        from .fix import fix_g005

        for r in fix_g005(paths):
            verdict = "fixed" if r.applied else "NOT fixed"
            print(f"{r.path}:{r.line}: G005 {verdict}: {r.detail}")

    select = {
        s.strip() for s in args.select.split(",") if s.strip()
    } or None
    findings = run_lint(
        paths, select=select, sync_artifact=args.sync_artifact,
        thread_artifact=args.thread_artifact,
        fs_artifact=args.fs_artifact,
        lifecycle_artifact=args.lifecycle_artifact,
        ranges_artifact=args.ranges_artifact,
    )
    out = (
        format_json(findings) if args.format == "json"
        else format_sarif(findings) if args.format == "sarif"
        else format_text(findings)
    )
    print(out)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
