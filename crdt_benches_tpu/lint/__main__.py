"""graftlint CLI.

    python -m crdt_benches_tpu.lint [paths...] [--format text|json]
                                    [--select G001,G002] [--boundaries]

Exits nonzero when any finding survives suppression (CI gates on this).
``--boundaries`` dumps the jit-boundary contract registry as JSON by
importing the package modules that declare them (the only mode that
imports anything heavy; plain linting is pure-AST and jax-free).
"""

from __future__ import annotations

import argparse
import json
import sys

from .core import format_json, format_text, run_lint


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="graftlint")
    ap.add_argument(
        "paths", nargs="*", default=["crdt_benches_tpu"],
        help="files or directories to lint (default: the package)",
    )
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument(
        "--select", default="",
        help="comma-separated rule ids to run (default: all)",
    )
    ap.add_argument(
        "--boundaries", action="store_true",
        help="dump the jit-boundary contract registry as JSON and exit",
    )
    args = ap.parse_args(argv)

    if args.boundaries:
        # importing serve/engine registers every @boundary contract
        import importlib

        for mod in (
            "crdt_benches_tpu.serve.pool",
            "crdt_benches_tpu.engine.replay",
            "crdt_benches_tpu.engine.replay_range",
            "crdt_benches_tpu.engine.merge",
            "crdt_benches_tpu.engine.merge_range",
            "crdt_benches_tpu.engine.downstream",
            "crdt_benches_tpu.engine.downstream_range",
        ):
            importlib.import_module(mod)
        from .boundary import boundary_table

        print(json.dumps(boundary_table(), indent=2))
        return 0

    select = {
        s.strip() for s in args.select.split(",") if s.strip()
    } or None
    findings = run_lint(args.paths, select=select)
    out = (
        format_json(findings) if args.format == "json"
        else format_text(findings)
    )
    print(out)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
