"""crdt_benches_tpu — a TPU-native batched CRDT replay/merge framework.

A ground-up reimplementation of the capability surface of noib3/crdt-benches
(reference: /root/reference, a single-threaded Rust Criterion harness replaying
collaborative-editing traces through four CRDT libraries), re-designed for TPU:

- trace replay over many simulated replicas at once as padded (replica x op)
  integer tensors (``jax.vmap`` / ``shard_map`` over a ``replicas`` mesh axis),
- sequence-CRDT position resolution and tombstone handling as scan/prefix-sum
  kernels under ``jax.lax.scan`` (the sequential per-op dependency of the
  reference's hot loop, src/main.rs:30-34, restructured around scans),
- cross-replica update exchange and convergence checking via XLA collectives
  (``psum`` / ``all_gather``) over a device mesh,
- a C++ native tier (CPU rope baseline + op-log CRDT engine) mirroring the
  reference's native (Rust) components,
- a Criterion-equivalent measurement harness (warmup, sampling, throughput in
  elements/sec where element = one trace patch, src/main.rs:25).

Package layout:
  traces/    trace loading + tensorization (L1)
  oracle/    pure-Python ground-truth document replay + RGA merge oracle
  ops/       JAX kernels: within-batch resolution, batch merge, decode
  engine/    replica state pytrees, full-trace replay, downstream apply
  models/    CRDT model families (RGA tree model, etc.)
  parallel/  mesh helpers, shard_map replay, collective convergence
  backends/  pluggable Upstream/Downstream backends (JAX, C++ rope, C++ CRDT,
             pure Python) behind one trait, per-backend offset units
  bench/     criterion-equivalent harness + bench matrix runner
  utils/     config, profiling, digests
"""

__version__ = "0.1.0"
