"""Whole-document-reconcile backend — the automerge capability shape (C6).

The reference's automerge adapter (src/rope.rs:35-78) is distinctive among
the four CRDTs: ``insert``/``remove`` are unimplemented; ``replace`` is
overridden to splice a typed shadow ``Text`` and then run a **whole-document
``autosurgeon::reconcile``** (src/rope.rs:67-72) — every edit re-diffs the
full document against the typed value and converts the diff into CRDT ops.
``len`` reports the byte length of the materialized string
(src/rope.rs:74-77).

This backend reproduces that exact shape rather than collapsing it into the
positional oracle (round-2 verdict, C6): the edit is applied positionally to
a shadow buffer, and the document-of-stable-element-ids is updated ONLY by
diffing the whole shadow against the current document (common-prefix /
common-suffix reconcile, the classic text-reconcile strategy) — the edit
position is *recovered from the diff*, never trusted.  Per-edit cost is
O(document), the same asymptotic shape that makes the reference's automerge
column its known-slow path (SURVEY.md section 3.5).

NumPy is used for the per-edit whole-document scans so the Python column
remains benchable on the real traces (the reconcile is still O(doc) work
per edit — nothing is skipped, only vectorized).
"""

from __future__ import annotations

import numpy as np

from .base import Upstream, register_upstream


@register_upstream
class PyReconcile(Upstream):
    """Automerge-shaped upstream: splice a shadow, reconcile the whole doc.

    The "document" is a sequence of stable element ids (the automerge op-id
    analog): reconcile assigns fresh ids to exactly the spliced-in middle
    and drops the ids of the removed middle, preserving ids of the common
    prefix/suffix — matching what ``autosurgeon::reconcile`` derives from
    its whole-value diff.
    """

    NAME = "py-reconcile"
    EDITS_USE_BYTE_OFFSETS = False  # char offsets, as the reference feeds
    # automerge (no chars_to_bytes call for it, src/main.rs:21-23,43)

    def __init__(self, s: str = ""):
        self._shadow = np.frombuffer(
            s.encode("utf-32-le"), dtype=np.uint32
        ).astype(np.int64)
        self._doc_chars = self._shadow.copy()
        self._doc_ids = np.arange(len(self._shadow), dtype=np.int64)
        self._next_id = len(self._shadow)

    @classmethod
    def from_str(cls, s: str) -> "PyReconcile":
        return cls(s)

    # insert/remove are deliberately unsupported, as in the reference
    # (src/rope.rs:59-65 unimplemented!()) — all edits arrive via replace.
    def insert(self, at: int, text: str) -> None:
        raise NotImplementedError("py-reconcile edits only via replace")

    def remove(self, start: int, end: int) -> None:
        raise NotImplementedError("py-reconcile edits only via replace")

    def replace(self, start: int, end: int, text: str) -> None:
        ins = np.frombuffer(
            text.encode("utf-32-le"), dtype=np.uint32
        ).astype(np.int64)
        # 1. splice the typed shadow (Text::splice, src/rope.rs:70)
        self._shadow = np.concatenate(
            [self._shadow[:start], ins, self._shadow[end:]]
        )
        # 2. whole-document reconcile (src/rope.rs:71): diff shadow vs doc
        #    by longest common prefix + suffix; only the middle changes.
        old, new = self._doc_chars, self._shadow
        no, nn = len(old), len(new)
        m = min(no, nn)
        neq = old[:m] != new[:m]
        p = int(np.argmax(neq)) if neq.any() else m
        neq = old[no - m:][::-1] != new[nn - m:][::-1]
        s = int(np.argmax(neq)) if neq.any() else m
        s = min(s, m - p)  # suffix may not overlap the prefix
        fresh = np.arange(
            self._next_id, self._next_id + (nn - p - s), dtype=np.int64
        )
        self._next_id += len(fresh)
        self._doc_ids = np.concatenate(
            [self._doc_ids[:p], fresh, self._doc_ids[no - s:]]
        )
        self._doc_chars = new.copy()
        assert len(self._doc_ids) == len(self._doc_chars)

    def __len__(self) -> int:
        # byte length of the materialized string (src/rope.rs:74-77)
        return len(self.content().encode())

    def content(self) -> str:
        return self._doc_chars.astype(np.uint32).tobytes().decode(
            "utf-32-le"
        )
