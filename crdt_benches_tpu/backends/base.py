"""The pluggable-backend traits — the capability at the center of the
reference's design (SURVEY.md section 2.3): one uniform editing interface over
interchangeable document/CRDT engines, with per-backend offset units.

Mirrors the reference's two traits:

- ``Upstream`` (reference src/rope.rs:6-33): ``NAME``,
  ``EDITS_USE_BYTE_OFFSETS`` (default False), ``from_str`` / ``insert`` /
  ``remove`` / ``__len__``, and a default ``replace`` = remove-then-insert.
- ``Downstream`` (reference src/rope.rs:185-191): ``upstream_updates(trace)``
  pre-generates one encoded update per patch by replaying the trace on a
  separate upstream replica (untimed), and ``apply_update`` integrates one
  update into this replica (timed).

Backends that operate on whole op *batches* (the JAX engine) additionally
implement ``BatchedReplay``, the TPU-native face of the same capability — the
bench harness prefers it when present so the replay loop runs on-device
instead of through per-op Python calls.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, Sequence

from ..traces.loader import TestData


class Upstream(ABC):
    """Uniform local-editing interface over document engines."""

    NAME: str = "?"
    #: If True the bench feeds byte offsets (trace.chars_to_bytes()), matching
    #: the reference's cola/yrs adapters (src/rope.rs:82,147).
    EDITS_USE_BYTE_OFFSETS: bool = False

    @classmethod
    @abstractmethod
    def from_str(cls, s: str) -> "Upstream":
        ...

    @abstractmethod
    def insert(self, at: int, text: str) -> None:
        ...

    @abstractmethod
    def remove(self, start: int, end: int) -> None:
        ...

    @abstractmethod
    def __len__(self) -> int:
        """Length in codepoints, or bytes when EDITS_USE_BYTE_OFFSETS."""

    def replace(self, start: int, end: int, text: str) -> None:
        """Default: remove-then-insert (reference src/rope.rs:21-32)."""
        if end > start:
            self.remove(start, end)
        if text:
            self.insert(start, text)

    def content(self) -> str | None:
        """Final document content, if the backend stores text (cola-style
        length-only engines return None; reference src/rope.rs:86-97)."""
        return None


class Downstream(ABC):
    """Remote-replica interface: pre-generated updates, timed apply."""

    NAME: str = "?"
    EDITS_USE_BYTE_OFFSETS: bool = False

    @classmethod
    @abstractmethod
    def upstream_updates(cls, trace: TestData) -> tuple["Downstream", Sequence[Any]]:
        """Replay ``trace`` on a fresh upstream replica, emitting one encoded
        update per patch; return (fresh downstream replica, updates)."""

    @abstractmethod
    def apply_update(self, update: Any) -> None:
        ...

    @abstractmethod
    def __len__(self) -> int:
        ...

    def clone(self) -> "Downstream":
        """Fresh copy for one timed iteration (reference src/main.rs:64)."""
        raise NotImplementedError


class BatchedReplay(ABC):
    """Whole-trace replay interface for batched/on-device backends.

    The timed region covers document init + full replay + the final length
    check, matching the reference's timed closure (src/main.rs:28-37)."""

    NAME: str = "?"

    @abstractmethod
    def prepare(self, trace: TestData) -> None:
        """Untimed: load/tensorize/stage the trace (analog of trace loading
        at src/main.rs:19, which Criterion does not time)."""

    @abstractmethod
    def replay_once(self) -> int:
        """Timed: init + replay + return final length (blocking)."""

    def final_content(self) -> str | None:
        return None

    @property
    def replicas(self) -> int:
        return 1


_UPSTREAM_REGISTRY: dict[str, type] = {}
_DOWNSTREAM_REGISTRY: dict[str, type] = {}


def register_upstream(cls):
    _UPSTREAM_REGISTRY[cls.NAME] = cls
    return cls


def register_downstream(cls):
    _DOWNSTREAM_REGISTRY[cls.NAME] = cls
    return cls


def upstream_backends() -> dict[str, type]:
    return dict(_UPSTREAM_REGISTRY)


def downstream_backends() -> dict[str, type]:
    return dict(_DOWNSTREAM_REGISTRY)
