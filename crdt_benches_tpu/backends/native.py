"""ctypes bindings to the C++ native tier (native/libcrdtnative.so).

Two backends behind the Upstream trait (reference src/rope.rs:6-33):

- ``CppRope`` — gap-buffer text rope; the "CPU rope backend" baseline column
  of the bench table (BASELINE.md config 1).
- ``CppCrdt`` — treap-based sequence CRDT with op log + incremental update
  encode/decode; also implements Downstream (reference src/rope.rs:185-225
  capability).

Each also exposes a ``replay_patches`` one-call path so benchmark iterations
run the hot loop natively (per-op ctypes calls would measure FFI overhead,
not the engine).
"""

from __future__ import annotations

import ctypes
import os
from typing import Sequence

import numpy as np

from ..traces.loader import TestData
from ..traces.patches import PatchArrays, patch_arrays
from .base import Downstream, Upstream, register_downstream, register_upstream

_LIB_PATHS = (
    os.path.join(os.path.dirname(__file__), "..", "..", "native", "libcrdtnative.so"),
    "./native/libcrdtnative.so",
)

_i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
_i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
_u32p = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")
_i64 = ctypes.c_int64
_vp = ctypes.c_void_p


def _load_lib():
    for p in _LIB_PATHS:
        p = os.path.normpath(p)
        _check_fresh(p)  # builds the .so if missing/stale (it is untracked)
        if os.path.exists(p):
            lib = ctypes.CDLL(p)
            break
    else:
        raise OSError(
            "libcrdtnative.so not found and `make -C native` failed"
        )
    sig = lambda fn, res, args: (setattr(fn, "restype", res), setattr(fn, "argtypes", args))
    sig(lib.rope_new, _vp, [_i32p, _i64])
    sig(lib.rope_free, None, [_vp])
    sig(lib.rope_len, _i64, [_vp])
    sig(lib.rope_insert, None, [_vp, _i64, _i32p, _i64])
    sig(lib.rope_remove, None, [_vp, _i64, _i64])
    sig(lib.rope_read, None, [_vp, _i32p])
    sig(lib.rope_replay, _i64, [_i32p, _i64, _i32p, _i32p, _i32p, _i32p, _i64])
    sig(lib.rope_replay_read, _i64, [_i32p, _i64, _i32p, _i32p, _i32p, _i32p, _i64, _i32p, _i64])
    sig(lib.crdt_new, _vp, [_i32p, _i64, ctypes.c_uint32])
    sig(lib.crdt_free, None, [_vp])
    sig(lib.crdt_len, _i64, [_vp])
    sig(lib.crdt_oplog_len, _i64, [_vp])
    sig(lib.crdt_insert, None, [_vp, _i64, _i32p, _i64])
    sig(lib.crdt_remove, None, [_vp, _i64, _i64])
    sig(lib.crdt_read, None, [_vp, _i32p])
    sig(lib.crdt_encode_from, _i64, [_vp, _i64, _u8p, _i64])
    sig(lib.crdt_apply_update, None, [_vp, _u8p, _i64])
    sig(lib.crdt_apply_updates, _i64, [_vp, _u8p, _i64p, _i64])
    sig(lib.crdt_replay, _i64, [_i32p, _i64, _i32p, _i32p, _i32p, _i32p, _i64])
    sig(lib.crdt_gen_updates, _i64, [_i32p, _i64, _i32p, _i32p, _i32p, _i32p, _i64, _u8p, _i64, _i64p])
    sig(lib.crdt_integrate_ops, _i64, [_vp, _i64, _u8p, _u32p, _u32p, _u32p, _u32p, _i32p])
    sig(lib.crdt_replay_dump, _i64, [_i32p, _i64, _i32p, _i32p, _i32p, _i32p, _i64, _i32p, _i64, _u8p, _i32p, _i64])
    sig(lib.cola_new, _vp, [_i64])
    sig(lib.cola_free, None, [_vp])
    sig(lib.cola_len, _i64, [_vp])
    sig(lib.cola_insert, None, [_vp, _i64, _i64])
    sig(lib.cola_remove, None, [_vp, _i64, _i64])
    sig(lib.cola_replay, _i64, [_i64, _i32p, _i32p, _i32p, _i64])
    return lib


def _check_fresh(so_path: str) -> None:
    """Build the .so if missing, rebuild if any C++ source is newer — edits
    to native/ can't be silently ignored in favor of a stale binary, and a
    fresh checkout self-builds on first use."""
    import glob
    import subprocess
    import sys

    native_dir = os.path.dirname(so_path)
    srcs = glob.glob(os.path.join(native_dir, "*.cpp"))
    if not srcs:
        return
    if os.path.exists(so_path) and max(map(os.path.getmtime, srcs)) <= (
        os.path.getmtime(so_path)
    ):
        return
    print(f"note: building {so_path} from native sources", file=sys.stderr)
    try:
        subprocess.run(
            ["make", "-C", native_dir], check=True, capture_output=True
        )
    except subprocess.CalledProcessError as e:
        err = (e.stderr or b"").decode(errors="replace")[-2000:]
        print(f"warning: native build failed ({e})\n{err}", file=sys.stderr)
    except Exception as e:  # a stale lib (if any) stays usable; tests tell
        print(f"warning: native build failed ({e})", file=sys.stderr)


_lib = None


def lib():
    global _lib
    if _lib is None:
        _lib = _load_lib()
    return _lib


def native_available() -> bool:
    try:
        lib()
        return True
    except OSError:
        return False


def _codes(s: str) -> np.ndarray:
    return np.asarray([ord(c) for c in s], np.int32)


@register_upstream
class CppRope(Upstream):
    """Gap-buffer rope (native/rope.cpp)."""

    NAME = "cpp-rope"

    def __init__(self, handle):
        self._h = handle

    @classmethod
    def from_str(cls, s: str) -> "CppRope":
        return cls(lib().rope_new(_codes(s), len(s)))

    def insert(self, at: int, text: str) -> None:
        lib().rope_insert(self._h, at, _codes(text), len(text))

    def remove(self, start: int, end: int) -> None:
        lib().rope_remove(self._h, start, end)

    def __len__(self) -> int:
        return lib().rope_len(self._h)

    def content(self) -> str:
        out = np.zeros(len(self), np.int32)
        lib().rope_read(self._h, out)
        return "".join(map(chr, out.tolist()))

    def __del__(self):
        if getattr(self, "_h", None):
            lib().rope_free(self._h)
            self._h = None

    # fast whole-iteration path
    @staticmethod
    def replay_patches(pa: PatchArrays) -> int:
        return lib().rope_replay(
            pa.init, len(pa.init), pa.pos, pa.del_count, pa.ins_off,
            pa.ins_flat, pa.n_patches,
        )

    @staticmethod
    def replay_patches_content(pa: PatchArrays) -> str:
        out = np.zeros(max(pa.end_len * 2 + 16, 64), np.int32)
        n = lib().rope_replay_read(
            pa.init, len(pa.init), pa.pos, pa.del_count, pa.ins_off,
            pa.ins_flat, pa.n_patches, out, len(out),
        )
        return "".join(map(chr, out[:n].tolist()))


@register_upstream
class CppRopeBytes(CppRope):
    """Byte-addressed gap-buffer rope: the reference's byte-offset adapter
    capability (cola/yrs set EDITS_USE_BYTE_OFFSETS, src/rope.rs:82,147).
    Same native engine as CppRope but addressed and fed in UTF-8 byte
    units via ``trace.chars_to_bytes()`` + ``patch_arrays(...,
    bytes_mode=True)``; ``len`` is a byte count."""

    NAME = "cpp-rope-bytes"
    EDITS_USE_BYTE_OFFSETS = True

    @classmethod
    def from_str(cls, s: str) -> "CppRopeBytes":
        b = np.frombuffer(s.encode("utf-8"), np.uint8).astype(np.int32)
        return cls(lib().rope_new(np.ascontiguousarray(b), len(b)))

    def insert(self, at: int, text: str) -> None:
        b = np.frombuffer(text.encode("utf-8"), np.uint8).astype(np.int32)
        lib().rope_insert(self._h, at, np.ascontiguousarray(b), len(b))

    def content(self) -> str:
        out = np.zeros(len(self), np.int32)
        lib().rope_read(self._h, out)
        return bytes(out.astype(np.uint8).tobytes()).decode("utf-8")

    @staticmethod
    def replay_patches_content(pa: PatchArrays) -> str:
        out = np.zeros(max(pa.end_len * 2 + 16, 64), np.int32)
        n = lib().rope_replay_read(
            pa.init, len(pa.init), pa.pos, pa.del_count, pa.ins_off,
            pa.ins_flat, pa.n_patches, out, len(out),
        )
        # Elements are UTF-8 bytes, not codepoints.
        return bytes(out[:n].astype(np.uint8).tobytes()).decode("utf-8")


@register_upstream
class CppCola(Upstream):
    """Content-free (lengths-only) sequence-CRDT replica: the cola
    capability (reference src/rope.rs:79-101 — ``Replica::new(1,
    s.len())`` seeds from a LENGTH, edits are ``(offset, length)`` pairs,
    and the only readback is ``len()``).  No character data is stored or
    even crosses the FFI; ``content()`` stays None (the trait default for
    lengths-only engines).  Byte-addressed like the reference's cola
    adapter (EDITS_USE_BYTE_OFFSETS, src/rope.rs:82).  Engine:
    native/cola.cpp run-granular implicit treap with retained tombstones.
    """

    NAME = "cpp-cola"
    EDITS_USE_BYTE_OFFSETS = True

    def __init__(self, handle):
        self._h = handle

    @classmethod
    def from_str(cls, s: str) -> "CppCola":
        return cls(lib().cola_new(len(s.encode("utf-8"))))

    def insert(self, at: int, text: str) -> None:
        lib().cola_insert(self._h, at, len(text.encode("utf-8")))

    def remove(self, start: int, end: int) -> None:
        lib().cola_remove(self._h, start, end)

    def __len__(self) -> int:
        return lib().cola_len(self._h)

    def __del__(self):
        if getattr(self, "_h", None):
            lib().cola_free(self._h)
            self._h = None

    @staticmethod
    def replay_patches(pa: PatchArrays) -> int:
        return lib().cola_replay(
            len(pa.init), pa.pos, pa.del_count, pa.ins_off, pa.n_patches
        )


@register_upstream
class CppCrdt(Upstream):
    """Treap op-log sequence CRDT (native/crdt.cpp)."""

    NAME = "cpp-crdt"

    def __init__(self, handle):
        self._h = handle

    @classmethod
    def from_str(cls, s: str, agent: int = 1) -> "CppCrdt":
        return cls(lib().crdt_new(_codes(s), len(s), agent))

    def insert(self, at: int, text: str) -> None:
        lib().crdt_insert(self._h, at, _codes(text), len(text))

    def remove(self, start: int, end: int) -> None:
        lib().crdt_remove(self._h, start, end)

    def __len__(self) -> int:
        return lib().crdt_len(self._h)

    def content(self) -> str:
        out = np.zeros(len(self), np.int32)
        lib().crdt_read(self._h, out)
        return "".join(map(chr, out.tolist()))

    def oplog_len(self) -> int:
        return lib().crdt_oplog_len(self._h)

    def encode_from(self, from_op: int) -> bytes:
        buf = np.zeros(4096, np.uint8)
        n = lib().crdt_encode_from(self._h, from_op, buf, len(buf))
        if n < 0:
            buf = np.zeros(-n, np.uint8)
            n = lib().crdt_encode_from(self._h, from_op, buf, len(buf))
        return bytes(buf[:n].tobytes())

    def apply_update(self, update: bytes) -> None:
        arr = np.frombuffer(update, np.uint8)
        lib().crdt_apply_update(self._h, arr, len(arr))

    def __del__(self):
        if getattr(self, "_h", None):
            lib().crdt_free(self._h)
            self._h = None

    @staticmethod
    def replay_patches(pa: PatchArrays) -> int:
        return lib().crdt_replay(
            pa.init, len(pa.init), pa.pos, pa.del_count, pa.ins_off,
            pa.ins_flat, pa.n_patches,
        )


@register_upstream
class CppCrdtBytes(CppCrdt):
    """Byte-addressed sequence CRDT: the yrs capability — a full CRDT whose
    edit offsets and lengths are UTF-8 byte units (reference src/rope.rs:147
    sets EDITS_USE_BYTE_OFFSETS for the yrs adapter; offsets are rewritten
    via chars_to_bytes, src/main.rs:21-23).  Same native treap engine
    (native/crdt.cpp) with each element holding one UTF-8 byte, so ``len``
    is a byte count and positions address bytes."""

    NAME = "cpp-crdt-bytes"
    EDITS_USE_BYTE_OFFSETS = True

    @classmethod
    def from_str(cls, s: str, agent: int = 1) -> "CppCrdtBytes":
        b = np.frombuffer(s.encode("utf-8"), np.uint8).astype(np.int32)
        return cls(lib().crdt_new(np.ascontiguousarray(b), len(b), agent))

    def insert(self, at: int, text: str) -> None:
        b = np.frombuffer(text.encode("utf-8"), np.uint8).astype(np.int32)
        lib().crdt_insert(self._h, at, np.ascontiguousarray(b), len(b))

    def content(self) -> str:
        out = np.zeros(len(self), np.int32)
        lib().crdt_read(self._h, out)
        return bytes(out.astype(np.uint8).tobytes()).decode("utf-8")


@register_downstream
class CppCrdtDownstream(Downstream):
    """Downstream over the native CRDT: one encoded update per patch,
    generated untimed on a separate upstream replica; timed apply loop runs
    in one native call (reference src/main.rs:50-70 semantics)."""

    NAME = "cpp-crdt"

    def __init__(self, start_content: str, flat: np.ndarray, offsets: np.ndarray):
        self._start = start_content
        self._flat = flat
        self._offsets = offsets
        self._doc = CppCrdt.from_str(start_content, agent=1)

    OP_WIRE = 21  # bytes per op record (native/crdt.cpp OP_WIRE)

    @classmethod
    def upstream_updates(cls, trace: TestData):
        pa = patch_arrays(trace)
        # exact size: one wire record per unit op (delete or inserted char)
        cap = int(pa.del_count.sum() + len(pa.ins_flat)) * cls.OP_WIRE
        offsets = np.zeros(pa.n_patches + 1, np.int64)
        buf = np.zeros(max(cap, 1), np.uint8)
        n = lib().crdt_gen_updates(
            pa.init, len(pa.init), pa.pos, pa.del_count, pa.ins_off,
            pa.ins_flat, pa.n_patches, buf, len(buf), offsets,
        )
        assert n >= 0, f"update buffer undersized: need {-n}, had {cap}"
        inst = cls(trace.start_content, buf[:n], offsets)
        updates = [
            bytes(buf[offsets[i] : offsets[i + 1]].tobytes())
            for i in range(pa.n_patches)
        ]
        return inst, updates

    def clone(self) -> "CppCrdtDownstream":
        return CppCrdtDownstream(self._start, self._flat, self._offsets)

    def apply_update(self, update: bytes) -> None:
        self._doc.apply_update(update)

    def apply_all_native(self) -> int:
        """The whole timed downstream iteration in one native call: fresh
        replica + apply every update + final length.  The fresh replica
        becomes this object's document, so ``len``/``content`` afterwards
        reflect the run."""
        doc = CppCrdt.from_str(self._start, agent=1)
        n = lib().crdt_apply_updates(
            doc._h, self._flat, self._offsets, len(self._offsets) - 1
        )
        self._doc = doc
        return n

    def __len__(self) -> int:
        return len(self._doc)

    def content(self) -> str:
        return self._doc.content()


class NativeMerge:
    """Independent native RGA oracle/baseline for concurrent merge
    (native/crdt.cpp crdt_integrate_ops): an order-statistic treap with the
    same (lamport, agent) id order and insert-after-origin intention rule
    as engine/merge.py, in an entirely separate implementation.  Used to
    cross-validate the JAX merge kernels at scales where the pure-Python
    oracle is infeasible, and as the merge bench's single-core baseline.
    """

    def __init__(self, base: str, base_agent: int = 1_000_000):
        self.base = base
        self.base_agent = base_agent
        self._h = lib().crdt_new(_codes(base), len(base), base_agent)

    def integrate(self, type_, id_agent, id_seq, org_agent, org_seq, ch) -> int:
        """Integrate struct-of-array ops (already (lamport, agent)-sorted;
        ids per NativeMerge id convention).  Returns visible length."""
        n = len(type_)
        return lib().crdt_integrate_ops(
            self._h, n,
            np.ascontiguousarray(type_, np.uint8),
            np.ascontiguousarray(id_agent, np.uint32),
            np.ascontiguousarray(id_seq, np.uint32),
            np.ascontiguousarray(org_agent, np.uint32),
            np.ascontiguousarray(org_seq, np.uint32),
            np.ascontiguousarray(ch, np.int32),
        )

    def __len__(self) -> int:
        return lib().crdt_len(self._h)

    def content(self) -> str:
        out = np.zeros(len(self), np.int32)
        lib().crdt_read(self._h, out)
        return "".join(map(chr, out.tolist()))

    def __del__(self):
        if getattr(self, "_h", None):
            lib().crdt_free(self._h)
            self._h = None
