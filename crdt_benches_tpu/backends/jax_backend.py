"""JAX engine as a bench backend (the 'tpu'/'jax' column of the bench table).

Implements BatchedReplay: the timed region is document init + full replay +
final length fetch with ``block_until_ready`` (matching the reference's timed
closure — doc init and final check included, reference src/main.rs:28-37 —
plus honest device sync, SURVEY.md section 7 hard-part 6).  Trace
tensorization and op upload happen untimed in ``prepare`` (the analog of
untimed trace loading, src/main.rs:19).
"""

from __future__ import annotations

import jax
import numpy as np

from ..engine.replay import ReplayEngine
from ..traces.loader import TestData
from ..traces.tensorize import tensorize
from .base import BatchedReplay


class JaxReplayBackend(BatchedReplay):
    def __init__(self, n_replicas: int = 1, batch: int = 512):
        self.n_replicas = n_replicas
        self.batch = batch
        self._eng: ReplayEngine | None = None
        self._tt = None

    @property
    def NAME(self) -> str:  # type: ignore[override]
        plat = jax.devices()[0].platform
        return f"jax-{plat}" + (f"-r{self.n_replicas}" if self.n_replicas > 1 else "")

    @property
    def replicas(self) -> int:
        return self.n_replicas

    def prepare(self, trace: TestData) -> None:
        # Layout auto-selection (SURVEY.md section 7 hard-part 4): block-edit
        # traces explode to many unit ops per patch — use the range engine
        # when the explosion ratio is significant; keystroke traces stay on
        # the exploded engine (lower per-op constants).
        import os

        unit_ops = sum(
            d + len(ins) for _, d, ins in trace.iter_patches()
        )
        range_ops = sum(
            (1 if d else 0) + (1 if ins else 0)
            for _, d, ins in trace.iter_patches()
        )
        layout = os.environ.get("CRDT_ENGINE_LAYOUT", "auto")
        use_range = (
            layout == "range"
            or (layout == "auto" and unit_ops >= 2 * range_ops)
        )
        if use_range:
            from ..engine.replay_range import RangeReplayEngine
            from ..traces.tensorize import tensorize_ranges

            rt = tensorize_ranges(trace, batch=self.batch)
            self._eng = RangeReplayEngine(
                rt, n_replicas=self.n_replicas, pack=8
            )
        else:
            self._tt = tensorize(trace, batch=self.batch)
            self._eng = ReplayEngine(self._tt, n_replicas=self.n_replicas)
        self._end_len = len(trace.end_content)

    def replay_once(self) -> int:
        eng = self._eng
        state = eng.run()  # includes fresh_state init (timed, as in reference)
        lengths = np.asarray(state.nvis)  # device->host sync point
        n = int(lengths.reshape(-1)[0])
        assert (lengths == self._end_len).all(), (
            f"length mismatch: {lengths} != {self._end_len}"
        )
        return n

    def final_content(self) -> str:
        state = self._eng.run()
        return self._eng.decode(state)
