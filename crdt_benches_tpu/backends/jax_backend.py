"""JAX engine as a bench backend (the 'tpu'/'jax' column of the bench table).

Implements BatchedReplay: the timed region is document init + full replay +
final length fetch with ``block_until_ready`` (matching the reference's timed
closure — doc init and final check included, reference src/main.rs:28-37 —
plus honest device sync, SURVEY.md section 7 hard-part 6).  Trace
tensorization and op upload happen untimed in ``prepare`` (the analog of
untimed trace loading, src/main.rs:19).
"""

from __future__ import annotations

import jax
import numpy as np

from ..engine.replay import ReplayEngine
from ..traces.loader import TestData
from ..traces.tensorize import tensorize
from .base import BatchedReplay


class JaxReplayBackend(BatchedReplay):
    def __init__(self, n_replicas: int = 1, batch: int = 512,
                 layout: str | None = None, pack: int = 8,
                 range_engine: str | None = None,
                 unit_engine: str | None = None,
                 resolver: str | None = None):
        self.n_replicas = n_replicas
        self.batch = batch
        #: 'auto' (default; overridable via CRDT_ENGINE_LAYOUT) picks the
        #: coalesced range engine when RLE shrinks the op stream >= 2x;
        #: 'unit' forces the per-char engine (the labeled jax-unit bench
        #: column); 'range' forces the range engine.
        self.layout = layout
        self.pack = pack
        #: range-apply pick ('v4' fused kernel / 'v3' XLA per-pass);
        #: None defers to CRDT_RANGE_APPLY (default v4).
        self.range_engine = range_engine
        #: unit-apply pick and unit resolver; None defers to the
        #: ReplayEngine defaults (CRDT_ENGINE_APPLY / platform auto).
        self.unit_engine = unit_engine
        self.resolver = resolver
        self._eng: ReplayEngine | None = None
        self._tt = None

    @property
    def NAME(self) -> str:  # type: ignore[override]
        plat = jax.devices()[0].platform
        suffix = f"-{self.layout}" if self.layout else ""
        return (
            f"jax-{plat}"
            + (f"-r{self.n_replicas}" if self.n_replicas > 1 else "")
            + suffix
        )

    @property
    def replicas(self) -> int:
        return self.n_replicas

    @property
    def engine(self):
        """The constructed replay engine (RangeReplayEngine or
        ReplayEngine); available after :meth:`prepare`."""
        if self._eng is None:
            raise RuntimeError("call prepare(trace) first")
        return self._eng

    def prepare(self, trace: TestData) -> None:
        # Layout auto-selection (SURVEY.md section 7 hard-part 4): the edit
        # stream is run-length encoded across patch boundaries
        # (traces/tensorize.py coalesce_patches — the same RLE diamond-
        # types' op log applies internally, reference src/rope.rs:119-126)
        # and replayed as range ops whenever that shrinks the sequential
        # op count materially; the unit-op engine remains for streams with
        # no run structure (and as the labeled jax-unit bench column).
        import os

        layout = self.layout or os.environ.get("CRDT_ENGINE_LAYOUT", "auto")
        coalesce = os.environ.get("CRDT_ENGINE_COALESCE", "1") != "0"
        patches = None
        if layout == "auto":
            from ..traces.tensorize import coalesce_patches

            unit_ops = sum(
                d + len(ins) for _, d, ins in trace.iter_patches()
            )
            patches = list(
                coalesce_patches(trace) if coalesce
                else trace.iter_patches()
            )
            range_ops = sum(
                (1 if d else 0) + (1 if ins else 0)
                for _, d, ins in patches
            )
            use_range = unit_ops >= 2 * range_ops
        else:
            use_range = layout == "range"
        if use_range:
            from ..engine.replay_range import RangeReplayEngine
            from ..traces.tensorize import tensorize_ranges

            rt = tensorize_ranges(
                trace, batch=self.batch, coalesce=coalesce,
                patches=patches,
            )
            self._eng = RangeReplayEngine(
                rt, n_replicas=self.n_replicas, pack=self.pack,
                engine=self.range_engine,
            )
        else:
            self._tt = tensorize(trace, batch=self.batch)
            self._eng = ReplayEngine(
                self._tt, n_replicas=self.n_replicas,
                resolver=self.resolver, engine=self.unit_engine,
                pack=self.pack,
            )
        self._end_len = len(trace.end_content)

    def replay_once(self) -> int:
        eng = self._eng
        state = eng.run()  # includes fresh_state init (timed, as in reference)
        lengths = np.asarray(state.nvis)  # device->host sync point
        n = int(lengths.reshape(-1)[0])
        assert (lengths == self._end_len).all(), (
            f"length mismatch: {lengths} != {self._end_len}"
        )
        return n

    def final_content(self) -> str:
        state = self._eng.run()
        return self._eng.decode(state)
