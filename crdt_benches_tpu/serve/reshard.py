"""Elastic fleet reconfiguration: live shard-map changes without downtime.

The serve stack froze its topology at pool construction: row ``r`` of
every capacity class lives on shard ``r // Rg`` forever, and the only
topology event the chaos model knew was ``device_loss`` — a rebuild
*within* the static map.  This module makes the shard map a live,
journaled object:

- a **shard-map change** (``shrink:FROM:TO``, ``grow:FROM:TO``, or
  ``drain:S``) flips shards between ``live`` → ``draining`` →
  ``retired`` (or back to ``live`` on grow) while the fleet keeps
  serving — allocation stops on a draining shard immediately, but its
  resident docs keep taking ops until their migration round;
- **migrations are batched cross-shard doc moves** through the existing
  boundary-bucket machinery: each migrated doc is either a row-to-row
  ``("pull", cls, src_row)`` install onto a live shard (stays hot) or,
  when its class has no free live row, a plain eviction (readmitted on
  a live shard at its next scheduling).  Migrating docs briefly DEFER
  (their lane is pulled from the round), they are never shed;
- **every migration decision is durable before it executes**: the
  coordinator's commit point is ``RESHARD_MANIFEST.json`` (tmp + fsync
  + ``os.replace`` — the ``# graftlint: durable=reshard`` protocol),
  per-round move batches are journaled ``reshard``/``phase=move``
  records ahead of the boundary, and the final commit record is
  followed by a read-witnessed manifest unlink (G019's torn-pass
  completion form).  A crash at ANY mutating-op boundary leaves a state
  :func:`recover_torn_reshard` resolves deterministically: manifest
  present → roll the reshard FORWARD (retire the shards, move restored
  docs off); manifest absent → the journal's ``phase=commit`` records
  are the truth (a staged ``.tmp`` never committed and rolls back);
- the chaos kind ``reshard_crash`` kills the coordinator exactly
  between the manifest commit and the first per-doc move; the next
  round's tick resumes from the on-disk manifest (the same roll-forward
  recovery uses), so the event always closes recovered.

The invariant "every doc exists on exactly one shard at every crash
point" is machine-checked by :func:`check_shard_partition`, called at
every boundary of the ``serve/fscrash.py`` enumeration.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

from ..lint import lifecycle_sanitizer as lifecycle
from ..lint.fs_sanitizer import fs_protocol
from ..lint.sanitizer import fenced
from ..utils.fsdur import fsync_dir

#: The migration manifest: the reshard's durable commit point, living
#: in the journal directory next to ``GC_MANIFEST.json`` (same
#: two-phase discipline, PR 12).
RESHARD_MANIFEST = "RESHARD_MANIFEST.json"

#: The benign-garbage error set a manifest read must absorb (G020): a
#: bit-flipped manifest that still parses surfaces as one of these.
_MANIFEST_ERRORS = (OSError, json.JSONDecodeError, KeyError, TypeError,
                    ValueError)


# ---------------------------------------------------------------------------
# spec grammar
# ---------------------------------------------------------------------------


@dataclass
class ReshardPlan:
    """One parsed ``--serve-reshard`` spec.

    Grammar (see README "Elastic reconfiguration")::

        shrink:FROM:TO[@ROUND][,batch=N][,imbalance=X]
        grow:FROM:TO[@ROUND][,batch=N]
        drain:SHARD[@ROUND][,of=N][,batch=N][,imbalance=X]

    ``@ROUND`` arms a round trigger; ``imbalance=X`` arms the PR 7
    per-shard gauge as an alternative trigger (the reshard begins at
    the FIRST round where either condition holds).  A spec with neither
    trigger begins at round 2.  ``batch`` bounds doc moves per
    macro-round (default 8) — the knob that trades migration duration
    for mid-reshard tail latency.  ``drain`` takes its physical shard
    count from the mesh when one is present; single-host logical
    sharding needs ``of=N`` (drain shard S of N).
    """

    kind: str  # "shrink" | "grow" | "drain"
    from_sh: int  # live shard count before the change
    to_sh: int  # live shard count after the change
    shards: tuple[int, ...]  # shard ids changing state
    at_round: int | None = None
    imbalance: float | None = None
    batch: int = 8
    spec: str = ""

    @property
    def n_shards(self) -> int:
        """Physical shard count the pool must be built with."""
        return max(self.from_sh, self.to_sh)

    @property
    def initial_live(self) -> int:
        """Live shards at construction (grow starts below physical)."""
        return self.from_sh


def parse_reshard_spec(spec: str) -> ReshardPlan:
    """Parse a ``--serve-reshard`` spec string (grammar above)."""
    head, *opts = str(spec).split(",")
    head = head.strip()
    at_round: int | None = None
    if "@" in head:
        head, at = head.rsplit("@", 1)
        at_round = int(at)
    parts = head.split(":")
    kind = parts[0].strip()
    try:
        if kind in ("shrink", "grow"):
            if len(parts) != 3:
                raise ValueError("expected KIND:FROM:TO")
            from_sh, to_sh = int(parts[1]), int(parts[2])
        elif kind == "drain":
            if len(parts) != 2:
                raise ValueError("expected drain:SHARD")
            shard = int(parts[1])
            from_sh, to_sh = shard + 1, shard  # lower bounds; fixed below
        else:
            raise ValueError(f"unknown reshard kind {kind!r}")
    except ValueError as e:
        raise ValueError(
            f"reshard spec {spec!r}: {e} "
            "(grammar: shrink:FROM:TO[@R] | grow:FROM:TO[@R] | "
            "drain:SHARD[@R], options batch=N, imbalance=X)"
        ) from None
    imbalance: float | None = None
    batch = 8
    of = 0
    for tok in opts:
        tok = tok.strip()
        if not tok:
            continue
        if "=" not in tok:
            raise ValueError(
                f"reshard spec option {tok!r}: expected key=value"
            )
        key, val = tok.split("=", 1)
        key = key.strip()
        if key == "batch":
            batch = max(1, int(val))
        elif key == "imbalance":
            imbalance = float(val)
        elif key == "of":
            if kind != "drain":
                raise ValueError(
                    "reshard spec: of=N only applies to drain:SHARD"
                )
            of = int(val)
        else:
            raise ValueError(
                f"reshard spec: unknown option {key!r} "
                "(expected batch, imbalance or of)"
            )
    if kind == "shrink":
        if not 1 <= to_sh < from_sh:
            raise ValueError(
                f"reshard spec {spec!r}: shrink needs FROM > TO >= 1"
            )
        shards = tuple(range(to_sh, from_sh))
    elif kind == "grow":
        if not 1 <= from_sh < to_sh:
            raise ValueError(
                f"reshard spec {spec!r}: grow needs TO > FROM >= 1"
            )
        shards = tuple(range(from_sh, to_sh))
    else:  # drain one specific shard
        shard = int(parts[1])
        if shard < 0:
            raise ValueError(f"reshard spec {spec!r}: negative shard id")
        shards = (shard,)
        if of:
            if not 0 <= shard < of or of < 2:
                raise ValueError(
                    f"reshard spec {spec!r}: drain:{shard},of={of} "
                    "needs 0 <= SHARD < N and N >= 2"
                )
            from_sh, to_sh = of, of - 1
        else:
            from_sh, to_sh = 0, 0  # resolved against the mesh at bind
    return ReshardPlan(
        kind=kind, from_sh=from_sh, to_sh=to_sh, shards=shards,
        at_round=at_round, imbalance=imbalance, batch=batch,
        spec=str(spec),
    )


# ---------------------------------------------------------------------------
# manifest (the durable commit point)
# ---------------------------------------------------------------------------


def commit_manifest(journal_dir: str, manifest: dict) -> str:  # graftlint: durable=reshard
    """Commit the migration manifest: the reshard's point of no return.
    Staged to a ``.tmp`` sibling, fsynced, then atomically installed
    (G018) — after the ``os.replace`` the reshard WILL complete, by the
    coordinator, by its in-run resume, or by recovery's roll-forward."""
    path = os.path.join(journal_dir, RESHARD_MANIFEST)
    tmp = path + ".tmp"
    with fs_protocol("reshard"):
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(manifest, f, separators=(",", ":"))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)  # THE reshard commit point
        fsync_dir(journal_dir)
    return path


def read_manifest(journal_dir: str) -> dict | None:
    """The committed migration manifest, or None (absent/garbage —
    garbage rolls back exactly like absence: nothing was promised)."""
    path = os.path.join(journal_dir, RESHARD_MANIFEST)
    try:
        with open(path, encoding="utf-8") as f:
            m = json.load(f)
        return {
            "id": int(m["id"]),
            "kind": str(m["kind"]),
            "shards": [int(s) for s in m["shards"]],
            "round": int(m["round"]),
            "docs": int(m.get("docs", 0)),
        }
    except _MANIFEST_ERRORS:
        return None


def retire_manifest(journal_dir: str) -> bool:  # graftlint: durable=reshard
    """Retire a completed reshard's manifest (idempotent).  The unlink
    is read-witnessed inside the protocol entry — G019's torn-pass
    completion form: destruction dominated by a read of the committed
    record.  A staged ``.tmp`` (crash before the commit) is discarded
    too: it promised nothing."""
    path = os.path.join(journal_dir, RESHARD_MANIFEST)
    with fs_protocol("reshard"):
        tmp = path + ".tmp"
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass
        if not os.path.exists(path):
            return False
        try:
            with open(path, encoding="utf-8") as f:
                json.load(f)  # read-witness of the committed record
        except _MANIFEST_ERRORS:
            pass  # garbage manifest: still ours to retire
        try:
            os.unlink(path)
        except OSError:
            return False
        return True


# ---------------------------------------------------------------------------
# the partition invariant (machine-checked at every fscrash boundary)
# ---------------------------------------------------------------------------


def check_shard_partition(pool) -> list[str]:
    """Every doc exists on exactly one shard (or on no shard at all,
    when warm/cold/genesis).  Returns human-readable violations; empty
    means the invariant holds.  Checked against ground truth — the
    bucket row tables and free sets — not the per-doc records alone,
    so a half-applied move shows up from either side:

    - a doc occupying two rows anywhere in the pool;
    - a bucket row naming a doc whose record points elsewhere;
    - a record naming a row the bucket believes is free;
    - a resident doc on a RETIRED shard;
    - a resident doc still carrying a cold-spool claim (its tier state
      would be ambiguous — the deferred-unlink discipline requires
      ``rec.spool is None`` while hot);
    - per-shard occupancy failing to sum to the resident-doc count.
    """
    problems: list[str] = []
    owner: dict[int, tuple[int, int]] = {}  # doc -> (cls, row)
    occupied = 0
    for cls, b in pool.buckets.items():
        free = set(b.free)
        for row, doc_id in enumerate(b.rows):
            if doc_id is None:
                continue
            occupied += 1
            if row in free:
                problems.append(
                    f"c{cls} row {row}: doc {doc_id} occupies a row "
                    "the free set also lists"
                )
            if doc_id in owner:
                o_cls, o_row = owner[doc_id]
                problems.append(
                    f"doc {doc_id}: resident on two shards/rows "
                    f"(c{o_cls} r{o_row} and c{cls} r{row})"
                )
            owner[doc_id] = (cls, row)
            rec = pool.docs.get(doc_id)
            if rec is None:
                problems.append(
                    f"c{cls} row {row}: doc {doc_id} has no pool record"
                )
            elif (rec.cls, rec.row) != (cls, row):
                problems.append(
                    f"doc {doc_id}: bucket says c{cls} r{row}, record "
                    f"says c{rec.cls} r{rec.row}"
                )
            shard = row // b.Rg
            if pool.shard_state[shard] == "retired":
                problems.append(
                    f"doc {doc_id}: resident on RETIRED shard {shard} "
                    f"(c{cls} r{row})"
                )
    for doc_id, rec in pool.docs.items():
        if rec.cls is not None and doc_id not in owner:
            problems.append(
                f"doc {doc_id}: record claims c{rec.cls} r{rec.row} but "
                "no bucket row names it"
            )
        if rec.cls is not None and rec.spool is not None:
            problems.append(
                f"doc {doc_id}: resident AND cold (spool claim "
                f"{os.path.basename(rec.spool)}) — ambiguous tier"
            )
    if sum(pool.shard_occupancy()) != occupied:
        problems.append(
            f"shard occupancy {pool.shard_occupancy()} does not sum to "
            f"the {occupied} occupied rows"
        )
    return problems


# ---------------------------------------------------------------------------
# the coordinator
# ---------------------------------------------------------------------------


class ReshardCoordinator:  # graftlint: state=row field=state states=idle,active,crashed,done edges=idle->active,active->crashed,crashed->active,active->done
    """Drives one shard-map change through a serving fleet.

    Ticked by the scheduler once per macro-round, AFTER the round's
    plan is placed and BEFORE its WAL record — so every migration this
    round executes lands in the same boundary compose as the round's
    own moves, and the journal sees the decision before the bytes
    move.  States: ``idle`` → (trigger) → ``active`` → ``done``, with
    a ``crashed`` detour when the ``reshard_crash`` chaos kind kills
    the first attempt between the manifest commit and the per-doc
    moves."""

    def __init__(self, pool, journal, plan: ReshardPlan, faults=None,
                 telemetry=None):
        if journal is None:
            raise ValueError(
                "reshard requires the write-ahead journal "
                "(--serve-journal): migration decisions must be durable"
            )
        self.pool = pool
        self.journal = journal
        self.plan = plan
        self.faults = faults
        self.telemetry = telemetry
        self.state = "idle"
        self.reshard_id = 0
        # the coordinator machine's legal graph, mirrored from the
        # class marker (G022/G025): the only exit from `crashed` is a
        # resume — a commit straight out of a crash would retire shards
        # whose pending set was never re-derived
        lifecycle.declare_machine(
            "row", ("idle", "active", "crashed", "done"),
            (("idle", "active"), ("active", "crashed"),
             ("crashed", "active"), ("active", "done")),
        )
        self._shards: tuple[int, ...] = self._resolve_shards()
        if plan.kind == "grow":
            # the target shards are provisioned (rows exist) but not
            # yet live: docs place on the FROM set until the grow's
            # begin revives them
            for s in self._shards:
                self.pool.drain_shard(s)
        self._crash_ev = None
        self.begin_round = -1
        self.commit_round = -1
        self.migrated = 0  # row-to-row moves (stayed hot)
        self.evicted = 0  # no free live row: demoted, readmits live
        self.deferred_lanes = 0  # scheduled lanes pulled for migration
        self.deferred_ops = 0  # ops those lanes would have applied
        self.rounds_active = 0
        self.resumes = 0
        # mid-reshard tail visibility: per-round latencies while the
        # move is in flight (the bench's reshard block quantiles them)
        self.round_latencies: list[float] = []
        self._g = {}

    def _resolve_shards(self) -> tuple[int, ...]:
        n = self.pool.n_sh
        p = self.plan
        if p.kind == "drain":
            if p.shards[0] >= n:
                raise ValueError(
                    f"reshard drain:{p.shards[0]}: pool has {n} shards"
                )
            if p.from_sh and p.from_sh != n:
                raise ValueError(
                    f"reshard {p.spec!r}: of={p.from_sh} but the pool "
                    f"has {n} physical shards"
                )
            return p.shards
        if p.n_shards != n:
            raise ValueError(
                f"reshard {p.spec!r}: pool has {n} physical shards, "
                f"spec needs {p.n_shards} (pass --serve-mesh or shards=)"
            )
        return p.shards

    def bind_metrics(self, registry) -> None:
        """Pre-register the ``serve.reshard.*`` series (G013: never on
        the hot path)."""
        g = registry.gauge
        c = registry.counter
        self._g = {
            "active": g("serve.reshard.active"),
            "draining": g("serve.reshard.draining_shards"),
            "pending": g("serve.reshard.pending_docs"),
            "migrated": c("serve.reshard.migrated"),
            "evicted": c("serve.reshard.evicted"),
            "deferred": c("serve.reshard.deferred_lanes"),
            "rounds": c("serve.reshard.rounds"),
            "resumes": c("serve.reshard.resumes"),
        }

    # ---- helpers ----

    def _draining_docs(self) -> list[tuple[int, int, int]]:
        """(doc_id, cls, row) of every doc resident on a changing
        shard, deterministic order."""
        out = []
        for s in self._shards:
            if self.pool.shard_state[s] != "draining":
                continue
            out.extend(self.pool.docs_on_shard(s))
        out.sort()
        return out

    def _event(self, phase: str, rnd: int, **fields) -> None:
        self.journal.event(
            "reshard", phase=phase, id=self.reshard_id, r=rnd, **fields
        )
        if self.telemetry is not None:
            self.telemetry.note_event(
                "reshard", phase=phase, id=self.reshard_id, round=rnd,
                **fields,
            )

    def _gauge_refresh(self, pending: int) -> None:
        if not self._g:
            return
        self._g["active"].set(1 if self.state in ("active", "crashed")
                              else 0)
        self._g["draining"].set(sum(
            1 for s in self._shards
            if self.pool.shard_state[s] == "draining"
        ))
        self._g["pending"].set(pending)
        if self.telemetry is not None:
            # out-of-window publish: a shard-map change is exactly the
            # event an operator scrapes for, and a small fleet's whole
            # migration can begin and commit INSIDE one telemetry
            # window — without this the live /metrics endpoint would
            # never show the move in flight
            self.telemetry.publish_metrics_now()

    @property
    def active(self) -> bool:
        return self.state in ("active", "crashed")

    def migrating_docs(self) -> set[int]:
        """Docs currently mid-move (resident on a draining shard while
        the reshard is active): these DEFER, they are never shed."""
        if not self.active:
            return set()
        return {d for d, _cls, _row in self._draining_docs()}

    # ---- the per-round hook ----

    @fenced
    def tick(self, rnd: int, plan, imbalance: float,  # graftlint: fence=reshard
             note_deferred=None) -> None:
        """One round of coordination: trigger, (re)plan, migrate a
        batch, commit when drained.  ``plan`` is the round's placed
        ``_Plan`` — migrations append to its installs/evictions so the
        boundary executes them with everything else.  ``note_deferred``
        receives the op count of every lane pulled for migration.

        A declared sync boundary (``fence=reshard``): the manifest
        commit, journal records, and host-side row staging all live
        inside the per-round tick, so the fence sits at its mouth —
        the same place the scheduler crosses it."""
        if self.state == "done":
            return
        if self.state == "idle":
            if not self._should_begin(rnd, imbalance):
                return
            self._begin(rnd)
            if self.state != "active":
                return  # reshard_crash: coordinator died post-commit
        elif self.state == "crashed":
            self._resume(rnd)
        self.rounds_active += 1
        if self._g:
            self._g["rounds"].inc()
        pending = self._draining_docs()
        if pending and plan is not None:
            self._migrate_batch(rnd, plan, pending, note_deferred)
            pending = self._draining_docs()
        if not pending:
            self._commit(rnd)
        self._gauge_refresh(len(pending))

    def _should_begin(self, rnd: int, imbalance: float) -> bool:
        p = self.plan
        if p.at_round is not None and rnd >= p.at_round:
            return True
        if p.imbalance is not None and imbalance > p.imbalance:
            return True
        return p.at_round is None and p.imbalance is None and rnd >= 2

    def _begin(self, rnd: int) -> None:  # graftlint: transition=row:idle->active,active->crashed
        """The commit point: manifest first (durable decision), then
        the live shard-map flip, then the begin record.  The
        ``reshard_crash`` kill point sits immediately after — between
        the committed manifest and the first per-doc move."""
        self.reshard_id += 1
        self.begin_round = rnd
        docs0 = 0
        if self.plan.kind != "grow":
            for s in self._shards:
                docs0 += len(self.pool.docs_on_shard(s))
        commit_manifest(self.journal.dir, {
            "id": self.reshard_id,
            "kind": self.plan.kind,
            "shards": list(self._shards),
            "round": rnd,
            "docs": docs0,
        })
        if self.plan.kind == "grow":
            for s in self._shards:
                self.pool.revive_shard(s)
        else:
            for s in self._shards:
                self.pool.drain_shard(s)
        self._event("begin", rnd, change=self.plan.kind,
                    shards=list(self._shards), docs=docs0)
        lifecycle.transition("row", "idle", "active", key=id(self))
        self.state = "active"
        if self.faults is not None:
            ev = self.faults.reshard_crash_event(rnd)
            if ev is not None:
                # the coordinator dies here: its in-memory migration
                # plan is gone, the manifest is not.  The next tick's
                # resume (or a real recovery's roll-forward) completes
                # the reshard from the manifest alone.
                ev.fire(rnd, stage="post_manifest_pre_moves",
                        shards=list(self._shards), docs=docs0)
                self._crash_ev = ev
                lifecycle.transition("row", "active", "crashed",
                                     key=id(self))
                self.state = "crashed"
        self._gauge_refresh(docs0)

    def _resume(self, rnd: int) -> None:  # graftlint: transition=row:crashed->active
        """Deterministic in-run recovery of a crashed coordinator:
        everything needed to finish lives in the committed manifest
        and the pool's own shard map — re-read the manifest (the
        read-witness), re-derive the pending set, carry on."""
        m = read_manifest(self.journal.dir)
        if m is not None:
            self._shards = tuple(int(s) for s in m["shards"])
        self.resumes += 1
        if self._g:
            self._g["resumes"].inc()
        self._event("resume", rnd, shards=list(self._shards))
        if self._crash_ev is not None:
            self._crash_ev.recover(via="coordinator_resume", round=rnd)
            self._crash_ev = None
        lifecycle.transition("row", "crashed", "active", key=id(self))
        self.state = "active"

    def _migrate_batch(self, rnd: int, plan, pending, note_deferred
                       ) -> None:
        """Move up to ``batch`` docs off the draining shards through
        the round's boundary compose.  A doc scheduled this round has
        its lane pulled first (defer, never shed) — its ops reschedule
        next round from the live shard."""
        pool = self.pool
        moved: list[list[int]] = []
        # A doc ADMITTED this very round is not movable yet: its row
        # install composes at this round's boundary, but both migration
        # paths (row-to-row "pull" and demote-to-spool) read the PRE-
        # compose bucket snapshot — the row's bytes before the install
        # land, i.e. a previous tenant's state or garbage.  Skip it;
        # the next tick's pending recompute picks it up with real state.
        installing = {
            d for items in plan.installs.values() for d, _row, _src in items
        }
        batch = [m for m in pending
                 if m[0] not in installing][: self.plan.batch]
        for doc_id, cls, src_row in batch:
            b = pool.buckets[cls]
            lane_ops = self._pull_lane(plan, cls, doc_id, note_deferred)
            rec = pool.docs[doc_id]
            if b.n_free_live > 0:
                # row-to-row move onto a live shard: the doc stays hot
                dst = b.alloc_row()
                inst = plan.installs.setdefault(cls, [])
                inst.append((doc_id, dst, ("pull", cls, src_row)))
                plan.pull_classes.add(cls)
                b.rows[dst] = doc_id
                b.rows[src_row] = None
                b.release_row(src_row)
                rec.row = dst
                self.migrated += 1
                if self._g:
                    self._g["migrated"].inc()
                if self.telemetry is not None:
                    self.telemetry.shards.note_relocation(dst // b.Rg)
                moved.append([doc_id, cls, src_row, dst])
            else:
                # no free live row in the class: demote through the
                # normal eviction boundary; the next admission lands it
                # on a live shard (draining shards refuse allocation)
                plan.evictions.append((doc_id, cls, src_row))
                plan.pull_classes.add(cls)
                if pool.warm.budget <= 0:
                    pool._set_spool(rec, pool._spool_path(doc_id))
                b.rows[src_row] = None
                b.release_row(src_row)
                rec.cls = rec.row = None
                pool.evictions += 1
                self.evicted += 1
                if self._g:
                    self._g["evicted"].inc()
                moved.append([doc_id, cls, src_row, -1])
        if moved:
            # the decision is journaled BEFORE the boundary applies it
            self._event("move", rnd, docs=moved)

    def _pull_lane(self, plan, cls: int, doc_id: int, note_deferred
                   ) -> int:
        """Remove the doc's lane from the round (if it was scheduled):
        a migrating doc defers.  Returns the deferred op count."""
        lanes = plan.lanes.get(cls)
        if not lanes:
            return 0
        for i, lane in enumerate(lanes):
            if lane.stream.doc_id != doc_id:
                continue
            ops = lane.end - lane.stream.cursor
            del lanes[i]
            if not lanes:
                del plan.lanes[cls]
            self.deferred_lanes += 1
            self.deferred_ops += ops
            if self._g:
                self._g["deferred"].inc()
            if note_deferred is not None:
                note_deferred(ops)
            return ops
        return 0

    def _commit(self, rnd: int) -> None:  # graftlint: transition=row:active->done
        """The draining shards are empty: retire them, journal the
        commit record, retire the manifest (read-witnessed unlink)."""
        retired: list[int] = []
        if self.plan.kind != "grow":
            for s in self._shards:
                if self.pool.shard_state[s] == "draining":
                    self.pool.retire_shard(s)
                    retired.append(s)
        self.commit_round = rnd
        self._event(
            "commit", rnd, change=self.plan.kind, retired=retired,
            revived=(list(self._shards) if self.plan.kind == "grow"
                     else []),
            migrated=self.migrated, evicted=self.evicted,
        )
        retire_manifest(self.journal.dir)
        lifecycle.transition("row", "active", "done", key=id(self))
        self.state = "done"
        self._gauge_refresh(0)

    @fenced
    def finalize(self, rnd: int) -> None:  # graftlint: fence=reshard
        """End-of-drain sweep: a reshard still in flight when the last
        op drains completes NOW — remaining residents of the draining
        shards are demoted host-side (their streams are done; nothing
        re-admits them) and the commit lands.  A crashed coordinator
        resumes first, closing its chaos event — a completed drain
        never ends with a torn manifest."""
        if self.state == "done":
            return
        if self.state == "idle":
            return
        if self.state == "crashed":
            self._resume(rnd)
        moved = []
        for doc_id, cls, _row in self._draining_docs():
            self.pool.evict(doc_id)
            self.evicted += 1
            if self._g:
                self._g["evicted"].inc()
            moved.append([doc_id, cls, _row, -1])
        if moved:
            self._event("move", rnd, docs=moved, finalize=True)
        self._commit(rnd)

    # ---- reporting ----

    def note_round_latency(self, seconds: float) -> None:
        if self.active:
            self.round_latencies.append(seconds)

    def status_fields(self) -> dict:
        return {
            "state": self.state,
            "kind": self.plan.kind,
            "shards": list(self._shards),
            "pending_docs": (len(self._draining_docs())
                             if self.active else 0),
            "migrated": self.migrated,
            "evicted": self.evicted,
            "deferred_lanes": self.deferred_lanes,
        }

    def summary(self) -> dict:
        """The artifact's ``reshard`` block body."""
        import numpy as np

        lat = sorted(self.round_latencies)
        qs = {}
        if lat:
            arr = np.asarray(lat)
            qs = {
                "p50": float(np.quantile(arr, 0.5)),
                "p99": float(np.quantile(arr, 0.99)),
                "max": float(arr[-1]),
            }
        return {
            "version": 1,
            "spec": self.plan.spec,
            "kind": self.plan.kind,
            "state": self.state,
            "shards": list(self._shards),
            "begin_round": self.begin_round,
            "commit_round": self.commit_round,
            "rounds_active": self.rounds_active,
            "migrated": self.migrated,
            "evicted": self.evicted,
            "deferred_lanes": self.deferred_lanes,
            "deferred_ops": self.deferred_ops,
            "resumes": self.resumes,
            "mid_latency": qs,
            "live_shards": self.pool.live_shard_count,
        }


# ---------------------------------------------------------------------------
# recovery (complete or roll back, deterministically)
# ---------------------------------------------------------------------------


def scan_reshard_records(records) -> tuple[set[int], int]:
    """Replay the journal's reshard lifecycle records in order: the
    retired-shard set a recovered pool must honor, and the count of
    commit records seen.  Grow commits revive — the set is a running
    state, not a union."""
    retired: set[int] = set()
    commits = 0
    for rec in records:
        if rec.get("t") != "reshard":
            continue
        if rec.get("phase") != "commit":
            continue
        commits += 1
        for s in rec.get("retired", []):
            retired.add(int(s))
        for s in rec.get("revived", []):
            retired.discard(int(s))
    return retired, commits


def recover_torn_reshard(pool, journal_dir: str, records) -> dict:
    """Resolve any reshard state a crash left behind — called by
    ``recover_fleet`` after the snapshot restore, before serving
    resumes.  Deterministic by construction:

    - journaled ``commit`` records are settled history: their retired
      shards are re-retired (a snapshot OLDER than the reshard may
      have restored docs onto them — those docs are demoted to the
      spool, the same migration semantics, before the shard closes);
    - a committed manifest with no commit record is a torn reshard:
      ROLLED FORWARD the same way (the manifest was the promise);
    - no manifest and no commit record: the reshard never committed —
      rolled back by doing nothing (a staged ``.tmp`` is discarded).

    Returns ``{"retired": [...], "moved": n, "completed": bool}``.
    """
    retired, _commits = scan_reshard_records(records)
    manifest = read_manifest(journal_dir)
    completed = False
    if manifest is not None and manifest["kind"] != "grow":
        retired |= set(manifest["shards"])
    moved = 0
    for s in sorted(retired):
        if s >= pool.n_sh:
            continue
        if pool.shard_state[s] != "retired":
            pool.drain_shard(s)
        for doc_id, _cls, _row in pool.docs_on_shard(s):
            pool.evict(doc_id)
            moved += 1
        if pool.shard_state[s] != "retired":
            pool.retire_shard(s)
    if manifest is not None or os.path.exists(
            os.path.join(journal_dir, RESHARD_MANIFEST + ".tmp")):
        completed = retire_manifest(journal_dir) or manifest is not None
    return {
        "retired": sorted(retired),
        "moved": moved,
        "completed": completed,
    }
