"""Predictive async prefetch: the cold→warm rehydrate thread.

The tiered ``DocPool`` (serve/pool.py) keeps a bounded pinned-host
**warm** tier between the device-resident hot rows and the compressed
cold spool.  A cold doc the scheduler is about to admit would pay a
synchronous disk read (decompress + CRC verify) on the hot thread; this
module moves that read OFF the drain: the scheduler's look-ahead plan
(the front of its round-robin rotation plus the arrival horizon) is
submitted here, a dedicated **prefetch thread** rehydrates the spools,
and the rows come back to the hot thread through a declared publish
point on a bounded queue — by the time ``_select`` wants the doc, it is
a warm hit.

Thread-confinement contract (graftlint G014–G017 + the runtime race
sanitizer, the constraint ROADMAP pinned on this work):

- the worker loop is its own declared root (``# graftlint:
  thread=prefetch``) and touches NOTHING the hot thread owns — a
  request is an immutable ``(doc_id, spool_path, generation)`` tuple
  carrying everything the load needs, so ``pool.docs`` / streams /
  buckets never cross;
- rehydrated rows cross back ONLY through :meth:`Prefetcher._publish`,
  a declared ``# graftlint: publish=prefetch`` swap point on the
  bounded result queue.  Under ``CRDT_BENCH_SANITIZE_RACES=1`` each
  payload becomes an ownership-tracking proxy published by that point;
  the hot thread's :meth:`drain` is the ``reveal`` gate, so every
  crossing is counted and an unpublished handoff raises at its
  callsite.  The per-point counters land in the serve artifact's
  ``thread_crossings`` block (surface key ``prefetch``) and G017
  cross-checks them against these annotations;
- the hot thread NEVER blocks on this thread (G016): submission is
  ``put_nowait`` (queue full = the prefetch is dropped and counted),
  harvest is ``get_nowait``, and an admission that misses warm falls
  back to the synchronous rehydrate it always had — the prefetcher is
  pure opportunism, never a dependency.

Staleness is the hot thread's problem by design: a payload carries the
doc's spool **generation** at submit time (``DocPool.spool_gen``), and
the harvest drops any result whose generation moved — the doc was
re-admitted and re-evicted while the read was in flight, so the bytes
describe a superseded state.  ``save_state`` lands spools via
``os.replace``, so an in-flight read races only ever against a
complete old inode, never a torn file.

Streaming construction rides the same channel: a **construct** request
carries a pure builder callable (closed over the immutable
``FleetSpec``) instead of a spool path, the worker tensorizes the doc's
op stream, and the finished arrays come back through the SAME declared
publish point — first-admission tensorization never runs on the drain.
The builder crosses threads on the request queue itself, so no shared
mutable attribute exists for G014 to find.

Every submission is stamped with a monotonically increasing **sequence
number** and reaping is by sequence: ``note_lost`` remembers the reaped
seqs, and a payload whose read outlived its reaping is dropped at
harvest WITHOUT touching ``inflight`` — the counter can no longer be
double-decremented below zero by a slow result racing the reaper.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable

import numpy as np

from ..lint import lifecycle_sanitizer as lifecycle
from ..lint.race_sanitizer import published, reveal, share
from ..utils.checkpoint import load_state

#: Default bound of the request/result queues: deep enough to cover one
#: macro-round's admission fan-in, small enough that a wedged worker
#: surfaces as dropped submissions, not unbounded memory.
DEFAULT_CAPACITY = 256


class Prefetcher:
    """The cold→warm rehydrate worker (module docstring has the model).

    Hot-thread surface: :meth:`submit` / :meth:`drain` / :meth:`stop`
    (all non-blocking or bounded).  Worker surface: :meth:`_run` /
    :meth:`_publish` (the declared prefetch thread).  All counters are
    owned by the hot thread — the worker only ever touches the two
    queues."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        cap = max(4, int(capacity))
        #: the submission bound the scheduler respects: never more
        #: than ``capacity`` reads outstanding, so the result queue
        #: (same size) can always absorb every completion and the
        #: worker's publish never times out in a healthy drain
        self.capacity = cap
        self._req: queue.Queue = queue.Queue(maxsize=cap)
        self._res: queue.Queue = queue.Queue(maxsize=cap)
        self._thread: threading.Thread | None = None
        # hot-thread-owned accounting (never touched by the worker)
        self.submitted = 0
        self.dropped = 0  # request queue full: prefetch refused
        self.harvested = 0
        self.errors = 0  # payloads that came back with a load error
        self.lost = 0  # reaped by the scheduler (publish-drop leak fix)
        self.reap_dropped = 0  # payloads that arrived after their reap
        self.inflight = 0
        #: next submission sequence number.  Starts at 1 so a
        #: successful :meth:`submit` is always truthy; 0 means refused.
        self._seq = 1
        #: seqs the scheduler reaped whose payloads may still arrive —
        #: their harvest must NOT decrement ``inflight`` again
        self._reaped: set[int] = set()

    def note_lost(self, seqs: int | Iterable[int]) -> None:
        """The scheduler reaped in-flight entries whose results never
        arrived (a wedged round forced the worker's bounded publish to
        time out and drop).  Without this, a dropped payload would pin
        ``inflight`` — and shrink the submission budget — for the rest
        of the run.

        Pass the reaped submissions' sequence numbers: ``inflight`` is
        decremented for each ONCE, here, and the seqs are remembered so
        a payload that merely *outlived* its reaping (the read was slow,
        not dropped) is discarded at harvest without a second decrement
        — the underflow that used to drive ``inflight`` negative.  A
        bare int is accepted for callers that never see the payload
        again (count-only reap; no double-decrement protection)."""
        if isinstance(seqs, int):
            n = seqs
        else:
            seqs = [int(s) for s in seqs]
            self._reaped.update(seqs)
            n = len(seqs)
        self.lost += n
        self.inflight = max(0, self.inflight - n)
        lifecycle.gauge("prefetch_inflight", self.inflight)

    # ---- driver-side lifecycle (G013: never constructed mid-drain) --

    def start(self) -> None:  # graftlint: acquire=thread
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="serve-prefetch", daemon=True
        )
        self._thread.start()
        lifecycle.acquire("thread", id(self))

    def stop(self) -> None:  # graftlint: release=thread
        """Stop the worker (driver side).  Bounded waits only — a
        wedged worker is abandoned as a daemon, never joined forever."""
        if self._thread is None:
            return
        try:
            self._req.put(None, timeout=1.0)
        except queue.Full:
            pass  # worker wedged mid-load: daemon thread, abandoned
        self._thread.join(timeout=5.0)
        self._thread = None
        lifecycle.release("thread", id(self))

    @property
    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    # ---- hot-thread surface (non-blocking by contract, G016) ----

    def submit(self, doc_id: int, spool_path: str, gen: int) -> int:
        """Queue one cold→warm rehydrate.  Never blocks: a full queue
        refuses the prefetch (counted; admission will simply take the
        synchronous path).  The request tuple is immutable — the only
        mutable data crossing threads is the RESULT, through the
        declared publish point.

        Returns the submission's sequence number (>= 1, so the result
        is truthy iff accepted) or 0 when refused.  The caller hands
        the seq back to :meth:`note_lost` if it reaps the entry."""
        return self._enqueue(
            ("spool", self._seq, int(doc_id), str(spool_path), int(gen))
        )

    def submit_construct(
        self, doc_id: int, builder: Callable[[], dict]
    ) -> int:
        """Queue one first-admission stream construction.  ``builder``
        must be PURE — a callable closed over immutable inputs only
        (the ``FleetSpec``), since it executes on the prefetch thread;
        its returned dict crosses back through the declared publish
        point like any rehydrate.  Same seq/refusal contract as
        :meth:`submit`."""
        return self._enqueue(("construct", self._seq, int(doc_id), builder))

    def _enqueue(self, item: tuple) -> int:
        try:
            self._req.put_nowait(item)
        except queue.Full:
            self.dropped += 1
            return 0
        seq = item[1]
        self._seq += 1
        self.submitted += 1
        self.inflight += 1
        return seq

    def drain(self) -> list[dict]:
        """Harvest every completed rehydrate (never blocks).  Each
        payload passes the ``reveal`` gate — the reader side of the
        publish contract — so armed runs attribute the crossing to
        :meth:`_publish` (and raise on an unpublished handoff).

        A payload whose seq was already reaped via :meth:`note_lost`
        (the read outlived the reaper) is discarded here WITHOUT a
        second ``inflight`` decrement — the underflow fix."""
        out: list[dict] = []
        while True:
            try:
                item = self._res.get_nowait()
            except queue.Empty:
                break
            payload = reveal(item)
            seq = payload.get("seq", 0)
            if seq in self._reaped:
                self._reaped.discard(seq)
                self.reap_dropped += 1
                continue
            self.inflight -= 1
            lifecycle.gauge("prefetch_inflight", self.inflight)
            self.harvested += 1
            if payload.get("error") is not None:
                self.errors += 1
            out.append(payload)
        return out

    # ---- the prefetch thread ----

    def _run(self) -> None:  # graftlint: thread=prefetch
        """Worker loop: block on the request queue (this thread's ONLY
        job is waiting on it — G016 polices the hot thread, not this
        one), rehydrate the spool, publish the result.  A damaged spool
        is not a failure here: the error rides back in the payload and
        the hot thread's synchronous path (with its heal machinery)
        owns the repair."""
        while True:
            item = self._req.get()
            if item is None:
                return
            kind, seq = item[0], item[1]
            if kind == "spool":
                _, _, doc_id, path, gen = item
                try:
                    st = load_state(path)
                    payload = {
                        "kind": "spool",
                        "seq": seq,
                        "doc": doc_id,
                        "gen": gen,
                        "row": np.asarray(st.doc[0], np.int32),
                        "length": int(st.length[0]),
                        "nvis": int(st.nvis[0]),
                        "error": None,
                    }
                except Exception as e:  # CRC damage, vanished file, ...
                    payload = {
                        "kind": "spool", "seq": seq, "doc": doc_id,
                        "gen": gen, "row": None, "length": 0, "nvis": 0,
                        "error": f"{type(e).__name__}: {e}",
                    }
            else:  # construct: first-admission tensorization off-drain
                _, _, doc_id, builder = item
                try:
                    payload = dict(builder())
                    payload.update(
                        kind="construct", seq=seq, doc=doc_id, error=None
                    )
                except Exception as e:
                    payload = {
                        "kind": "construct", "seq": seq, "doc": doc_id,
                        "error": f"{type(e).__name__}: {e}",
                    }
            try:
                self._publish(payload)
            except queue.Full:
                # hot thread stopped draining (drain abandoned): the
                # prefetch is best-effort, the payload is dropped
                continue

    @published
    def _publish(self, payload: dict) -> None:  # graftlint: publish=prefetch  # graftlint: thread=prefetch
        """THE declared swap point: one rehydrated row leaves the
        prefetch thread.  ``share`` stamps the payload with this
        point's publish generation (armed), and the bounded ``put``
        carries a timeout so a wedged consumer can never park the
        worker forever."""
        self._res.put(
            share(payload, "Prefetcher.result"), timeout=30.0
        )
