"""Write-ahead op journal + fleet snapshot barriers + crash recovery.

CRDTs exist to stay available under faults; this module makes the serve/
fleet *provably* recoverable: after any crash, recovery restores the
last consistent snapshot set and replays the journal tail through the
existing macro-round path, and the oracle byte-verify confirms the
result is exactly the converged state an uninterrupted run produces.

Durability v2 turns the machinery from a correctness device into a
bounded-footprint subsystem.  Three persistent artifacts live under one
journal directory:

- **op journal** (``journal.log`` + sealed ``wal_<seq>.log`` segments):
  an append-only record stream.  Every macro-round, the scheduler
  journals the per-class lane set — one ``(doc, start_cursor,
  end_cursor)`` triple per scheduled document — BEFORE dispatching the
  staged tensors (write-ahead).  Because every doc's op stream is
  deterministic host data, a cursor interval IS the op batch: replaying
  ``[start, end)`` of the stream reproduces the exact device work.
  Records are one line each, ``<crc32hex> <json>``; a torn tail (crash
  mid-write) fails CRC/JSON and is dropped at read time, never
  propagated.  The active file rolls into a numbered **segment** once it
  passes ``segment_bytes``, and a **GC pass** after each committed
  snapshot deletes segments whose every record is older than the
  barrier — the WAL footprint is O(ops since the last committed
  snapshot), not O(history).  GC is crash-safe: the victim list is
  committed to ``GC_MANIFEST.json`` before any unlink, and a torn pass
  (crash between manifest and unlink) is completed on the next open,
  compaction, or recovery.
- **snapshot barriers** (``snap_<round>/``): every ``snapshot_every``
  macro-rounds the scheduler persists a consistent fleet state, staged
  in ``<dir>.tmp`` with the manifest written LAST and committed by a
  single directory rename.  A barrier is either **full** (one CRC'd
  .npz per capacity class — the whole bucket) or a **delta** (only the
  rows the pool marked dirty since the previous barrier), CRC-chained
  to its base: the delta's manifest records its base snapshot's name
  plus the CRC of the base's manifest bytes, down to the full snapshot
  that roots the chain.  A periodic full barrier re-roots the chain so
  depth stays bounded.  Snapshots are pruned by CHAIN — a delta's base
  is never deleted out from under it.
- **recovery** (:func:`recover_fleet`): pick the newest snapshot whose
  whole chain verifies (base links CRC-checked, every member's arrays
  CRC-checked), composing root → deltas newest-last so the latest write
  to each row wins; any broken link falls back DOWN the chain — older
  delta, then the full root, then an older chain, then a cold start
  (streams are deterministic, so a fleet is recoverable from nothing).
  Restored cursors sit at the chosen barrier; resumed serving drives
  the journal tail through the normal macro-round path.

:func:`rebuild_doc` is the in-run repair primitive shared by the
scheduler's fault handling (corrupt spool, device-state loss): rebuild
one document's row at cursor ``target`` from a base state at cursor
``start`` by replaying the stream interval through the same
scan-of-slices dispatch shape the macro engine uses.
"""

from __future__ import annotations

import json
import os
import shutil
import zlib
from dataclasses import dataclass, field

import numpy as np

from ..lint.fs_sanitizer import durable_protocol, fs_protocol
from ..lint.race_sanitizer import published
from ..obs.metrics import Counter, Gauge
from ..traces.tensorize import PAD
from ..utils.checkpoint import (
    CorruptCheckpointError,
    fsync_dir,
    fsync_file,
    load_state,
    save_state,
)

SNAP_PREFIX = "snap_"
WAL_PREFIX = "wal_"
WAL_ACTIVE = "journal.log"
GC_MANIFEST = "GC_MANIFEST.json"

#: Roll the active WAL file into a sealed segment past this many bytes.
DEFAULT_SEGMENT_BYTES = 1 << 20

#: Safety valve: a delta chain deeper than this is re-rooted with a full
#: snapshot regardless of the caller's cadence (recovery walks the whole
#: chain, so unbounded depth would unbound the RTO).
MAX_CHAIN_DEPTH = 64


class ChainError(CorruptCheckpointError):
    """A snapshot chain failed verification: missing base directory,
    base-manifest CRC mismatch, depth overflow, or an unreadable link
    manifest.  Subclasses :class:`CorruptCheckpointError` so every
    fallback path that already degrades on member damage degrades the
    same way on link damage."""


#: What a recovery candidate may raise before the walk falls back to an
#: older snapshot.  Wider than CorruptCheckpointError on purpose: a
#: bit-flipped manifest can stay PARSEABLE json with garbled values
#: (a resident row index past the bucket, a non-int round), which
#: surfaces as IndexError/KeyError/TypeError deep in the restore — a
#: designed-recoverable corruption must degrade to the next candidate,
#: never crash the recovery itself.
_RECOVER_ERRORS = (ValueError, KeyError, IndexError, TypeError, OSError)


# ---------------------------------------------------------------------------
# the op journal (append-only, CRC-framed JSON lines, rolled segments)
# ---------------------------------------------------------------------------


class OpJournal:  # graftlint: thread=hot
    """Append-only write-ahead journal.  One record per line:
    ``<crc32 of payload, 8 hex chars> <compact json payload>``.

    Thread confinement (G014-G016 audit, ISSUE 10): the journal writer
    is owned by the hot thread — WAL appends happen inside the
    macro-round (write-ahead of dispatch) and recovery readers run
    before a drain starts, on the same thread.  Nothing here may be
    touched from the status/bus threads; when the tiered-residency
    prefetch work moves journaling off-thread, the handoff must become
    a declared publish point (a bounded queue), not shared file-handle
    state.

    ``fsync=True`` makes every record durable before the append returns
    (the strict WAL discipline); the default leaves flushing to the OS —
    a lost *suffix* is exactly what recovery tolerates, torn or not.

    ``segment_bytes`` bounds the active file: once it has passed the
    threshold, the next roll point (:meth:`maybe_roll` — invoked by
    every :meth:`compact`, i.e. at each snapshot barrier) seals it as
    ``wal_<seq>.log`` and opens a fresh active file.  Sealed segments
    are immutable, which is what makes the GC pass safe; rolling lives
    OFF the append hot path because a segment can only ever be
    collected at a barrier anyway.

    Reopening an existing log first completes any torn GC pass, sweeps
    abandoned snapshot staging directories, and truncates a torn tail
    of the ACTIVE file: appending new records BEHIND a damaged line
    would hide them from the next recovery (readers stop at the first
    bad line).  Sealed segments are only ever complete records — a
    crash can only tear the file that was being appended."""

    def __init__(self, journal_dir: str, fsync: bool = False,  # graftlint: durable=wal
                 segment_bytes: int = DEFAULT_SEGMENT_BYTES):
        os.makedirs(journal_dir, exist_ok=True)
        self.dir = journal_dir
        self.path = os.path.join(journal_dir, WAL_ACTIVE)
        self.fsync = fsync
        self.segment_bytes = max(0, int(segment_bytes))
        self.torn_gc_completed = finish_torn_gc(journal_dir)
        self.staging_swept = len(sweep_staging(journal_dir))
        if os.path.exists(self.path):
            good = _valid_prefix_bytes(self.path)
            if good < os.path.getsize(self.path):
                with fs_protocol("wal"):
                    with open(self.path, "r+b") as f:
                        f.truncate(good)
        self._seq = 1 + max(
            (_segment_seq(s) for s in wal_segments(journal_dir)),
            default=0,
        )
        with fs_protocol("wal"):
            self._f = open(self.path, "a", encoding="utf-8")
        self._active_bytes = os.path.getsize(self.path)
        self._since_snapshot = 0
        # per-segment GC-eligibility cache: max round of a SEALED
        # segment (None = has a round-less/unparseable record, never
        # eligible).  Sealed segments are immutable, so the value is
        # computed once — tracked live for segments this process seals
        # (append -> roll), lazily parsed for ones found on open.
        self._seg_max: dict[str, int | None] = {}
        self._active_max_r = -1
        self._active_roundless = False
        self._active_records = 0
        if self._active_bytes:
            # surviving pre-crash records: parse once to seed the
            # tracker (the file was just truncated to its valid prefix)
            recs, _n, _clean = _file_records(self.path)
            self._active_records = len(recs)
            for rec in recs:
                r = rec.get("r")
                if isinstance(r, int):
                    self._active_max_r = max(self._active_max_r, r)
                else:
                    self._active_roundless = True
        self._m_records = Counter("serve.journal.records")
        self._m_bytes = Counter("serve.journal.bytes")
        self._m_snap_bytes = Counter("serve.journal.snapshot_bytes")
        self._m_sealed = Counter("serve.journal.segments_sealed")
        self._m_gc_passes = Counter("serve.journal.gc_passes")
        self._m_gc_segments = Counter("serve.journal.gc_segments")
        self._g_segments = Gauge("serve.journal.wal_segments")
        self._g_since = Gauge("serve.journal.bytes_since_snapshot")
        self._g_segments.set(1 + len(wal_segments(journal_dir)))

    def bind_metrics(self, registry) -> None:
        """Attach the journal's counters + durability gauges to a
        drain's MetricsRegistry (pre-registered here, off the hot path —
        G013; they render on /metrics as ``serve_journal_*``)."""
        for m in (self._m_records, self._m_bytes, self._m_snap_bytes,
                  self._m_sealed, self._m_gc_passes, self._m_gc_segments,
                  self._g_segments, self._g_since):
            registry.attach(m)

    @property
    def records(self) -> int:
        return self._m_records.value

    @property
    def bytes_written(self) -> int:
        return self._m_bytes.value

    @property
    def bytes_total(self) -> int:
        """Cumulative WAL bytes appended plus committed snapshot bytes —
        the journal's write-rate surface, which is what the soak leak
        detector watches (monotonic by construction; GC shrinks the
        on-disk footprint, never this)."""
        return self._m_bytes.value + self._m_snap_bytes.value

    @property
    def segments_sealed(self) -> int:
        return self._m_sealed.value

    @property
    def gc_segments(self) -> int:
        return self._m_gc_segments.value

    def on_disk_bytes(self) -> int:
        """Live WAL footprint: sealed segments + the active file (cold
        path — walks the directory).  This is the number the bounded-
        footprint acceptance gates on: with GC it tracks ops since the
        last committed snapshot, not history."""
        total = 0
        for name in wal_segments(self.dir) + [WAL_ACTIVE]:
            try:
                total += os.path.getsize(os.path.join(self.dir, name))
            except OSError:
                pass
        return total

    def note_snapshot(self, snap_dir: str) -> int:
        """Account a committed snapshot barrier's on-disk bytes (walked
        once per barrier — cold path).  Hard-linked spool members count
        at full size: the number tracks what a recovery would read, not
        unique blocks.  Also resets the bytes-since-snapshot gauge —
        the WAL tail a recovery would replay restarts here."""
        total = 0
        for root, _dirs, files in os.walk(snap_dir):
            for f in files:
                try:
                    total += os.path.getsize(os.path.join(root, f))
                except OSError:
                    pass  # pruned concurrently by keep= rotation
        self._m_snap_bytes.inc(total)
        self._since_snapshot = 0
        self._g_since.set(0)
        return total

    def append(self, obj: dict) -> None:  # graftlint: durable=wal
        payload = json.dumps(obj, separators=(",", ":"))
        line = f"{zlib.crc32(payload.encode()):08x} {payload}\n"
        with fs_protocol("wal"):
            self._f.write(line)
            self._f.flush()
            if self.fsync:
                os.fsync(self._f.fileno())
        self._m_records.inc()
        self._m_bytes.inc(len(line))
        self._active_bytes += len(line)
        self._since_snapshot += len(line)
        self._g_since.set(self._since_snapshot)
        self._active_records += 1
        r = obj.get("r")
        if isinstance(r, int):
            if r > self._active_max_r:
                self._active_max_r = r
        else:
            self._active_roundless = True

    def maybe_roll(self) -> bool:  # graftlint: durable=wal
        """Seal the active file as the next numbered segment (once it
        has passed ``segment_bytes``) and open a fresh one.  NOT called
        from the append hot path: a segment can only be GC'd at a
        snapshot barrier, so sealing between barriers buys nothing —
        :meth:`compact` rolls first, inside the barrier fence.  Crash
        windows are benign: after the rename but before the new open
        there is simply no active file, and the next append (or
        reopen) creates one.

        The seal fsyncs the active file BEFORE renaming it (graftlint
        v4 audit fix, G018): a sealed segment is immutable and
        GC-eligible — committing its name while its tail pages were
        never flushed would let a power cut tear a file the reader
        trusts to hold only complete records."""
        if not self.segment_bytes \
                or self._active_bytes < self.segment_bytes:
            return False
        with fs_protocol("wal"):
            self._f.flush()
            os.fsync(self._f.fileno())
            self._f.close()
            name = _segment_name(self._seq)
            os.replace(self.path, os.path.join(self.dir, name))
            fsync_dir(self.dir)
            self._seg_max[name] = (
                None if self._active_roundless or not self._active_records
                else self._active_max_r
            )
            self._seq += 1
            self._f = open(self.path, "a", encoding="utf-8")
        self._active_bytes = 0
        self._active_max_r = -1
        self._active_roundless = False
        self._active_records = 0
        self._m_sealed.inc()
        self._g_segments.set(1 + len(wal_segments(self.dir)))
        return True

    @published
    def round_record(  # graftlint: publish=journal
        self, rnd: int, lanes: dict[int, list[tuple[int, int, int]]]
    ) -> None:
        """The write-ahead record for one macro-round: per class, the
        ``[doc, start_cursor, end_cursor]`` of every scheduled lane.
        MUST be appended before the round's dispatch.

        Declared a publish point (``publish=journal``): the WAL append
        is where a round's lane set leaves the hot thread's live state
        and becomes durable — the journal-replay reader consumes it in
        another lifetime (and, when the tiered-residency work moves
        journaling off-thread, this point becomes the real queue
        handoff).  Entries are counted in every journaled run (G017
        ground truth) and request traces record the hop as their WAL
        propagation edge (obs/reqtrace.py)."""
        self.append({
            "t": "round",
            "r": rnd,
            "lanes": {str(c): spans for c, spans in lanes.items()},
        })

    def event(self, kind: str, **fields) -> None:
        self.append({"t": kind, **fields})

    # ---- segment GC (cold path: runs inside the barrier fence) ----

    def compact(self, covered_round: int, crash_hook=None) -> dict:  # graftlint: durable=gc
        """Delete sealed segments fully covered at ``covered_round``: a
        segment whose every record carries ``r < covered_round`` is
        durable below that barrier (decisions live in the manifest,
        cursors at the barrier) and a recovery landing at or above it
        would ignore the records anyway.  Segments with any record at
        or above the round — or any record without a round — survive.
        Callers must pass the :func:`retained_floor` (the OLDEST
        retained snapshot's round), not the newest barrier's: chain
        fallback may land recovery on any retained snapshot, and its
        redo tail starts there.

        Crash-safe two-phase delete: the victim list is committed to
        ``GC_MANIFEST.json`` (tmp + ``os.replace``) BEFORE the first
        unlink; a crash mid-pass leaves the manifest, and the next
        open / compaction / recovery completes the pass
        (:func:`finish_torn_gc`).  ``crash_hook`` sits exactly in that
        window — the chaos injector's ``crash_compact`` kill point.

        Rolls the active file first (:meth:`maybe_roll`): the records
        below the barrier it seals become this pass's own victims, so
        the WAL footprint after a barrier is exactly the uncovered
        tail."""
        self.maybe_roll()
        torn = self.finish_torn_gc()
        victims: list[str] = []
        freed = 0
        for name in wal_segments(self.dir):
            path = os.path.join(self.dir, name)
            if name not in self._seg_max:  # sealed before this open
                self._seg_max[name] = _segment_max_round(path)
            max_r = self._seg_max[name]
            if max_r is not None and max_r < covered_round:
                victims.append(name)
                try:
                    freed += os.path.getsize(path)
                except OSError:
                    pass
        info = {
            "round": covered_round,
            "checked": len(wal_segments(self.dir)),
            "deleted": 0,
            "freed_bytes": 0,
            "torn_completed": torn,
            "crashed": False,
        }
        if not victims:
            return info
        manifest = {"round": int(covered_round), "segments": victims}
        mpath = os.path.join(self.dir, GC_MANIFEST)
        tmp = mpath + ".tmp"
        with fs_protocol("gc"):
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(manifest, f, separators=(",", ":"))
                # the manifest IS the commit record: fsync before the
                # rename so a power cut cannot commit a name whose
                # victim list never reached the platter (G018)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, mpath)  # the GC commit point
            fsync_dir(self.dir)
            if crash_hook is not None and crash_hook():
                # simulated crash between manifest write and unlink: the
                # torn pass is recovered on the next open/compact/recovery
                info["crashed"] = True
                return info
            for name in victims:
                try:
                    os.unlink(os.path.join(self.dir, name))
                except OSError:
                    pass
                self._seg_max.pop(name, None)
            os.unlink(mpath)
        self._m_gc_passes.inc()
        self._m_gc_segments.inc(len(victims))
        self._g_segments.set(1 + len(wal_segments(self.dir)))
        info["deleted"] = len(victims)
        info["freed_bytes"] = freed
        return info

    def finish_torn_gc(self) -> int:  # graftlint: durable=gc
        """Complete a GC pass torn by a crash (instance-side wrapper:
        same repair as the module helper, plus the metrics every GC
        path must report — :meth:`compact` routes through here so a
        crash-repaired pass and a clean pass count identically)."""
        n = finish_torn_gc(self.dir)
        if n:
            live = set(wal_segments(self.dir))
            for name in list(self._seg_max):
                if name not in live:
                    del self._seg_max[name]
            self._m_gc_passes.inc()
            self._m_gc_segments.inc(n)
            self._g_segments.set(1 + len(live))
        return n

    def status_fields(self) -> dict:
        """Small-scalar durability view for ``/status.json`` (no disk
        walk — gauge/counter reads only)."""
        return {
            "wal_segments": int(self._g_segments.value),
            "bytes_since_snapshot": int(self._g_since.value),
            "segments_sealed": self._m_sealed.value,
            "gc_segments": self._m_gc_segments.value,
        }

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()


def _segment_name(seq: int) -> str:
    return f"{WAL_PREFIX}{seq:08d}.log"


def _segment_seq(name: str) -> int:
    return int(name[len(WAL_PREFIX):-len(".log")])


def wal_segments(journal_dir: str) -> list[str]:
    """Sealed WAL segment file names, oldest first."""
    if not os.path.isdir(journal_dir):
        return []
    return sorted(
        f for f in os.listdir(journal_dir)
        if f.startswith(WAL_PREFIX) and f.endswith(".log")
    )


def finish_torn_gc(journal_dir: str) -> int:  # graftlint: durable=gc
    """Complete a GC pass that crashed between its manifest write and
    the unlinks: delete every victim the manifest lists that still
    exists, then retire the manifest.  Idempotent; returns the number
    of segments removed now.  A half-written ``GC_MANIFEST.json.tmp``
    (crash before the manifest commit) is simply discarded — the pass
    never started, all segments survive.  (G019's read-witness form:
    the destruction is dominated by a read of the committed manifest,
    the one case where destroy-without-install is legal.)"""
    with fs_protocol("gc"):
        tmp = os.path.join(journal_dir, GC_MANIFEST + ".tmp")
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass
        mpath = os.path.join(journal_dir, GC_MANIFEST)
        if not os.path.exists(mpath):
            return 0
        try:
            with open(mpath, encoding="utf-8") as f:
                manifest = json.load(f)
            victims = [str(s) for s in manifest.get("segments", [])]
        except (OSError, json.JSONDecodeError, AttributeError):
            victims = []  # unreadable manifest: drop, keep every segment
        removed = 0
        for name in victims:
            path = os.path.join(journal_dir, name)
            if os.path.exists(path):
                try:
                    os.unlink(path)
                    removed += 1
                except OSError:
                    pass
        try:
            os.unlink(mpath)
        except OSError:
            pass
        return removed


def sweep_staging(journal_dir: str) -> list[str]:  # graftlint: durable=snapshot
    """Remove snapshot staging directories abandoned by a crash before
    the atomic rename (``snap_*.tmp``).  They may contain a
    valid-looking manifest — the rename IS the commit, so anything
    still carrying the ``.tmp`` suffix was never committed and must
    neither be listed as a candidate nor left to accumulate."""
    if not os.path.isdir(journal_dir):
        return []
    removed = []
    with fs_protocol("snapshot"):
        for d in sorted(os.listdir(journal_dir)):
            if d.startswith(SNAP_PREFIX) and d.endswith(".tmp") and \
                    os.path.isdir(os.path.join(journal_dir, d)):
                shutil.rmtree(os.path.join(journal_dir, d),
                              ignore_errors=True)
                removed.append(d)
    return removed


def _valid_prefix_bytes(path: str) -> int:
    """Byte length of the longest CRC-valid record prefix of a journal
    file (everything from the first damaged line on is a torn tail)."""
    good = 0
    with open(path, "rb") as f:
        for raw in f:
            try:
                line = raw.decode("utf-8")
                crc_hex, payload = line.rstrip("\n").split(" ", 1)
                if int(crc_hex, 16) != zlib.crc32(payload.encode()):
                    break
                json.loads(payload)
            except (ValueError, UnicodeDecodeError, json.JSONDecodeError):
                break
            good += len(raw)
    return good


def _file_records(path: str) -> tuple[list[dict], int, bool]:
    """CRC-valid records of one journal file: ``(records, total_lines,
    clean)`` where ``clean`` is False when a damaged line stopped the
    read early."""
    records: list[dict] = []
    if not os.path.exists(path):
        return records, 0, True
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        lines = f.readlines()
    for i, line in enumerate(lines):
        try:
            crc_hex, payload = line.rstrip("\n").split(" ", 1)
            if int(crc_hex, 16) != zlib.crc32(payload.encode()):
                raise ValueError("crc mismatch")
            records.append(json.loads(payload))
        except (ValueError, json.JSONDecodeError):
            return records, len(lines), False
    return records, len(lines), True


def _segment_max_round(path: str) -> int | None:
    """Highest round any CRC-valid record of a sealed segment carries;
    None when the segment holds no records, a damaged line, or a record
    without a round — all of which make it ineligible for GC (keep is
    always the safe answer)."""
    records, _n, clean = _file_records(path)
    if not clean or not records:
        return None
    max_r = -1
    for rec in records:
        r = rec.get("r")
        if not isinstance(r, int):
            return None
        max_r = max(max_r, r)
    return max_r


def read_journal(journal_dir: str) -> tuple[list[dict], int]:
    """All CRC-valid records across sealed segments + the active file,
    in append order.  Reading stops at the first damaged line (a crash
    can only tear the tail of the file that was being appended; once a
    line is suspect, so is everything after it — including later
    files).  An empty trailing segment or a missing active file reads
    as zero records, cleanly.  Returns ``(records, dropped_lines)``."""
    records: list[dict] = []
    dropped = 0
    files = wal_segments(journal_dir) + [WAL_ACTIVE]
    for i, name in enumerate(files):
        path = os.path.join(journal_dir, name)
        recs, total, clean = _file_records(path)
        records.extend(recs)
        if not clean:
            dropped = total - len(recs)
            for later in files[i + 1:]:
                _r, t, _c = _file_records(
                    os.path.join(journal_dir, later)
                )
                dropped += t
            break
    return records, dropped


# ---------------------------------------------------------------------------
# snapshot barriers (full + CRC-chained deltas)
# ---------------------------------------------------------------------------


def _manifest_crc(snap_dir: str) -> str | None:
    """CRC32 (8 hex chars) of a snapshot's manifest FILE BYTES — the
    chain link fingerprint: a delta records its base's manifest CRC, so
    a re-written / damaged / swapped base breaks the chain loudly
    instead of composing the wrong rows."""
    try:
        with open(os.path.join(snap_dir, "MANIFEST.json"), "rb") as f:
            return f"{zlib.crc32(f.read()):08x}"
    except OSError:
        return None


@durable_protocol("snapshot")
def write_snapshot(journal_dir: str, pool, streams, rnd: int,  # graftlint: durable=snapshot
                   keep: int = 2, kind: str = "full"
                   ) -> tuple[str, dict]:
    """One fleet snapshot barrier: per-class bucket state (CRC'd .npz),
    hard links of all live eviction spools, and a manifest of
    cursors/residency.  The set is staged in ``<dir>.tmp`` with the
    manifest written LAST, then committed by a single directory rename —
    a crash mid-snapshot leaves only an ignorable ``.tmp`` directory
    (swept by the next open/recovery), never a half snapshot that
    recovery could mistake for consistent.

    ``kind="full"`` persists every used class's whole bucket (the chain
    root).  ``kind="delta"`` persists only the rows the pool marked
    dirty since the previous barrier (``DocPool.take_dirty``), chained
    to the newest committed snapshot: the manifest records the base's
    name + manifest CRC and the chain's full root.  A delta with no
    committed base — or a base whose manifest no longer verifies, or a
    chain already at :data:`MAX_CHAIN_DEPTH` — silently upgrades to a
    full snapshot (re-rooting is always safe).  Either kind consumes
    the pool's dirty set: the barrier IS the reset point.

    Old snapshots are pruned by CHAIN (a delta's base is never deleted
    from under it): the newest ``keep`` chains survive (``keep <= 0``
    = never prune).  Returns ``(path, manifest)`` — the manifest as
    committed, so callers read the re-rooted kind/depth without a disk
    round-trip."""
    from .pool import PackedState  # local: avoid import cycle at module load

    if kind not in ("full", "delta"):
        raise ValueError(f"unknown snapshot kind {kind!r}")
    dirty = pool.take_dirty()  # consumed by EVERY barrier kind

    base_name = None
    base_crc = None
    chain_root = None
    depth = 1
    if kind == "delta":
        snaps = list_snapshots(journal_dir)
        base_name = snaps[-1] if snaps else None
        m_base = (
            _read_manifest(os.path.join(journal_dir, base_name))
            if base_name else None
        )
        if m_base is None:
            kind, base_name = "full", None  # no usable base: re-root
        else:
            depth = int(m_base.get("depth", 1)) + 1
            if depth > MAX_CHAIN_DEPTH:
                kind, base_name, depth = "full", None, 1
            else:
                base_crc = _manifest_crc(
                    os.path.join(journal_dir, base_name)
                )
                chain_root = m_base.get("chain", base_name)

    final = os.path.join(journal_dir, f"{SNAP_PREFIX}{rnd:08d}")
    tmp = final + ".tmp"
    shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(tmp)

    def _adopt(src: str, fname: str) -> None:
        # spools are immutable once written (save_state lands them
        # via os.replace, so a re-eviction swaps in a NEW inode):
        # hard-link the snapshot member instead of copying — a
        # thousands-of-cold-docs fleet barrier stays cheap.  The
        # adopted member is fsynced HERE (one shared inode): hot-path
        # spool writes skip the per-eviction fsync, and the barrier is
        # where their contents must become durable — before the commit
        # rename makes the snapshot real (G018).
        dst = os.path.join(tmp, fname)
        try:
            os.link(src, dst)
        except OSError:  # cross-device / unsupported fs
            shutil.copy2(src, dst)
        fsync_file(dst)

    resident: dict[str, list[int]] = {}
    spooled: dict[str, str] = {}
    warm: dict[str, str] = {}
    for doc_id, rec in pool.docs.items():
        if rec.cls is not None:
            resident[str(doc_id)] = [int(rec.cls), int(rec.row)]
        elif rec.spool is not None and os.path.exists(rec.spool):
            fname = f"doc{doc_id}.npz"
            _adopt(rec.spool, fname)
            spooled[str(doc_id)] = fname
    # warm tier (tiered pool): the barrier and the tiers share ONE
    # residency story — every warm doc gets a durable on-disk shadow
    # (written once per warm lifetime; entries are immutable) and the
    # shadow rides the snapshot exactly like a cold spool member.
    warm_tier = getattr(pool, "warm", None)
    if warm_tier is not None:
        for doc_id in sorted(warm_tier.entries):
            fname = f"doc{doc_id}.npz"
            _adopt(pool.ensure_warm_shadow(doc_id), fname)
            warm[str(doc_id)] = fname

    class_shapes: dict[str, list[int]] = {}
    delta_rows: dict[str, list[int]] = {}
    if kind == "full":
        used_classes = sorted({int(v[0]) for v in resident.values()})
        for cls in used_classes:
            doc, length, nvis = pool.pull_bucket(cls)  # the sync barrier
            save_state(
                os.path.join(tmp, f"class_{cls}.npz"),
                PackedState(doc=doc, length=length, nvis=nvis),
                compress=False, durable=True,
            )
            class_shapes[str(cls)] = [int(doc.shape[0]),
                                      int(doc.shape[1])]
    else:
        used_classes = sorted(
            cls for cls, rows in dirty.items() if rows
        )
        for cls in used_classes:
            rows = [r for r in dirty[cls]
                    if 0 <= r < pool.buckets[cls].R]
            if not rows:
                continue
            doc, length, nvis = pool.pull_bucket(cls)  # sync: dirty only
            rows_a = np.asarray(rows, np.int64)
            # trim to the dirty rows' used prefix (the tail is the
            # constant beyond-length coding 2 that compose re-pads)
            ltrim = max(1, int(length[rows_a].max(initial=0)))
            save_state(
                os.path.join(tmp, f"delta_{cls}.npz"),
                PackedState(
                    doc=np.ascontiguousarray(doc[rows_a, :ltrim]),
                    length=np.asarray(length[rows_a], np.int32),
                    nvis=np.asarray(nvis[rows_a], np.int32),
                ),
                compress=False, durable=True,
            )
            delta_rows[str(cls)] = [int(r) for r in rows]
            class_shapes[str(cls)] = [int(doc.shape[0]),
                                      int(doc.shape[1])]
        used_classes = sorted(int(c) for c in delta_rows)

    docs = {}
    for doc_id, st in streams.items():
        docs[str(doc_id)] = {
            "c": int(st.cursor),
            "lim": None if st.limit is None else int(st.limit),
            "lossy": bool(st.lossy),
        }
    name = os.path.basename(final)
    manifest = {
        "round": int(rnd),
        "kind": kind,
        "base": base_name,
        "base_crc": base_crc,
        "chain": chain_root if kind == "delta" else name,
        "depth": depth,
        "classes": used_classes,
        "class_shapes": class_shapes,
        "delta_rows": delta_rows,
        "resident": resident,
        "spooled": spooled,
        "warm": warm,
        "docs": docs,
    }
    mtmp = os.path.join(tmp, "MANIFEST.tmp")
    with open(mtmp, "w", encoding="utf-8") as f:
        json.dump(manifest, f, separators=(",", ":"))
        f.flush()
        os.fsync(f.fileno())
    os.replace(mtmp, os.path.join(tmp, "MANIFEST.json"))
    # every member + the manifest are fsynced; flush the staging
    # directory's ENTRIES too, then commit — and make the commit
    # itself durable (G018: a rename is only a commit once the parent
    # directory knows about it across a power cut)
    fsync_dir(tmp)
    os.rename(tmp, final)  # the commit point
    fsync_dir(journal_dir)

    _prune_chains(journal_dir, keep)
    return final, manifest


def _prune_chains(journal_dir: str, keep: int) -> None:  # graftlint: durable=snapshot
    """Prune committed snapshots by CHAIN: group directories into
    chains (a full snapshot starts one; a delta whose base is the
    previous member continues it; anything orphaned is its own
    prunable group) and delete everything but the newest ``keep``
    chains — a retained delta's base links always survive with it."""
    names = list_snapshots(journal_dir)
    chains: list[list[str]] = []
    for n in names:
        m = _read_manifest(os.path.join(journal_dir, n))
        if (
            m is not None
            and m.get("kind", "full") == "delta"
            and chains
            and m.get("base") == chains[-1][-1]
        ):
            chains[-1].append(n)
        else:
            chains.append([n])
    # keep <= 0 = never prune (the historical keep-all contract)
    for chain in (chains[:-keep] if keep > 0 else []):
        for n in chain:
            shutil.rmtree(os.path.join(journal_dir, n),
                          ignore_errors=True)


def retained_floor(journal_dir: str) -> int | None:
    """The OLDEST retained snapshot's round — the WAL GC floor.  Chain
    fallback may land recovery on ANY retained snapshot, and a
    landing at round R re-applies journaled decisions (quarantine /
    shed) from records with ``r >= R``; GC below the newest barrier
    alone would delete records a fallback still needs.  Decisions
    older than a snapshot are durable in its manifest, so the floor is
    exactly the oldest retained barrier.  (A cold start below the
    floor — every retained chain dead — may still lose GC'd decisions;
    that takes multiple independent corruptions and full replay keeps
    the oracle gate honest.)"""
    snaps = list_snapshots(journal_dir)
    return int(snaps[0][len(SNAP_PREFIX):]) if snaps else None


def list_snapshots(journal_dir: str) -> list[str]:
    """Committed snapshot directory names, oldest first.  Staging
    directories (``.tmp`` suffix — abandoned by a crash before the
    atomic rename) are never candidates, whatever they contain."""
    if not os.path.isdir(journal_dir):
        return []
    return sorted(
        d for d in os.listdir(journal_dir)
        if d.startswith(SNAP_PREFIX) and not d.endswith(".tmp")
        and os.path.isdir(os.path.join(journal_dir, d))
    )


def _read_manifest(snap_dir: str) -> dict | None:
    try:
        with open(os.path.join(snap_dir, "MANIFEST.json"),
                  encoding="utf-8") as f:
            return json.load(f)
    except (OSError, UnicodeDecodeError, ValueError):
        # ValueError covers JSONDecodeError; bit-flip damage can also
        # surface as undecodable UTF-8 before the parser even runs
        return None


def chain_members(journal_dir: str, name: str,
                  manifests: dict | None = None) -> list[str]:
    """The snapshot chain ending at ``name``, root first.  Every link
    is verified: the base directory must exist, its manifest must
    parse, and its manifest-file CRC must match what the dependent
    delta recorded.  Raises :class:`ChainError` on any broken link —
    callers fall back to an older candidate."""
    members: list[str] = []
    cur = name
    for _ in range(MAX_CHAIN_DEPTH + 1):
        sd = os.path.join(journal_dir, cur)
        if manifests is not None and cur in manifests:
            m = manifests[cur]
        else:
            m = _read_manifest(sd)
            if manifests is not None:
                manifests[cur] = m
        if m is None:
            raise ChainError(f"snapshot {cur}: unreadable manifest")
        members.append(cur)
        if m.get("kind", "full") != "delta":
            members.reverse()
            return members
        base = m.get("base")
        if not base:
            raise ChainError(f"delta {cur}: no base link")
        got = _manifest_crc(os.path.join(journal_dir, base))
        if got is None or got != m.get("base_crc"):
            raise ChainError(
                f"delta {cur}: base {base} manifest CRC mismatch "
                f"(chain link broken)"
            )
        cur = base
    raise ChainError(f"snapshot {name}: chain deeper than "
                     f"{MAX_CHAIN_DEPTH}")


def _compose_class(journal_dir: str, members: list[str],
                   manifests: dict, cls: int) -> tuple | None:
    """Materialize one capacity class's full ``(doc, length, nvis)``
    bucket arrays as of the chain tip: start from the full root's
    member (or a fresh all-empty bucket when the class first appears in
    a delta) and overlay each delta's dirty rows in chain order —
    the latest write to a row wins, exactly the dirty-tracking
    invariant.  Returns None when no member of the chain mentions the
    class.  Raises CorruptCheckpointError on member damage."""
    key = str(cls)
    state = None  # (doc, length, nvis) np arrays, (R, C)
    for name in members:
        m = manifests[name]
        sd = os.path.join(journal_dir, name)
        if m.get("kind", "full") != "delta":
            if int(cls) in [int(c) for c in m.get("classes", [])]:
                st = load_state(os.path.join(sd, f"class_{cls}.npz"))
                state = (
                    np.array(st.doc, np.int32),
                    np.array(st.length, np.int32),
                    np.array(st.nvis, np.int32),
                )
            continue
        rows = m.get("delta_rows", {}).get(key)
        if not rows:
            continue
        if state is None:
            R, C = m["class_shapes"][key]
            state = (
                np.full((R, C), 2, np.int32),
                np.zeros(R, np.int32),
                np.zeros(R, np.int32),
            )
        st = load_state(os.path.join(sd, f"delta_{cls}.npz"))
        doc, length, nvis = state
        d = np.asarray(st.doc, np.int32)
        rows_a = np.asarray(rows, np.int64)
        doc[rows_a, :d.shape[1]] = d
        doc[rows_a, d.shape[1]:] = 2
        length[rows_a] = np.asarray(st.length, np.int32)
        nvis[rows_a] = np.asarray(st.nvis, np.int32)
    return state


def load_chain_states(journal_dir: str, name: str,
                      manifests: dict | None = None
                      ) -> tuple[dict, dict, list[str]]:
    """Materialize snapshot ``name`` by walking its chain: returns
    ``(manifest, states, members)`` where ``states`` maps every class
    the tip's residency needs to composed host arrays.  Raises
    :class:`ChainError` / :class:`CorruptCheckpointError` on any broken
    link or damaged member — the caller's cue to fall back down."""
    manifests = {} if manifests is None else manifests
    members = chain_members(journal_dir, name, manifests)
    tip = manifests[name]
    needed = sorted({
        int(v[0]) for v in tip.get("resident", {}).values()
    })
    states = {}
    for cls in needed:
        st = _compose_class(journal_dir, members, manifests, cls)
        if st is None:
            raise ChainError(
                f"snapshot {name}: class {cls} resident but absent "
                "from every chain member"
            )
        states[cls] = st
    return tip, states, members


def probe_recovery(journal_dir: str) -> tuple[str | None, int]:  # graftlint: durable=snapshot
    """Dry-run the snapshot selection recovery performs: walk
    candidates newest-first, materializing each chain, and return
    ``(first_usable_snapshot, fallbacks)`` — ``fallbacks`` counts
    candidates skipped over damage.  ``(None, n)`` means cold start.
    Used by the chaos finalizer to prove ``delta_corrupt`` recovery
    (chain fallback exercised, state materializable) without building
    a pool."""
    manifests: dict = {}
    fallbacks = 0
    for snap in reversed(list_snapshots(journal_dir)):
        try:
            load_chain_states(journal_dir, snap, manifests)
        except _RECOVER_ERRORS:
            fallbacks += 1
            continue
        return snap, fallbacks
    return None, fallbacks


class SnapshotBases:
    """Lazy, cached access to per-doc base states across the retained
    snapshots — the rebuild path's source of truth.  ``base(doc_id)``
    walks snapshots newest-first and returns the first intact base:
    ``(doc_row, length, nvis, cursor)`` with the row trimmed/padded to
    the caller's target capacity by :func:`rebuild_doc`.  Returns None
    when no snapshot holds the doc (fresh rebuild from cursor 0).

    Chain-aware: a doc resident at a delta snapshot resolves through
    the composed chain (root + dirty-row overlays); any damaged link
    falls back to the next older snapshot, same as full recovery.

    Manifests are cached per snapshot (a class-loss recovery calls
    ``base`` once per resident doc); the per-class state cache can hold
    whole bucket arrays, so callers ``release()`` it once a recovery
    pass is done instead of pinning tens of MB for the run."""

    def __init__(self, journal_dir: str | None):
        self.dir = journal_dir
        self._class_cache: dict[tuple, object] = {}
        self._manifests: dict[str, dict | None] = {}

    def release(self) -> None:
        """Drop cached bucket states (and manifests — a new snapshot
        may have pruned old directories)."""
        self._class_cache.clear()
        self._manifests.clear()

    def _manifest(self, snap: str, sd: str) -> dict | None:
        if snap not in self._manifests:
            self._manifests[snap] = _read_manifest(sd)
        return self._manifests[snap]

    def _class_state(self, snap: str, cls: int):
        """Composed (doc, length, nvis) for ``cls`` as of ``snap``
        (chain-walked, cached).  Raises on damage."""
        ck = (snap, int(cls))
        if ck not in self._class_cache:
            members = chain_members(self.dir, snap, self._manifests)
            st = _compose_class(self.dir, members, self._manifests, cls)
            if st is None:
                raise ChainError(
                    f"snapshot {snap}: class {cls} absent from chain"
                )
            self._class_cache[ck] = st
        return self._class_cache[ck]

    def base(self, doc_id: int):  # graftlint: durable=snapshot
        if self.dir is None:
            return None
        for snap in reversed(list_snapshots(self.dir)):
            sd = os.path.join(self.dir, snap)
            m = self._manifest(snap, sd)
            if m is None:
                continue
            key = str(doc_id)
            try:
                if key in m.get("resident", {}):
                    cls, row = m["resident"][key]
                    doc, length, nvis = self._class_state(snap, cls)
                    return (
                        np.array(doc[row]),
                        int(length[row]),
                        int(nvis[row]),
                        int(m["docs"][key]["c"]),
                    )
                if key in m.get("spooled", {}):
                    st = load_state(
                        os.path.join(sd, m["spooled"][key])
                    )
                    return (
                        np.array(st.doc[0]),
                        int(st.length[0]),
                        int(st.nvis[0]),
                        int(m["docs"][key]["c"]),
                    )
            except _RECOVER_ERRORS:
                continue  # damaged member/link: fall back to older
        return None


# ---------------------------------------------------------------------------
# targeted rebuild: replay a stream interval through the macro scan path
# ---------------------------------------------------------------------------

_REPLAYERS: dict[tuple, object] = {}


def _replayer(C: int, B: int, K: int, nbits: int):
    """The jitted single-row macro replayer for one (capacity, batch,
    depth, nbits) shape: a ``lax.scan`` over K slices of (1, B) range
    ops — the same resolve/apply body as ``DocPool.macro_step``, on a
    one-row stack.  Cached per shape (the recovery path's compile cost
    is paid once)."""
    key = (C, B, K, nbits)
    if key not in _REPLAYERS:
        import jax

        from ..engine.merge_fleet import merge_rows_body

        def body(st, sl):
            k, p, ln, s0 = sl
            return merge_rows_body(st, k, p, ln, s0, nbits=nbits), None

        def fn(state, kind, pos, rlen, slot0):
            out, _ = jax.lax.scan(body, state, (kind, pos, rlen, slot0))
            return out

        _REPLAYERS[key] = jax.jit(fn, donate_argnums=(0,))
    return _REPLAYERS[key]


def _pad_row(row: np.ndarray, C: int) -> np.ndarray:
    """Pad/keep a doc row to capacity ``C`` with the beyond-length
    coding ``2`` (trimmed spools and smaller-class bases)."""
    row = np.asarray(row, np.int32)
    if len(row) >= C:
        return row[:C]
    return np.concatenate([row, np.full(C - len(row), 2, np.int32)])


def rebuild_doc(
    stream,
    C: int,
    base,  # (doc_row, length, nvis, base_cursor) or None for fresh
    target: int,
    *,
    n_init: int,
    batch: int,
    batch_chars: int,
    nbits: int,
    macro_k: int = 1,
) -> tuple[np.ndarray, int, int, int]:
    """Rebuild one document's row state at cursor ``target`` by
    replaying stream ops ``[base_cursor, target)`` over the base state,
    through the macro scan dispatch shape.  Returns
    ``(doc_row[C], length, nvis, dispatches)`` — ``dispatches`` is the
    macro-round-equivalent count (the MTTR unit).

    Ops at indices below the base cursor are never re-applied — the
    cursor IS the idempotence high-water mark, the same dedup rule the
    scheduler uses for redelivered batches."""
    import jax.numpy as jnp

    from .pool import PackedState, _fresh_row_np

    if base is None:
        doc_row, length, nvis, c = _fresh_row_np(C, n_init), n_init, n_init, 0
    else:
        doc_row, length, nvis, c = base
        doc_row = _pad_row(doc_row, C)
    c = max(0, min(int(c), target))
    state = PackedState(
        doc=jnp.asarray(doc_row[None]),
        length=jnp.asarray([length], jnp.int32),
        nvis=jnp.asarray([nvis], jnp.int32),
    )
    K = max(1, macro_k)
    dispatches = 0
    while c < target:
        kind = np.full((K, 1, batch), PAD, np.int32)
        pos = np.zeros((K, 1, batch), np.int32)
        rlen = np.zeros((K, 1, batch), np.int32)
        slot0 = np.full((K, 1, batch), -1, np.int32)
        for k in range(K):
            if c >= target:
                break  # trailing slices stay PAD (no-ops)
            # the scheduler's slice-budget rule, verbatim (DocStream)
            e = stream.slice_end(c, batch, batch_chars, target)
            take = e - c
            kind[k, 0, :take] = stream.kind[c:e]
            pos[k, 0, :take] = stream.pos[c:e]
            rlen[k, 0, :take] = stream.rlen[c:e]
            slot0[k, 0, :take] = stream.slot0[c:e]
            c = e
        state = _replayer(C, batch, K, nbits)(
            state,
            jnp.asarray(kind), jnp.asarray(pos),
            jnp.asarray(rlen), jnp.asarray(slot0),
        )
        dispatches += 1
    return (
        np.asarray(state.doc[0]),
        int(np.asarray(state.length[0])),
        int(np.asarray(state.nvis[0])),
        dispatches,
    )


# ---------------------------------------------------------------------------
# crash recovery
# ---------------------------------------------------------------------------


@dataclass
class RecoveryReport:
    """What a :func:`recover_fleet` run found and did."""

    snapshot_round: int = -1  # -1 = cold start (no usable snapshot)
    snapshot_dir: str | None = None
    resume_round: int = 0
    docs_restored: int = 0  # residency/cursor restored from the snapshot
    spools_restored: int = 0
    warm_restored: int = 0  # warm-tier members restored (tiered pool)
    ops_replayed: int = 0  # journal-tail redo span (snap cursor -> WAL tip)
    torn_records: int = 0  # damaged journal tail lines dropped
    quarantined: list[int] = field(default_factory=list)
    shed_ops: int = 0
    records: int = 0
    chain_depth: int = 0  # members composed for the chosen snapshot
    chain_fallbacks: int = 0  # damaged candidates skipped on the way down
    gc_segments_completed: int = 0  # torn GC finished by this recovery
    staging_removed: int = 0  # abandoned snap_*.tmp dirs swept
    # elastic reconfiguration (serve/reshard.py): shard-map changes
    # re-applied from journal commit records, plus any torn reshard
    # whose committed manifest this recovery rolled FORWARD
    reshard_retired: list[int] = field(default_factory=list)
    reshard_docs_moved: int = 0  # restored residents demoted off them
    reshard_completed: bool = False  # a torn manifest was resolved


@durable_protocol("snapshot")
def recover_fleet(pool, streams, journal_dir: str) -> RecoveryReport:  # graftlint: durable=snapshot
    """Restore a crashed fleet into a FRESH pool + stream set (built by
    the same ``prepare_streams`` the original run used): complete any
    GC pass torn by the crash, sweep abandoned staging directories,
    materialize the newest snapshot whose whole chain verifies
    (delta → older delta → full root), re-apply journaled
    quarantine/shed decisions from the tail, and leave cursors at the
    chosen barrier so resumed serving replays the journal tail through
    the normal macro-round path.  Falls back down the chain — and
    across chains — on damage, and to a cold start (round 0) when
    nothing is usable: per-doc streams are deterministic, so the fleet
    is recoverable from nothing but the workload."""
    report = RecoveryReport()
    report.gc_segments_completed = finish_torn_gc(journal_dir)
    report.staging_removed = len(sweep_staging(journal_dir))
    records, dropped = read_journal(journal_dir)
    report.torn_records = dropped
    report.records = len(records)

    # ---- newest snapshot whose chain verifies end to end ----
    manifest = None
    manifests: dict = {}
    for snap in reversed(list_snapshots(journal_dir)):
        sd = os.path.join(journal_dir, snap)
        try:
            m, states, members = load_chain_states(
                journal_dir, snap, manifests
            )
        except _RECOVER_ERRORS:
            report.chain_fallbacks += 1
            continue
        try:
            _restore_snapshot(pool, streams, sd, m, states)
        except _RECOVER_ERRORS:
            _reset_fleet(pool, streams)
            report.chain_fallbacks += 1
            continue
        manifest = m
        report.snapshot_dir = sd
        report.snapshot_round = int(m["round"])
        report.docs_restored = len(m["resident"])
        report.spools_restored = len(m["spooled"])
        report.warm_restored = len(m.get("warm", {}))
        report.chain_depth = len(members)
        pool.recount_cold()  # bulk restore wrote spools directly
        break

    # ---- journal tail: redo span + re-applied decisions ----
    snap_round = report.snapshot_round
    high: dict[int, int] = {}
    max_r = snap_round
    for rec in records:
        r = int(rec.get("r", -1))
        if rec["t"] == "round":
            max_r = max(max_r, r)
            # the barrier round value is the clock AFTER the last
            # snapshotted round advanced, so a record with r == the
            # snapshot round was journaled after the barrier: redo it
            if r < snap_round:
                continue  # already durable in the snapshot
            for spans in rec["lanes"].values():
                for doc, _start, end in spans:
                    high[int(doc)] = max(high.get(int(doc), 0), int(end))
        elif rec["t"] in ("quarantine", "shed") and r >= snap_round:
            doc = int(rec["doc"])
            st = streams.get(doc)
            if st is None:
                continue
            lim = int(rec["at"])
            st.limit = lim if st.limit is None else min(st.limit, lim)
            st.lossy = True
            report.shed_ops += int(rec.get("ops", 0))
            if rec["t"] == "quarantine":
                report.quarantined.append(doc)
    for doc, hw in high.items():
        st = streams.get(doc)
        if st is None:
            continue
        report.ops_replayed += max(
            0, min(hw, st.n_total) - st.cursor
        )

    # ---- elastic shard map: committed reshards are settled history
    # (their shards re-retire, restored residents from OLDER snapshots
    # are demoted off them), and a torn reshard — committed manifest,
    # no commit record — rolls FORWARD deterministically.  AFTER the
    # snapshot restore: _restore_snapshot places docs by the OLD map.
    from .reshard import recover_torn_reshard

    rs = recover_torn_reshard(pool, journal_dir, records)
    report.reshard_retired = rs["retired"]
    report.reshard_docs_moved = rs["moved"]
    report.reshard_completed = rs["completed"]

    report.resume_round = max(0, max_r + 1)
    return report


def _reset_fleet(pool, streams) -> None:
    """Undo a partially applied snapshot restore (damage discovered
    mid-restore): drop all residency/cursor state back to cold."""
    warm_tier = getattr(pool, "warm", None)
    for rec in pool.docs.values():
        if rec.cls is not None:
            b = pool.buckets[rec.cls]
            b.rows[rec.row] = None
            b.release_row(rec.row)
        rec.cls = rec.row = None
        rec.spool = None  # bulk reset; recount below restores the counter
        rec.length = rec.n_init
        rec.last_sched = -1
        if warm_tier is not None:
            warm_tier.take(rec.doc_id)
    for st in streams.values():
        st.cursor = 0
        st.limit = None
        st.lossy = False
        if st.delivered is not None:
            st.delivered = 0
    pool.recount_cold()


def _restore_snapshot(pool, streams, snap_dir: str, manifest: dict,
                      states: dict) -> None:
    """Apply one materialized snapshot (``states`` = chain-composed
    per-class host arrays) to a fresh pool/streams.  Raises
    CorruptCheckpointError on any damaged spool member... the caller
    falls back down the chain."""
    by_class: dict[int, list[tuple[int, int]]] = {}
    for key, (cls, row) in manifest["resident"].items():
        by_class.setdefault(int(cls), []).append((int(key), int(row)))
    for cls, docs in by_class.items():
        b = pool.buckets[cls]
        st_doc, st_len, st_nvis = states[cls]
        doc_w = np.full((b.R, b.C), 2, np.int32)
        len_w = np.zeros(b.R, np.int32)
        nvis_w = np.zeros(b.R, np.int32)
        for doc_id, row in docs:
            doc_w[row] = np.asarray(st_doc[row], np.int32)
            len_w[row] = int(st_len[row])
            nvis_w[row] = int(st_nvis[row])
            b.rows[row] = doc_id
            b.take_row(row)
            rec = pool.docs[doc_id]
            rec.cls, rec.row = cls, row
        pool.upload_bucket(cls, doc_w, len_w, nvis_w)
    # spool members: damage here degrades ONE doc to a cold restart
    # (deterministic streams make a from-scratch replay byte-exact), it
    # does not void the rest of the snapshot
    damaged: set[int] = set()
    for key, fname in manifest["spooled"].items():
        doc_id = int(key)
        src = os.path.join(snap_dir, fname)
        try:
            load_state(src)  # verify BEFORE adopting
        except CorruptCheckpointError:
            damaged.add(doc_id)
            continue
        rec = pool.docs[doc_id]
        rec.spool = pool._spool_path(doc_id)  # bulk restore; recount below
        shutil.copy2(src, rec.spool)
    # warm members (tiered pool): restored back into the warm tier
    # when the recovering pool has one (shadowed by the copied member,
    # so a later demotion is free); a warm-less pool — or a damaged
    # member — degrades them to cold / cold-restart, same ladder as
    # spooled members.
    for key, fname in manifest.get("warm", {}).items():
        doc_id = int(key)
        src = os.path.join(snap_dir, fname)
        try:
            st = load_state(src)
        except CorruptCheckpointError:
            damaged.add(doc_id)
            continue
        rec = pool.docs[doc_id]
        dst = pool._spool_path(doc_id)
        shutil.copy2(src, dst)
        warm_tier = getattr(pool, "warm", None)
        if warm_tier is not None and warm_tier.budget > 0:
            pool.warm_restore(
                doc_id, np.asarray(st.doc[0], np.int32),
                int(st.length[0]), int(st.nvis[0]), shadow=dst,
            )
        else:
            rec.spool = dst  # bulk restore; recount below
    for key, d in manifest["docs"].items():
        doc_id = int(key)
        st = streams.get(doc_id)
        if st is None:
            continue
        st.cursor = 0 if doc_id in damaged else int(d["c"])
        st.limit = d["lim"]
        st.lossy = bool(d["lossy"])
        if st.delivered is not None:
            st.delivered = st.cursor
        rec = pool.docs[doc_id]
        rec.length = rec.n_init + st.ins_before(st.cursor)
        rec.last_sched = int(manifest["round"])
