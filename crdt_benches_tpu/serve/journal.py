"""Write-ahead op journal + fleet snapshot barriers + crash recovery.

CRDTs exist to stay available under faults; this module makes the serve/
fleet *provably* recoverable: after any crash, recovery restores the
last consistent snapshot set and replays the journal tail through the
existing macro-round path, and the oracle byte-verify confirms the
result is exactly the converged state an uninterrupted run produces.

Three persistent artifacts live under one journal directory:

- **op journal** (``journal.log``): an append-only record stream.  Every
  macro-round, the scheduler journals the per-class lane set — one
  ``(doc, start_cursor, end_cursor)`` triple per scheduled document —
  BEFORE dispatching the staged tensors (write-ahead).  Because every
  doc's op stream is deterministic host data, a cursor interval IS the
  op batch: replaying ``[start, end)`` of the stream reproduces the
  exact device work.  Records are one line each, ``<crc32hex> <json>``;
  a torn tail (crash mid-write) fails CRC/JSON and is dropped at read
  time, never propagated.  Quarantine / load-shed decisions are also
  journaled — they change what the converged state *is*, so recovery
  must re-apply them.
- **snapshot barriers** (``snap_<round>/``): every ``snapshot_every``
  macro-rounds the scheduler pulls each bucket once (a sync barrier —
  the same boundary discipline as row moves), writes one CRC-verified
  ``.npz`` per capacity class plus copies of every live eviction spool,
  and commits the set atomically by renaming the staging directory.
  A snapshot bounds the journal tail a recovery must replay.
- **recovery** (:func:`recover_fleet`): pick the newest loadable
  snapshot (older ones are fallbacks; cold start from round 0 is the
  last resort — streams are deterministic, so a fleet is recoverable
  from nothing), restore residency/cursors/spools into a fresh pool,
  re-apply journaled quarantine/shed decisions from the tail, and
  report the redo span (``ops_replayed``).  Resumed serving then drives
  the tail through the normal macro-round path.

:func:`rebuild_doc` is the in-run repair primitive shared by the
scheduler's fault handling (corrupt spool, device-state loss): rebuild
one document's row at cursor ``target`` from a base state at cursor
``start`` by replaying the stream interval through the same
scan-of-slices dispatch shape the macro engine uses.
"""

from __future__ import annotations

import json
import os
import shutil
import zlib
from dataclasses import dataclass, field

import numpy as np

from ..lint.race_sanitizer import published
from ..obs.metrics import Counter
from ..traces.tensorize import PAD
from ..utils.checkpoint import (
    CorruptCheckpointError,
    load_state,
    save_state,
)

SNAP_PREFIX = "snap_"


# ---------------------------------------------------------------------------
# the op journal (append-only, CRC-framed JSON lines)
# ---------------------------------------------------------------------------


class OpJournal:  # graftlint: thread=hot
    """Append-only write-ahead journal.  One record per line:
    ``<crc32 of payload, 8 hex chars> <compact json payload>``.

    Thread confinement (G014-G016 audit, ISSUE 10): the journal writer
    is owned by the hot thread — WAL appends happen inside the
    macro-round (write-ahead of dispatch) and recovery readers run
    before a drain starts, on the same thread.  Nothing here may be
    touched from the status/bus threads; when the tiered-residency
    prefetch work moves journaling off-thread, the handoff must become
    a declared publish point (a bounded queue), not shared file-handle
    state.

    ``fsync=True`` makes every record durable before the append returns
    (the strict WAL discipline); the default leaves flushing to the OS —
    a lost *suffix* is exactly what recovery tolerates, torn or not.

    Reopening an existing log first truncates any torn tail: appending
    new records BEHIND a damaged line would hide them from the next
    recovery (readers stop at the first bad line)."""

    def __init__(self, journal_dir: str, fsync: bool = False):
        os.makedirs(journal_dir, exist_ok=True)
        self.dir = journal_dir
        self.path = os.path.join(journal_dir, "journal.log")
        self.fsync = fsync
        if os.path.exists(self.path):
            good = _valid_prefix_bytes(self.path)
            if good < os.path.getsize(self.path):
                with open(self.path, "r+b") as f:
                    f.truncate(good)
        self._f = open(self.path, "a", encoding="utf-8")
        self._m_records = Counter("serve.journal.records")
        self._m_bytes = Counter("serve.journal.bytes")
        self._m_snap_bytes = Counter("serve.journal.snapshot_bytes")

    def bind_metrics(self, registry) -> None:
        """Attach the journal's counters to a drain's MetricsRegistry."""
        registry.attach(self._m_records)
        registry.attach(self._m_bytes)
        registry.attach(self._m_snap_bytes)

    @property
    def records(self) -> int:
        return self._m_records.value

    @property
    def bytes_written(self) -> int:
        return self._m_bytes.value

    @property
    def bytes_total(self) -> int:
        """WAL bytes plus committed snapshot bytes — the journal's full
        on-disk footprint rate, which is what the soak leak detector
        watches (WAL bytes alone would hide snapshot bloat)."""
        return self._m_bytes.value + self._m_snap_bytes.value

    def note_snapshot(self, snap_dir: str) -> int:
        """Account a committed snapshot barrier's on-disk bytes (walked
        once per barrier — cold path).  Hard-linked spool members count
        at full size: the number tracks what a recovery would read, not
        unique blocks."""
        total = 0
        for root, _dirs, files in os.walk(snap_dir):
            for f in files:
                try:
                    total += os.path.getsize(os.path.join(root, f))
                except OSError:
                    pass  # pruned concurrently by keep= rotation
        self._m_snap_bytes.inc(total)
        return total

    def append(self, obj: dict) -> None:
        payload = json.dumps(obj, separators=(",", ":"))
        line = f"{zlib.crc32(payload.encode()):08x} {payload}\n"
        self._f.write(line)
        self._f.flush()
        if self.fsync:
            os.fsync(self._f.fileno())
        self._m_records.inc()
        self._m_bytes.inc(len(line))

    @published
    def round_record(  # graftlint: publish=journal
        self, rnd: int, lanes: dict[int, list[tuple[int, int, int]]]
    ) -> None:
        """The write-ahead record for one macro-round: per class, the
        ``[doc, start_cursor, end_cursor]`` of every scheduled lane.
        MUST be appended before the round's dispatch.

        Declared a publish point (``publish=journal``): the WAL append
        is where a round's lane set leaves the hot thread's live state
        and becomes durable — the journal-replay reader consumes it in
        another lifetime (and, when the tiered-residency work moves
        journaling off-thread, this point becomes the real queue
        handoff).  Entries are counted in every journaled run (G017
        ground truth) and request traces record the hop as their WAL
        propagation edge (obs/reqtrace.py)."""
        self.append({
            "t": "round",
            "r": rnd,
            "lanes": {str(c): spans for c, spans in lanes.items()},
        })

    def event(self, kind: str, **fields) -> None:
        self.append({"t": kind, **fields})

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()


def _valid_prefix_bytes(path: str) -> int:
    """Byte length of the longest CRC-valid record prefix of a journal
    file (everything from the first damaged line on is a torn tail)."""
    good = 0
    with open(path, "rb") as f:
        for raw in f:
            try:
                line = raw.decode("utf-8")
                crc_hex, payload = line.rstrip("\n").split(" ", 1)
                if int(crc_hex, 16) != zlib.crc32(payload.encode()):
                    break
                json.loads(payload)
            except (ValueError, UnicodeDecodeError, json.JSONDecodeError):
                break
            good += len(raw)
    return good


def read_journal(journal_dir: str) -> tuple[list[dict], int]:
    """All CRC-valid records, in order.  Reading stops at the first
    damaged line (a crash can only tear the TAIL of an append-only
    file); returns ``(records, dropped_lines)``."""
    path = os.path.join(journal_dir, "journal.log")
    records: list[dict] = []
    dropped = 0
    if not os.path.exists(path):
        return records, dropped
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        lines = f.readlines()
    for i, line in enumerate(lines):
        try:
            crc_hex, payload = line.rstrip("\n").split(" ", 1)
            if int(crc_hex, 16) != zlib.crc32(payload.encode()):
                raise ValueError("crc mismatch")
            records.append(json.loads(payload))
        except (ValueError, json.JSONDecodeError):
            dropped = len(lines) - i
            break
    return records, dropped


# ---------------------------------------------------------------------------
# snapshot barriers
# ---------------------------------------------------------------------------


def write_snapshot(journal_dir: str, pool, streams, rnd: int,
                   keep: int = 2) -> str:
    """One fleet snapshot: per-class bucket states (CRC'd .npz), copies
    of all live eviction spools, and a manifest of cursors/residency.
    The set is staged in ``<dir>.tmp`` with the manifest written LAST,
    then committed by a single directory rename — a crash mid-snapshot
    leaves only an ignorable ``.tmp`` directory, never a half snapshot
    that recovery could mistake for consistent."""
    from .pool import PackedState  # local: avoid import cycle at module load

    final = os.path.join(journal_dir, f"{SNAP_PREFIX}{rnd:08d}")
    tmp = final + ".tmp"
    shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(tmp)

    resident: dict[str, list[int]] = {}
    spooled: dict[str, str] = {}
    for doc_id, rec in pool.docs.items():
        if rec.cls is not None:
            resident[str(doc_id)] = [int(rec.cls), int(rec.row)]
        elif rec.spool is not None and os.path.exists(rec.spool):
            fname = f"doc{doc_id}.npz"
            dst = os.path.join(tmp, fname)
            # spools are immutable once written (save_state lands them
            # via os.replace, so a re-eviction swaps in a NEW inode):
            # hard-link the snapshot member instead of copying — a
            # thousands-of-cold-docs fleet barrier stays cheap
            try:
                os.link(rec.spool, dst)
            except OSError:  # cross-device / unsupported fs
                shutil.copy2(rec.spool, dst)
            spooled[str(doc_id)] = fname

    used_classes = sorted({int(v[0]) for v in resident.values()})
    for cls in used_classes:
        doc, length, nvis = pool.pull_bucket(cls)  # the sync barrier
        save_state(
            os.path.join(tmp, f"class_{cls}.npz"),
            PackedState(doc=doc, length=length, nvis=nvis),
            compress=False,
        )

    docs = {}
    for doc_id, st in streams.items():
        docs[str(doc_id)] = {
            "c": int(st.cursor),
            "lim": None if st.limit is None else int(st.limit),
            "lossy": bool(st.lossy),
        }
    manifest = {
        "round": int(rnd),
        "classes": used_classes,
        "resident": resident,
        "spooled": spooled,
        "docs": docs,
    }
    mtmp = os.path.join(tmp, "MANIFEST.tmp")
    with open(mtmp, "w", encoding="utf-8") as f:
        json.dump(manifest, f, separators=(",", ":"))
    os.replace(mtmp, os.path.join(tmp, "MANIFEST.json"))
    os.rename(tmp, final)  # the commit point

    for old in list_snapshots(journal_dir)[:-keep]:
        shutil.rmtree(os.path.join(journal_dir, old), ignore_errors=True)
    return final


def list_snapshots(journal_dir: str) -> list[str]:
    """Committed snapshot directory names, oldest first."""
    if not os.path.isdir(journal_dir):
        return []
    return sorted(
        d for d in os.listdir(journal_dir)
        if d.startswith(SNAP_PREFIX) and not d.endswith(".tmp")
        and os.path.isdir(os.path.join(journal_dir, d))
    )


def _read_manifest(snap_dir: str) -> dict | None:
    try:
        with open(os.path.join(snap_dir, "MANIFEST.json"),
                  encoding="utf-8") as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


class SnapshotBases:
    """Lazy, cached access to per-doc base states across the retained
    snapshots — the rebuild path's source of truth.  ``base(doc_id)``
    walks snapshots newest-first and returns the first intact base:
    ``(doc_row, length, nvis, cursor)`` with the row trimmed/padded to
    the caller's target capacity by :func:`rebuild_doc`.  Returns None
    when no snapshot holds the doc (fresh rebuild from cursor 0).

    Manifests are cached per snapshot (a class-loss recovery calls
    ``base`` once per resident doc); the per-class state cache can hold
    whole bucket arrays, so callers ``release()`` it once a recovery
    pass is done instead of pinning tens of MB for the run."""

    def __init__(self, journal_dir: str | None):
        self.dir = journal_dir
        self._class_cache: dict[str, object] = {}
        self._manifests: dict[str, dict | None] = {}

    def release(self) -> None:
        """Drop cached bucket states (and manifests — a new snapshot
        may have pruned old directories)."""
        self._class_cache.clear()
        self._manifests.clear()

    def _manifest(self, snap: str, sd: str) -> dict | None:
        if snap not in self._manifests:
            self._manifests[snap] = _read_manifest(sd)
        return self._manifests[snap]

    def base(self, doc_id: int):
        if self.dir is None:
            return None
        for snap in reversed(list_snapshots(self.dir)):
            sd = os.path.join(self.dir, snap)
            m = self._manifest(snap, sd)
            if m is None:
                continue
            key = str(doc_id)
            try:
                if key in m.get("resident", {}):
                    cls, row = m["resident"][key]
                    ck = f"{snap}/class_{cls}"
                    if ck not in self._class_cache:
                        self._class_cache[ck] = load_state(
                            os.path.join(sd, f"class_{cls}.npz")
                        )
                    st = self._class_cache[ck]
                    return (
                        np.array(st.doc[row]),
                        int(st.length[row]),
                        int(st.nvis[row]),
                        int(m["docs"][key]["c"]),
                    )
                if key in m.get("spooled", {}):
                    st = load_state(
                        os.path.join(sd, m["spooled"][key])
                    )
                    return (
                        np.array(st.doc[0]),
                        int(st.length[0]),
                        int(st.nvis[0]),
                        int(m["docs"][key]["c"]),
                    )
            except CorruptCheckpointError:
                continue  # damaged snapshot member: fall back to older
        return None


# ---------------------------------------------------------------------------
# targeted rebuild: replay a stream interval through the macro scan path
# ---------------------------------------------------------------------------

_REPLAYERS: dict[tuple, object] = {}


def _replayer(C: int, B: int, K: int, nbits: int):
    """The jitted single-row macro replayer for one (capacity, batch,
    depth, nbits) shape: a ``lax.scan`` over K slices of (1, B) range
    ops — the same resolve/apply body as ``DocPool.macro_step``, on a
    one-row stack.  Cached per shape (the recovery path's compile cost
    is paid once)."""
    key = (C, B, K, nbits)
    if key not in _REPLAYERS:
        import jax

        from ..engine.merge_fleet import merge_rows_body

        def body(st, sl):
            k, p, ln, s0 = sl
            return merge_rows_body(st, k, p, ln, s0, nbits=nbits), None

        def fn(state, kind, pos, rlen, slot0):
            out, _ = jax.lax.scan(body, state, (kind, pos, rlen, slot0))
            return out

        _REPLAYERS[key] = jax.jit(fn, donate_argnums=(0,))
    return _REPLAYERS[key]


def _pad_row(row: np.ndarray, C: int) -> np.ndarray:
    """Pad/keep a doc row to capacity ``C`` with the beyond-length
    coding ``2`` (trimmed spools and smaller-class bases)."""
    row = np.asarray(row, np.int32)
    if len(row) >= C:
        return row[:C]
    return np.concatenate([row, np.full(C - len(row), 2, np.int32)])


def rebuild_doc(
    stream,
    C: int,
    base,  # (doc_row, length, nvis, base_cursor) or None for fresh
    target: int,
    *,
    n_init: int,
    batch: int,
    batch_chars: int,
    nbits: int,
    macro_k: int = 1,
) -> tuple[np.ndarray, int, int, int]:
    """Rebuild one document's row state at cursor ``target`` by
    replaying stream ops ``[base_cursor, target)`` over the base state,
    through the macro scan dispatch shape.  Returns
    ``(doc_row[C], length, nvis, dispatches)`` — ``dispatches`` is the
    macro-round-equivalent count (the MTTR unit).

    Ops at indices below the base cursor are never re-applied — the
    cursor IS the idempotence high-water mark, the same dedup rule the
    scheduler uses for redelivered batches."""
    import jax.numpy as jnp

    from .pool import PackedState, _fresh_row_np

    if base is None:
        doc_row, length, nvis, c = _fresh_row_np(C, n_init), n_init, n_init, 0
    else:
        doc_row, length, nvis, c = base
        doc_row = _pad_row(doc_row, C)
    c = max(0, min(int(c), target))
    state = PackedState(
        doc=jnp.asarray(doc_row[None]),
        length=jnp.asarray([length], jnp.int32),
        nvis=jnp.asarray([nvis], jnp.int32),
    )
    K = max(1, macro_k)
    dispatches = 0
    while c < target:
        kind = np.full((K, 1, batch), PAD, np.int32)
        pos = np.zeros((K, 1, batch), np.int32)
        rlen = np.zeros((K, 1, batch), np.int32)
        slot0 = np.full((K, 1, batch), -1, np.int32)
        for k in range(K):
            if c >= target:
                break  # trailing slices stay PAD (no-ops)
            # the scheduler's slice-budget rule, verbatim (DocStream)
            e = stream.slice_end(c, batch, batch_chars, target)
            take = e - c
            kind[k, 0, :take] = stream.kind[c:e]
            pos[k, 0, :take] = stream.pos[c:e]
            rlen[k, 0, :take] = stream.rlen[c:e]
            slot0[k, 0, :take] = stream.slot0[c:e]
            c = e
        state = _replayer(C, batch, K, nbits)(
            state,
            jnp.asarray(kind), jnp.asarray(pos),
            jnp.asarray(rlen), jnp.asarray(slot0),
        )
        dispatches += 1
    return (
        np.asarray(state.doc[0]),
        int(np.asarray(state.length[0])),
        int(np.asarray(state.nvis[0])),
        dispatches,
    )


# ---------------------------------------------------------------------------
# crash recovery
# ---------------------------------------------------------------------------


@dataclass
class RecoveryReport:
    """What a :func:`recover_fleet` run found and did."""

    snapshot_round: int = -1  # -1 = cold start (no usable snapshot)
    snapshot_dir: str | None = None
    resume_round: int = 0
    docs_restored: int = 0  # residency/cursor restored from the snapshot
    spools_restored: int = 0
    ops_replayed: int = 0  # journal-tail redo span (snap cursor -> WAL tip)
    torn_records: int = 0  # damaged journal tail lines dropped
    quarantined: list[int] = field(default_factory=list)
    shed_ops: int = 0
    records: int = 0


def recover_fleet(pool, streams, journal_dir: str) -> RecoveryReport:
    """Restore a crashed fleet into a FRESH pool + stream set (built by
    the same ``prepare_streams`` the original run used): load the newest
    intact snapshot, re-apply journaled quarantine/shed decisions from
    the tail, and leave cursors at the snapshot barrier so resumed
    serving replays the journal tail through the normal macro-round
    path.  Falls back to older snapshots on damage, and to a cold start
    (round 0) when none is usable — per-doc streams are deterministic,
    so the fleet is recoverable from nothing but the workload."""
    report = RecoveryReport()
    records, dropped = read_journal(journal_dir)
    report.torn_records = dropped
    report.records = len(records)

    # ---- newest intact snapshot ----
    manifest = None
    for snap in reversed(list_snapshots(journal_dir)):
        sd = os.path.join(journal_dir, snap)
        m = _read_manifest(sd)
        if m is None:
            continue
        try:
            _restore_snapshot(pool, streams, sd, m)
        except CorruptCheckpointError:
            _reset_fleet(pool, streams)
            continue
        manifest = m
        report.snapshot_dir = sd
        report.snapshot_round = int(m["round"])
        report.docs_restored = len(m["resident"])
        report.spools_restored = len(m["spooled"])
        break

    # ---- journal tail: redo span + re-applied decisions ----
    snap_round = report.snapshot_round
    high: dict[int, int] = {}
    max_r = snap_round
    for rec in records:
        r = int(rec.get("r", -1))
        if rec["t"] == "round":
            max_r = max(max_r, r)
            # the barrier round value is the clock AFTER the last
            # snapshotted round advanced, so a record with r == the
            # snapshot round was journaled after the barrier: redo it
            if r < snap_round:
                continue  # already durable in the snapshot
            for spans in rec["lanes"].values():
                for doc, _start, end in spans:
                    high[int(doc)] = max(high.get(int(doc), 0), int(end))
        elif rec["t"] in ("quarantine", "shed") and r >= snap_round:
            doc = int(rec["doc"])
            st = streams.get(doc)
            if st is None:
                continue
            lim = int(rec["at"])
            st.limit = lim if st.limit is None else min(st.limit, lim)
            st.lossy = True
            report.shed_ops += int(rec.get("ops", 0))
            if rec["t"] == "quarantine":
                report.quarantined.append(doc)
    for doc, hw in high.items():
        st = streams.get(doc)
        if st is None:
            continue
        report.ops_replayed += max(
            0, min(hw, st.n_total) - st.cursor
        )
    report.resume_round = max(0, max_r + 1)
    return report


def _reset_fleet(pool, streams) -> None:
    """Undo a partially applied snapshot restore (damage discovered
    mid-restore): drop all residency/cursor state back to cold."""
    for rec in pool.docs.values():
        if rec.cls is not None:
            b = pool.buckets[rec.cls]
            b.rows[rec.row] = None
            b.release_row(rec.row)
        rec.cls = rec.row = None
        rec.spool = None
        rec.length = rec.n_init
        rec.last_sched = -1
    for st in streams.values():
        st.cursor = 0
        st.limit = None
        st.lossy = False
        if st.delivered is not None:
            st.delivered = 0


def _restore_snapshot(pool, streams, snap_dir: str, manifest: dict) -> None:
    """Apply one snapshot to a fresh pool/streams.  Raises
    CorruptCheckpointError on any damaged member (caller falls back)."""
    # per-class bucket states first (so damage aborts before bookkeeping)
    states = {
        cls: load_state(os.path.join(snap_dir, f"class_{cls}.npz"))
        for cls in manifest["classes"]
    }
    by_class: dict[int, list[tuple[int, int]]] = {}
    for key, (cls, row) in manifest["resident"].items():
        by_class.setdefault(int(cls), []).append((int(key), int(row)))
    for cls, docs in by_class.items():
        b = pool.buckets[cls]
        st = states[cls]
        doc_w = np.full((b.R, b.C), 2, np.int32)
        len_w = np.zeros(b.R, np.int32)
        nvis_w = np.zeros(b.R, np.int32)
        for doc_id, row in docs:
            doc_w[row] = np.asarray(st.doc[row], np.int32)
            len_w[row] = int(st.length[row])
            nvis_w[row] = int(st.nvis[row])
            b.rows[row] = doc_id
            b.take_row(row)
            rec = pool.docs[doc_id]
            rec.cls, rec.row = cls, row
        pool.upload_bucket(cls, doc_w, len_w, nvis_w)
    # spool members: damage here degrades ONE doc to a cold restart
    # (deterministic streams make a from-scratch replay byte-exact), it
    # does not void the rest of the snapshot
    damaged: set[int] = set()
    for key, fname in manifest["spooled"].items():
        doc_id = int(key)
        src = os.path.join(snap_dir, fname)
        try:
            load_state(src)  # verify BEFORE adopting
        except CorruptCheckpointError:
            damaged.add(doc_id)
            continue
        rec = pool.docs[doc_id]
        rec.spool = pool._spool_path(doc_id)
        shutil.copy2(src, rec.spool)
    for key, d in manifest["docs"].items():
        doc_id = int(key)
        st = streams.get(doc_id)
        if st is None:
            continue
        st.cursor = 0 if doc_id in damaged else int(d["c"])
        st.limit = d["lim"]
        st.lossy = bool(d["lossy"])
        if st.delivered is not None:
            st.delivered = st.cursor
        rec = pool.docs[doc_id]
        rec.length = rec.n_init + st.ins_before(st.cursor)
        rec.last_sched = int(manifest["round"])
