"""DocPool: N independent documents in a few batched device states.

Every replay engine in this repo batches over a *replica* axis — R copies
of the same document consuming the same op stream.  The pool re-purposes
that axis as a **document** axis: each row of a ``PackedState`` stack is a
different document with its own ``length``/``nvis`` lane, its own slot-id
space, and its own op stream.  Per-row independence is exactly what the
range-op machinery already provides:

- ``ops/resolve_range_scan.py`` resolves a *different* range batch per
  row under vmap (the Pallas kernel shares one stream across rows);
- ``ops/apply_range.py apply_range_batch`` is row-local throughout.

Documents are bucketed by **capacity class** (e.g. 256 / 1024 / 4096
slots): a small doc must not pay a 4096-wide apply pass, so each class is
its own (R_class, C_class) stack.  Docs are admitted into a free row of
their class, **promoted** to the next class when their slot need outgrows
the current one (capacity need is host-known: n_init + cumulative insert
count, so promotion never requires a device sync), and evicted when
their bucket is full — cold docs rehydrate into *any* free row later.

Residency is an explicit THREE-tier story (``warm_docs > 0``):

- **hot** — the device-resident capacity-class rows above;
- **warm** — a bounded pinned-host tier (:class:`WarmTier`) of
  ready-to-upload packed rows (numpy, class-shaped, trimmed to their
  used prefix).  Evictions land here as pure host copies — no disk
  I/O — and a warm admission is a memory compose, LRU-by-last-scheduled
  eviction demotes overflow to cold;
- **cold** — the checkpoint spool (``utils/checkpoint.py`` .npz),
  COMPRESSED for cold-tier writes (the deflate cost is off the hot
  eviction path now that evictions land warm).  A cold admission pays
  the synchronous rehydrate — unless the predictive prefetcher
  (serve/prefetch.py, armed with the warm tier) already rehydrated the
  doc into warm ahead of the scheduler's admission plan.

With ``warm_docs == 0`` the pool is exactly the historical two-tier
store (hot rows + uncompressed spool): the tier machinery costs nothing
when everything fits.

The serving hot path is the **macro step**: K rounds of per-class
``(R, B)`` range-op tensors staged (in packed narrow lane dtypes —
``ops/packing.py``) and applied with donated device state through one of
two byte-identical kernels (``serve_kernel``): the default **fused**
path (``ops/serve_fused.py`` — shape-shared resolve executables, a
per-round host-tuned apply off TPU, one VMEM-resident ``pallas_call``
for all K rounds on TPU) or the legacy **scan** path (one jitted
``lax.scan`` whose body resolves + applies, compiled per shape).
Either way the device, not the Python round loop, owns the steady
state.  Because mean lane occupancy in a serving fleet is low, the step
runs on a **row-tier slice** of the stack: the scheduler compacts the
macro-round's active documents into the first ``Rt`` rows (per shard,
under a mesh) and the step slices/writes back around the dispatch, so
idle rows cost nothing.

The optional ``mesh`` shards every bucket's row (document) axis over the
``parallel/mesh.py`` replica mesh axis — the docs-over-mesh layout.  All
per-row work in resolve/apply is row-local, so the step partitions with
zero collectives; row allocation is shard-aware so tier slices stay
balanced across devices.
"""

from __future__ import annotations

import heapq
import json
import os
import tempfile
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..engine.merge_fleet import merge_rows_body
from ..lint.boundary import boundary
from ..lint.sanitizer import fenced
from ..obs.metrics import Counter, Gauge
from .prefetch import Prefetcher
from ..ops.apply2 import LANE, PackedState, apply_batch3
from ..ops.packing import NARROW_ID_BOUND, op_lane_dtypes, widen_ops
from ..ops.resolve import resolve_batch
from ..ops.serve_fused import (
    NARROW_RESOLVE_OPS,
    RESOLVE_CHUNK_ROWS,
    AotJit,
    resolve_round_rows_grow,
    resolve_round_rows_padded,
    round_starts,
    round_total_delta,
    serve_apply_round_xla,
    serve_fused_fits,
    serve_macro_fused,
    serve_macro_rounds_xla,
    trivial_round_tokens,
)
from ..lint import lifecycle_sanitizer as lifecycle
from ..lint import range_sanitizer as range_rt
from ..lint.fs_sanitizer import fs_protocol
from ..traces.tensorize import PAD
from ..utils.checkpoint import (
    CorruptCheckpointError,
    load_state,
    save_state,
)
from ..utils.fsdur import fsync_dir

#: Two-phase spool GC manifest (drained-doc footprint reclamation):
#: the same commit-point discipline as the journal's GC_MANIFEST —
#: the manifest names every member about to die, so a crash mid-pass
#: is completed (not re-decided) on the next pool construction.
SPOOL_GC_MANIFEST = "SPOOL_GC_MANIFEST.json"

#: Garbage a manifest read must absorb (G020).
_SPOOL_GC_ERRORS = (OSError, ValueError, KeyError, TypeError)

#: Serve-step kernel selections (`--serve-kernel`): "fused" = the
#: ops/serve_fused.py path (shared resolve executables, host-tuned
#: apply off TPU, the single-pallas_call macro kernel on TPU); "scan" =
#: the PR 2 lax.scan body (resolve + apply per scanned round in one
#: jit per shape) kept as the differential baseline.
SERVE_KERNELS = ("fused", "scan")


@boundary(
    dtypes=("int32", "int32", "int32", "int32"),
    shapes=(None, "R B", "R B", "R B"),
    donates=(0,),
)
@partial(jax.jit, donate_argnums=(0,))
def fleet_step(state: PackedState, kind, pos, slot) -> PackedState:
    """One UNIT-op batch per resident doc (the pre-macro hot path, kept
    for API compatibility and as the minimal single-round reference).

    ``kind``/``pos``/``slot``: int32[R, B], row r = the next B ops of the
    doc in row r (``kind == PAD`` everywhere for idle rows — a no-op end
    to end, the fixed-shape padding the scheduler relies on).
    """
    resolved = jax.vmap(resolve_batch)(kind, pos, state.nvis)
    return apply_batch3(state, resolved, slot)


@partial(jax.jit, donate_argnums=(0,))
def _write_row(state: PackedState, row, doc, length, nvis) -> PackedState:
    # graftlint: inrange=row<nrows check=pool.write-row
    # (row is a host int validated against the bucket's row count by
    # range_sanitizer.check_index at _install, the only caller — an
    # out-of-range row here would silently DROP the write)
    return PackedState(
        doc=state.doc.at[row].set(doc),
        length=state.length.at[row].set(length),
        nvis=state.nvis.at[row].set(nvis),
    )


@jax.jit
def _read_row(state: PackedState, row):
    return state.doc[row], state.length[row], state.nvis[row]


def _fresh_row_np(C: int, n_init: int) -> np.ndarray:
    """A fresh document row: slots 0..n_init-1 visible in order, the rest
    the beyond-length coding ``pack_doc(-1, 0) == 2`` (matches
    ``ops/apply2.py init_state3`` for one replica)."""
    idx = np.arange(C, dtype=np.int32)
    return np.where(idx < n_init, ((idx + 2) << 1) | 1, 2).astype(np.int32)


def decode_row_np(doc: np.ndarray, length: int, nvis: int,
                  chars: np.ndarray) -> str:
    """Host-side decode of one packed doc row (the numpy twin of
    ``ops/apply2.py decode_state3`` for a single row — off the hot path,
    used for verification and spool inspection)."""
    order = (doc[:length] >> 1) - 2
    vis = (doc[:length] & 1).astype(bool)
    slots = order[vis]
    assert len(slots) == nvis, f"decode: {len(slots)} visible != nvis {nvis}"
    return "".join(chr(int(c)) for c in chars[slots])


@dataclass
class DocRecord:  # graftlint: state=doc field=spool states=live,cold edges=live->cold,cold->live
    """Host-side bookkeeping for one document (no device syncs needed to
    schedule it: length/capacity evolve deterministically with the
    stream, so the scheduler promotes/admits from host state alone).

    The ``spool`` field is a declared lifecycle state machine on the
    cold-tier axis (``live`` = no spool claim, ``cold`` = checkpointed
    out): every write MUST route through ``DocPool._set_spool`` — the
    ``_n_cold`` counter the tier gauges read is maintained there, so a
    direct write silently drifts the cold-doc accounting (exactly the
    bug G022 caught in ``admit``'s restore path)."""

    doc_id: int
    n_init: int
    capacity_need: int  # n_init + total inserted chars of the full stream
    chars: np.ndarray  # int32[capacity_need] slot -> codepoint
    length: int = 0  # host mirror of device length (slots used)
    cls: int | None = None  # resident capacity class (None = cold)
    row: int | None = None
    spool: str | None = None  # checkpoint path when evicted
    last_sched: int = -1  # round counter, for LRU eviction


class Bucket:
    """One capacity class: a PackedState stack whose rows are docs.

    Row allocation is **shard-aware**: row ``r`` lives on mesh shard
    ``r // (R / n_sh)``.  Free rows are per-shard min-heaps (with a lazy
    invalidation set so the scheduler can claim *specific* rows for
    compaction), and fresh allocations balance shards while preferring
    the lowest local index — keeping the occupied set packed toward the
    front of every shard, which is what makes tier slicing effective.
    """

    def __init__(self, C: int, R: int, n_sh: int = 1, sharding=None):
        self.C = C
        self.R = R
        self.n_sh = n_sh
        self.Rg = R // n_sh  # rows per shard
        state = PackedState(
            doc=jnp.full((R, C), 2, jnp.int32),
            length=jnp.zeros(R, jnp.int32),
            nvis=jnp.zeros(R, jnp.int32),
        )
        if sharding is not None:
            state = jax.tree.map(lambda x: jax.device_put(x, sharding), state)
        self.state = state
        self.rows: list[int | None] = [None] * R  # row -> doc_id
        self._heaps: list[list[int]] = [
            list(range(self.Rg)) for _ in range(n_sh)
        ]
        self._free: list[set[int]] = [
            set(range(self.Rg)) for _ in range(n_sh)
        ]
        #: elastic shard map (serve/reshard.py): allocation is confined
        #: to LIVE shards; a draining/retired shard keeps its physical
        #: rows (the device array never reshapes mid-run) but never
        #: receives another doc.  Residents of a draining shard still
        #: serve until their migration round.
        self.live: list[bool] = [True] * n_sh
        self.steps = 0

    # ---- row allocation ----

    @property
    def free(self) -> list[int]:
        """Free GLOBAL row ids (read-only view; kept for compatibility
        with callers that test emptiness / count)."""
        return [
            s * self.Rg + l for s in range(self.n_sh) for l in self._free[s]
        ]

    @property
    def n_free(self) -> int:
        return sum(len(f) for f in self._free)

    def free_locals(self, shard: int) -> set[int]:
        return self._free[shard]

    @property
    def n_free_live(self) -> int:
        """Free rows on LIVE shards — the allocatable supply.  Distinct
        from :attr:`n_free` (physical): ``hot_rows`` and the occupancy
        gauges count physical rows, the scheduler's make-room loop and
        the reshard coordinator count live ones."""
        return sum(
            len(f) for s, f in enumerate(self._free) if self.live[s]
        )

    @property
    def live_rows(self) -> int:
        """Physical row budget of the live shards."""
        return self.Rg * sum(self.live)

    @property
    def usable_rows(self) -> int:
        """Rows a round may schedule: every live row, plus the still-
        occupied rows of draining shards (their residents keep serving
        until migrated).  Free rows of non-live shards are the only
        exclusion — they can never be filled again."""
        return self.R - (self.n_free - self.n_free_live)

    def set_live(self, shard: int, flag: bool) -> None:
        self.live[shard] = bool(flag)

    def alloc_row(self) -> int:  # graftlint: acquire=rows
        """Lowest local index on the emptiest LIVE shard (ties ->
        lowest shard) — balances the mesh while packing rows toward the
        front.  Draining/retired shards never allocate."""
        lives = [i for i in range(self.n_sh) if self.live[i]]
        if not lives:
            raise RuntimeError(f"bucket c{self.C}: no live shard")
        s = max(lives, key=lambda i: (len(self._free[i]), -i))
        if not self._free[s]:
            raise RuntimeError(f"bucket c{self.C}: no free row")
        h = self._heaps[s]
        while h:
            l = heapq.heappop(h)
            if l in self._free[s]:
                self._free[s].discard(l)
                lifecycle.acquire("rows", (self.C, s * self.Rg + l))
                return s * self.Rg + l
        raise RuntimeError(f"bucket c{self.C}: free-heap drift")

    def take_row(self, row: int) -> None:  # graftlint: acquire=rows
        """Claim a SPECIFIC free row (compaction relocations)."""
        s, l = divmod(row, self.Rg)
        if l not in self._free[s]:
            raise RuntimeError(f"bucket c{self.C}: row {row} not free")
        self._free[s].discard(l)  # heap entry invalidated lazily
        lifecycle.acquire("rows", (self.C, row))

    def release_row(self, row: int) -> None:  # graftlint: release=rows
        s, l = divmod(row, self.Rg)
        self._free[s].add(l)
        heapq.heappush(self._heaps[s], l)
        lifecycle.release("rows", (self.C, row))


@dataclass
class WarmEntry:
    """One warm-tier document: a ready-to-upload packed row (host
    numpy, trimmed to its used ``length`` prefix — the tail is the
    constant beyond-length coding ``2`` that ``_install`` re-pads).
    Entries are IMMUTABLE once deposited (the doc's state only evolves
    while hot), which is what makes the ``shadow`` — a durable on-disk
    copy written lazily by snapshot barriers — valid for the entry's
    whole warm lifetime: a shadowed entry demotes to cold for free."""

    doc_row: np.ndarray
    length: int
    nvis: int
    origin: str = "evict"  # "evict" | "prefetch" | "recover"
    shadow: str | None = None  # durable spool copy (None = memory only)
    last_sched: int = -1  # LRU key: round the doc was last scheduled
    token: int = 0  # heap-entry invalidation tag


class WarmTier:
    """Bounded pinned-host tier: doc_id -> :class:`WarmEntry`, with
    LRU-by-last-scheduled eviction order.  The eviction heap is lazily
    invalidated (a doc re-deposited after a warm hit gets a new token;
    stale heap entries are skipped on pop), so put/take stay O(log n).
    Owned by the hot thread — the prefetch thread never touches it;
    prefetched rows arrive through the pool's harvest path."""

    def __init__(self, budget: int):
        self.budget = max(0, int(budget))
        self.entries: dict[int, WarmEntry] = {}
        self._heap: list[tuple[int, int, int]] = []  # (last_sched, doc, token)
        self._tokens = 0

    def __contains__(self, doc_id: int) -> bool:
        return doc_id in self.entries

    def __len__(self) -> int:
        return len(self.entries)

    def put(self, doc_id: int, entry: WarmEntry) -> None:
        self._tokens += 1
        entry.token = self._tokens
        self.entries[doc_id] = entry
        heapq.heappush(
            self._heap, (entry.last_sched, doc_id, entry.token)
        )

    def take(self, doc_id: int) -> WarmEntry | None:
        """Remove and return the doc's entry (heap entry invalidated
        lazily)."""
        return self.entries.pop(doc_id, None)

    def pop_lru(self) -> tuple[int, WarmEntry] | None:
        """Remove and return the least-recently-scheduled entry."""
        while self._heap:
            last_sched, doc_id, token = heapq.heappop(self._heap)
            e = self.entries.get(doc_id)
            if e is not None and e.token == token:
                del self.entries[doc_id]
                return doc_id, e
        return None

    def over_budget(self) -> int:
        return max(0, len(self.entries) - self.budget)


class DocPool:
    """The document fleet: buckets + admit/evict/promote + macro step.

    ``classes``: ascending capacity classes, each a multiple of 128 (the
    packed kernels tile by LANE).  ``slots``: resident rows per class.
    ``mesh``: optional ``parallel/mesh.py`` mesh; every bucket's row axis
    is then sharded over the mesh's replica axis (slots must divide by
    the mesh size).
    """

    def __init__(
        self,
        classes: tuple[int, ...] = (256, 1024, 4096, 8192, 49152),
        slots: tuple[int, ...] = (2048, 512, 128, 32, 16),
        mesh=None,
        spool_dir: str | None = None,
        serve_kernel: str = "fused",
        warm_docs: int = 0,
        prefetch: bool = True,
        prefetch_capacity: int = 256,
        shards: int | None = None,
    ):
        if len(classes) != len(slots):
            raise ValueError("classes and slots must have equal length")
        if serve_kernel not in SERVE_KERNELS:
            raise ValueError(
                f"unknown serve kernel {serve_kernel!r}"
                f" (expected one of {SERVE_KERNELS})"
            )
        if list(classes) != sorted(set(classes)):
            raise ValueError(f"classes must be ascending/unique: {classes}")
        for c in classes:
            if c % LANE:
                raise ValueError(f"capacity class {c} not a multiple of {LANE}")
        self._sharding = None
        self._op_sharding = None
        self.n_sh = 1
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ..parallel.mesh import AXIS, fleet_sharding

            n_dev = mesh.devices.size
            for r in slots:
                if r % n_dev:
                    raise ValueError(
                        f"bucket slots {r} not divisible by mesh size {n_dev}"
                    )
            self._sharding = fleet_sharding(mesh)
            # staged macro tensors (K, R, B): shard the row axis
            self._op_sharding = NamedSharding(mesh, P(None, AXIS, None))
            self.n_sh = n_dev
        if shards is not None:
            # logical shard map without (or validating) a device mesh:
            # reshard workloads and single-host tests exercise the full
            # topology machinery on one device
            if mesh is not None and shards != self.n_sh:
                raise ValueError(
                    f"shards={shards} conflicts with mesh size {self.n_sh}"
                )
            for r in slots:
                if r % shards:
                    raise ValueError(
                        f"bucket slots {r} not divisible by shards={shards}"
                    )
            self.n_sh = shards
        #: elastic shard lifecycle (serve/reshard.py): live -> draining
        #: (no allocation, residents still serve) -> retired (empty,
        #: closed); grow revives retired/pre-provisioned shards.
        self.shard_state: list[str] = ["live"] * self.n_sh
        self.classes = tuple(classes)
        self.buckets = {
            c: Bucket(c, r, self.n_sh, self._sharding)
            for c, r in zip(classes, slots)
        }
        self.docs: dict[int, DocRecord] = {}
        self._owns_spool = spool_dir is None
        self.spool_dir = spool_dir or tempfile.mkdtemp(prefix="crdt_serve_")
        os.makedirs(self.spool_dir, exist_ok=True)
        # adopt-time completion of a torn drained-doc GC pass: the
        # committed manifest is the predecessor's promise, kept before
        # any member could be re-read as live state
        self.finish_torn_spool_gc()
        self.serve_kernel = serve_kernel
        #: staged op-lane dtypes (ops/packing.py): static per pool, so
        #: every class shares one resolve executable and a quiet round
        #: can never flip dtypes mid-run
        self.op_dtypes = op_lane_dtypes(max(classes))
        self._macro_fns: dict[tuple, object] = {}
        # fused-path executable caches — keyed so compiles are SHARED:
        # the resolve depends only on (B,), the per-round apply on
        # (C, Rt, B, nbits) but not on K or the macro depth, the tier
        # slice/writeback on (cls, Rt).  The scan path recompiles its
        # whole body per (cls, K, Rt, B, nbits) — that compile spread
        # was ~55% of the serve/mixed/4096 wall time.
        self._starts_fns: dict[tuple, object] = {}
        self._resolve_fns: dict[tuple, object] = {}
        self._apply_fns: dict[tuple, object] = {}
        self._tier_takes: dict[tuple, object] = {}
        self._tier_puts: dict[tuple, object] = {}
        self._fused_tpu_fns: dict[tuple, object] = {}
        # counters (reported by the scheduler / bench): typed
        # obs/metrics.py Counters so a serve drain's registry carries
        # them in the artifact's metrics block (bind_metrics); the
        # int-valued properties below keep the historical accessors.
        self._counters = {
            name: Counter("serve.pool." + name)
            for name in ("evictions", "restores", "promotions",
                         "fresh_admits")
        }
        # ---- tiered residency (hot / pinned-host warm / compressed
        # cold).  warm_docs == 0 = the historical two-tier pool; > 0
        # arms the warm tier and (prefetch=True) the async prefetcher.
        # Counters/gauges are pre-registered HERE, off the hot path
        # (G013); the scheduler refreshes the gauges once per round.
        self.warm = WarmTier(warm_docs)
        for name in ("warm_hits", "warm_evictions", "prefetch_hits"):
            self._counters[name] = Counter("serve.tier." + name)
        self._gauges = {
            name: Gauge("serve.tier." + name)
            for name in ("hot_rows", "warm_docs", "cold_docs",
                         "genesis_docs", "prefetch_inflight")
        }
        #: docs born in GENESIS residency (streaming construction):
        #: specified by the fleet but never yet registered — no record,
        #: no row, no spool, no checkpoint, nothing resident.  Armed by
        #: :meth:`set_genesis_population`; every :meth:`register` moves
        #: one doc genesis → tracked.  0 when construction was eager.
        self._n_genesis = 0
        #: per-doc spool write generation: bumped at every spool_save,
        #: so an in-flight prefetch read can be recognized as stale at
        #: harvest (the doc was re-evicted while the read ran)
        self._spool_gens: dict[int, int] = {}
        #: live cold-tier population, maintained incrementally: every
        #: rec.spool transition routes through :meth:`_set_spool`, so
        #: the per-round gauge refresh never scans the fleet
        self._n_cold = 0
        # the doc residency machine's legal graph, mirrored from the
        # DocRecord marker — armed runs enforce it live, every run
        # counts its edges for the artifact's lifecycle block (G025)
        lifecycle.declare_machine(
            "doc", ("live", "cold"),
            (("live", "cold"), ("cold", "live")),
        )
        self.prefetcher: Prefetcher | None = None
        if warm_docs > 0 and prefetch:
            self.prefetcher = Prefetcher(capacity=prefetch_capacity)
            self.prefetcher.start()
        # per-row dirty tracking (durability v2): rows whose device
        # content changed since the last snapshot barrier.  Pure host
        # set arithmetic — delta snapshots persist exactly these rows,
        # and the barrier consumes the set (take_dirty).
        self._dirty: dict[int, set[int]] = {c: set() for c in classes}

    def bind_metrics(self, registry) -> None:
        """Attach this pool's counters to a drain's MetricsRegistry
        (identity-preserving: the pool keeps incrementing the same
        objects the registry now serializes)."""
        for c in self._counters.values():
            registry.attach(c)
        for g in self._gauges.values():
            registry.attach(g)

    @property
    def evictions(self) -> int:
        return self._counters["evictions"].value

    @evictions.setter
    def evictions(self, v: int) -> None:
        self._counters["evictions"].value = int(v)

    @property
    def restores(self) -> int:
        return self._counters["restores"].value

    @restores.setter
    def restores(self, v: int) -> None:
        self._counters["restores"].value = int(v)

    @property
    def promotions(self) -> int:
        return self._counters["promotions"].value

    @promotions.setter
    def promotions(self, v: int) -> None:
        self._counters["promotions"].value = int(v)

    @property
    def fresh_admits(self) -> int:
        return self._counters["fresh_admits"].value

    @fresh_admits.setter
    def fresh_admits(self, v: int) -> None:
        self._counters["fresh_admits"].value = int(v)

    @property
    def warm_hits(self) -> int:
        """Admissions served from the warm tier (no disk I/O)."""
        return self._counters["warm_hits"].value

    @property
    def prefetch_hits(self) -> int:
        """Warm hits whose entry the prefetcher deposited."""
        return self._counters["prefetch_hits"].value

    @property
    def warm_evictions(self) -> int:
        """Warm→cold demotions (LRU overflow or forced pressure)."""
        return self._counters["warm_evictions"].value

    # ---- dirty tracking (delta-snapshot substrate) ----

    def note_rows_dirty(self, cls: int, rows) -> None:
        """Mark rows of ``cls`` as touched since the last barrier."""
        self._dirty[cls].update(int(r) for r in rows)

    def take_dirty(self) -> dict[int, list[int]]:
        """Consume the dirty set: ``{cls: sorted rows}`` for classes
        with any dirty row, cleared as a unit — the snapshot barrier is
        the reset point (full barriers consume it too: they capture
        everything, so the chain restarts clean)."""
        out = {
            c: sorted(s) for c, s in self._dirty.items() if s
        }
        for s in self._dirty.values():
            s.clear()
        return out

    def dirty_rows(self, cls: int) -> set[int]:
        """Read-only view for tests/diagnostics."""
        return set(self._dirty[cls])

    def _mark_op_rows(self, cls: int, kind, Rt: int) -> None:
        """Mark the rows an op tensor actually touches.  ``kind`` is
        the staged host array ((K, Rt, B) or (R, B)); rows whose every
        lane is PAD are no-ops end to end and stay clean.  Tier-sliced
        indices map back to global rows via the shard layout.  A
        non-host tensor (direct jnp callers) marks the whole tier
        conservatively — correctness over delta size, and never a
        device sync on the hot path."""
        b = self.buckets[cls]
        dd = self._dirty[cls]
        if not isinstance(kind, np.ndarray):
            rows = range(Rt)
        elif kind.ndim == 3:
            rows = np.flatnonzero((kind != PAD).any(axis=(0, 2)))
        else:
            rows = np.flatnonzero((kind != PAD).any(axis=1))
        if Rt == b.R:
            dd.update(int(r) for r in rows)
            return
        rt = Rt // b.n_sh
        for r in rows:
            s, l = divmod(int(r), rt)
            dd.add(s * b.Rg + l)

    # ---- registration / class arithmetic ----

    def set_genesis_population(self, n: int) -> None:
        """Arm the GENESIS residency state (streaming construction):
        ``n`` docs exist in the fleet spec but have nothing resident
        anywhere — not even a record.  Each :meth:`register` call
        decrements the population; the ``serve.tier.genesis_docs``
        gauge makes never-materialized docs first-class in the
        residency story."""
        self._n_genesis = max(0, int(n))

    @property
    def genesis_docs(self) -> int:
        """Docs specified by the fleet but never yet materialized."""
        return self._n_genesis

    def register(self, doc_id: int, n_init: int, capacity_need: int,
                 chars: np.ndarray) -> DocRecord:
        if capacity_need > self.classes[-1]:
            raise ValueError(
                f"doc {doc_id}: capacity need {capacity_need} exceeds the "
                f"largest class {self.classes[-1]}"
            )
        rec = DocRecord(
            doc_id=doc_id, n_init=n_init, capacity_need=capacity_need,
            chars=np.asarray(chars, np.int32), length=n_init,
        )
        if doc_id not in self.docs and self._n_genesis > 0:
            self._n_genesis -= 1
        self.docs[doc_id] = rec
        return rec

    def class_for(self, need: int) -> int:
        for c in self.classes:
            if need <= c:
                return c
        raise ValueError(f"slot need {need} exceeds largest class")

    def residents(self, cls: int) -> list[tuple[int, int]]:
        """(doc_id, row) pairs currently resident in class ``cls``."""
        b = self.buckets[cls]
        return [(d, r) for r, d in enumerate(b.rows) if d is not None]

    def tiers(self, cls: int) -> list[int]:
        """Row-count tiers the macro step compiles for, ascending.
        Factor-4 steps bound the compile count while capping tier waste
        at 4x; the smallest tier keeps >= 4 local rows per shard (when
        the bucket has that many) so tiny slices don't starve the mesh."""
        b = self.buckets[cls]
        out, rt = [], b.Rg
        while True:
            out.append(rt * b.n_sh)
            if rt <= 4:
                break
            rt = max(rt // 4, 4)
        return sorted(out)

    # ---- row movement (host round-trips: off the macro hot path) ----

    @fenced
    def _pull_row(self, rec: DocRecord) -> PackedState:  # graftlint: fence
        b = self.buckets[rec.cls]
        doc, length, nvis = _read_row(b.state, rec.row)
        return PackedState(
            doc=np.asarray(doc)[None],
            length=np.asarray(length)[None],
            nvis=np.asarray(nvis)[None],
        )

    def _free_row(self, rec: DocRecord) -> None:
        b = self.buckets[rec.cls]
        b.rows[rec.row] = None
        b.release_row(rec.row)
        rec.cls = rec.row = None

    def _install(self, rec: DocRecord, cls: int, doc_row: np.ndarray,
                 length: int, nvis: int, row: int | None = None
                 ) -> tuple[int, int]:
        b = self.buckets[cls]
        if row is None:
            row = b.alloc_row()
        if len(doc_row) < b.C:  # promotion / trimmed-spool pad
            doc_row = np.concatenate(
                [doc_row, np.full(b.C - len(doc_row), 2, np.int32)]
            )
        range_rt.check_index(
            "pool.write-row", row, len(b.rows), doc=rec.doc_id, cls=cls,
        )
        b.state = _write_row(
            b.state, jnp.int32(row), jnp.asarray(doc_row),
            jnp.int32(length), jnp.int32(nvis),
        )
        b.rows[row] = rec.doc_id
        rec.cls, rec.row = cls, row
        self._dirty[cls].add(row)
        return cls, row

    def _spool_path(self, doc_id: int) -> str:
        return os.path.join(self.spool_dir, f"doc{doc_id}.npz")

    def _set_spool(self, rec: DocRecord, path: str | None) -> None:  # graftlint: transition=doc:live->cold,cold->live
        """THE rec.spool transition point: every move of a doc into or
        out of the cold tier goes through here so ``cold_docs`` stays
        an O(1) counter (the per-round gauge refresh must never scan a
        64k-doc fleet).  Idempotent on no-op transitions."""
        if (rec.spool is None) != (path is None):
            if path is not None:
                self._n_cold += 1
                lifecycle.transition("doc", "live", "cold",
                                     key=rec.doc_id)
            else:
                self._n_cold -= 1
                lifecycle.transition("doc", "cold", "live",
                                     key=rec.doc_id)
        rec.spool = path

    def recount_cold(self) -> int:
        """Re-derive the cold counter from ground truth (recovery /
        reset paths, where bulk state lands outside the transition
        helper)."""
        self._n_cold = sum(
            1 for rec in self.docs.values() if rec.spool is not None
        )
        return self._n_cold

    def spool_gen(self, doc_id: int) -> int:
        """The doc's spool write generation (bumped per spool_save):
        the staleness tag a prefetch submission carries, so a harvest
        can drop a read that raced a re-eviction."""
        return self._spool_gens.get(doc_id, 0)

    def spool_save(  # graftlint: durable=spool
            self, doc_id: int, doc_row: np.ndarray, length: int,
            nvis: int, compress: bool = False) -> str:
        """Write one doc's checkpoint to the spool.  Only the used
        ``length`` prefix is stored (the tail is the constant
        beyond-length coding ``2`` that ``_install`` re-pads).
        ``compress`` defaults off — zlib on the two-tier eviction path
        was the single largest host cost of the round-loop engine;
        COLD-tier writes of the three-tier pool (warm→cold demotions,
        warm shadows, direct evictions with the warm tier armed) pass
        True, where the deflate runs off the per-round eviction path.

        NOT a fence: every input is already a host array (callers pull
        through ``_pull_row``/``pull_bucket``, the real boundaries) and
        the body is pure file I/O.  PR 4 shipped it fence-annotated; the
        sanitizer's per-fence sync counters proved it never observes a
        single device transfer, so the stale declaration is gone (G011
        would flag it as dead against any sanitized artifact)."""
        path = self._spool_path(doc_id)
        save_state(
            path,
            PackedState(
                doc=np.ascontiguousarray(doc_row[None, :length]),
                length=np.asarray([length], np.int32),
                nvis=np.asarray([nvis], np.int32),
            ),
            compress=compress,
        )
        self._spool_gens[doc_id] = self._spool_gens.get(doc_id, 0) + 1
        return path

    @fenced
    def evict(self, doc_id: int) -> str:  # graftlint: fence=cold
        """Round-trip a resident doc out to the checkpoint spool
        (``utils/checkpoint.py`` .npz) and free its row.  Tagged a COLD
        fence: the macro drain never calls it (``_execute_moves`` spools
        evictions from its own bucket pull); it serves direct pool users
        (tests, tools) and the chaos injector's spool-tear path."""
        rec = self.docs[doc_id]
        if rec.cls is None:
            raise ValueError(f"doc {doc_id} is not resident")
        st = self._pull_row(rec)
        self._set_spool(rec, self.spool_save(
            doc_id, np.asarray(st.doc[0]), int(st.length[0]),
            int(st.nvis[0]), compress=self.warm.budget > 0,
        ))
        self._free_row(rec)
        self.evictions += 1
        return rec.spool

    # ---- drained-doc footprint GC (two-phase, manifest-committed) ----

    def gc_drained_docs(self, doc_ids) -> int:  # graftlint: durable=spool
        """Reclaim the O(fleet) footprint of drained docs: the pool
        record, the spool member (live claim OR the stale file the
        deferred-unlink discipline leaves behind), and any warm
        entry/shadow.  Two-phase like the journal's segment GC: the
        manifest naming every member is committed first (tmp + fsync +
        replace), then the members die, then the manifest — a crash at
        any point is completed (never re-decided) by
        :meth:`finish_torn_spool_gc` at the next pool construction.
        Non-resident docs only; resident ids are skipped, not errors.
        Returns the number of docs reclaimed."""
        victims: list[tuple[int, list[str]]] = []
        seen: set[int] = set()
        for d in doc_ids:
            rec = self.docs.get(d)
            if rec is None or rec.cls is not None or d in seen:
                continue
            seen.add(d)
            paths: list[str] = []
            if rec.spool is not None:
                paths.append(rec.spool)
            else:
                p = self._spool_path(d)
                if os.path.exists(p):
                    paths.append(p)  # stale deferred-unlink leftover
            e = self.warm.take(d)
            if e is not None and e.shadow and e.shadow not in paths:
                paths.append(e.shadow)
            victims.append((d, paths))
        if not victims:
            return 0
        manifest = os.path.join(self.spool_dir, SPOOL_GC_MANIFEST)
        tmp = manifest + ".tmp"
        members = sorted({p for _d, ps in victims for p in ps})
        with fs_protocol("spool"):
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({
                    "version": 1,
                    "members": [os.path.basename(p) for p in members],
                }, f, separators=(",", ":"))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, manifest)  # the GC commit point
            fsync_dir(self.spool_dir)
            for p in members:
                try:
                    os.unlink(p)
                except OSError:
                    pass
            os.unlink(manifest)
            fsync_dir(self.spool_dir)
        for d, _paths in victims:
            rec = self.docs.pop(d)
            self._set_spool(rec, None)
            self._spool_gens.pop(d, None)
        return len(victims)

    def finish_torn_spool_gc(self) -> int:
        """Complete a predecessor's torn spool-GC pass.  A committed
        manifest means the decision was durable: finish the member
        unlinks it names, then retire it (read-witnessed).  A staged
        ``.tmp`` never committed and rolls back.  Called from
        ``__init__`` for adopted spool dirs; returns members removed."""
        manifest = os.path.join(self.spool_dir, SPOOL_GC_MANIFEST)
        tmp = manifest + ".tmp"
        if not (os.path.exists(manifest) or os.path.exists(tmp)):
            return 0
        done = 0
        with fs_protocol("spool"):
            if os.path.exists(tmp):
                try:
                    os.unlink(tmp)  # uncommitted: rolls back
                except OSError:
                    pass
            if not os.path.exists(manifest):
                return 0
            try:
                with open(manifest, encoding="utf-8") as f:
                    names = json.load(f)["members"]
            except _SPOOL_GC_ERRORS:
                names = []
            for name in names:
                p = os.path.join(
                    self.spool_dir, os.path.basename(str(name))
                )
                if os.path.exists(p):
                    try:
                        os.unlink(p)
                        done += 1
                    except OSError:
                        pass
            try:
                os.unlink(manifest)
            except OSError:
                pass
            fsync_dir(self.spool_dir)
        return done

    def admit(self, doc_id: int, need: int) -> tuple[int, int]:
        """Make ``doc_id`` resident in the class covering ``need`` slots
        (promoting a doc resident in a smaller class, composing a warm
        entry in, rehydrating a spooled doc, or installing a fresh
        one).  The target bucket must have a free row — eviction policy
        lives in the scheduler.  Returns (class, row)."""
        rec = self.docs[doc_id]
        cls = self.class_for(max(need, rec.length, 1))
        if rec.cls is not None:
            if rec.cls >= cls:
                return rec.cls, rec.row  # already resident, big enough
            st = self._pull_row(rec)  # promotion to a larger class
            self._free_row(rec)
            self.promotions += 1
            return self._install(
                rec, cls, np.asarray(st.doc[0]),
                int(st.length[0]), int(st.nvis[0]),
            )
        entry = self.take_warm_hit(doc_id)
        if entry is not None:
            return self._install(
                rec, cls, entry.doc_row, entry.length, entry.nvis
            )
        if rec.spool is not None:
            try:
                st = load_state(rec.spool)
            except CorruptCheckpointError as e:
                # surface WHICH doc is stuck; the scheduler's heal path
                # (serve/scheduler.py _heal_spool) repairs or quarantines
                raise CorruptCheckpointError(
                    f"doc {doc_id}: eviction spool damaged: {e}"
                ) from e
            self.restores += 1
            out = self._install(
                rec, cls, np.asarray(st.doc[0]),
                int(st.length[0]), int(st.nvis[0]),
            )
            # DEFERRED unlink: the spool stays on disk until the doc is
            # safely resident and dirty-tracked (_install marked the
            # row).  Unlinking before the install (the historical
            # order) opened a crash window where the only durable copy
            # of the doc was gone with nothing device-resident yet —
            # under the warm tier a doc cycles warm→cold repeatedly, so
            # the window would reopen on every cycle.  The file itself
            # is left behind (clearing the claim marks it stale); a
            # later re-eviction's save_state atomically replaces it, so
            # the spool stays bounded at one file per doc.  The clear
            # MUST route through _set_spool: the direct write this used
            # to be left ``_n_cold`` permanently high — every restore
            # leaked one phantom cold doc into the tier gauges (G022).
            self._set_spool(rec, None)
            return out
        self.fresh_admits += 1
        return self._install(
            rec, cls, _fresh_row_np(cls, rec.n_init), rec.n_init, rec.n_init
        )

    # ---- the warm tier (pinned host; hot-thread owned) ----

    def take_warm_hit(self, doc_id: int) -> WarmEntry | None:
        """THE warm-hit admission rule, shared by :meth:`admit` and the
        scheduler's ``_place``: remove the doc's warm entry (a pure
        memory compose follows — no disk I/O on promotion), bump the
        hit counters, and mark the doc tierless until its install
        lands.  Any on-disk shadow stays behind as a stale file the
        next eviction's atomic os.replace supersedes.  Returns None
        when the doc is not warm."""
        entry = self.warm.take(doc_id)
        if entry is None:
            return None
        self._counters["warm_hits"].inc()
        if entry.origin == "prefetch":
            self._counters["prefetch_hits"].inc()
        self._set_spool(self.docs[doc_id], None)
        return entry

    def warm_deposit(self, doc_id: int, doc_row: np.ndarray, length: int,
                     nvis: int, origin: str = "evict",
                     last_sched: int = -1) -> int:
        """Deposit one evicted doc into the warm tier (a trimmed host
        copy — no disk I/O) and enforce the budget: overflow demotes
        LRU-by-last-scheduled entries to the compressed cold spool.
        Returns the number of docs demoted to cold."""
        rec = self.docs[doc_id]
        self.warm.put(doc_id, WarmEntry(
            doc_row=np.array(doc_row[:length], np.int32),
            length=int(length), nvis=int(nvis), origin=origin,
            last_sched=last_sched if last_sched >= 0 else rec.last_sched,
        ))
        return self._enforce_warm_budget()

    def _enforce_warm_budget(self, extra: int = 0) -> int:
        """Demote ``over_budget() + extra`` LRU entries warm→cold.  A
        shadowed entry demotes for FREE (its durable copy already
        exists — warm entries are immutable, so the shadow is exact);
        an unshadowed one pays one compressed spool write."""
        demoted = 0
        n = self.warm.over_budget() + max(0, extra)
        for _ in range(n):
            hit = self.warm.pop_lru()
            if hit is None:
                break
            doc_id, e = hit
            rec = self.docs[doc_id]
            self._set_spool(
                rec,
                e.shadow if e.shadow is not None else self.spool_save(
                    doc_id, e.doc_row, e.length, e.nvis, compress=True
                ),
            )
            self._counters["warm_evictions"].inc()
            demoted += 1
        return demoted

    def warm_pressure(self, n: int) -> int:
        """Force-demote up to ``n`` warm entries to cold (the
        ``tier_evict_pressure`` chaos kind: warm-tier churn under
        load).  Returns the demoted count."""
        return self._enforce_warm_budget(extra=min(n, len(self.warm)))

    def store_prefetched(self, doc_id: int, doc_row: np.ndarray,
                         length: int, nvis: int, round_no: int,
                         gen: int | None = None) -> bool:
        """Adopt one harvested prefetch payload into the warm tier.
        The caller (the scheduler's harvest) already dropped stale
        generations; this guards residency — a doc that went hot (or
        warm) while the read was in flight keeps its current state and
        the payload is discarded.  The doc's spool file becomes the
        entry's shadow: same bytes, so a later warm→cold demotion is
        free.

        Predictive PROMOTION, not just caching: the entry's LRU key is
        ``round_no`` (the admission horizon it was prefetched for), so
        it outranks genuinely-stale warm entries — a full tier demotes
        its least-recently-scheduled entry to make room (free when
        shadowed), it never refuses the doc the scheduler is about to
        want."""
        rec = self.docs.get(doc_id)
        if rec is None or rec.cls is not None or doc_id in self.warm \
                or rec.spool is None:
            return False
        if gen is not None and self.spool_gen(doc_id) != gen:
            return False  # the read raced a re-eviction: superseded
        shadow = rec.spool
        self._set_spool(rec, None)
        # the payload row is the worker's freshly-loaded array —
        # exclusively ours once harvested, already trimmed: adopted
        # as-is (no copy, and no spool write here: overflow past the
        # budget is trimmed at the next boundary moves, inside the
        # fence disk writes belong behind)
        self.warm.put(doc_id, WarmEntry(
            doc_row=doc_row[:length],
            length=int(length), nvis=int(nvis), origin="prefetch",
            shadow=shadow, last_sched=int(round_no),
        ))
        return True

    def warm_restore(self, doc_id: int, doc_row: np.ndarray, length: int,
                     nvis: int, shadow: str | None) -> None:
        """Recovery-path deposit (journal ``_restore_snapshot``): the
        snapshot's warm residency comes back as warm, shadowed by the
        copied member so later demotion is free."""
        rec = self.docs[doc_id]
        self._set_spool(rec, None)
        self.warm.put(doc_id, WarmEntry(
            doc_row=np.asarray(doc_row[:length], np.int32),
            length=int(length), nvis=int(nvis), origin="recover",
            shadow=shadow, last_sched=rec.last_sched,
        ))
        self._enforce_warm_budget()

    def ensure_warm_shadow(self, doc_id: int) -> str:
        """Durable on-disk copy of a warm entry (snapshot barriers:
        warm docs must be persistable through the SAME spool-member
        path cold docs use — one composed residency story).  Written
        once per warm lifetime; entries are immutable so the shadow
        never goes stale."""
        e = self.warm.entries[doc_id]
        if e.shadow is None:
            e.shadow = self.spool_save(
                doc_id, e.doc_row, e.length, e.nvis, compress=True
            )
        return e.shadow

    @property
    def cold_docs(self) -> int:
        """Docs whose only live copy is a cold spool (O(1): every
        ``rec.spool`` transition routes through :meth:`_set_spool`)."""
        return self._n_cold

    @property
    def hot_rows(self) -> int:
        """Occupied device rows across every capacity class."""
        return sum(b.R - b.n_free for b in self.buckets.values())

    def update_tier_gauges(self) -> None:
        """Refresh the residency gauges (scheduler: once per round —
        pure host arithmetic on pre-registered objects, G013)."""
        g = self._gauges
        g["hot_rows"].set(self.hot_rows)
        g["warm_docs"].set(len(self.warm))
        g["cold_docs"].set(self.cold_docs)
        g["genesis_docs"].set(self._n_genesis)
        g["prefetch_inflight"].set(
            self.prefetcher.inflight if self.prefetcher is not None else 0
        )

    def tier_status(self) -> dict:
        """Small-scalar residency view (``/status.json``)."""
        pf = self.prefetcher
        return {
            "hot_rows": self.hot_rows,
            "hot_budget": sum(b.R for b in self.buckets.values()),
            "warm_docs": len(self.warm),
            "warm_budget": self.warm.budget,
            "cold_docs": self.cold_docs,
            "genesis_docs": self._n_genesis,
            "warm_hits": self.warm_hits,
            "warm_evictions": self.warm_evictions,
            "cold_restores": self.restores,
            "prefetch_hits": self.prefetch_hits,
            "prefetch_inflight": pf.inflight if pf is not None else 0,
            "prefetch_submitted": pf.submitted if pf is not None else 0,
            "prefetch_dropped": pf.dropped if pf is not None else 0,
        }

    # ---- boundary bulk movement (one sync, one upload per class) ----

    @fenced
    def pull_bucket(self, cls: int):  # graftlint: fence
        """Host snapshot of a whole bucket (doc, length, nvis as numpy).
        SYNCS with any in-flight macro step — this is the forced
        boundary the scheduler pays only when rows actually move."""
        b = self.buckets[cls]
        return (
            np.asarray(b.state.doc),
            np.asarray(b.state.length),
            np.asarray(b.state.nvis),
        )

    def upload_bucket(self, cls: int, doc: np.ndarray, length: np.ndarray,
                      nvis: np.ndarray, dirty_rows=None) -> None:
        """Replace a bucket's device state from host arrays (the write
        half of a boundary compose; re-applies the mesh sharding).
        ``dirty_rows`` scopes the delta-snapshot dirty marks to the
        rows the compose actually rewrote; the default (None) marks
        every row — conservative, never wrong."""
        b = self.buckets[cls]
        if dirty_rows is not None:
            dirty_rows = [int(r) for r in dirty_rows]
            # the scheduler's batched install path rewrites these rows
            # on host and re-uploads — same row-bound contract as the
            # unit _install, same declared check name, so either write
            # path keeps the pool.write-row runtime evidence alive
            # graftlint: inrange=row<nrows check=pool.write-row
            range_rt.check_index(
                "pool.write-row", dirty_rows, len(b.rows), cls=cls,
            )
        self._dirty[cls].update(
            range(b.R) if dirty_rows is None else dirty_rows
        )
        state = PackedState(
            doc=jnp.asarray(doc), length=jnp.asarray(length),
            nvis=jnp.asarray(nvis),
        )
        if self._sharding is not None:
            state = jax.tree.map(
                lambda x: jax.device_put(x, self._sharding), state
            )
        b.state = state

    # ---- the hot paths ----

    def step(self, cls: int, kind: np.ndarray, pos: np.ndarray,
             slot: np.ndarray) -> None:
        """Apply one (R, B) UNIT-op batch to class ``cls`` (row r = ops
        for the doc resident in row r; PAD rows are no-ops)."""
        b = self.buckets[cls]
        self._mark_op_rows(cls, kind, b.R)
        args = [jnp.asarray(a) for a in (kind, pos, slot)]
        if self._sharding is not None:
            args = [jax.device_put(a, self._sharding) for a in args]
        b.state = fleet_step(b.state, *args)
        b.steps += 1

    def _build_macro_fn(self, cls: int, Rt: int, nbits: int):
        b = self.buckets[cls]
        R, n_sh = b.R, b.n_sh
        shard = self._sharding
        full = Rt == R

        def body(st, sl):
            # the engine's batched downstream-merge primitive: the scan
            # serve kernel, the recovery replayer, and the replication
            # remote-apply are ONE body (engine/merge_fleet.py)
            k, p, ln, s0 = sl
            return merge_rows_body(st, k, p, ln, s0, nbits=nbits), None

        def fn(state, kind, pos, rlen, slot0):
            # staged lanes arrive in the pool's narrow dtypes
            # (ops/packing.py); widening here is a free cast and keeps
            # the host->device transfer at the packed width
            kind, pos, rlen, slot0 = widen_ops(kind, pos, rlen, slot0)
            if full:
                out, _ = jax.lax.scan(
                    body, state, (kind, pos, rlen, slot0)
                )
                return out
            Rg, rt = R // n_sh, Rt // n_sh

            def take(x):
                y = x.reshape((n_sh, Rg) + x.shape[1:])[:, :rt]
                return y.reshape((Rt,) + x.shape[1:])

            sub = PackedState(
                doc=take(state.doc), length=take(state.length),
                nvis=take(state.nvis),
            )
            if shard is not None:
                sub = jax.tree.map(
                    lambda x: jax.lax.with_sharding_constraint(x, shard),
                    sub,
                )
            sub, _ = jax.lax.scan(body, sub, (kind, pos, rlen, slot0))

            def put(x, s):
                y = x.reshape((n_sh, Rg) + x.shape[1:])
                z = y.at[:, :rt].set(
                    s.reshape((n_sh, rt) + s.shape[1:])
                )
                return z.reshape(x.shape)

            out = PackedState(
                doc=put(state.doc, sub.doc),
                length=put(state.length, sub.length),
                nvis=put(state.nvis, sub.nvis),
            )
            if shard is not None:
                out = jax.tree.map(
                    lambda x: jax.lax.with_sharding_constraint(x, shard),
                    out,
                )
            return out

        return jax.jit(fn, donate_argnums=(0,))

    # ---- fused-path executables (ops/serve_fused.py) ----

    @property
    def fused_accel_form(self) -> bool:
        """True when the fused dispatch runs as the accelerator form —
        ONE jit wrapping the serve kernel (real TPU, or the Pallas
        interpreter under CRDT_BENCH_SERVE_INTERPRET=1) — rather than
        the host-orchestrated shared-executable form.  The scheduler's
        exact-k_eff trim and :meth:`warm_fused` both key off this: the
        accelerator form's jit IS keyed by K, and none of the host
        executables are ever called there."""
        return (
            os.environ.get("CRDT_BENCH_SERVE_INTERPRET") == "1"
            or jax.default_backend() == "tpu"
        )

    def _tier_closures(self, cls: int, Rt: int):
        """Plain (take, put) tier slice/writeback closures — traceable,
        so the accelerator-form fused jit can inline them; the host
        path wraps them in AotJit via :meth:`_tier_fns`."""
        b = self.buckets[cls]
        R, n_sh = b.R, b.n_sh
        shard = self._sharding
        Rg, rt = R // n_sh, Rt // n_sh

        def take(state):
            def tk(x):
                y = x.reshape((n_sh, Rg) + x.shape[1:])[:, :rt]
                return y.reshape((Rt,) + x.shape[1:])

            sub = PackedState(
                doc=tk(state.doc), length=tk(state.length),
                nvis=tk(state.nvis),
            )
            if shard is not None:
                sub = jax.tree.map(
                    lambda x: jax.lax.with_sharding_constraint(x, shard),
                    sub,
                )
            return sub

        def put(state, sub):
            def pt(x, s):
                y = x.reshape((n_sh, Rg) + x.shape[1:])
                z = y.at[:, :rt].set(
                    s.reshape((n_sh, rt) + s.shape[1:])
                )
                return z.reshape(x.shape)

            out = PackedState(
                doc=pt(state.doc, sub.doc),
                length=pt(state.length, sub.length),
                nvis=pt(state.nvis, sub.nvis),
            )
            if shard is not None:
                out = jax.tree.map(
                    lambda x: jax.lax.with_sharding_constraint(x, shard),
                    out,
                )
            return out

        return take, put

    def _tier_fns(self, cls: int, Rt: int):
        """(take, put) jitted tier slice/writeback for the fused HOST
        path (the scan path fuses these into its one executable, the
        accelerator form inlines the plain closures into its jit).
        ``take`` must not donate (``put`` re-reads the full state)."""
        key = (cls, Rt)
        fresh = key not in self._tier_takes
        if fresh:
            take, put = self._tier_closures(cls, Rt)
            self._tier_takes[key] = AotJit(take)
            # only the full state donates: the sub-tier's buffers can
            # never back the (R, C) output, so donating them just emits
            # "donated buffers were not usable" warnings
            self._tier_puts[key] = AotJit(put, donate_argnums=(0,))
        return self._tier_takes[key], self._tier_puts[key], fresh

    def _starts_fn(self, Rtp: int, Rt: int, B: int):
        """(seed, delta) for the chained round-start totals, keyed
        (padded-rows, true-rows, B) — NOT by the macro depth K: the
        host advances the recurrence one round at a time
        (``round_total_delta``), so k_eff-trimmed dispatches of any
        depth share these two executables.  ``seed`` zero-pads the
        tier's nvis out to the resolve-chunk row count."""
        fresh = False
        skey = ("seed", Rtp, Rt)
        if skey not in self._starts_fns:
            fresh = True
            pad = Rtp - Rt

            def seed(nvis):
                if pad:
                    return jnp.concatenate(
                        [nvis, jnp.zeros((pad,), jnp.int32)]
                    )
                return jnp.asarray(nvis, jnp.int32)

            self._starts_fns[skey] = AotJit(seed)
        dkey = ("delta", Rtp, B)
        if dkey not in self._starts_fns:
            fresh = True

            def delta(kind, pos, rlen, v0):
                return round_total_delta(
                    kind.astype(jnp.int32), pos.astype(jnp.int32),
                    rlen.astype(jnp.int32), v0,
                )

            self._starts_fns[dkey] = AotJit(delta)
        return self._starts_fns[skey], self._starts_fns[dkey], fresh

    def _resolve_fn(self, B: int):
        """THE shared resolve executable: one compile per op-batch
        width serves every class, tier, and macro depth (the resolve is
        row-local and capacity-independent; rows stream through it in
        RESOLVE_CHUNK_ROWS chunks)."""
        key = (B,)
        fresh = key not in self._resolve_fns
        if fresh:
            self._resolve_fns[key] = AotJit(resolve_round_rows_grow)
            self._resolve_fns[("trivial", B)] = AotJit(
                partial(trivial_round_tokens, B=B)
            )
            self._resolve_fns[("narrow", B)] = (
                AotJit(partial(resolve_round_rows_padded, out_B=B))
                if B > NARROW_RESOLVE_OPS else None
            )
        return (
            self._resolve_fns[key],
            self._resolve_fns[("trivial", B)],
            self._resolve_fns[("narrow", B)],
            fresh,
        )

    def _apply_fn(self, cls: int, Rt: int, B: int, nbits: int):
        """Per-round fused apply, keyed WITHOUT the macro depth K (the
        host loops rounds), so k_eff-trimmed dispatches reuse the same
        executable."""
        key = (cls, Rt, B, nbits)
        fresh = key not in self._apply_fns
        if fresh:
            self._apply_fns[key] = AotJit(
                partial(serve_apply_round_xla, nbits=nbits),
                donate_argnums=(0,),
            )
        return self._apply_fns[key], fresh

    def _build_fused_tpu_fn(self, cls: int, Rt: int, nbits: int,
                            interpret: bool):
        """The accelerator form of the fused dispatch: ONE jit per
        (cls, K, Rt, B) whose capacity-wide work is a single
        pallas_call over grid (row_blocks, K) — document state rides
        VMEM across the K rounds while the pipeline double-buffers
        round m+1's op tensors during round m (ops/serve_fused.py
        serve_macro_fused).  ``interpret`` runs the same kernel under
        the Pallas interpreter (the CPU differential-test path,
        CRDT_BENCH_SERVE_INTERPRET=1)."""
        b = self.buckets[cls]
        full = Rt == b.R
        take = put = None
        if not full:
            # the PLAIN closures: this whole fn is traced by jax.jit,
            # and an AotJit-compiled executable cannot be applied to
            # tracers (code-review r8)
            take, put = self._tier_closures(cls, Rt)

        def fn(state, kind, pos, rlen, slot0):
            kind, pos, rlen, slot0 = widen_ops(kind, pos, rlen, slot0)
            sub = state if full else take(state)
            starts = round_starts(kind, pos, rlen, sub.nvis)
            tokens, dints = jax.vmap(resolve_round_rows_grow)(
                kind, pos, rlen, slot0, starts
            )
            C = b.C
            if interpret or (
                jax.default_backend() == "tpu"
                and serve_fused_fits(C, kind.shape[2])
            ):
                sub = serve_macro_fused(
                    sub, tokens, dints, nbits=nbits, interpret=interpret
                )
            else:
                sub = serve_macro_rounds_xla(sub, tokens, dints, nbits)
            return sub if full else put(state, sub)

        return jax.jit(fn, donate_argnums=(0,))

    def _fused_macro(self, cls: int, kind, pos, rlen, slot0,
                     nbits: int) -> bool:
        """Host-orchestrated fused dispatch (everything enqueued async;
        no host syncs): round starts -> chunked shared resolve ->
        per-round apply, with tier slice/writeback around it.  Returns
        True when any executable compiled for the first time."""
        b = self.buckets[cls]
        K, Rt, B = kind.shape
        interpret = os.environ.get("CRDT_BENCH_SERVE_INTERPRET") == "1"
        if self.fused_accel_form:
            key = (cls, K, Rt, B, nbits, interpret)
            fresh = key not in self._fused_tpu_fns
            if fresh:
                self._fused_tpu_fns[key] = self._build_fused_tpu_fn(
                    cls, Rt, nbits, interpret
                )
            args = [jnp.asarray(a) for a in (kind, pos, rlen, slot0)]
            if self._op_sharding is not None:
                args = [
                    jax.device_put(a, self._op_sharding) for a in args
                ]
            b.state = self._fused_tpu_fns[key](b.state, *args)
            return fresh
        RC = RESOLVE_CHUNK_ROWS
        Rtp = -(-Rt // RC) * RC
        pad = Rtp - Rt
        # the host can SEE which rounds/chunks carry no ops (the narrow
        # staged arrays are right here): an all-PAD round is an exact
        # no-op (skipped outright) and an all-PAD chunk's resolution is
        # the trivial one-token list (built directly, no scan).  With
        # k_eff trimmed exactly for the fused path, trailing drained
        # lanes stop costing resolve time at all.
        # per-(round, chunk) max op count: 0 = all-PAD (skip/trivial),
        # <= NARROW_RESOLVE_OPS = the cheap narrow resolve (ops are
        # front-packed per lane at staging, so a per-lane count is the
        # filled prefix length)
        chunk_ops = [
            [
                int(
                    (kind[k, c : min(c + RC, Rt)] != PAD)
                    .sum(axis=1).max(initial=0)
                )
                for c in range(0, Rtp, RC)
            ]
            for k in range(K)
        ]
        live_round = [any(chunk_ops[k]) for k in range(K)]
        if pad:
            z = lambda a, v: np.concatenate(
                [a, np.full((K, pad, B), v, a.dtype)], axis=1
            )
            kind, pos, rlen, slot0 = (
                z(kind, PAD), z(pos, 0), z(rlen, 0), z(slot0, 0)
            )
        args = [jnp.asarray(a) for a in (kind, pos, rlen, slot0)]
        if self._op_sharding is not None:
            args = [jax.device_put(a, self._op_sharding) for a in args]
        kd, pd, ld, sd = args

        full = Rt == b.R
        fresh = False
        if full:
            sub = b.state
        else:
            take, _put, f = self._tier_fns(cls, Rt)
            fresh |= f
            sub = take(b.state)
        seed_fn, delta_fn, f = self._starts_fn(Rtp, Rt, B)
        fresh |= f
        v0 = seed_fn(sub.nvis)
        resolve, trivial, narrow, f = self._resolve_fn(B)
        fresh |= f
        apply_fn, f = self._apply_fn(cls, Rt, B, nbits)
        fresh |= f
        NB = NARROW_RESOLVE_OPS
        for k in range(K):
            if not live_round[k]:
                continue  # no ops anywhere: byte-exact no-op round
            parts = []
            for j, c in enumerate(range(0, Rtp, RC)):
                v0c = v0[c : c + RC]
                n_ops = chunk_ops[k][j]
                if n_ops == 0:
                    parts.append(trivial(v0c))
                elif narrow is not None and n_ops <= NB:
                    parts.append(narrow(
                        kd[k, c : c + RC, :NB], pd[k, c : c + RC, :NB],
                        ld[k, c : c + RC, :NB], sd[k, c : c + RC, :NB],
                        v0c,
                    ))
                else:
                    parts.append(resolve(
                        kd[k, c : c + RC], pd[k, c : c + RC],
                        ld[k, c : c + RC], sd[k, c : c + RC], v0c,
                    ))
            # dead rounds advance nothing, so the recurrence only needs
            # to cross LIVE rounds that still have a live successor
            if any(live_round[k + 1 :]):
                v0 = delta_fn(kd[k], pd[k], ld[k], v0)
            if len(parts) == 1:
                tokens, dints = parts[0]
            else:
                tokens = tuple(
                    jnp.concatenate([p[0][i] for p in parts])
                    for i in range(4)
                )
                dints = tuple(
                    jnp.concatenate([p[1][i] for p in parts])
                    for i in range(3)
                )
            if pad:
                tokens = tuple(t[:Rt] for t in tokens)
                dints = tuple(d[:Rt] for d in dints)
            sub = apply_fn(sub, tokens, dints)
        if full:
            b.state = sub
        else:
            _take, put, _ = self._tier_fns(cls, Rt)
            b.state = put(b.state, sub)
        return fresh

    def warm_fused(self, batch: int, nbits: int) -> None:
        """Pre-compile the fused path's SHARED executables at
        deployment time (fleet construction), before the drain clock
        starts: the resolve / narrow-resolve / trivial-tokens builders
        (keyed only by the op-batch width) and the round-totals
        seed/delta pair for every tier the classes can compact to.
        These are exactly the executables whose keys do not depend on
        which shapes traffic happens to produce, so warming them is
        deterministic; the per-(class, tier) applies stay lazy (their
        tier usage is traffic-dependent) and keep the compile-round
        tagging.  Idempotent — every warmed entry is a cache hit at
        serve time.  No-op for the scan kernel (its executables are
        monolithic per shape; nothing is shareable ahead of time)."""
        if self.serve_kernel != "fused":
            return
        if self._sharding is not None:
            # mesh pools: runtime inputs arrive mesh-sharded, so
            # single-device warm compiles would never be hit (and would
            # pin the AOT executables to the wrong shardings)
            return
        if self.fused_accel_form:
            # the accelerator form never calls the host executables —
            # warming them there is pure wasted compile (code-review r8)
            return
        del nbits  # applies stay lazy; reserved for future warm tiers
        B = batch
        RC = RESOLVE_CHUNK_ROWS
        resolve, trivial, narrow, _ = self._resolve_fn(B)
        zeros = [
            jnp.zeros((RC, B), dtype=dt) for dt in self.op_dtypes
        ]
        v0c = jnp.zeros((RC,), jnp.int32)
        resolve(*zeros, v0c)
        trivial(v0c)
        if narrow is not None:
            nz = [z[:, : NARROW_RESOLVE_OPS] for z in zeros]
            narrow(*nz, v0c)
        warmed_delta: set[int] = set()
        for cls in self.classes:
            for Rt in self.tiers(cls):
                Rtp = -(-Rt // RC) * RC
                seed_fn, delta_fn, _ = self._starts_fn(Rtp, Rt, B)
                seed_fn(jnp.zeros((Rt,), jnp.int32))
                if Rtp not in warmed_delta:
                    warmed_delta.add(Rtp)
                    delta_fn(
                        *(jnp.zeros((Rtp, B), dtype=dt)
                          for dt in self.op_dtypes[:3]),
                        jnp.zeros((Rtp,), jnp.int32),
                    )

    @boundary(
        # op lanes arrive in the pool's packed dtypes (op_dtypes), so
        # the historical all-int32 dtype contract is gone on purpose;
        # the shape contract still pins the staged (K, Rt, B) layout
        dtypes=(),
        shapes=(None, None, "K R B", "K R B", "K R B", "K R B"),
    )
    def macro_step(self, cls: int, kind: np.ndarray, pos: np.ndarray,
                   rlen: np.ndarray, slot0: np.ndarray, nbits: int) -> bool:
        """ONE async dispatch applying K staged rounds to class ``cls``:
        op tensors [K, Rt, B] in the pool's staged lane dtypes
        (:attr:`op_dtypes`; Rt a row tier from :meth:`tiers`, row r
        covering local rows ``0..Rt/n_sh`` of every shard), applied on
        device with donated state through the selected serve kernel
        (:attr:`serve_kernel`).  No host sync — callers fence via
        :meth:`block` or a boundary pull.  Returns True when any
        executable for this shape compiled for the first time (the
        scheduler tags the round as compile-skewed)."""
        b = self.buckets[cls]
        K, Rt, B = kind.shape
        if Rt % b.n_sh or not b.n_sh <= Rt <= b.R:
            raise ValueError(f"tier {Rt} incompatible with bucket {b.R}")
        self._mark_op_rows(cls, kind, Rt)
        # the staged-lane bound checks: host numpy, pre-dispatch, PAD
        # lanes masked out (their pos/slot payloads are don't-care).
        # Disarmed this is two counter bumps; armed it is the oracle
        # for the silent clamp/wrap XLA would otherwise hand us.
        # graftlint: inrange=pos<=cap check=pool.macro-pos
        range_rt.check_index(
            "pool.macro-pos", lambda: pos[kind != PAD], b.C + 1, cls=cls,
        )
        # graftlint: inrange=slot0<=NARROW_ID_BOUND check=pool.macro-ids
        # (the declared fact is the NARROW ladder's repack ceiling; a
        # wide ladder has no narrow repack, so its id space is bounded
        # by the class capacity instead — ids are per-doc slot indices
        # < capacity_need <= C)
        narrow = self.op_dtypes[3] == np.dtype(np.uint16)
        range_rt.check_narrow(
            "pool.macro-ids", lambda: slot0[kind != PAD],
            NARROW_ID_BOUND if narrow else b.C - 1, cls=cls,
        )
        # both serve kernels dispatch the count_le_tiled clamp region
        # (fused directly, scan through the merge body's count passes)
        range_rt.note_mask("count-le-clamp")
        if self.serve_kernel == "fused":
            range_rt.note_mask("fused-gap-gather")
            fresh = self._fused_macro(cls, kind, pos, rlen, slot0, nbits)
            b.steps += K
            return fresh
        key = (cls, K, Rt, B, nbits)
        fresh = key not in self._macro_fns
        if fresh:
            self._macro_fns[key] = self._build_macro_fn(cls, Rt, nbits)
        args = [jnp.asarray(a) for a in (kind, pos, rlen, slot0)]
        if self._op_sharding is not None:
            args = [jax.device_put(a, self._op_sharding) for a in args]
        b.state = self._macro_fns[key](b.state, *args)
        b.steps += K
        return fresh

    @fenced
    def block(self) -> None:  # graftlint: fence
        """Fence all outstanding bucket steps (honest drain timing)."""
        for b in self.buckets.values():
            b.state.doc.block_until_ready()

    # ---- decode / verify (off the hot path) ----

    def decode(self, doc_id: int) -> str:
        """The doc's visible content, whether resident or spooled.
        Raises ``CorruptCheckpointError`` when the doc is cold and its
        spool is damaged (a chaos drain heals such spools before it
        finishes — see scheduler ``finalize_faults``)."""
        rec = self.docs[doc_id]
        if rec.cls is not None:
            st = self._pull_row(rec)
        elif doc_id in self.warm:
            e = self.warm.entries[doc_id]
            return decode_row_np(e.doc_row, e.length, e.nvis, rec.chars)
        elif rec.spool is not None:
            st = load_state(rec.spool)
        else:
            raise ValueError(f"doc {doc_id} was never admitted")
        return decode_row_np(
            np.asarray(st.doc[0]), int(st.length[0]), int(st.nvis[0]),
            rec.chars,
        )

    def occupancy(self) -> dict[int, float]:
        return {
            c: 1.0 - b.n_free / b.R for c, b in self.buckets.items()
        }

    def shard_occupancy(self) -> list[int]:
        """Occupied rows per mesh shard, summed across every capacity
        class (host bookkeeping only — the free sets are the truth).
        Partition invariant: ``sum(shard_occupancy())`` equals the
        fleet's total resident-doc count."""
        out = [0] * self.n_sh
        for b in self.buckets.values():
            for s in range(b.n_sh):
                out[s] += b.Rg - len(b.free_locals(s))
        return out

    # ---- elastic shard map (serve/reshard.py drives these) ----

    @property
    def live_shard_count(self) -> int:
        return sum(1 for s in self.shard_state if s == "live")

    def docs_on_shard(self, shard: int) -> list[tuple[int, int, int]]:
        """``(doc_id, cls, row)`` for every resident of ``shard``, read
        from the bucket row tables (ground truth, not the records)."""
        out: list[tuple[int, int, int]] = []
        for cls, b in self.buckets.items():
            base = shard * b.Rg
            for l in range(b.Rg):
                d = b.rows[base + l]
                if d is not None:
                    out.append((d, cls, base + l))
        return out

    def drain_shard(self, shard: int) -> None:
        """live → draining: allocation stops NOW (every bucket drops
        the shard from its live mask); residents keep serving until the
        reshard coordinator migrates them.  Idempotent — recovery
        re-applies drains."""
        if self.shard_state[shard] == "retired":
            raise ValueError(f"shard {shard} already retired")
        self.shard_state[shard] = "draining"
        for b in self.buckets.values():
            b.set_live(shard, False)

    def retire_shard(self, shard: int) -> None:
        """draining → retired: requires the shard empty in every
        class — the coordinator's commit precondition."""
        occupied = len(self.docs_on_shard(shard))
        if occupied:
            raise RuntimeError(
                f"shard {shard}: {occupied} residents, cannot retire"
            )
        self.shard_state[shard] = "retired"

    def revive_shard(self, shard: int) -> None:
        """→ live (the grow path): the shard re-enters allocation in
        every bucket."""
        self.shard_state[shard] = "live"
        for b in self.buckets.values():
            b.set_live(shard, True)

    def close(self) -> None:
        """Stop the prefetch thread and delete the spool directory if
        this pool created it (a caller who passed spool_dir owns its
        lifecycle).  Spooled docs become undecodable afterwards — call
        only once served docs are done."""
        if self.prefetcher is not None:
            self.prefetcher.stop()
        if self._owns_spool and os.path.isdir(self.spool_dir):
            import shutil

            shutil.rmtree(self.spool_dir, ignore_errors=True)
