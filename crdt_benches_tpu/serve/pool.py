"""DocPool: N independent documents in a few batched device states.

Every replay engine in this repo batches over a *replica* axis — R copies
of the same document consuming the same op stream.  The pool re-purposes
that axis as a **document** axis: each row of a ``PackedState`` stack is a
different document with its own ``length``/``nvis`` lane, its own slot-id
space, and its own op stream.  Per-row independence is exactly what the
unit-op machinery already provides:

- ``ops/resolve.py resolve_batch`` is written per-document and jit/vmap
  compatible, so ``vmap(resolve_batch)`` over (kind[R, B], pos[R, B],
  nvis[R]) resolves a *different* op batch per row;
- ``ops/apply2.py apply_batch3`` (the packed v3 apply) already consumes
  per-row resolved batches — it only needed per-row ``slots`` support.

Documents are bucketed by **capacity class** (e.g. 256 / 1024 / 4096
slots): a small doc must not pay a 4096-wide apply pass, so each class is
its own (R_class, C_class) stack.  Docs are admitted into a free row of
their class, **promoted** to the next class when their slot need outgrows
the current one (capacity need is host-known: n_init + cumulative insert
count, so promotion never requires a device sync), and **evicted** to a
checkpoint spool (``utils/checkpoint.py`` .npz round-trip) when their
bucket is full — cold docs rehydrate into *any* free row later.

The optional ``mesh`` shards every bucket's row (document) axis over the
``parallel/mesh.py`` replica mesh axis — the docs-over-mesh layout.  All
per-row work in resolve/apply is row-local, so the step partitions with
zero collectives.
"""

from __future__ import annotations

import os
import tempfile
from dataclasses import dataclass, field
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.apply2 import LANE, PackedState, apply_batch3
from ..ops.resolve import resolve_batch
from ..traces.tensorize import PAD
from ..utils.checkpoint import load_state, save_state


@partial(jax.jit, donate_argnums=(0,))
def fleet_step(state: PackedState, kind, pos, slot) -> PackedState:
    """One op batch per resident doc: per-row resolve, one batched apply.

    ``kind``/``pos``/``slot``: int32[R, B], row r = the next B ops of the
    doc in row r (``kind == PAD`` everywhere for idle rows — a no-op end
    to end, the fixed-shape padding the scheduler relies on).
    """
    resolved = jax.vmap(resolve_batch)(kind, pos, state.nvis)
    return apply_batch3(state, resolved, slot)


@partial(jax.jit, donate_argnums=(0,))
def _write_row(state: PackedState, row, doc, length, nvis) -> PackedState:
    return PackedState(
        doc=state.doc.at[row].set(doc),
        length=state.length.at[row].set(length),
        nvis=state.nvis.at[row].set(nvis),
    )


@jax.jit
def _read_row(state: PackedState, row):
    return state.doc[row], state.length[row], state.nvis[row]


def _fresh_row_np(C: int, n_init: int) -> np.ndarray:
    """A fresh document row: slots 0..n_init-1 visible in order, the rest
    the beyond-length coding ``pack_doc(-1, 0) == 2`` (matches
    ``ops/apply2.py init_state3`` for one replica)."""
    idx = np.arange(C, dtype=np.int32)
    return np.where(idx < n_init, ((idx + 2) << 1) | 1, 2).astype(np.int32)


def decode_row_np(doc: np.ndarray, length: int, nvis: int,
                  chars: np.ndarray) -> str:
    """Host-side decode of one packed doc row (the numpy twin of
    ``ops/apply2.py decode_state3`` for a single row — off the hot path,
    used for verification and spool inspection)."""
    order = (doc[:length] >> 1) - 2
    vis = (doc[:length] & 1).astype(bool)
    slots = order[vis]
    assert len(slots) == nvis, f"decode: {len(slots)} visible != nvis {nvis}"
    return "".join(chr(int(c)) for c in chars[slots])


@dataclass
class DocRecord:
    """Host-side bookkeeping for one document (no device syncs needed to
    schedule it: length/capacity evolve deterministically with the
    stream, so the scheduler promotes/admits from host state alone)."""

    doc_id: int
    n_init: int
    capacity_need: int  # n_init + total inserts of the full stream
    chars: np.ndarray  # int32[capacity_need] slot -> codepoint
    length: int = 0  # host mirror of device length (slots used)
    cls: int | None = None  # resident capacity class (None = cold)
    row: int | None = None
    spool: str | None = None  # checkpoint path when evicted
    last_sched: int = -1  # round counter, for LRU eviction


class Bucket:
    """One capacity class: a PackedState stack whose rows are docs."""

    def __init__(self, C: int, R: int, sharding=None):
        self.C = C
        self.R = R
        state = PackedState(
            doc=jnp.full((R, C), 2, jnp.int32),
            length=jnp.zeros(R, jnp.int32),
            nvis=jnp.zeros(R, jnp.int32),
        )
        if sharding is not None:
            state = jax.tree.map(lambda x: jax.device_put(x, sharding), state)
        self.state = state
        self.rows: list[int | None] = [None] * R  # row -> doc_id
        self.free: list[int] = list(range(R - 1, -1, -1))
        self.steps = 0


class DocPool:
    """The document fleet: buckets + admit/evict/promote + vmapped step.

    ``classes``: ascending capacity classes, each a multiple of 128 (the
    packed kernels tile by LANE).  ``slots``: resident rows per class.
    ``mesh``: optional ``parallel/mesh.py`` mesh; every bucket's row axis
    is then sharded over the mesh's replica axis (slots must divide by
    the mesh size).
    """

    def __init__(
        self,
        classes: tuple[int, ...] = (256, 1024, 4096, 8192, 49152),
        slots: tuple[int, ...] = (2048, 512, 128, 32, 16),
        mesh=None,
        spool_dir: str | None = None,
    ):
        if len(classes) != len(slots):
            raise ValueError("classes and slots must have equal length")
        if list(classes) != sorted(set(classes)):
            raise ValueError(f"classes must be ascending/unique: {classes}")
        for c in classes:
            if c % LANE:
                raise ValueError(f"capacity class {c} not a multiple of {LANE}")
        self._sharding = None
        if mesh is not None:
            from ..parallel.mesh import fleet_sharding

            n_dev = mesh.devices.size
            for r in slots:
                if r % n_dev:
                    raise ValueError(
                        f"bucket slots {r} not divisible by mesh size {n_dev}"
                    )
            self._sharding = fleet_sharding(mesh)
        self.classes = tuple(classes)
        self.buckets = {
            c: Bucket(c, r, self._sharding) for c, r in zip(classes, slots)
        }
        self.docs: dict[int, DocRecord] = {}
        self._owns_spool = spool_dir is None
        self.spool_dir = spool_dir or tempfile.mkdtemp(prefix="crdt_serve_")
        os.makedirs(self.spool_dir, exist_ok=True)
        # counters (reported by the scheduler / bench)
        self.evictions = 0
        self.restores = 0
        self.promotions = 0
        self.fresh_admits = 0

    # ---- registration / class arithmetic ----

    def register(self, doc_id: int, n_init: int, capacity_need: int,
                 chars: np.ndarray) -> DocRecord:
        if capacity_need > self.classes[-1]:
            raise ValueError(
                f"doc {doc_id}: capacity need {capacity_need} exceeds the "
                f"largest class {self.classes[-1]}"
            )
        rec = DocRecord(
            doc_id=doc_id, n_init=n_init, capacity_need=capacity_need,
            chars=np.asarray(chars, np.int32), length=n_init,
        )
        self.docs[doc_id] = rec
        return rec

    def class_for(self, need: int) -> int:
        for c in self.classes:
            if need <= c:
                return c
        raise ValueError(f"slot need {need} exceeds largest class")

    def residents(self, cls: int) -> list[tuple[int, int]]:
        """(doc_id, row) pairs currently resident in class ``cls``."""
        b = self.buckets[cls]
        return [(d, r) for r, d in enumerate(b.rows) if d is not None]

    # ---- row movement (all host round-trips: off the vmapped hot path) ----

    def _pull_row(self, rec: DocRecord) -> PackedState:
        b = self.buckets[rec.cls]
        doc, length, nvis = _read_row(b.state, rec.row)
        return PackedState(
            doc=np.asarray(doc)[None],
            length=np.asarray(length)[None],
            nvis=np.asarray(nvis)[None],
        )

    def _free_row(self, rec: DocRecord) -> None:
        b = self.buckets[rec.cls]
        b.rows[rec.row] = None
        b.free.append(rec.row)
        rec.cls = rec.row = None

    def _install(self, rec: DocRecord, cls: int, doc_row: np.ndarray,
                 length: int, nvis: int) -> tuple[int, int]:
        b = self.buckets[cls]
        if not b.free:
            raise RuntimeError(
                f"bucket c{cls} full — scheduler must evict before admit"
            )
        row = b.free.pop()
        if len(doc_row) < b.C:  # promotion / spooled-at-smaller-class pad
            doc_row = np.concatenate(
                [doc_row, np.full(b.C - len(doc_row), 2, np.int32)]
            )
        b.state = _write_row(
            b.state, jnp.int32(row), jnp.asarray(doc_row),
            jnp.int32(length), jnp.int32(nvis),
        )
        b.rows[row] = rec.doc_id
        rec.cls, rec.row = cls, row
        return cls, row

    def evict(self, doc_id: int) -> str:
        """Round-trip a resident doc out to the checkpoint spool
        (``utils/checkpoint.py`` .npz) and free its row."""
        rec = self.docs[doc_id]
        if rec.cls is None:
            raise ValueError(f"doc {doc_id} is not resident")
        st = self._pull_row(rec)
        path = os.path.join(self.spool_dir, f"doc{doc_id}.npz")
        save_state(path, st)
        rec.spool = path
        self._free_row(rec)
        self.evictions += 1
        return path

    def admit(self, doc_id: int, need: int) -> tuple[int, int]:
        """Make ``doc_id`` resident in the class covering ``need`` slots
        (promoting a doc resident in a smaller class, rehydrating a
        spooled doc, or installing a fresh one).  The target bucket must
        have a free row — eviction policy lives in the scheduler.
        Returns (class, row)."""
        rec = self.docs[doc_id]
        cls = self.class_for(max(need, rec.length, 1))
        if rec.cls is not None:
            if rec.cls >= cls:
                return rec.cls, rec.row  # already resident, big enough
            st = self._pull_row(rec)  # promotion to a larger class
            self._free_row(rec)
            self.promotions += 1
            return self._install(
                rec, cls, np.asarray(st.doc[0]),
                int(st.length[0]), int(st.nvis[0]),
            )
        if rec.spool is not None:
            st = load_state(rec.spool)
            os.unlink(rec.spool)  # rehydrated: keep the spool bounded
            rec.spool = None
            self.restores += 1
            return self._install(
                rec, cls, np.asarray(st.doc[0]),
                int(st.length[0]), int(st.nvis[0]),
            )
        self.fresh_admits += 1
        return self._install(
            rec, cls, _fresh_row_np(cls, rec.n_init), rec.n_init, rec.n_init
        )

    # ---- the hot path ----

    def step(self, cls: int, kind: np.ndarray, pos: np.ndarray,
             slot: np.ndarray) -> None:
        """Apply one (R, B) op batch to class ``cls`` (row r = ops for the
        doc resident in row r; PAD rows are no-ops)."""
        b = self.buckets[cls]
        args = [jnp.asarray(a) for a in (kind, pos, slot)]
        if self._sharding is not None:
            args = [jax.device_put(a, self._sharding) for a in args]
        b.state = fleet_step(b.state, *args)
        b.steps += 1

    def block(self) -> None:
        """Fence all outstanding bucket steps (honest per-round timing)."""
        for b in self.buckets.values():
            b.state.doc.block_until_ready()

    # ---- decode / verify (off the hot path) ----

    def decode(self, doc_id: int) -> str:
        """The doc's visible content, whether resident or spooled."""
        rec = self.docs[doc_id]
        if rec.cls is not None:
            st = self._pull_row(rec)
        elif rec.spool is not None:
            st = load_state(rec.spool)
        else:
            raise ValueError(f"doc {doc_id} was never admitted")
        return decode_row_np(
            np.asarray(st.doc[0]), int(st.length[0]), int(st.nvis[0]),
            rec.chars,
        )

    def occupancy(self) -> dict[int, float]:
        return {
            c: 1.0 - len(b.free) / b.R for c, b in self.buckets.items()
        }

    def close(self) -> None:
        """Delete the spool directory if this pool created it (a caller
        who passed spool_dir owns its lifecycle).  Spooled docs become
        undecodable afterwards — call only once served docs are done."""
        if self._owns_spool and os.path.isdir(self.spool_dir):
            import shutil

            shutil.rmtree(self.spool_dir, ignore_errors=True)
