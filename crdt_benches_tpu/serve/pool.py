"""DocPool: N independent documents in a few batched device states.

Every replay engine in this repo batches over a *replica* axis — R copies
of the same document consuming the same op stream.  The pool re-purposes
that axis as a **document** axis: each row of a ``PackedState`` stack is a
different document with its own ``length``/``nvis`` lane, its own slot-id
space, and its own op stream.  Per-row independence is exactly what the
range-op machinery already provides:

- ``ops/resolve_range_scan.py`` resolves a *different* range batch per
  row under vmap (the Pallas kernel shares one stream across rows);
- ``ops/apply_range.py apply_range_batch`` is row-local throughout.

Documents are bucketed by **capacity class** (e.g. 256 / 1024 / 4096
slots): a small doc must not pay a 4096-wide apply pass, so each class is
its own (R_class, C_class) stack.  Docs are admitted into a free row of
their class, **promoted** to the next class when their slot need outgrows
the current one (capacity need is host-known: n_init + cumulative insert
count, so promotion never requires a device sync), and **evicted** to a
checkpoint spool (``utils/checkpoint.py`` .npz round-trip) when their
bucket is full — cold docs rehydrate into *any* free row later.

The serving hot path is the **macro step**: K rounds of per-class
``(R, B)`` range-op tensors staged into one device buffer and consumed by
a single jitted ``lax.scan`` — the device, not the Python round loop,
owns the steady state (one dispatch instead of K, donated state keeps the
scan allocation-free).  Because mean lane occupancy in a serving fleet is
low, the step can run on a **row-tier slice** of the stack: the scheduler
compacts the macro-round's active documents into the first ``Rt`` rows
(per shard, under a mesh) and the jitted step slices/writes back inside
the same dispatch, so idle rows cost nothing.

The optional ``mesh`` shards every bucket's row (document) axis over the
``parallel/mesh.py`` replica mesh axis — the docs-over-mesh layout.  All
per-row work in resolve/apply is row-local, so the step partitions with
zero collectives; row allocation is shard-aware so tier slices stay
balanced across devices.
"""

from __future__ import annotations

import heapq
import os
import tempfile
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..lint.boundary import boundary
from ..lint.sanitizer import fenced
from ..obs.metrics import Counter
from ..ops.apply2 import LANE, PackedState, apply_batch3
from ..ops.apply_range import apply_range_batch
from ..ops.resolve import resolve_batch
from ..ops.resolve_range_scan import resolve_ranges_rows
from ..utils.checkpoint import (
    CorruptCheckpointError,
    load_state,
    save_state,
)


@boundary(
    dtypes=("int32", "int32", "int32", "int32"),
    shapes=(None, "R B", "R B", "R B"),
    donates=(0,),
)
@partial(jax.jit, donate_argnums=(0,))
def fleet_step(state: PackedState, kind, pos, slot) -> PackedState:
    """One UNIT-op batch per resident doc (the pre-macro hot path, kept
    for API compatibility and as the minimal single-round reference).

    ``kind``/``pos``/``slot``: int32[R, B], row r = the next B ops of the
    doc in row r (``kind == PAD`` everywhere for idle rows — a no-op end
    to end, the fixed-shape padding the scheduler relies on).
    """
    resolved = jax.vmap(resolve_batch)(kind, pos, state.nvis)
    return apply_batch3(state, resolved, slot)


@partial(jax.jit, donate_argnums=(0,))
def _write_row(state: PackedState, row, doc, length, nvis) -> PackedState:
    return PackedState(
        doc=state.doc.at[row].set(doc),
        length=state.length.at[row].set(length),
        nvis=state.nvis.at[row].set(nvis),
    )


@jax.jit
def _read_row(state: PackedState, row):
    return state.doc[row], state.length[row], state.nvis[row]


def _fresh_row_np(C: int, n_init: int) -> np.ndarray:
    """A fresh document row: slots 0..n_init-1 visible in order, the rest
    the beyond-length coding ``pack_doc(-1, 0) == 2`` (matches
    ``ops/apply2.py init_state3`` for one replica)."""
    idx = np.arange(C, dtype=np.int32)
    return np.where(idx < n_init, ((idx + 2) << 1) | 1, 2).astype(np.int32)


def decode_row_np(doc: np.ndarray, length: int, nvis: int,
                  chars: np.ndarray) -> str:
    """Host-side decode of one packed doc row (the numpy twin of
    ``ops/apply2.py decode_state3`` for a single row — off the hot path,
    used for verification and spool inspection)."""
    order = (doc[:length] >> 1) - 2
    vis = (doc[:length] & 1).astype(bool)
    slots = order[vis]
    assert len(slots) == nvis, f"decode: {len(slots)} visible != nvis {nvis}"
    return "".join(chr(int(c)) for c in chars[slots])


@dataclass
class DocRecord:
    """Host-side bookkeeping for one document (no device syncs needed to
    schedule it: length/capacity evolve deterministically with the
    stream, so the scheduler promotes/admits from host state alone)."""

    doc_id: int
    n_init: int
    capacity_need: int  # n_init + total inserted chars of the full stream
    chars: np.ndarray  # int32[capacity_need] slot -> codepoint
    length: int = 0  # host mirror of device length (slots used)
    cls: int | None = None  # resident capacity class (None = cold)
    row: int | None = None
    spool: str | None = None  # checkpoint path when evicted
    last_sched: int = -1  # round counter, for LRU eviction


class Bucket:
    """One capacity class: a PackedState stack whose rows are docs.

    Row allocation is **shard-aware**: row ``r`` lives on mesh shard
    ``r // (R / n_sh)``.  Free rows are per-shard min-heaps (with a lazy
    invalidation set so the scheduler can claim *specific* rows for
    compaction), and fresh allocations balance shards while preferring
    the lowest local index — keeping the occupied set packed toward the
    front of every shard, which is what makes tier slicing effective.
    """

    def __init__(self, C: int, R: int, n_sh: int = 1, sharding=None):
        self.C = C
        self.R = R
        self.n_sh = n_sh
        self.Rg = R // n_sh  # rows per shard
        state = PackedState(
            doc=jnp.full((R, C), 2, jnp.int32),
            length=jnp.zeros(R, jnp.int32),
            nvis=jnp.zeros(R, jnp.int32),
        )
        if sharding is not None:
            state = jax.tree.map(lambda x: jax.device_put(x, sharding), state)
        self.state = state
        self.rows: list[int | None] = [None] * R  # row -> doc_id
        self._heaps: list[list[int]] = [
            list(range(self.Rg)) for _ in range(n_sh)
        ]
        self._free: list[set[int]] = [
            set(range(self.Rg)) for _ in range(n_sh)
        ]
        self.steps = 0

    # ---- row allocation ----

    @property
    def free(self) -> list[int]:
        """Free GLOBAL row ids (read-only view; kept for compatibility
        with callers that test emptiness / count)."""
        return [
            s * self.Rg + l for s in range(self.n_sh) for l in self._free[s]
        ]

    @property
    def n_free(self) -> int:
        return sum(len(f) for f in self._free)

    def free_locals(self, shard: int) -> set[int]:
        return self._free[shard]

    def alloc_row(self) -> int:
        """Lowest local index on the emptiest shard (ties -> lowest
        shard) — balances the mesh while packing rows toward the front."""
        s = max(range(self.n_sh), key=lambda i: (len(self._free[i]), -i))
        if not self._free[s]:
            raise RuntimeError(f"bucket c{self.C}: no free row")
        h = self._heaps[s]
        while h:
            l = heapq.heappop(h)
            if l in self._free[s]:
                self._free[s].discard(l)
                return s * self.Rg + l
        raise RuntimeError(f"bucket c{self.C}: free-heap drift")

    def take_row(self, row: int) -> None:
        """Claim a SPECIFIC free row (compaction relocations)."""
        s, l = divmod(row, self.Rg)
        if l not in self._free[s]:
            raise RuntimeError(f"bucket c{self.C}: row {row} not free")
        self._free[s].discard(l)  # heap entry invalidated lazily

    def release_row(self, row: int) -> None:
        s, l = divmod(row, self.Rg)
        self._free[s].add(l)
        heapq.heappush(self._heaps[s], l)


class DocPool:
    """The document fleet: buckets + admit/evict/promote + macro step.

    ``classes``: ascending capacity classes, each a multiple of 128 (the
    packed kernels tile by LANE).  ``slots``: resident rows per class.
    ``mesh``: optional ``parallel/mesh.py`` mesh; every bucket's row axis
    is then sharded over the mesh's replica axis (slots must divide by
    the mesh size).
    """

    def __init__(
        self,
        classes: tuple[int, ...] = (256, 1024, 4096, 8192, 49152),
        slots: tuple[int, ...] = (2048, 512, 128, 32, 16),
        mesh=None,
        spool_dir: str | None = None,
    ):
        if len(classes) != len(slots):
            raise ValueError("classes and slots must have equal length")
        if list(classes) != sorted(set(classes)):
            raise ValueError(f"classes must be ascending/unique: {classes}")
        for c in classes:
            if c % LANE:
                raise ValueError(f"capacity class {c} not a multiple of {LANE}")
        self._sharding = None
        self._op_sharding = None
        self.n_sh = 1
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ..parallel.mesh import AXIS, fleet_sharding

            n_dev = mesh.devices.size
            for r in slots:
                if r % n_dev:
                    raise ValueError(
                        f"bucket slots {r} not divisible by mesh size {n_dev}"
                    )
            self._sharding = fleet_sharding(mesh)
            # staged macro tensors (K, R, B): shard the row axis
            self._op_sharding = NamedSharding(mesh, P(None, AXIS, None))
            self.n_sh = n_dev
        self.classes = tuple(classes)
        self.buckets = {
            c: Bucket(c, r, self.n_sh, self._sharding)
            for c, r in zip(classes, slots)
        }
        self.docs: dict[int, DocRecord] = {}
        self._owns_spool = spool_dir is None
        self.spool_dir = spool_dir or tempfile.mkdtemp(prefix="crdt_serve_")
        os.makedirs(self.spool_dir, exist_ok=True)
        self._macro_fns: dict[tuple, object] = {}
        # counters (reported by the scheduler / bench): typed
        # obs/metrics.py Counters so a serve drain's registry carries
        # them in the artifact's metrics block (bind_metrics); the
        # int-valued properties below keep the historical accessors.
        self._counters = {
            name: Counter("serve.pool." + name)
            for name in ("evictions", "restores", "promotions",
                         "fresh_admits")
        }

    def bind_metrics(self, registry) -> None:
        """Attach this pool's counters to a drain's MetricsRegistry
        (identity-preserving: the pool keeps incrementing the same
        objects the registry now serializes)."""
        for c in self._counters.values():
            registry.attach(c)

    @property
    def evictions(self) -> int:
        return self._counters["evictions"].value

    @evictions.setter
    def evictions(self, v: int) -> None:
        self._counters["evictions"].value = int(v)

    @property
    def restores(self) -> int:
        return self._counters["restores"].value

    @restores.setter
    def restores(self, v: int) -> None:
        self._counters["restores"].value = int(v)

    @property
    def promotions(self) -> int:
        return self._counters["promotions"].value

    @promotions.setter
    def promotions(self, v: int) -> None:
        self._counters["promotions"].value = int(v)

    @property
    def fresh_admits(self) -> int:
        return self._counters["fresh_admits"].value

    @fresh_admits.setter
    def fresh_admits(self, v: int) -> None:
        self._counters["fresh_admits"].value = int(v)

    # ---- registration / class arithmetic ----

    def register(self, doc_id: int, n_init: int, capacity_need: int,
                 chars: np.ndarray) -> DocRecord:
        if capacity_need > self.classes[-1]:
            raise ValueError(
                f"doc {doc_id}: capacity need {capacity_need} exceeds the "
                f"largest class {self.classes[-1]}"
            )
        rec = DocRecord(
            doc_id=doc_id, n_init=n_init, capacity_need=capacity_need,
            chars=np.asarray(chars, np.int32), length=n_init,
        )
        self.docs[doc_id] = rec
        return rec

    def class_for(self, need: int) -> int:
        for c in self.classes:
            if need <= c:
                return c
        raise ValueError(f"slot need {need} exceeds largest class")

    def residents(self, cls: int) -> list[tuple[int, int]]:
        """(doc_id, row) pairs currently resident in class ``cls``."""
        b = self.buckets[cls]
        return [(d, r) for r, d in enumerate(b.rows) if d is not None]

    def tiers(self, cls: int) -> list[int]:
        """Row-count tiers the macro step compiles for, ascending.
        Factor-4 steps bound the compile count while capping tier waste
        at 4x; the smallest tier keeps >= 4 local rows per shard (when
        the bucket has that many) so tiny slices don't starve the mesh."""
        b = self.buckets[cls]
        out, rt = [], b.Rg
        while True:
            out.append(rt * b.n_sh)
            if rt <= 4:
                break
            rt = max(rt // 4, 4)
        return sorted(out)

    # ---- row movement (host round-trips: off the macro hot path) ----

    @fenced
    def _pull_row(self, rec: DocRecord) -> PackedState:  # graftlint: fence
        b = self.buckets[rec.cls]
        doc, length, nvis = _read_row(b.state, rec.row)
        return PackedState(
            doc=np.asarray(doc)[None],
            length=np.asarray(length)[None],
            nvis=np.asarray(nvis)[None],
        )

    def _free_row(self, rec: DocRecord) -> None:
        b = self.buckets[rec.cls]
        b.rows[rec.row] = None
        b.release_row(rec.row)
        rec.cls = rec.row = None

    def _install(self, rec: DocRecord, cls: int, doc_row: np.ndarray,
                 length: int, nvis: int, row: int | None = None
                 ) -> tuple[int, int]:
        b = self.buckets[cls]
        if row is None:
            row = b.alloc_row()
        if len(doc_row) < b.C:  # promotion / trimmed-spool pad
            doc_row = np.concatenate(
                [doc_row, np.full(b.C - len(doc_row), 2, np.int32)]
            )
        b.state = _write_row(
            b.state, jnp.int32(row), jnp.asarray(doc_row),
            jnp.int32(length), jnp.int32(nvis),
        )
        b.rows[row] = rec.doc_id
        rec.cls, rec.row = cls, row
        return cls, row

    def _spool_path(self, doc_id: int) -> str:
        return os.path.join(self.spool_dir, f"doc{doc_id}.npz")

    def spool_save(
            self, doc_id: int, doc_row: np.ndarray, length: int,
            nvis: int) -> str:
        """Write one doc's checkpoint to the spool.  Only the used
        ``length`` prefix is stored (the tail is the constant
        beyond-length coding ``2`` that ``_install`` re-pads), and the
        .npz is uncompressed — zlib on the eviction path was the single
        largest host cost of the round-loop engine.

        NOT a fence: every input is already a host array (callers pull
        through ``_pull_row``/``pull_bucket``, the real boundaries) and
        the body is pure file I/O.  PR 4 shipped it fence-annotated; the
        sanitizer's per-fence sync counters proved it never observes a
        single device transfer, so the stale declaration is gone (G011
        would flag it as dead against any sanitized artifact)."""
        path = self._spool_path(doc_id)
        save_state(
            path,
            PackedState(
                doc=np.ascontiguousarray(doc_row[None, :length]),
                length=np.asarray([length], np.int32),
                nvis=np.asarray([nvis], np.int32),
            ),
            compress=False,
        )
        return path

    @fenced
    def evict(self, doc_id: int) -> str:  # graftlint: fence=cold
        """Round-trip a resident doc out to the checkpoint spool
        (``utils/checkpoint.py`` .npz) and free its row.  Tagged a COLD
        fence: the macro drain never calls it (``_execute_moves`` spools
        evictions from its own bucket pull); it serves direct pool users
        (tests, tools) and the chaos injector's spool-tear path."""
        rec = self.docs[doc_id]
        if rec.cls is None:
            raise ValueError(f"doc {doc_id} is not resident")
        st = self._pull_row(rec)
        rec.spool = self.spool_save(
            doc_id, np.asarray(st.doc[0]), int(st.length[0]),
            int(st.nvis[0]),
        )
        self._free_row(rec)
        self.evictions += 1
        return rec.spool

    def admit(self, doc_id: int, need: int) -> tuple[int, int]:
        """Make ``doc_id`` resident in the class covering ``need`` slots
        (promoting a doc resident in a smaller class, rehydrating a
        spooled doc, or installing a fresh one).  The target bucket must
        have a free row — eviction policy lives in the scheduler.
        Returns (class, row)."""
        rec = self.docs[doc_id]
        cls = self.class_for(max(need, rec.length, 1))
        if rec.cls is not None:
            if rec.cls >= cls:
                return rec.cls, rec.row  # already resident, big enough
            st = self._pull_row(rec)  # promotion to a larger class
            self._free_row(rec)
            self.promotions += 1
            return self._install(
                rec, cls, np.asarray(st.doc[0]),
                int(st.length[0]), int(st.nvis[0]),
            )
        if rec.spool is not None:
            try:
                st = load_state(rec.spool)
            except CorruptCheckpointError as e:
                # surface WHICH doc is stuck; the scheduler's heal path
                # (serve/scheduler.py _heal_spool) repairs or quarantines
                raise CorruptCheckpointError(
                    f"doc {doc_id}: eviction spool damaged: {e}"
                ) from e
            os.unlink(rec.spool)  # rehydrated: keep the spool bounded
            rec.spool = None
            self.restores += 1
            return self._install(
                rec, cls, np.asarray(st.doc[0]),
                int(st.length[0]), int(st.nvis[0]),
            )
        self.fresh_admits += 1
        return self._install(
            rec, cls, _fresh_row_np(cls, rec.n_init), rec.n_init, rec.n_init
        )

    # ---- boundary bulk movement (one sync, one upload per class) ----

    @fenced
    def pull_bucket(self, cls: int):  # graftlint: fence
        """Host snapshot of a whole bucket (doc, length, nvis as numpy).
        SYNCS with any in-flight macro step — this is the forced
        boundary the scheduler pays only when rows actually move."""
        b = self.buckets[cls]
        return (
            np.asarray(b.state.doc),
            np.asarray(b.state.length),
            np.asarray(b.state.nvis),
        )

    def upload_bucket(self, cls: int, doc: np.ndarray, length: np.ndarray,
                      nvis: np.ndarray) -> None:
        """Replace a bucket's device state from host arrays (the write
        half of a boundary compose; re-applies the mesh sharding)."""
        b = self.buckets[cls]
        state = PackedState(
            doc=jnp.asarray(doc), length=jnp.asarray(length),
            nvis=jnp.asarray(nvis),
        )
        if self._sharding is not None:
            state = jax.tree.map(
                lambda x: jax.device_put(x, self._sharding), state
            )
        b.state = state

    # ---- the hot paths ----

    def step(self, cls: int, kind: np.ndarray, pos: np.ndarray,
             slot: np.ndarray) -> None:
        """Apply one (R, B) UNIT-op batch to class ``cls`` (row r = ops
        for the doc resident in row r; PAD rows are no-ops)."""
        b = self.buckets[cls]
        args = [jnp.asarray(a) for a in (kind, pos, slot)]
        if self._sharding is not None:
            args = [jax.device_put(a, self._sharding) for a in args]
        b.state = fleet_step(b.state, *args)
        b.steps += 1

    def _build_macro_fn(self, cls: int, Rt: int, nbits: int):
        b = self.buckets[cls]
        R, n_sh = b.R, b.n_sh
        shard = self._sharding
        full = Rt == R

        def body(st, sl):
            k, p, ln, s0 = sl
            tokens, dints, _ = resolve_ranges_rows(k, p, ln, s0, st.nvis)
            return apply_range_batch(st, tokens, dints, nbits=nbits), None

        def fn(state, kind, pos, rlen, slot0):
            if full:
                out, _ = jax.lax.scan(
                    body, state, (kind, pos, rlen, slot0)
                )
                return out
            Rg, rt = R // n_sh, Rt // n_sh

            def take(x):
                y = x.reshape((n_sh, Rg) + x.shape[1:])[:, :rt]
                return y.reshape((Rt,) + x.shape[1:])

            sub = PackedState(
                doc=take(state.doc), length=take(state.length),
                nvis=take(state.nvis),
            )
            if shard is not None:
                sub = jax.tree.map(
                    lambda x: jax.lax.with_sharding_constraint(x, shard),
                    sub,
                )
            sub, _ = jax.lax.scan(body, sub, (kind, pos, rlen, slot0))

            def put(x, s):
                y = x.reshape((n_sh, Rg) + x.shape[1:])
                z = y.at[:, :rt].set(
                    s.reshape((n_sh, rt) + s.shape[1:])
                )
                return z.reshape(x.shape)

            out = PackedState(
                doc=put(state.doc, sub.doc),
                length=put(state.length, sub.length),
                nvis=put(state.nvis, sub.nvis),
            )
            if shard is not None:
                out = jax.tree.map(
                    lambda x: jax.lax.with_sharding_constraint(x, shard),
                    out,
                )
            return out

        return jax.jit(fn, donate_argnums=(0,))

    @boundary(
        dtypes=(None, None, "int32", "int32", "int32", "int32"),
        shapes=(None, None, "K R B", "K R B", "K R B", "K R B"),
    )
    def macro_step(self, cls: int, kind: np.ndarray, pos: np.ndarray,
                   rlen: np.ndarray, slot0: np.ndarray, nbits: int) -> bool:
        """ONE async dispatch applying K staged rounds to class ``cls``:
        op tensors int32[K, Rt, B] (Rt a row tier from :meth:`tiers`,
        row r covering local rows ``0..Rt/n_sh`` of every shard), scanned
        on device with donated state.  No host sync — callers fence via
        :meth:`block` or a boundary pull.  Returns True when this
        (shape, nbits) compiled for the first time (the scheduler tags
        the round as compile-skewed)."""
        b = self.buckets[cls]
        K, Rt, B = kind.shape
        if Rt % b.n_sh or not b.n_sh <= Rt <= b.R:
            raise ValueError(f"tier {Rt} incompatible with bucket {b.R}")
        key = (cls, K, Rt, B, nbits)
        fresh = key not in self._macro_fns
        if fresh:
            self._macro_fns[key] = self._build_macro_fn(cls, Rt, nbits)
        args = [jnp.asarray(a) for a in (kind, pos, rlen, slot0)]
        if self._op_sharding is not None:
            args = [jax.device_put(a, self._op_sharding) for a in args]
        b.state = self._macro_fns[key](b.state, *args)
        b.steps += K
        return fresh

    @fenced
    def block(self) -> None:  # graftlint: fence
        """Fence all outstanding bucket steps (honest drain timing)."""
        for b in self.buckets.values():
            b.state.doc.block_until_ready()

    # ---- decode / verify (off the hot path) ----

    def decode(self, doc_id: int) -> str:
        """The doc's visible content, whether resident or spooled.
        Raises ``CorruptCheckpointError`` when the doc is cold and its
        spool is damaged (a chaos drain heals such spools before it
        finishes — see scheduler ``finalize_faults``)."""
        rec = self.docs[doc_id]
        if rec.cls is not None:
            st = self._pull_row(rec)
        elif rec.spool is not None:
            st = load_state(rec.spool)
        else:
            raise ValueError(f"doc {doc_id} was never admitted")
        return decode_row_np(
            np.asarray(st.doc[0]), int(st.length[0]), int(st.nvis[0]),
            rec.chars,
        )

    def occupancy(self) -> dict[int, float]:
        return {
            c: 1.0 - b.n_free / b.R for c, b in self.buckets.items()
        }

    def shard_occupancy(self) -> list[int]:
        """Occupied rows per mesh shard, summed across every capacity
        class (host bookkeeping only — the free sets are the truth).
        Partition invariant: ``sum(shard_occupancy())`` equals the
        fleet's total resident-doc count."""
        out = [0] * self.n_sh
        for b in self.buckets.values():
            for s in range(b.n_sh):
                out[s] += b.Rg - len(b.free_locals(s))
        return out

    def close(self) -> None:
        """Delete the spool directory if this pool created it (a caller
        who passed spool_dir owns its lifecycle).  Spooled docs become
        undecodable afterwards — call only once served docs are done."""
        if self._owns_spool and os.path.isdir(self.spool_dir):
            import shutil

            shutil.rmtree(self.spool_dir, ignore_errors=True)
