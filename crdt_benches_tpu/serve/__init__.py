"""serve/ — multi-tenant document-fleet serving engine.

The reference (and every other engine in this repo) replays ONE document —
possibly vmapped into many replicas *of the same document*.  This package
hosts N **independent** documents in a small number of batched device
states and drives them with a mixed multi-tenant workload, the defining
shape of real CRDT deployments (server-side multi-document hosting, as
surveyed in "Approaches to Conflict-free Replicated Data Types",
arxiv 2310.18220):

- :mod:`.pool`       — ``DocPool``: documents bucketed by capacity class,
  one ``PackedState`` stack per class (rows = docs, not replicas), with
  admit/evict that round-trips cold docs through ``utils/checkpoint.py``
  and a vmapped per-row resolve+apply step;
- :mod:`.scheduler`  — ``FleetScheduler``: admission + batching; drains
  per-doc op queues into fixed-shape device batches (idle lanes padded
  with no-ops), promotes docs between buckets as they outgrow capacity,
  reports queue depth / occupancy;
- :mod:`.workload`   — multi-tenant generator interleaving the four real
  traces (as prefixes) plus ``traces/synth.py`` streams across N
  simulated sessions with a configurable arrival mix;
- :mod:`.bench`      — the ``serve`` bench family (fleet patches/sec +
  p50/p95/p99 per-batch latency), wired into ``bench/runner.py`` under
  ``--family serve`` with bench ids ``serve/<mix>/<fleet-size>``.

Correctness gate: sampled docs from every capacity bucket finish
byte-identical to ``oracle/text_oracle.py`` replaying the same per-doc
stream (tests/test_serve.py, and the in-run verify of the bench family).
"""

from .pool import DocPool
from .scheduler import FleetScheduler, ServeStats, prepare_streams
from .workload import BANDS, MIXES, build_fleet

__all__ = [
    "DocPool",
    "FleetScheduler",
    "ServeStats",
    "prepare_streams",
    "BANDS",
    "MIXES",
    "build_fleet",
]
