"""serve/ — multi-tenant document-fleet serving engine.

The reference (and every other engine in this repo) replays ONE document —
possibly vmapped into many replicas *of the same document*.  This package
hosts N **independent** documents in a small number of batched device
states and drives them with a mixed multi-tenant workload, the defining
shape of real CRDT deployments (server-side multi-document hosting, as
surveyed in "Approaches to Conflict-free Replicated Data Types",
arxiv 2310.18220):

- :mod:`.pool`       — ``DocPool``: documents bucketed by capacity class,
  one ``PackedState`` stack per class (rows = docs, not replicas), with
  admit/evict that round-trips cold docs through ``utils/checkpoint.py``
  and a device-resident MACRO step: K staged rounds of per-row range ops
  consumed by one jitted ``lax.scan`` over a compacted row tier;
- :mod:`.prefetch`   — ``Prefetcher``: the tiered pool's predictive
  cold→warm rehydrate thread (``# graftlint: thread=prefetch``) —
  reads the scheduler's look-ahead admission plan, rehydrates cold
  spools off the drain, and hands rows back through a declared
  ``# graftlint: publish`` swap point on a bounded queue (G014–G017
  gated; the hot thread never blocks on it);
- :mod:`.scheduler`  — ``FleetScheduler``: macro-round admission +
  batching; drains per-doc RLE-coalesced range-op queues into
  ``(K, Rt, B)`` staged tensors (idle lanes padded with no-ops, staging
  overlapped with device execution), promotes docs between buckets as
  they outgrow capacity, reports queue depth / occupancy /
  pad_fraction / coalesce_ratio;
- :mod:`.workload`   — multi-tenant generator interleaving the four real
  traces (as prefixes) plus ``traces/synth.py`` streams across N
  simulated sessions with a configurable arrival mix;
- :mod:`.journal`    — fault tolerance: per-round write-ahead op journal
  (CRC-framed, torn-tail safe), periodic fleet snapshot barriers
  (atomic directory commit), crash recovery (``recover_fleet``) and the
  targeted rebuild primitive (``rebuild_doc``) used by in-run repair;
- :mod:`.faults`     — deterministic chaos: a seeded ``FaultPlan``
  (spool corruption/truncation, mid-macro device-state loss, duplicated
  op batches, host stalls, queue-overflow bursts) injected through
  scheduler hooks, every event tracked fired/recovered;
- :mod:`.bench`      — the ``serve`` bench family (fleet patches/sec +
  p50/p95/p99 per-batch latency, recovery metrics in chaos mode), wired
  into ``bench/runner.py`` under ``--family serve`` with bench ids
  ``serve/<mix>/<fleet-size>``;
- :mod:`.replicate`  — multi-writer replication: every doc becomes a
  writer GROUP of N replica rows fed by a broadcast bus (paced publish,
  lagged sequence-keyed delivery, partition/reorder chaos), remote ops
  merged through the same macro dispatch as local ones, verified by a
  convergence + RA-linearizability checker tier; bench ids
  ``serve/repl/<mix>/<fleet>x<writers>`` (``--serve-writers``).

Correctness gate: sampled docs from every capacity bucket finish
byte-identical to ``oracle/text_oracle.py`` replaying the same per-doc
stream (tests/test_serve.py, and the in-run verify of the bench family)
— including after recovery from injected faults (tests/test_journal.py,
tests/test_serve_faults.py).
"""

from .faults import FaultInjector, FaultPlan
from .journal import OpJournal, RecoveryReport, recover_fleet
from .pool import DocPool, WarmTier
from .prefetch import Prefetcher
from .replicate import ReplicatedScheduler, build_writer_groups
from .scheduler import FleetScheduler, ServeStats, prepare_streams
from .workload import BANDS, MIXES, build_fleet, split_turns

__all__ = [
    "DocPool",
    "FaultInjector",
    "FaultPlan",
    "FleetScheduler",
    "OpJournal",
    "Prefetcher",
    "RecoveryReport",
    "ReplicatedScheduler",
    "WarmTier",
    "ServeStats",
    "build_writer_groups",
    "prepare_streams",
    "recover_fleet",
    "split_turns",
    "BANDS",
    "MIXES",
    "build_fleet",
]
