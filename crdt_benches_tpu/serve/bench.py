"""The ``serve`` bench family: fleet throughput + per-batch latency.

Bench id scheme: ``serve/<mix>/<fleet-size>`` (group/trace/backend slots
of ``bench/harness.py BenchResult``).  Reported numbers:

- **fleet throughput**: trace patches applied across the whole fleet per
  second of drain wall time (the ``Throughput::Elements`` analog, with
  element = one patch, summed over every tenant document);
- **per-macro-round latency**: p50/p95/p99 over per-macro-round wall
  times (one macro-round = planning + staging + boundary row moves + one
  async K-slice dispatch per active capacity class; the final fence is
  folded into the last round).  Rounds that triggered an XLA compile
  (first use of a (class, K, Rt, B) shape) are EXCLUDED from the
  quantiles and reported separately as ``compile_time`` — compile skew
  is a cold-start cost, not serving jitter;
- **occupancy waste**: ``pad_fraction`` (PAD share of staged op slots
  after row-tier compaction) and ``coalesce_ratio`` (unit ops carried
  per staged RLE range op) are tracked per run.

Correctness gate (in-run, not optional): a sample of docs spanning every
capacity class that hosted documents is decoded and byte-compared
against ``oracle/text_oracle.py`` replaying the same per-doc stream; a
mismatch fails the run.  Docs that lost ops to an EXPLICIT load-shed or
quarantine decision are excluded from the sample (their loss is the
decision, surfaced in the artifact) — everything else must match.

Chaos mode (``faults=<spec>``): a seeded ``serve/faults.py`` FaultPlan
is wired into the drain (journal + snapshot barriers recommended via
``journal_dir``), and the artifact grows a ``faults`` block — the event
list with fired/recovered flags, MTTR in macro-rounds, ops replayed /
shed / deferred, quarantines, degraded rounds.  ``info["faults_ok"]``
is False when any event failed to fire or went unrecovered — the chaos
smoke's exit gate, alongside ``verify_ok``.
"""

from __future__ import annotations

import os
import shutil
import sys
import tempfile
import time

import numpy as np

from ..bench.harness import (
    BenchResult,
    save_results,
    summarize,
)
from ..lint import (
    fs_sanitizer,
    lifecycle_sanitizer,
    race_sanitizer,
    range_sanitizer,
    sanitizer,
)
from ..obs import trace as obs_trace
from ..obs.anomaly import AnomalyDetector
from ..obs.flight import FlightRecorder
from ..obs.profiler import DeviceProfiler
from ..obs.reqtrace import RequestTracker
from ..obs.slo import SloTracker
from ..obs.status import StatusServer
from ..obs.timeseries import ServeTelemetry, TimeseriesRecorder
from ..oracle.text_oracle import replay_trace
from .faults import (
    INGEST_KINDS,
    JOURNAL_KINDS,
    REPLICATION_KINDS,
    RESHARD_KINDS,
    TIER_KINDS,
    FaultInjector,
    FaultPlan,
)
from .ingest.admission import (
    DEFAULT_TENANT,
    AdmissionController,
    TenantPolicy,
    parse_tenant_spec,
)
from .ingest.deadline import DeadlineScheduler
from .ingest.front import IngestFront
from .ingest.loadgen import (
    IngestPump,
    OpenLoadClient,
    build_open_plan,
    drive_open_loop,
    parse_open_spec,
)
from .construction import current_rss_bytes, peak_rss_bytes
from .journal import DEFAULT_SEGMENT_BYTES, OpJournal, recover_fleet
from .pool import DocPool
from .reshard import (
    ReshardCoordinator,
    check_shard_partition,
    parse_reshard_spec,
)
from .scheduler import FleetScheduler, LazyStreams, prepare_streams
from .workload import FleetSpec, build_fleet


def parse_slo(slo_spec):
    """Fail-fast parse of a ``--serve-slo`` spec (None when unset).

    The ONLY raising step of reqtrace arming — callers invoke this
    BEFORE acquiring resources (journal tempdir, telemetry threads), so
    a malformed spec fails the run with nothing to release.  The
    tracker itself is constructed by :func:`arm_reqtrace`, last before
    the resource-releasing try."""
    return SloTracker.from_spec(slo_spec) if slo_spec else None


def arm_reqtrace(samples, slo, slo_spec, log, prefix="serve"):
    """Construct + log the request tracker (obs/ v3) for a bench family.

    Called LAST before the try whose finally releases it: the armed
    tracker installs a global publish observer that only
    ``reqtrace.release()`` drops, and nothing in here can raise — the
    raising half (spec parse) happened up front in :func:`parse_slo`."""
    reqtrace = RequestTracker(samples=samples, slo=slo)
    if reqtrace.armed:
        log(
            f"{prefix}: request tracing ARMED "
            f"(samples={reqtrace.samples_cap}"
            + (f", slo={slo_spec}" if slo_spec else "") + ")"
        )
    return reqtrace


def build_telemetry(
    *,
    status_port: int | None = None,
    timeseries_path: str | None = None,
    timeseries_window: int = 8,
    anomaly: bool = False,
    watchdog_s: float = 0.0,
    stale_after: float | None = None,
    flight_path: str | None = None,
    log=print,
) -> ServeTelemetry | None:
    """Assemble the continuous-telemetry bundle a serve run threads
    through its scheduler(s): the windowed time-series recorder (armed
    by a stream path, a status port, or soak mode — the artifact block
    and the detectors both need it), the live status endpoint
    (``stale_after`` seconds without a publish turns ``/healthz`` 503 —
    the external-probe view of a wedged publisher), and the soak
    anomaly detectors.  Returns None when nothing is armed."""
    if status_port is None and not timeseries_path and not anomaly \
            and not flight_path:
        return None
    telemetry = ServeTelemetry(
        recorder=TimeseriesRecorder(
            window_rounds=timeseries_window, stream_path=timeseries_path
        ),
        anomaly=AnomalyDetector(watchdog_s=watchdog_s) if anomaly
        else None,
        status=StatusServer(port=status_port, stale_after=stale_after)
        if status_port is not None else None,
        flight=FlightRecorder(flight_path) if flight_path else None,
    )
    if telemetry.flight is not None:
        log(f"serve: flight recorder armed -> {flight_path} "
            "(dumped on anomaly fire / unrecovered fault / crash)")
    if telemetry.status is not None:
        port = telemetry.status.start()
        log(
            f"serve: status server on http://127.0.0.1:{port} "
            "(/healthz /status.json /metrics)"
        )
    if timeseries_path:
        log(f"serve: time-series stream -> {timeseries_path}")
    return telemetry


def ensure_virtual_devices(n: int) -> int:
    """Best-effort: make ``n`` virtual host CPU devices available for
    the docs-over-mesh path.  Must run before the JAX *backend*
    initializes (merely having ``jax`` imported is fine — this image's
    sitecustomize imports it into every process); the same dance as
    tests/conftest.py: force the host device count via XLA_FLAGS, then
    pin the platform config to cpu before first device use.  Skipped
    when the caller explicitly selected a non-CPU platform; if the
    backend is already live with fewer devices, falls back (returns the
    usable device count)."""
    if n <= 1:
        return 1
    env_plat = os.environ.get("JAX_PLATFORMS", "")
    if env_plat in ("", "cpu"):
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n}"
            ).strip()
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:  # backend already initialized
            pass
    else:
        import jax
    avail = len(jax.devices())
    if avail < n:
        print(
            f"serve: wanted {n} mesh devices, have {avail}; "
            f"{'using ' + str(avail) if avail > 1 else 'mesh disabled'}",
            file=sys.stderr,
        )
    return min(n, avail)


def _parse_int_tuple(s: str | tuple) -> tuple[int, ...]:
    if isinstance(s, tuple):
        return s
    return tuple(int(x) for x in str(s).split(",") if x)


def parse_tier_spec(spec: str, slots: tuple[int, ...]
                    ) -> tuple[tuple[int, ...], int]:
    """The ``--serve-tiers hot=ROWS,warm=DOCS`` grammar.

    ``hot=ROWS`` scales the per-class slot table proportionally so the
    total device-row budget lands at ~ROWS (each class keeps >= 2 rows
    so every capacity class stays servable); ``warm=DOCS`` bounds the
    pinned-host warm tier (and arms the async prefetcher).  Either key
    may be omitted: ``warm=256`` alone keeps the explicit
    ``--serve-slots`` hot budget.  Returns ``(slots, warm_docs)``."""
    hot = None
    warm = None
    for tok in str(spec).split(","):
        tok = tok.strip()
        if not tok:
            continue
        if "=" not in tok:
            raise ValueError(f"tier spec token {tok!r}: expected k=v")
        key, val = tok.split("=", 1)
        key = key.strip()
        if key == "hot":
            hot = int(val)
        elif key == "warm":
            warm = int(val)
        else:
            raise ValueError(
                f"tier spec: unknown key {key!r} (expected hot/warm)"
            )
    if warm is None or warm <= 0:
        raise ValueError(
            f"tier spec {spec!r}: warm=DOCS (> 0) is required — the "
            "three-tier pool IS the warm tier"
        )
    if hot is not None:
        if hot < 2 * len(slots):
            raise ValueError(
                f"tier spec: hot={hot} below the floor of 2 rows per "
                f"capacity class ({2 * len(slots)})"
            )
        total = sum(slots)
        slots = tuple(
            max(2, round(s * hot / total)) for s in slots
        )
    return slots, warm


def run_serve_bench(
    mix="mixed",
    n_docs: int = 4096,
    batch: int = 64,
    classes=(256, 1024, 4096, 8192, 49152),
    slots=(2048, 512, 128, 32, 16),
    seed: int = 0,
    arrival_span: int = 8,
    arrival_dist: str = "uniform",
    mesh_devices: int = 0,
    verify_sample: int = 8,
    stream: bool = False,
    sample_seed: int | None = None,
    construction_scaling: list | None = None,
    bands: dict | None = None,
    macro_k: int = 8,
    batch_chars: int = 256,
    serve_kernel: str = "fused",
    serve_tiers: str | None = None,
    spool_dir: str | None = None,
    journal_dir: str | None = None,
    snapshot_every: int = 32,
    snapshot_keep: int = 2,
    snapshot_full_every: int = 4,
    wal_segment_bytes: int = DEFAULT_SEGMENT_BYTES,
    journal_fsync: bool = False,
    longhaul: int = 0,
    measure_recovery: bool = False,
    crash_after: int = 0,
    reshard_spec: str | None = None,
    record_evict: bool = False,
    open_spec: str | None = None,
    tenants_spec: str | None = None,
    deadline: bool = False,
    deadline_budget: int = 0,
    knee_block: dict | None = None,
    faults=None,
    queue_cap: int = 0,
    overflow_policy: str = "defer",
    delivery: str | None = None,
    results_dir: str | None = None,
    save_name: str | None = None,
    trace_path: str | None = None,
    profile_rounds: int = 0,
    status_port: int | None = None,
    timeseries_path: str | None = None,
    timeseries_window: int = 8,
    telemetry: ServeTelemetry | None = None,
    reqtrace_samples: int = 0,
    slo_spec: str | None = None,
    flight_path: str | None = None,
    log=print,
) -> tuple[BenchResult, dict]:
    """Build the fleet, drain it once, verify a per-class doc sample
    against the oracle, and persist the artifact.  Returns
    (BenchResult, info) with ``info["verify_ok"]`` (and, in chaos mode,
    ``info["faults_ok"]``).

    ``macro_k`` staged rounds ride each device dispatch (1 = the legacy
    round loop through the same machinery); ``batch`` range ops and
    ``batch_chars`` inserted chars bound one doc's slice.

    Fault-tolerance knobs: ``journal_dir`` enables the write-ahead op
    journal + snapshot barriers every ``snapshot_every`` macro-rounds
    ("auto" = an owned temp dir, removed after the run); ``faults`` is a
    ``serve/faults.py`` spec string or FaultPlan; ``queue_cap`` bounds
    each doc's pending ops with ``overflow_policy`` deciding
    defer-vs-shed at the cap (chaos with ``queue_overflow`` events
    auto-defaults the cap to ``8 * batch`` when unset).

    Observability knobs: ``trace_path`` arms the ``obs/trace.py`` span
    tracer for the drain and writes Perfetto-loadable Chrome trace JSON
    there (``CRDT_BENCH_TRACE=1`` arms it too, defaulting the path next
    to the artifact); ``profile_rounds`` > 0 captures a ``jax.profiler``
    device trace of that many steady rounds and embeds a top-ops table
    in the artifact's ``profile`` block.

    Continuous telemetry: ``status_port`` starts the live
    ``obs/status.py`` endpoint (0 = ephemeral; the bound port is
    logged), ``timeseries_path`` streams closed ``obs/timeseries.py``
    windows as JSONL; either arms the windowed recorder and the
    artifact gains versioned ``timeseries`` (and, under a soak's
    detectors, ``anomalies``) blocks plus per-shard labeled series in
    the metrics registry.  A caller-provided ``telemetry`` bundle (the
    soak wrapper's) is reused as-is and NOT closed here."""
    classes = _parse_int_tuple(classes)
    slots = _parse_int_tuple(slots)
    mix_name = mix if isinstance(mix, str) else "custom"
    # tiered residency (--serve-tiers): three-tier DocPool + the async
    # prefetcher; its own bench-id family serve/tier/<mix>/<fleet>
    warm_docs = 0
    if serve_tiers:
        slots, warm_docs = parse_tier_spec(serve_tiers, slots)
        if mesh_devices > 1:
            raise ValueError(
                "--serve-tiers is single-host for now (the warm tier "
                "composes through host boundary moves; the mesh form "
                "is the silicon-campaign item, see ROADMAP)"
            )
    # longhaul (serve/longhaul/<mix>/<fleet>): days-of-edits-scale
    # streams + a measured recovery-time objective — the durability
    # family, so the journal is mandatory and the recovery leg implied
    longhaul = max(0, int(longhaul))
    if longhaul:
        measure_recovery = True
    if crash_after:
        measure_recovery = True
    if measure_recovery and not journal_dir:
        raise ValueError(
            "the recovery leg (--serve-recover / --serve-longhaul / "
            "--serve-crash-round) measures journal recovery: "
            "--serve-journal is required"
        )
    if measure_recovery and mesh_devices > 1:
        raise ValueError(
            "--serve-mesh is not supported with the measured recovery "
            "leg (the recovered fleet is rebuilt single-host)"
        )
    if warm_docs and longhaul:
        raise ValueError(
            "--serve-tiers and --serve-longhaul are separate bench "
            "families (serve/tier/* vs serve/longhaul/*); pick one"
        )
    # open-loop serving (serve/open/<mix>/<fleet>): live ingest front +
    # per-tenant admission + the deadline-aware scheduler — arrivals
    # come over the wire at a configured offered load instead of the
    # closed-loop trace replay
    open_rate, open_process = 0.0, ""
    if open_spec:
        open_rate, open_process = parse_open_spec(open_spec)
        if longhaul or warm_docs:
            raise ValueError(
                "--serve-open is its own bench family (serve/open/*); "
                "--serve-longhaul / --serve-tiers do not compose with it"
            )
        if measure_recovery or crash_after:
            raise ValueError(
                "--serve-open does not support the measured recovery "
                "leg (--serve-recover / --serve-crash-round): the "
                "open-loop drain has no resumable closed-loop replay"
            )
        if mesh_devices > 1:
            raise ValueError(
                "--serve-open is single-host for now (the ingest pump "
                "feeds one scheduler's bounded queues)"
            )
        if queue_cap <= 0:
            # the pump delivers through the bounded-queue admission
            # rule; unbounded queues would make admission meaningless
            queue_cap = 8 * batch
            log(f"serve: open-loop needs a bounded queue; "
                f"defaulting queue_cap={queue_cap}")
    if tenants_spec and not open_spec:
        raise ValueError(
            "--serve-tenants configures the ingest admission "
            "controller: --serve-open is required"
        )
    if deadline and not open_spec:
        raise ValueError(
            "--serve-deadline selects EDF over the ingest deadline "
            "budgets: --serve-open is required"
        )
    # streaming construction (--serve-stream): the fleet is a lazy
    # FleetSpec — per-doc band/arrival/seed derived from (seed, doc_id),
    # traces tensorized on first admission — so setup cost and host
    # footprint scale with the ACTIVE set, not the fleet.  It rides the
    # existing closed-loop families (serve/ and serve/tier/); the legs
    # that replay eagerly built streams do not compose with it.
    stream = bool(stream)
    if stream:
        if longhaul or measure_recovery or crash_after:
            raise ValueError(
                "--serve-stream does not compose with the durability "
                "legs (--serve-longhaul / --serve-recover / "
                "--serve-crash-round): journal recovery rebuilds "
                "eagerly prepared streams"
            )
        if journal_dir:
            raise ValueError(
                "--serve-stream does not compose with --serve-journal: "
                "the lazy path releases drained streams, which the "
                "journal's replay window would still reference"
            )
        if open_spec:
            raise ValueError(
                "--serve-stream does not compose with --serve-open: "
                "the open-loop plan tensorizes every stream up front"
            )
        # --serve-mesh composes: the FleetSpec is pure (seed, doc_id)
        # arithmetic, so the doc range shards trivially
        # (FleetSpec.shard_range) and the pool's mesh sharding applies
        # to lazily installed rows exactly as it does to eager ones
    # elastic reconfiguration (--serve-reshard): a live shard-map change
    # mid-drain — its own bench-id family serve/reshard/<mix>/<fleet>.
    # The coordinator journals every migration decision, so the WAL is
    # mandatory; the other families pin their own topology assumptions.
    rplan = parse_reshard_spec(reshard_spec) if reshard_spec else None
    if rplan is not None:
        if not journal_dir:
            raise ValueError(
                "--serve-reshard journals every migration decision "
                "(the RESHARD_MANIFEST commit point lives in the "
                "journal dir): --serve-journal is required"
            )
        if longhaul or warm_docs or open_spec or stream:
            raise ValueError(
                "--serve-reshard is its own bench family "
                "(serve/reshard/*); --serve-longhaul / --serve-tiers / "
                "--serve-open / --serve-stream do not compose with it"
            )
        if mesh_devices <= 1 and rplan.n_shards < 2:
            raise ValueError(
                f"reshard spec {reshard_spec!r} does not determine a "
                "shard count: pass --serve-mesh, or drain:S,of=N for "
                "single-host logical sharding"
            )
    mix_label = f"reshard/{mix_name}" if rplan is not None else (
        f"longhaul/{mix_name}" if longhaul
        else f"tier/{mix_name}" if warm_docs
        else f"open/{mix_name}" if open_rate else mix_name
    )

    plan = None
    if faults is not None:
        plan = faults if isinstance(faults, FaultPlan) else (
            FaultPlan.from_spec(faults)
        )
        repl_kinds = sorted({
            e.kind for e in plan.events if e.kind in REPLICATION_KINDS
        })
        if repl_kinds:
            raise ValueError(
                f"fault kinds {repl_kinds} need a replicated fleet "
                "(--serve-writers >= 2, serve/replicate/); a plain "
                "serve drain never polls them"
            )
        tier_kinds = sorted({
            e.kind for e in plan.events if e.kind in TIER_KINDS
        })
        if tier_kinds and not warm_docs:
            raise ValueError(
                f"fault kinds {tier_kinds} target the warm tier / "
                "prefetcher: --serve-tiers is required — a two-tier "
                "drain never reaches their injection points"
            )
        ingest_kinds = sorted({
            e.kind for e in plan.events if e.kind in INGEST_KINDS
        })
        if ingest_kinds and not open_spec:
            raise ValueError(
                f"fault kinds {ingest_kinds} target the live ingest "
                "front: --serve-open is required — a closed-loop "
                "replay never polls them"
            )
        reshard_kinds = sorted({
            e.kind for e in plan.events if e.kind in RESHARD_KINDS
        })
        if reshard_kinds and rplan is None:
            raise ValueError(
                f"fault kinds {reshard_kinds} kill the live-reshard "
                "coordinator between its manifest commit and the "
                "per-doc moves: --serve-reshard is required — a fixed "
                "shard map never reaches the injection point"
            )
        if queue_cap <= 0 and any(
            e.kind == "queue_overflow" for e in plan.events
        ):
            queue_cap = 8 * batch
            log(f"serve: queue_overflow faults need a bounded queue; "
                f"defaulting queue_cap={queue_cap}")
        journal_kinds = sorted({
            e.kind for e in plan.events if e.kind in JOURNAL_KINDS
        })
        if journal_kinds:
            # the injection points live inside the snapshot barrier:
            # every precondition that would leave them unreachable is
            # a loud configuration error, not a drain-end not_fired
            if not journal_dir:
                raise ValueError(
                    f"fault kinds {journal_kinds} target the "
                    "durability subsystem (WAL GC / delta chains): "
                    "--serve-journal is required — a journal-less "
                    "drain never reaches their injection points"
                )
            if snapshot_every <= 0:
                raise ValueError(
                    f"fault kinds {journal_kinds} fire at snapshot "
                    "barriers: --serve-snapshot-every must be > 0"
                )
            if "delta_corrupt" in journal_kinds \
                    and snapshot_full_every <= 1:
                raise ValueError(
                    "delta_corrupt needs delta barriers: "
                    "--serve-full-every must be > 1 (1 = every "
                    "barrier full, so no delta ever exists)"
                )
            if "crash_compact" in journal_kinds \
                    and wal_segment_bytes <= 0:
                raise ValueError(
                    "crash_compact needs sealed WAL segments to "
                    "collect: --serve-wal-segment-bytes must be > 0"
                )
    # a malformed --serve-slo spec fails HERE, before the journal
    # tempdir / telemetry threads exist — nothing yet to release
    slo = parse_slo(slo_spec)

    default_name = (
        f"serve_reshard_{mix_name}_{n_docs}" if rplan is not None
        else f"serve_longhaul_{mix_name}_{n_docs}" if longhaul
        else f"serve_tier_{mix_name}_{n_docs}" if warm_docs
        else f"serve_open_{mix_name}_{n_docs}" if open_rate
        else f"serve_{mix_name}_{n_docs}"
    )

    owns_journal = journal_dir == "auto"
    if owns_journal:
        journal_dir = tempfile.mkdtemp(prefix="crdt_journal_")
    journal = OpJournal(journal_dir, fsync=journal_fsync,
                        segment_bytes=wal_segment_bytes) \
        if journal_dir else None

    owns_telemetry = telemetry is None
    if owns_telemetry:
        telemetry = build_telemetry(
            status_port=status_port, timeseries_path=timeseries_path,
            timeseries_window=timeseries_window,
            flight_path=flight_path, log=log,
        )  # None when nothing is armed

    mesh = None
    if mesh_devices > 1:
        from ..parallel.mesh import replica_mesh

        mesh = replica_mesh(mesh_devices)

    # request tracing + SLO accounting (obs/ v3): an SLO spec arms the
    # tracker too — burn rates are computed over closed requests
    reqtrace = arm_reqtrace(reqtrace_samples, slo, slo_spec, log)

    pool = None
    front = None
    # every exit path — including a failed drain or verify — must
    # close the journal, drop an owned journal dir, release the
    # pool's spool directory, and stop a live ingest front (CI chaos
    # runs must not leak temp dirs or listener threads)
    try:
        # publish-point / cross-thread counters must start counting
        # BEFORE the first status publish (the note_phase below enters
        # StatusServer.publish_status) — the artifact's thread_crossings
        # block is G017's ground truth, so a reset after the fact would
        # undercount the run's publishes; with CRDT_BENCH_SANITIZE_RACES=1
        # the status snapshots become ownership-tracking proxies and an
        # unpublished cross-thread access raises at its callsite
        # (lint/race_sanitizer.py)
        race_sanitizer.reset_counters()
        race_sanitized = race_sanitizer.sanitizing()
        if race_sanitized:
            log("serve: race sanitizer ARMED (CRDT_BENCH_SANITIZE_RACES)")
        # durable-protocol entry / fs-op counters (lint G021's ground
        # truth, the fs_ops block): reset per drain; with
        # CRDT_BENCH_SANITIZE_FS=1 the fs surface is interposed and
        # every op on the watched roots below is attributed to its
        # declared protocol (lint/fs_sanitizer.py)
        fs_sanitizer.reset_counters()
        fs_sanitized = fs_sanitizer.sanitizing()
        if fs_sanitized:
            log("serve: fs sanitizer ARMED (CRDT_BENCH_SANITIZE_FS)")
        # lifecycle ground truth (lint G025's lifecycle block): state-
        # machine edge + ownership acquire/release counters, reset per
        # drain; with CRDT_BENCH_SANITIZE_LIFECYCLE=1 illegal edges,
        # wrong-state departures, double releases, use-after-release
        # and gauge underflows raise typed errors at their callsites
        # (lint/lifecycle_sanitizer.py)
        lifecycle_sanitizer.reset_counters()
        lifecycle_sanitized = lifecycle_sanitizer.armed()
        if lifecycle_sanitized:
            log("serve: lifecycle sanitizer ARMED "
                "(CRDT_BENCH_SANITIZE_LIFECYCLE)")
        # value-range ground truth (lint G029's ranges block): staged
        # index-check and clamp-mask dispatch counters, reset per
        # drain; with CRDT_BENCH_SANITIZE_RANGES=1 every declared
        # index operand is validated against its bound on the staged
        # HOST tensors pre-dispatch — out-of-range indices, narrow-
        # lane overflow and PAD leaks raise typed errors at their
        # callsites instead of corrupting bytes silently
        # (lint/range_sanitizer.py)
        range_sanitizer.reset_counters()
        range_sanitized = range_sanitizer.armed()
        if range_sanitized:
            log("serve: range sanitizer ARMED "
                "(CRDT_BENCH_SANITIZE_RANGES)")
        if journal_dir:
            fs_sanitizer.watch_root(journal_dir)
        if telemetry is not None:
            telemetry.note_phase("building")  # staleness-clock heartbeat
        log(f"serve: building fleet n_docs={n_docs} mix={mix_label} "
            f"seed={seed}"
            + (f" horizon=x{longhaul}" if longhaul else "")
            + (" [streaming]" if stream else ""))
        # construction accounting (always measured, both modes): the
        # window is fleet spec/sessions -> pool -> streams -> scheduler
        # ready, i.e. everything before round 0 could run
        t_setup = time.perf_counter()
        spec = None
        sessions = None
        if stream:
            spec = FleetSpec.build(
                n_docs, mix=mix, seed=seed, arrival_span=arrival_span,
                bands=bands, delivery=delivery, horizon=max(1, longhaul),
                arrival_dist=arrival_dist,
            )
        else:
            sessions = build_fleet(
                n_docs, mix=mix, seed=seed, arrival_span=arrival_span,
                bands=bands, delivery=delivery, horizon=max(1, longhaul),
                arrival_dist=arrival_dist,
            )
        # single-host reshard runs shard the pool LOGICALLY (shards=)
        # so the live map has something to change; with a mesh the
        # device count is the shard count and the coordinator validates
        # the spec against it
        pool_shards = None
        if rplan is not None and mesh is None:
            pool_shards = rplan.n_shards
        pool = DocPool(classes=classes, slots=slots, mesh=mesh,
                       spool_dir=spool_dir, serve_kernel=serve_kernel,
                       warm_docs=warm_docs, shards=pool_shards)
        fs_sanitizer.watch_root(pool.spool_dir)
        if warm_docs:
            log(
                f"serve: tiered residency — hot {sum(slots)} rows "
                f"({'/'.join(str(s) for s in slots)}), warm {warm_docs} "
                f"docs, cold spool compressed, prefetch "
                f"{'armed' if pool.prefetcher is not None else 'off'}"
            )
        if stream:
            streams = LazyStreams(
                spec, pool, batch=batch, batch_chars=batch_chars
            )
            log(
                f"serve: streaming construction — {n_docs} docs born in "
                f"genesis (nothing resident); traces tensorize on first "
                f"admission"
                + (", off-drain via prefetch"
                   if pool.prefetcher is not None else "")
                + f"; classes={classes} slots={slots} batch={batch} "
                f"chars={batch_chars} K={macro_k} kernel={serve_kernel}"
            )
            if mesh is not None:
                # the lazy fleet over a mesh: doc ranges split per
                # shard by pure arithmetic — no shard ever touches
                # another shard's sessions to materialize its own
                spans = ", ".join(
                    "{}:[{},{})".format(s, *spec.shard_range(s, pool.n_sh))
                    for s in range(pool.n_sh)
                )
                log(f"serve: streaming doc range over mesh — {spans}")
        else:
            streams = prepare_streams(
                sessions, pool, batch=batch, batch_chars=batch_chars
            )
            total_ops = sum(s.remaining for s in streams.values())
            total_units = sum(
                int(s.unit_cum[-1])
                for s in streams.values() if len(s.kind)
            )
            log(
                f"serve: {len(sessions)} docs, {total_ops} range ops "
                f"({total_units} unit ops), classes={classes} "
                f"slots={slots} "
                f"batch={batch} chars={batch_chars} K={macro_k} "
                f"kernel={serve_kernel} "
                f"lanes={'/'.join(str(d) for d in pool.op_dtypes)} "
                f"mesh={mesh_devices if mesh else 'off'}"
            )

        profiler = DeviceProfiler(profile_rounds) \
            if profile_rounds > 0 else None
        injector = FaultInjector(plan) if plan else None
        reshard_coord = None
        if rplan is not None:
            reshard_coord = ReshardCoordinator(
                pool, journal, rplan, faults=injector,
                telemetry=telemetry,
            )
            log(
                f"serve: reshard ARMED — {rplan.kind} shards "
                f"{list(reshard_coord._shards)} of {pool.n_sh} "
                f"(batch {rplan.batch}/round; trigger "
                + (f"round {rplan.at_round}" if rplan.at_round is not None
                   else f"imbalance > {rplan.imbalance:g}"
                   if rplan.imbalance is not None else "round 2")
                + ")"
            )
        sched_kw = dict(
            batch=batch, macro_k=macro_k,
            batch_chars=batch_chars,
            queue_cap=queue_cap, overflow_policy=overflow_policy,
            faults=injector, reshard=reshard_coord,
            journal=journal, snapshot_every=snapshot_every,
            snapshot_keep=snapshot_keep,
            snapshot_full_every=snapshot_full_every,
            profiler=profiler, telemetry=telemetry,
            reqtrace=reqtrace, slo=slo,
            warm_start=True,
            # drained-doc record eviction (--serve-record-evict): the
            # scheduler rejects the combination with a journal itself
            # (recovery re-adopts spool members)
            drained_gc=record_evict,
        )
        open_plan = admission = pump = load_client = None
        if open_rate:
            # delivery belongs to the ingest pump alone: burst=0 makes
            # the scheduler's own per-round _deliver a no-op, so every
            # op reaches the bounded queues through admission
            for st in streams.values():
                st.burst = 0
            policies = parse_tenant_spec(tenants_spec) if tenants_spec \
                else {DEFAULT_TENANT: TenantPolicy(
                    DEFAULT_TENANT, rate=max(1.0, 2.0 * open_rate))}
            admission = AdmissionController(
                policies, slo=slo, journal=journal)
            open_plan = build_open_plan(
                streams, rate=open_rate, process=open_process,
                seed=seed, tenant_names=tuple(policies))
            expected = -(-open_plan.total_ops // max(1, int(open_rate)))
            sched = DeadlineScheduler(
                pool, streams, edf=deadline,
                default_budget=deadline_budget or max(
                    64, 2 * expected + arrival_span),
                **sched_kw,
            )
            log(
                f"serve: open-loop {open_process} arrivals at "
                f"{open_rate:g} ops/round over "
                f"{len(open_plan.sessions)} sessions "
                f"({open_plan.total_frames} frames, horizon "
                f"{open_plan.horizon} rounds); tenants "
                f"{','.join(sorted(policies))}; selection "
                f"{'EDF' if deadline else 'round-robin'}"
            )
        else:
            sched = FleetScheduler(pool, streams, **sched_kw)
        construction_ms = (time.perf_counter() - t_setup) * 1e3
        rss_setup = current_rss_bytes()
        log(
            f"serve: construction {construction_ms:.1f}ms "
            f"({'stream' if stream else 'eager'}; "
            f"rss {rss_setup / 2**20:.1f} MiB)"
        )
        # per-fence boundary-sync counters cover drain + verify; with
        # CRDT_BENCH_SANITIZE_SYNCS=1 any sync outside a declared fence
        # raises inside run() at its callsite
        sanitizer.reset_counters()
        sanitized = sanitizer.sanitizing()
        if sanitized:
            log("serve: sync sanitizer ARMED (CRDT_BENCH_SANITIZE_SYNCS)")
        # the flight recorder outlives soak iterations (one shared
        # bundle), so the artifact's per-drain dump accounting keys on
        # the DELTA — like the fence counters it sits beside
        flight_dumps_at_start = (
            telemetry.flight.dumps
            if telemetry is not None and telemetry.flight is not None
            else 0
        )
        # span tracing: an explicit trace_path arms it; CRDT_BENCH_TRACE=1
        # arms it too, defaulting the file next to the artifact
        if trace_path is None and obs_trace.env_armed():
            trace_path = os.path.join(
                results_dir or "bench_results",
                f"{save_name or default_name}_trace.json",
            )
        tracer = None
        armed_here = False
        if trace_path:
            obs_trace.arm()
            armed_here = True
            log(f"serve: span tracer ARMED -> {trace_path}")
        if open_rate:
            # the front goes live LAST — after the sanitizer resets
            # above, so every handler publish lands in the artifact's
            # thread_crossings counts (G017's ground truth)
            front = IngestFront(set(streams), tuple(admission.policies))
            admission.bind(sched.stats.metrics)
            port = front.start()
            log(f"serve: ingest front on 127.0.0.1:{port} "
                f"({len(open_plan.sessions)} sessions inbound)")
            pump = IngestPump(
                sched, front, admission,
                tenant_of=open_plan.tenant_of, faults=sched.faults,
            )
            sched.ingest_status = pump.status_fields
            load_client = OpenLoadClient(port, open_plan)
        profile_block = None
        try:
            try:
                if open_rate:
                    load_client.start()
                    stats = drive_open_loop(
                        sched, pump, load_client, log=log)
                    load_client.join()
                    front.stop()
                else:
                    # crash_after > 0 = the injected crash: kill the
                    # drain after N macro-rounds and let the recovery
                    # leg resume from nothing but the journal directory
                    stats = sched.run(
                        max_rounds=crash_after if crash_after else None
                    )
            except BaseException as e:
                # crash post-mortem: dump the flight window before the
                # exception leaves the drain (the exit code alone is
                # what this recorder exists to improve on).  The dump
                # is best-effort: a failure HERE (half-broken scheduler
                # state, unwritable path) must never replace the crash
                # it is documenting.
                if telemetry is not None and telemetry.flight is not None:
                    try:
                        telemetry.flight_dump(
                            f"crash: {type(e).__name__}: {e}",
                            status=sched.status_fields(),
                        )
                    except Exception:
                        pass
                raise
        finally:
            # only release what THIS run acquired: a failed drain must
            # not hijack a caller-armed tracer, and an open profiler
            # capture must be closed or the next start_trace errors
            if armed_here:
                tracer = obs_trace.disarm()
            if profiler is not None:
                profile_block = profiler.finalize(fence=pool.block)
        if tracer is not None:
            tracer.write(trace_path)
            log(f"serve: wrote {len(tracer.events)} trace events to "
                f"{trace_path} (load in Perfetto / chrome://tracing)")
        if profiler is not None:
            if profile_block is None:
                log("serve: profiler captured no steady rounds "
                    "(drain too short?)")
            else:
                top = profile_block["top_ops"][:3]
                log(f"serve: profiled {profile_block['rounds']} steady "
                    "rounds; top ops: "
                    + ", ".join(
                        f"{o['name']} {o['total_ms']:.1f}ms" for o in top
                    ))
        if front is not None:
            ff = front.status_fields()
            dl = sched.deadline_fields()
            hit = dl.get("hit_rate")
            log(
                f"serve: ingest — {ff['ops_frames']} op frames / "
                f"{ff['ops_delivered']} ops over "
                f"{ff['sessions_opened']} sessions "
                f"({ff['sessions_resumed']} resumed, "
                f"{ff['churn_drops']} churn drops); "
                + "; ".join(
                    f"{t}: admit {d['admitted_ops']} defer "
                    f"{d['deferred_ops']} shed {d['shed_ops']}"
                    for t, d in sorted(
                        admission.status_fields()["tenants"].items())
                )
                + (f"; deadline hit rate {hit:.3f}"
                   f" ({'EDF' if dl['edf'] else 'round-robin'})"
                   if hit is not None else "")
            )
        crashed = crash_after > 0 and not sched.done
        if crash_after:
            log(f"serve: CRASH injected after {stats.rounds} macro-"
                f"rounds ({'work pending' if crashed else 'drained'}); "
                "recovery leg resumes from the journal")
        else:
            assert sched.done, "scheduler stopped with pending work"
        if telemetry is not None:
            telemetry.drain_end(status={
                **sched.status_fields(), "phase": "done", "done": True,
            })
            if telemetry.anomaly is not None:
                a = telemetry.anomaly
                log(
                    f"serve: anomalies — {a.fired} fired, "
                    f"{a.uncleared} uncleared"
                    + (f" (active: {', '.join(a.active_kinds())})"
                       if a.uncleared else "")
                )
        # steady-state latency excludes BOTH compile rounds and snapshot
        # barrier rounds — ServeStats.note_round is the single
        # classification point; the histogram carries the quantiles
        lat = stats.latency_quantiles()
        compile_time = stats.compile_time
        compile_rounds = stats.compile_rounds
        throughput = stats.patches / stats.wall_time
        log(
            f"serve: drained in {stats.wall_time:.2f}s over {stats.rounds} "
            f"macro-rounds ({stats.slices} device rounds) -> "
            f"{throughput:,.0f} patches/s; steady batch latency "
            f"p50 {lat['p50'] * 1e3:.1f}ms p95 {lat['p95'] * 1e3:.1f}ms "
            f"p99 {lat['p99'] * 1e3:.1f}ms; compile {compile_time:.2f}s "
            f"over {compile_rounds} rounds; "
            f"coalesce x{stats.coalesce_ratio:.2f} "
            f"pad {stats.pad_fraction:.3f}; evictions {stats.evictions} "
            f"restores {stats.restores} promotions {stats.promotions}"
        )
        if warm_docs:
            pf = pool.prefetcher
            hits, miss = pool.warm_hits, pool.restores
            log(
                f"serve: residency — hot {pool.hot_rows}/{sum(slots)} "
                f"rows, warm {len(pool.warm)}/{warm_docs} docs, cold "
                f"{pool.cold_docs}; warm hits {hits} (prefetched "
                f"{pool.prefetch_hits}), cold restores {miss}, "
                f"warm→cold {pool.warm_evictions}; hit rate "
                + (f"{hits / (hits + miss):.3f}" if hits + miss else "n/a")
                + (
                    f"; prefetch {pf.submitted} submitted / "
                    f"{pf.harvested} back / {pf.dropped} dropped / "
                    f"{sched.prefetch_wasted} stale"
                    if pf is not None else ""
                )
            )
        if plan is not None or stats.recoveries or stats.shed_ops:
            log(
                f"serve: faults — injected {stats.faults_injected}, "
                f"recoveries {stats.recoveries} "
                f"(replayed {stats.ops_replayed} ops over "
                f"{stats.replay_dispatches} dispatches), "
                f"shed {stats.shed_ops} deferred {stats.deferred_ops} "
                f"dup-dropped {stats.dup_ops_dropped}, "
                f"quarantines {len(stats.quarantines)}, "
                f"degraded rounds {stats.degraded_rounds}, "
                f"snapshots {stats.snapshots}"
            )
        partition_errors: list[str] = []
        if reshard_coord is not None:
            rs = reshard_coord.summary()
            mid = rs["mid_latency"]
            log(
                f"serve: reshard — {rs['kind']} {rs['shards']} "
                f"{rs['state']} (begin r{rs['begin_round']} commit "
                f"r{rs['commit_round']}, {rs['rounds_active']} rounds); "
                f"{rs['migrated']} row moves + {rs['evicted']} "
                f"demotions, {rs['deferred_lanes']} lanes deferred "
                f"({rs['deferred_ops']} ops), {rs['resumes']} resumes; "
                f"live shards {rs['live_shards']}/{pool.n_sh}"
                + (f"; mid-reshard round p99 {mid['p99'] * 1e3:.1f}ms"
                   if mid else "")
            )
            if not crashed:
                # the partition invariant — every doc on exactly one
                # shard, none on a retired one — gates the run like the
                # oracle does; fscrash.py checks it at every crash
                # point, this checks the live end state
                partition_errors = check_shard_partition(pool)
                if partition_errors:
                    log("serve: SHARD PARTITION VIOLATED — "
                        + "; ".join(partition_errors[:8]))

        # ---- per-class byte verification against the oracle ----
        # docs whose ops were shed by an EXPLICIT decision (overflow shed /
        # quarantine) cannot match a full oracle replay; they are excluded
        # from the sample and surfaced in the artifact instead.
        # The sample is SEEDED and auditable: ``vseed`` (defaulting to
        # seed + 1, overridable via --serve-sample-seed) + the picked
        # doc ids both land in the artifact, so any sample can be
        # re-drawn and re-checked offline.  In streaming mode a full
        # fleet verify would itself be O(fleet) — the sampled verify is
        # the gate by design; post-drain every doc has materialized, so
        # the class census walks pool.docs instead of the sessions list.
        lossy = sorted(d for d, st in streams.items() if st.lossy)
        by_class: dict[int, list[int]] = {}
        verify_ids = sorted(pool.docs) if stream \
            else [s.doc_id for s in sessions]
        for doc_id in verify_ids:
            if streams[doc_id].lossy:
                continue
            rec = pool.docs[doc_id]
            final_cls = rec.cls or pool.class_for(max(rec.length, 1))
            by_class.setdefault(final_cls, []).append(doc_id)
        used_classes = sorted(by_class)
        per_class = max(1, -(-verify_sample // max(1, len(used_classes))))
        vseed = (seed + 1) if sample_seed is None else int(sample_seed)
        rng = np.random.default_rng(vseed)
        sample: list[int] = []
        for cls in used_classes:
            ids = by_class[cls]
            pick = rng.choice(ids, size=min(per_class, len(ids)), replace=False)
            sample.extend(int(x) for x in pick)
        failures = []
        session_of = {} if stream else {s.doc_id: s for s in sessions}

        def _trace_of(doc_id):
            # lazy fleets re-derive the sampled doc's trace from the
            # spec (seed-stable, byte-identical to first admission)
            return spec.session(doc_id).trace if stream \
                else session_of[doc_id].trace

        if crashed:
            # an interrupted drain's pool is mid-stream by design; the
            # byte-verify happens on the RECOVERED fleet below
            sample = []
            verify_ok = False
            log("serve: in-run verify skipped (injected crash); the "
                "recovered fleet carries the oracle gate")
        else:
            for doc_id in sample:
                want = replay_trace(_trace_of(doc_id))
                got = pool.decode(doc_id)
                if got != want:
                    failures.append(doc_id)
            # an EMPTY sample must not pass the gate: with every doc
            # lossy (mass shed/quarantine) there is nothing left to
            # verify, and a vacuous green would let the chaos smoke
            # pass while checking nothing
            verify_ok = not failures and bool(sample) \
                and not partition_errors
            log(
                f"serve: verified {len(sample)} docs across classes "
                f"{used_classes}: "
                + ("all byte-identical to oracle" if verify_ok
                   else "EMPTY SAMPLE (all docs lossy?)" if not sample
                   else f"MISMATCH on docs {failures}")
                + (f" ({len(lossy)} lossy docs excluded: {lossy[:16]})"
                   if lossy else "")
            )

        # ---- measured recovery-time objective (durability v2) ----
        # The "crash": the live pool/scheduler/journal handle are
        # dropped; a FRESH fleet recovers from nothing but the journal
        # directory, resumes the redo tail through the normal macro
        # path, and byte-verifies against the oracle.  recover_ms is
        # the first-class RTO metric bench_compare gates.
        recovery_block = None
        if measure_recovery and journal is not None:
            journal.close()  # flush; host state is now disk-only
            if telemetry is not None:
                telemetry.note_phase("recovering")
            rpool = DocPool(classes=classes, slots=slots,
                            serve_kernel=serve_kernel,
                            warm_docs=warm_docs, shards=pool_shards)
            rstreams = prepare_streams(
                sessions, rpool, batch=batch, batch_chars=batch_chars
            )
            t_rec = time.perf_counter()
            rep = recover_fleet(rpool, rstreams, journal_dir)
            recover_ms = (time.perf_counter() - t_rec) * 1e3
            rsched = FleetScheduler(
                rpool, rstreams, batch=batch, macro_k=macro_k,
                batch_chars=batch_chars, start_round=rep.resume_round,
            )
            t_redo = time.perf_counter()
            rsched.run()
            redo_ms = (time.perf_counter() - t_redo) * 1e3
            assert rsched.done, "recovered scheduler left pending work"
            rlossy = {d for d, st in rstreams.items() if st.lossy}
            rsample = [d for d in (sample or (
                s.doc_id for s in sessions)) if d not in rlossy]
            if not sample:  # crashed run: sample spread over classes
                rng_r = np.random.default_rng(seed + 2)
                cand = sorted(rsample)
                rsample = [int(x) for x in rng_r.choice(
                    cand, size=min(verify_sample, len(cand)),
                    replace=False,
                )] if cand else []
            rfail = [
                d for d in rsample
                if rpool.decode(d) != replay_trace(session_of[d].trace)
            ]
            rpartition = check_shard_partition(rpool) \
                if rplan is not None else []
            if rpartition:
                log("serve: recovered fleet SHARD PARTITION VIOLATED — "
                    + "; ".join(rpartition[:8]))
            recovered_ok = not rfail and bool(rsample) \
                and not rpartition
            wal_disk = journal.on_disk_bytes()
            recovery_block = {
                "version": 1,
                "recover_ms": recover_ms,
                "redo_ms": redo_ms,
                "redo_ops": rep.ops_replayed,
                "chain_depth": rep.chain_depth,
                "chain_fallbacks": rep.chain_fallbacks,
                "snapshot_round": rep.snapshot_round,
                "resume_round": rep.resume_round,
                "torn_records": rep.torn_records,
                "gc_segments_completed": rep.gc_segments_completed,
                "staging_removed": rep.staging_removed,
                "cold_start": rep.snapshot_round < 0,
                "docs_restored": rep.docs_restored,
                "spools_restored": rep.spools_restored,
                "warm_restored": rep.warm_restored,
                "journal_disk_bytes": wal_disk,
                "verified_docs": len(rsample),
                "verify_ok": recovered_ok,
                # reshard recovery (zeros when no reshard ran): shards
                # the recovered fleet re-retired from journal commit
                # records / a torn manifest, docs moved off them, and
                # whether a torn reshard was rolled forward to done
                "reshard_retired": rep.reshard_retired,
                "reshard_docs_moved": rep.reshard_docs_moved,
                "reshard_completed": rep.reshard_completed,
            }
            log(
                f"serve: recovery — {recover_ms:.1f}ms to restore "
                f"(snapshot round {rep.snapshot_round}, chain depth "
                f"{rep.chain_depth}, {rep.chain_fallbacks} fallbacks), "
                f"{rep.ops_replayed} redo ops in {redo_ms:.1f}ms, "
                f"WAL on disk {wal_disk} B; "
                f"{len(rsample)} recovered docs "
                + ("byte-identical to oracle" if recovered_ok
                   else f"MISMATCH on {rfail or 'EMPTY SAMPLE'}")
            )
            # the durability chaos kinds close on a PROVEN recovery:
            # chain fallback exercised / torn GC completed, and the
            # recovered fleet byte-verified.  On a CRASH run the
            # in-process finalizer never ran (the crash is the point),
            # so a full journal recovery is the universal repair for
            # EVERY fired fault — the dead pool's damaged spools and
            # lost device state are irrelevant to the fresh fleet
            # rebuilt from snapshots + deterministic streams.
            if plan is not None and recovered_ok:
                for e in plan.events:
                    if e.fired and not e.recovered and (
                            crashed or e.kind in JOURNAL_KINDS):
                        e.recover(
                            via="recovery_leg",
                            fallbacks=rep.chain_fallbacks,
                            gc_completed=rep.gc_segments_completed,
                        )
            rpool.close()
            verify_ok = recovered_ok if crashed \
                else (verify_ok and recovered_ok)

        fault_summary = plan.summary() if plan is not None else None
        faults_ok = fault_summary is None or (
            fault_summary["unrecovered"] == 0
            and fault_summary["not_fired"] == 0
        )
        if fault_summary is not None and not faults_ok:
            log(
                f"serve: FAULTS NOT CLEARED — "
                f"{fault_summary['unrecovered']} unrecovered, "
                f"{fault_summary['not_fired']} never fired"
            )
            if telemetry is not None and telemetry.flight is not None:
                # the dump reason distinguishes a fault that fired and
                # stuck from one that never fired (a plan/timing
                # problem, not a recovery failure) — both fail the run
                telemetry.flight_dump(
                    "unrecovered_fault"
                    if fault_summary["unrecovered"] > 0
                    else "unfired_fault",
                    status={**sched.status_fields(), "done": True},
                )

        if reqtrace.armed:
            log(
                f"serve: requests — {reqtrace.requests_closed} closed "
                f"({reqtrace.reopened} re-admissions opened fresh "
                f"contexts), hops "
                + (", ".join(
                    f"{k.split('.')[-1]}={v}"
                    for k, v in sorted(reqtrace.hop_counts.items())
                ) or "none")
            )
        if slo is not None:
            for name, st_cls in sorted(slo.classes.items()):
                d = st_cls.to_dict()
                log(
                    f"serve: slo {name} — compliance "
                    f"{d['compliance']:.4f} over {d['requests']} "
                    f"requests (objective p{st_cls.objective.quantile * 100:g}"
                    f" <= {st_cls.objective.threshold_s * 1e3:.0f}ms, "
                    f"burn fast {d['burn_rate_fast']:.2f} / slow "
                    f"{d['burn_rate_slow']:.2f})"
                )

        # ---- boundary-sync ground truth (lint G011 cross-checks the
        # static fence graph against exactly this block) ----
        sync_counts = sanitizer.counters()
        boundary_syncs = {
            "sanitized": sanitized,
            "chaos": plan is not None,
            "journal": journal is not None,
            # FlightRecorder.trigger (fence=flight) only crosses when a
            # dump actually fired — a chaos run whose faults all
            # recover cleanly never enters it, so G011 dead-checks it
            # only against runs that dumped.  Per-DRAIN delta: under
            # soak the recorder is shared across iterations, and a
            # clean later drain (fence entries reset, no trigger) must
            # not inherit an earlier iteration's dump
            "flight": (
                telemetry is not None and telemetry.flight is not None
                and telemetry.flight.dumps > flight_dumps_at_start
            ),
            # fence=reshard fences (the coordinator's per-round tick +
            # its end-of-drain finalize) cross on every armed run —
            # G011 dead-checks them only against reshard artifacts
            "reshard": reshard_coord is not None,
            "entries": sync_counts["entries"],
            "syncs": sync_counts["syncs"] if sanitized else None,
        }
        log(
            "serve: boundary syncs — "
            + (", ".join(
                f"{k.split('.')[-1]}={v}"
                for k, v in sync_counts["entries"].items()
            ) or "none")
            + (f"; observed {sum(sync_counts['syncs'].values())} fenced "
               f"transfers" if sanitized else "")
        )

        # ---- publish-point ground truth (lint G017 cross-checks the
        # static thread-confinement model against exactly this block) ----
        race_counts = race_sanitizer.counters()
        thread_crossings = {
            "sanitized": race_sanitized,
            # armed surfaces: G017's tag scoping (publish=status /
            # publish=journal / publish=bus) dead-checks a tagged point
            # only against artifacts whose run armed its surface
            "status": (
                telemetry is not None and telemetry.status is not None
            ),
            "journal": journal is not None,
            "bus": False,  # only the replicated family drives the bus
            # (its artifact arms the surface; see replicate/bench.py)
            # the prefetch surface (serve/prefetch.py publish=prefetch)
            # is armed exactly when the tiered pool ran its worker
            "prefetch": pool.prefetcher is not None,
            # the ingest surface (serve/ingest/front.py publish=ingest)
            # is armed exactly when a live front served the drain
            "ingest": front is not None,
            "publishes": race_counts["publishes"],
            "crossings": (
                race_counts["crossings"] if race_sanitized else None
            ),
        }
        log(
            "serve: thread crossings — publishes "
            + (", ".join(
                f"{k.split('.')[-1]}={v}"
                for k, v in race_counts["publishes"].items()
            ) or "none")
            + (f"; {sum(race_counts['crossings'].values())} cross-thread "
               "accesses attributed" if race_sanitized else "")
        )

        # ---- durable-protocol ground truth (lint G021 cross-checks
        # the static crash-consistency model against exactly this
        # block) ----
        fs_counts = fs_sanitizer.counters()
        fs_ops_block = {
            "version": 1,
            "sanitized": fs_sanitized,
            # armed surfaces (G021's dead-protocol scoping, the G011
            # fence-tag pattern): snapshot/gc/wal ride the journal,
            # spool rides real pool spool traffic, flight a dump that
            # actually fired this drain
            "journal": journal is not None,
            "spool": (stats.evictions + stats.restores
                      + pool.warm_evictions) > 0,
            "flight": boundary_syncs["flight"],
            # the reshard surface arms when the coordinator actually
            # committed a manifest (state left "idle") — an armed-but-
            # untriggered reshard never enters the protocol
            "reshard": (
                reshard_coord is not None
                and reshard_coord.state != "idle"
            ),
            "protocols": fs_counts["protocols"],
            "ops": fs_counts["ops"] if fs_sanitized else None,
            "unattributed": (
                fs_counts["unattributed"] if fs_sanitized else None
            ),
        }
        # ---- lifecycle ground truth (lint G025 cross-checks the
        # static state-machine/ownership model against exactly this
        # block) ----
        lc_counts = lifecycle_sanitizer.counters()
        lifecycle_block = {
            "version": 1,
            "sanitized": lifecycle_sanitized,
            # armed surfaces (G025's dead-machine/dead-resource
            # scoping, the G011/G021 fence-tag pattern): the pool
            # surface arms with real tier traffic (a fleet that never
            # leaves its rows walks no doc edges), reshard with a
            # coordinator that actually began, stream with streaming
            # construction, ingest with a live front, prefetch with
            # the tiered pool's worker
            "pool": (stats.evictions + stats.restores
                     + pool.warm_evictions) > 0,
            "reshard": (
                reshard_coord is not None
                and reshard_coord.state != "idle"
            ),
            "stream": stream,
            "ingest": front is not None,
            "journal": journal is not None,
            "prefetch": pool.prefetcher is not None,
            "machines": lc_counts["machines"],
            "resources": lc_counts["resources"],
            "unattributed": lc_counts["unattributed"],
        }
        # ---- value-range ground truth (lint G029 cross-checks the
        # declared inrange=/mask= model against exactly this block) ----
        range_counts = range_sanitizer.counters()
        ranges_block = {
            "version": 1,
            "sanitized": range_sanitized,
            # armed surfaces (the dead-fact/dead-mask scoping): the
            # staging boundary is crossed on every drain; fused/scan
            # track which serve kernel this run dispatched, so a
            # kernel-scoped mask (the fused gap gather) is only
            # dead-checked against runs that ran that kernel
            "staging": True,
            "fused": serve_kernel == "fused",
            "scan": serve_kernel == "scan",
            "checks": range_counts["checks"],
            "masks": range_counts["masks"],
        }
        log(
            "serve: fs protocols — entries "
            + (", ".join(
                f"{k}={v}" for k, v in fs_counts["protocols"].items()
            ) or "none")
            + (f"; {sum(n for t in fs_counts['ops'].values() for n in t.values())} "
               "fs ops attributed" if fs_sanitized else "")
        )

        occ = stats.occupancy.mean
        r = BenchResult(
            group="serve",
            trace=mix_label,
            backend=str(n_docs),
            elements=stats.patches,
            samples=[stats.wall_time],
            replicas=1,
            extra={
                "family": "serve",
                "fleet_docs": n_docs,
                "batch": batch,
                "batch_chars": batch_chars,
                "macro_k": macro_k,
                "kernel": serve_kernel,
                "op_dtypes": [str(d) for d in pool.op_dtypes],
                "classes": list(classes),
                "slots": list(slots),
                "mesh_devices": mesh_devices if mesh else 0,
                "rounds": stats.rounds,
                "device_rounds": stats.slices,
                "range_ops": stats.ops,
                "unit_ops": stats.unit_ops,
                "coalesce_ratio": stats.coalesce_ratio,
                "pad_fraction": stats.pad_fraction,
                "patches_per_sec": throughput,
                "batch_latency": lat,
                "compile_time": compile_time,
                "compile_rounds": compile_rounds,
                "barrier_time": stats.barrier_time,
                "barrier_rounds": stats.barrier_rounds,
                "steady_rounds": stats.steady_rounds,
                "occupancy_mean": occ,
                "queue_depth_mean": stats.queue_depth.mean,
                "queue_depth_max": int(stats.queue_depth.vmax or 0),
                "evictions": stats.evictions,
                "restores": stats.restores,
                "promotions": stats.promotions,
                "admissions": stats.admissions,
                # ---- fault tolerance / robustness surface ----
                "queue_cap": queue_cap,
                "overflow_policy": overflow_policy,
                "shed_ops": stats.shed_ops,
                "deferred_ops": stats.deferred_ops,
                "overflow_events": stats.overflow_events,
                "backpressure_rounds": stats.backpressure_rounds,
                "dup_ops_dropped": stats.dup_ops_dropped,
                "stall_rounds": stats.stall_rounds,
                "quarantines": stats.quarantines,
                "recoveries": stats.recoveries,
                "ops_replayed": stats.ops_replayed,
                "replay_dispatches": stats.replay_dispatches,
                "mttr_rounds": summarize(stats.mttr_rounds),
                "degraded_rounds": stats.degraded_rounds,
                "lossy_docs": lossy,
                "journal": None if journal is None else {
                    "dir": None if owns_journal else journal_dir,
                    "records": journal.records,
                    "bytes": journal.bytes_written,
                    "fsync": journal_fsync,
                    "snapshots": stats.snapshots,
                    "snapshots_full": stats.snapshots_full,
                    "snapshots_delta": stats.snapshots_delta,
                    "snapshot_every": snapshot_every,
                    "snapshot_full_every": snapshot_full_every,
                    "snapshot_time": stats.snapshot_time,
                    # durability v2: segmented-WAL footprint (disk
                    # bytes are the bounded-footprint acceptance
                    # surface — O(ops since last snapshot) under GC)
                    "segment_bytes": wal_segment_bytes,
                    "segments_sealed": journal.segments_sealed,
                    "gc_segments": journal.gc_segments,
                    "disk_bytes": journal.on_disk_bytes(),
                },
                "longhaul": longhaul,
                # streaming fleet construction (ALWAYS present — eager
                # runs carry it too, so bench_compare can gate
                # construction_ms / peak RSS across modes; artifacts
                # predating the block skip-with-note one-sided).  The
                # verify sample's seed + doc ids ("verified_docs"
                # below) make the sampled oracle gate auditable.
                "construction": {
                    "version": 1,
                    "mode": "stream" if stream else "eager",
                    "construction_ms": construction_ms,
                    "rss_after_construction_bytes": rss_setup,
                    "peak_rss_bytes": peak_rss_bytes(),
                    "fleet_docs": n_docs,
                    "materialized_docs": (
                        streams.materialized if stream else n_docs
                    ),
                    "released_docs": (
                        streams.released if stream else 0
                    ),
                    "prefetch_built": (
                        streams.prefetch_built if stream else 0
                    ),
                    "genesis_docs_end": pool.genesis_docs,
                    "verify_sample_seed": vseed,
                    # fleet-size-vs-construction/RSS scaling rows from
                    # the fresh-subprocess probe (serve/construction.py)
                    # when --serve-stream-scaling ran; None otherwise
                    "scaling": construction_scaling,
                },
                # tiered residency (None unless --serve-tiers armed):
                # tier budgets + hit/miss/prefetch accounting — the
                # warm+prefetch hit rate is the number bench_compare
                # gates (one-sided skip-with-note, like timeseries)
                "residency": None if not warm_docs else {
                    "version": 1,
                    "tiers": serve_tiers,
                    "hot_rows_budget": sum(slots),
                    "warm_budget": warm_docs,
                    "arrival_dist": arrival_dist,
                    "hot_rows_final": pool.hot_rows,
                    "warm_docs_final": len(pool.warm),
                    "cold_docs_final": pool.cold_docs,
                    "evictions": stats.evictions,
                    "warm_hits": pool.warm_hits,
                    "warm_evictions": pool.warm_evictions,
                    "cold_restores": pool.restores,
                    "prefetch_hits": pool.prefetch_hits,
                    "prefetch_submitted": (
                        pool.prefetcher.submitted
                        if pool.prefetcher is not None else 0
                    ),
                    "prefetch_harvested": (
                        pool.prefetcher.harvested
                        if pool.prefetcher is not None else 0
                    ),
                    "prefetch_dropped": (
                        pool.prefetcher.dropped
                        if pool.prefetcher is not None else 0
                    ),
                    "prefetch_errors": (
                        pool.prefetcher.errors
                        if pool.prefetcher is not None else 0
                    ),
                    "prefetch_wasted": sched.prefetch_wasted,
                    "prefetch_missed": sched.prefetch_missed,
                    # of the admissions that needed a doc's state back,
                    # how many avoided the synchronous cold read
                    "hit_rate": (
                        (pool.warm_hits)
                        / (pool.warm_hits + pool.restores)
                        if (pool.warm_hits + pool.restores) else None
                    ),
                },
                # measured recovery-time objective (None unless the
                # recovery leg ran): recover_ms + redo-span +
                # chain-depth breakdown, gated by bench_compare
                "recovery": recovery_block,
                # elastic reconfiguration (None unless --serve-reshard
                # armed): the coordinator's full ledger — move/demote
                # counts, deferred lanes/ops, crash resumes, and the
                # mid-reshard round-latency quantiles bench_compare
                # gates (one-sided skip-with-note, like recovery)
                "reshard": (
                    None if reshard_coord is None
                    else {
                        **reshard_coord.summary(),
                        "partition_errors": partition_errors,
                    }
                ),
                # live ingest (None unless --serve-open armed): wire +
                # admission + deadline ground truth — offered load,
                # front/session counters, per-tenant admit/defer/shed,
                # EDF hit rate (bench_compare: one-sided skip-with-note)
                "ingest": None if front is None else {
                    "version": 1,
                    "open": open_plan.to_dict(),
                    "front": front.status_fields(),
                    "client": load_client.to_dict(),
                    "admission": admission.to_dict(),
                    "deadline": sched.deadline_fields(),
                    "late_frames": pump.late_frames,
                    "admitted_frames": pump.admitted_frames,
                    "dup_frames": pump.dup_frames,
                    "shed_docs": pump.shed_docs,
                    "drained_frames": pump.drained_frames,
                },
                # offered-load sweep output (run_serve_open_sweep's
                # final run only): the p99-vs-utilization knee curve
                "knee": knee_block,
                "faults": fault_summary,
                "boundary_syncs": boundary_syncs,
                "thread_crossings": thread_crossings,
                "fs_ops": fs_ops_block,
                # versioned lifecycle block: state-machine edge counts
                # + ownership acquire/release ledger (lint G025's
                # ground truth; bench_compare: skip-with-note)
                "lifecycle": lifecycle_block,
                # versioned value-range block: staged index-check and
                # clamp-mask dispatch counters (lint G029's ground
                # truth; bench_compare: skip-with-note)
                "ranges": ranges_block,
                # versioned typed-metric registry: every counter /
                # gauge / histogram the drain emitted (obs/metrics.py)
                "metrics": stats.metrics.to_dict(),
                # per-doc admission-to-drain latency by cause tag
                "doc_drain_latency": {
                    tag: {
                        "count": h.count,
                        "quantiles": (
                            h.quantiles((0.5, 0.99, 0.999))
                            if h.count else None
                        ),
                    }
                    for tag, h in sorted(stats.doc_latency.items())
                },
                "profile": profile_block,
                # continuous telemetry (obs/ v2): windowed per-round
                # time-series + soak anomaly verdicts, both versioned
                "timeseries": (
                    telemetry.recorder.block()
                    if telemetry is not None and telemetry.recorder
                    is not None else None
                ),
                "anomalies": (
                    telemetry.anomaly.block()
                    if telemetry is not None and telemetry.anomaly
                    is not None else None
                ),
                # obs/ v3: request-scoped tracing, SLO accounting and
                # the flight recorder — all versioned, all optional
                # (disarmed runs carry None, bench_compare skips-with-
                # note like the other one-sided blocks)
                "reqtrace": reqtrace.block() if reqtrace.armed else None,
                "slo": slo.block() if slo is not None else None,
                "flight": (
                    telemetry.flight.summary()
                    if telemetry is not None and telemetry.flight
                    is not None else None
                ),
                "status_port": (
                    telemetry.status.port
                    if telemetry is not None and telemetry.status
                    is not None else None
                ),
                "trace": trace_path if tracer is not None else None,
                "docs_per_class": {
                    str(c): len(v) for c, v in sorted(by_class.items())
                },
                "verified_docs": sorted(sample),
                "verify_ok": verify_ok,
            },
        )
        kw = {"results_dir": results_dir} if results_dir else {}
        path = save_results([r], save_name or default_name, **kw)
        log(f"serve: wrote {path}")
        return r, {
            "verify_ok": verify_ok,
            "faults_ok": faults_ok,
            "anomalies_ok": (
                telemetry is None or telemetry.anomaly is None
                or telemetry.anomaly.uncleared == 0
            ),
            "path": path,
            "stats": stats,
        }
    finally:
        reqtrace.release()  # drop the publish observer: each run owns
        # its hop window (idempotent; no-op disarmed)
        if journal is not None:
            journal.close()
        if owns_journal:
            shutil.rmtree(journal_dir, ignore_errors=True)
        if owns_telemetry and telemetry is not None:
            telemetry.close()  # stop the status server, close the stream
        if front is not None:
            front.stop()  # idempotent; kills handler threads on a crash
        if pool is not None:
            pool.close()  # drop an owned spool directory


def run_serve_open_sweep(
    sweep_rates,
    *,
    open_spec: str,
    save_name: str | None = None,
    log=print,
    **kw,
) -> tuple[BenchResult, dict]:
    """Offered-load sweep: probe the open-loop drain at each rate in
    ``sweep_rates``, then run the CONFIGURED rate (``open_spec``) as
    the final, artifact-bearing run with the measured knee curve
    attached as its ``knee`` block.

    Each probe is a full open-loop drain (live front, real wire) at
    ``probe_rate`` with the heavyweight side-channels stripped
    (faults, status server, time-series, profiling — the probes
    measure latency vs load, nothing else).  Per probe we record
    offered rate, served rate (``range_ops / rounds``), p50/p99 batch
    latency, and the defer/shed tallies; ``capacity`` is the highest
    served rate any probe sustained, so each point's utilization is
    ``offered / capacity`` and the p99-vs-utilization series IS the
    knee curve the paper plots.
    """
    rate, process = parse_open_spec(open_spec)
    rates = sorted({float(r) for r in sweep_rates} | {rate})
    points = []
    for probe_rate in rates:
        probe_kw = dict(kw)
        for heavy in ("faults", "status_port", "timeseries_path",
                      "profile_rounds", "trace_path", "journal_dir"):
            probe_kw.pop(heavy, None)
        _, info = run_serve_bench(
            open_spec=f"{probe_rate:g}:{process}",
            log=lambda *_a, **_k: None,
            **probe_kw,
        )
        st = info["stats"]
        lat = st.latency_quantiles()
        served = st.ops / max(1, st.rounds)
        points.append({
            "offered_rate": probe_rate,
            "served_rate": round(served, 3),
            "rounds": st.rounds,
            "p50_ms": round(lat["p50"] * 1e3, 4),
            "p99_ms": round(lat["p99"] * 1e3, 4),
            "deferred_ops": st.deferred_ops,
            "shed_ops": st.shed_ops,
            "verify_ok": bool(info["verify_ok"]),
        })
        log(
            f"serve: sweep probe {probe_rate:g} ops/round — served "
            f"{served:.1f}, p99 {lat['p99'] * 1e3:.2f}ms, "
            f"deferred {st.deferred_ops} shed {st.shed_ops}"
        )
    capacity = max(p["served_rate"] for p in points) or 1.0
    for p in points:
        p["utilization"] = round(p["offered_rate"] / capacity, 4)
    knee_block = {
        "version": 1,
        "process": process,
        "capacity_ops_per_round": capacity,
        "points": points,
    }
    log(
        f"serve: knee — capacity {capacity:.1f} ops/round over "
        f"{len(points)} probes; final run at {rate:g} "
        f"(utilization {rate / capacity:.2f})"
    )
    return run_serve_bench(
        open_spec=open_spec, knee_block=knee_block,
        save_name=save_name, log=log, **kw,
    )


def run_serve_soak(
    soak_seconds: float = 0.0,
    *,
    seed: int = 0,
    status_port: int | None = None,
    timeseries_path: str | None = None,
    timeseries_window: int = 8,
    watchdog_s: float = 0.0,
    flight_path: str | None = None,
    log=print,
    **kw,
) -> tuple[BenchResult, dict]:
    """Soak harness: drain fleets back-to-back until ``soak_seconds``
    of wall time have elapsed (0 = exactly one drain), under ONE shared
    telemetry bundle — the time-series windows, anomaly detectors and
    status endpoint run continuously across every drain, so a slow leak
    or creeping degradation that no single drain would show still trips
    a detector.  Every iteration re-seeds the workload (``seed + i``)
    and byte-verifies against the oracle like a normal run; the LAST
    iteration's artifact carries the whole soak's ``timeseries`` /
    ``anomalies`` blocks (the recorder's ring is shared).

    Exit contract (surfaced via ``info``): ``verify_ok`` / ``faults_ok``
    are the AND over all iterations; ``anomalies_ok`` is False when any
    anomaly is still active at soak end — an anomaly that fired and
    CLEARED (a stall the engine absorbed) does not fail the soak.

    ``/healthz`` staleness is armed for the soak (120s without a
    publish -> 503; generous because fleet builds between drains do
    not publish — each drain opens with a "building" heartbeat)."""
    telemetry = build_telemetry(
        status_port=status_port, timeseries_path=timeseries_path,
        timeseries_window=timeseries_window,
        anomaly=True, watchdog_s=watchdog_s, stale_after=120.0,
        flight_path=flight_path, log=log,
    )
    import time as _time

    t0 = _time.perf_counter()
    i = 0
    verify_ok = faults_ok = True
    try:
        while True:
            r, info = run_serve_bench(
                seed=seed + i, telemetry=telemetry, log=log, **kw
            )
            verify_ok &= info["verify_ok"]
            faults_ok &= info["faults_ok"]
            i += 1
            elapsed = _time.perf_counter() - t0
            if elapsed >= soak_seconds:
                break
            log(
                f"serve: soak {elapsed:.1f}/{soak_seconds:.0f}s — "
                f"iteration {i} done, re-draining"
            )
        a = telemetry.anomaly
        log(
            f"serve: soak done — {i} drain(s) in "
            f"{_time.perf_counter() - t0:.1f}s; anomalies {a.fired} "
            f"fired / {a.uncleared} uncleared"
        )
        info = dict(info)
        info["verify_ok"] = verify_ok
        info["faults_ok"] = faults_ok
        info["anomalies_ok"] = a.uncleared == 0
        info["iterations"] = i
        return r, info
    finally:
        telemetry.close()

