"""The ``serve/repl`` bench family: writer groups at fleet scale.

Bench id grammar: ``serve/repl/<mix>/<fleet>x<writers>`` — ``fleet``
logical documents, each served by ``writers`` concurrent writer
replicas (so the pool hosts ``fleet * writers`` rows).  Reported on top
of the plain serve surface:

- **merge throughput** — remote (broadcast) unit ops merged into
  replica rows per second of drain wall time: the paper's *downstream*
  family at serve scale;
- **broadcast fan-out** — packed op-lane bytes delivered to remote
  replicas (the replication tax the wire would carry);
- **divergence window** — deepest per-replica broadcast lag observed,
  in turn blocks, plus the convergence window (rounds from last publish
  to full assembly everywhere);
- the ``replication`` artifact block with the full topology + counters
  (``ReplicatedScheduler.replication_block``).

The exit gate is the new verification tier, not just byte parity: after
drain (1) EVERY replica of every logical doc must decode byte-identical
to the sequential oracle replay — convergence — and (2) the sampled
per-doc broadcast histories must satisfy the RA-linearizability
visibility axioms (serve/replicate/checker.py).  Chaos mode wires the
two replication fault kinds (``replica_partition`` / ``merge_reorder``)
through the same seeded FaultPlan grammar as the plain family.
"""

from __future__ import annotations

import shutil
import tempfile

from ...bench.harness import BenchResult, save_results
from ...lint import race_sanitizer
from ..bench import _parse_int_tuple, arm_reqtrace, parse_slo
from ..faults import FaultInjector, FaultPlan
from ..journal import OpJournal
from ..pool import DocPool
from ..scheduler import prepare_streams
from ..workload import build_fleet
from .checker import (
    ConvergenceReport,
    check_convergence,
    check_ra_linearizability,
)
from .group import build_writer_groups
from .scheduler import ReplicatedScheduler


def run_serve_repl_bench(
    mix="mixed",
    n_docs: int = 512,
    writers: int = 4,
    batch: int = 64,
    classes=(256, 1024, 4096, 8192, 49152),
    slots=(2048, 512, 128, 32, 16),
    seed: int = 0,
    arrival_span: int = 8,
    bands: dict | None = None,
    macro_k: int = 8,
    batch_chars: int = 256,
    serve_kernel: str = "fused",
    turn_ops: int = 64,
    remote_lag: int = 1,
    history_sample: int = 16,
    spool_dir: str | None = None,
    journal_dir: str | None = None,
    snapshot_every: int = 32,
    faults=None,
    results_dir: str | None = None,
    save_name: str | None = None,
    reqtrace_samples: int = 0,
    slo_spec: str | None = None,
    log=print,
) -> tuple[BenchResult, dict]:
    """Build a replicated fleet, drain it, run the convergence +
    RA-linearizability verification tier, persist the artifact.
    Returns (BenchResult, info) with ``info["verify_ok"]`` (all
    replicas byte-identical to the oracle), ``info["ra_ok"]`` and, in
    chaos mode, ``info["faults_ok"]``."""
    if writers < 1:
        raise ValueError(f"writers must be >= 1, got {writers}")
    classes = _parse_int_tuple(classes)
    slots = _parse_int_tuple(slots)
    mix_name = mix if isinstance(mix, str) else "custom"

    plan = None
    if faults is not None:
        plan = faults if isinstance(faults, FaultPlan) else (
            FaultPlan.from_spec(faults)
        )
        if any(e.kind == "queue_overflow" for e in plan.events):
            # the mirror of run_serve_bench's REPLICATION_KINDS guard:
            # the replicated family has no bounded producer queue (the
            # broadcast bus owns delivery pacing), so the event could
            # never fire — reject up front instead of failing the chaos
            # gate with "never fired" after a whole drain
            raise ValueError(
                "queue_overflow needs the plain family's bounded queue "
                "(--serve-queue-cap); the replicated family's delivery "
                "pacing is the broadcast bus's"
            )
    # request tracing + SLO accounting (obs/ v3): same arming rule as
    # the plain family — replica requests are requests.  Spec parse
    # fails BEFORE the journal tempdir exists; the tracker (whose armed
    # form installs the publish observer the finally releases) is
    # constructed last before the try, same contract as the plain bench.
    slo = parse_slo(slo_spec)

    owns_journal = journal_dir == "auto"
    if owns_journal:
        journal_dir = tempfile.mkdtemp(prefix="crdt_repl_journal_")
    journal = OpJournal(journal_dir) if journal_dir else None

    reqtrace = arm_reqtrace(reqtrace_samples, slo, slo_spec, log,
                            prefix="serve/repl")

    pool = None
    try:
        # publish-point counters start BEFORE the first publish (the
        # artifact's thread_crossings block is G017's ground truth for
        # the bus surface — only this family drives it)
        race_sanitizer.reset_counters()
        race_sanitized = race_sanitizer.sanitizing()
        if race_sanitized:
            log("serve/repl: race sanitizer ARMED "
                "(CRDT_BENCH_SANITIZE_RACES)")
        log(
            f"serve/repl: building fleet n_docs={n_docs} x "
            f"writers={writers} mix={mix_name} seed={seed}"
        )
        sessions = build_fleet(
            n_docs, mix=mix, seed=seed, arrival_span=arrival_span,
            bands=bands,
        )
        replica_sessions, table = build_writer_groups(sessions, writers)
        pool = DocPool(classes=classes, slots=slots,
                       spool_dir=spool_dir, serve_kernel=serve_kernel)
        streams = prepare_streams(
            replica_sessions, pool, batch=batch, batch_chars=batch_chars
        )
        total_ops = sum(s.remaining for s in streams.values())
        log(
            f"serve/repl: {len(table)} groups, "
            f"{len(replica_sessions)} replica rows, {total_ops} range "
            f"ops staged fleet-wide, turn_ops={turn_ops} "
            f"lag={remote_lag} K={macro_k} kernel={serve_kernel}"
        )
        sched = ReplicatedScheduler(
            pool, streams, table,
            turn_ops=turn_ops, remote_lag=remote_lag,
            history_sample=history_sample, seed=seed,
            batch=batch, macro_k=macro_k, batch_chars=batch_chars,
            faults=FaultInjector(plan) if plan else None,
            journal=journal, snapshot_every=snapshot_every,
            reqtrace=reqtrace, slo=slo,
            warm_start=True,
        )
        stats = sched.run()
        assert sched.done, "replicated scheduler stopped with pending work"
        throughput = stats.patches / stats.wall_time
        merge_tput = sched.merged_unit_ops / stats.wall_time
        lat = stats.latency_quantiles()
        log(
            f"serve/repl: drained in {stats.wall_time:.2f}s over "
            f"{stats.rounds} macro-rounds -> {throughput:,.0f} "
            f"replica-patches/s, merge {merge_tput:,.0f} unit-ops/s "
            f"({sched.merged_ops} remote / {sched.local_ops} local "
            f"range ops), broadcast "
            f"{sched.bus.bytes_broadcast / 1024:.1f} KiB over "
            f"{sched.bus.blocks_delivered_remote} deliveries, "
            f"divergence max {sched.bus.divergence_max} blocks"
        )

        # ---- the verification tier: convergence + RA-linearizability
        report = ConvergenceReport()
        check_convergence(pool, table, sessions, streams, report)
        check_ra_linearizability(sched.bus, table, report)
        log(
            f"serve/repl: convergence — {report.replicas_checked} "
            f"replicas across {report.groups_checked} groups "
            + ("all byte-identical to oracle" if report.converged
               else f"MISMATCH x{len(report.byte_mismatches)}: "
                    f"{report.byte_mismatches[:4]}")
            + (f" ({len(report.lossy_groups)} lossy groups excluded)"
               if report.lossy_groups else "")
        )
        log(
            f"serve/repl: RA-linearizability — "
            f"{report.ra_groups_checked} sampled histories "
            + ("all axioms hold" if report.ra_ok
               else f"VIOLATIONS: {report.ra_violations[:4]}")
        )

        fault_summary = plan.summary() if plan is not None else None
        faults_ok = fault_summary is None or (
            fault_summary["unrecovered"] == 0
            and fault_summary["not_fired"] == 0
        )
        if fault_summary is not None and not faults_ok:
            log(
                f"serve/repl: FAULTS NOT CLEARED — "
                f"{fault_summary['unrecovered']} unrecovered, "
                f"{fault_summary['not_fired']} never fired"
            )

        r = BenchResult(
            group="serve/repl",
            trace=mix_name,
            backend=f"{n_docs}x{writers}",
            elements=stats.patches,
            samples=[stats.wall_time],
            replicas=writers,
            extra={
                "family": "serve-repl",
                "fleet_docs": n_docs,
                "writers": writers,
                "replica_rows": n_docs * writers,
                "batch": batch,
                "batch_chars": batch_chars,
                "macro_k": macro_k,
                "kernel": serve_kernel,
                "classes": list(classes),
                "slots": list(slots),
                "rounds": stats.rounds,
                "range_ops": stats.ops,
                "unit_ops": stats.unit_ops,
                "patches_per_sec": throughput,
                "merge_unit_ops_per_sec": merge_tput,
                "batch_latency": lat,
                "compile_time": stats.compile_time,
                "compile_rounds": stats.compile_rounds,
                "steady_rounds": stats.steady_rounds,
                "occupancy_mean": stats.occupancy.mean,
                "evictions": stats.evictions,
                "restores": stats.restores,
                "promotions": stats.promotions,
                "coalesce_ratio": stats.coalesce_ratio,
                "pad_fraction": stats.pad_fraction,
                "replication": sched.replication_block(),
                "convergence": report.to_dict(),
                "faults": fault_summary,
                "journal": None if journal is None else {
                    "records": journal.records,
                    "bytes": journal.bytes_written,
                    "snapshots": stats.snapshots,
                    "snapshot_every": snapshot_every,
                },
                "metrics": stats.metrics.to_dict(),
                # G017 ground truth: the ONLY family that arms the
                # broadcast-bus publish surface — without this block a
                # dead BroadcastBus._cross_block annotation (and the
                # silently missing bus hop in replica traces) would
                # never be flagged
                "thread_crossings": {
                    "sanitized": race_sanitized,
                    "status": False,  # repl family rejects --serve-status
                    "journal": journal is not None,
                    "bus": True,
                    # surfaces the replicated family never arms — the
                    # keys must still be RECORDED (False) or G017
                    # treats their publish tags as unmatchable
                    "prefetch": False,  # repl pool is flat, no tiers
                    "ingest": False,  # repl family rejects --serve-open
                    "publishes": race_sanitizer.counters()["publishes"],
                    "crossings": (
                        race_sanitizer.counters()["crossings"]
                        if race_sanitized else None
                    ),
                },
                "reqtrace": reqtrace.block() if reqtrace.armed else None,
                "slo": slo.block() if slo is not None else None,
                "verify_ok": report.converged,
                "ra_ok": report.ra_ok,
            },
        )
        kw = {"results_dir": results_dir} if results_dir else {}
        path = save_results(
            [r],
            save_name or f"serve_repl_{mix_name}_{n_docs}x{writers}",
            **kw,
        )
        log(f"serve/repl: wrote {path}")
        return r, {
            "verify_ok": report.converged,
            "ra_ok": report.ra_ok,
            "faults_ok": faults_ok,
            "path": path,
            "stats": stats,
            "report": report,
            "scheduler": sched,
        }
    finally:
        reqtrace.release()  # drop the publish observer (idempotent)
        if journal is not None:
            journal.close()
        if owns_journal:
            shutil.rmtree(journal_dir, ignore_errors=True)
        if pool is not None:
            pool.close()
