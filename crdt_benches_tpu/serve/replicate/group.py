"""Writer groups: the replica topology of the replicated fleet.

One logical document served by W concurrent writers becomes a **writer
group**: W replica documents (each a real pool row with its own
capacity-class residency, spool checkpoints, and journal lanes), one per
writer, plus a deterministic authorship split of the doc's op stream
into round-robin **turn blocks** (``serve/workload.py split_turns``).
Block ``j`` is authored by writer ``j % W``; ascending block sequence is
the group's **arbitration order**, and it concatenates back to exactly
the original stream — so the sequential oracle replay of the logical
doc is the converged state every replica must reach byte-for-byte.

Replica doc ids are dense: logical doc ``d``'s replica for writer ``w``
is ``d * W + w``.  Replicas share the logical session's trace object
(``workload.replicate_sessions``), so ``prepare_streams`` tensorizes
each stream once; the per-replica state that differs is cursor/delivery
bookkeeping, which is exactly what the broadcast bus owns.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..workload import Session, replicate_sessions, split_turns


@dataclass
class ReplicaGroup:
    """One logical document's writer group."""

    logical_id: int
    writers: int
    replica_ids: tuple[int, ...]  # replica_ids[w] = writer w's pool doc
    blocks: list[tuple[int, int, int]] = field(default_factory=list)
    n_ops: int = 0  # coalesced range ops in the logical stream

    @property
    def n_blocks(self) -> int:
        return len(self.blocks)

    def owner(self, seq: int) -> int:
        return self.blocks[seq][2]

    def block_span(self, seq: int) -> tuple[int, int]:
        # (named block_span, not span: the hot-path walks resolve
        # attribute calls by bare name, and `span` is the obs tracer's
        # G012-policed constant-name API)
        lo, hi, _w = self.blocks[seq]
        return lo, hi

    def prefix_ops(self, n_blocks: int) -> int:
        """Ops covered by the first ``n_blocks`` blocks (the assembled
        delivery prefix in op units)."""
        if n_blocks <= 0:
            return 0
        return self.blocks[min(n_blocks, len(self.blocks)) - 1][1]

    def _remote_segments(self, writer: int, lo: int, hi: int):
        """THE block walk: ``(a, b, owner)`` sub-segments of
        ``[lo, hi)`` authored by writers other than ``writer``, in
        stream order.  Host arithmetic over the few blocks a slice
        spans (blocks are uniform ``turn_ops`` wide except the last);
        every remote-share view below derives from this one walk so a
        block-layout change lands in exactly one place."""
        if hi <= lo or not self.blocks:
            return
        turn = self.blocks[0][1] - self.blocks[0][0]
        seq = min(lo // turn, len(self.blocks) - 1)
        while seq < len(self.blocks):
            blo, bhi, w = self.blocks[seq]
            if blo >= hi:
                break
            a, b = max(lo, blo), min(hi, bhi)
            if b > a and w != writer:
                yield a, b, w
            seq += 1

    def remote_intervals(self, writer: int, lo: int,
                         hi: int) -> list[tuple[int, int]]:
        """Sub-intervals of ``[lo, hi)`` authored by writers OTHER than
        ``writer`` — the remote (downstream-merge) share of a staged
        slice, adjacent segments coalesced."""
        out: list[tuple[int, int]] = []
        for a, b, _w in self._remote_segments(writer, lo, hi):
            if out and out[-1][1] == a:
                out[-1] = (out[-1][0], b)
            else:
                out.append((a, b))
        return out

    def split_local_remote(self, writer: int, lo: int,
                           hi: int) -> tuple[int, int]:
        """(local, remote) op counts of ``[lo, hi)`` for ``writer`` —
        local = ops in blocks this writer authored (the upstream half),
        remote = everything merged from its peers' broadcasts."""
        if hi <= lo:
            return 0, 0
        rem = sum(b - a for a, b in self.remote_intervals(writer, lo, hi))
        return (hi - lo) - rem, rem


class GroupTable:
    """The fleet's replica topology: groups plus the replica -> (group,
    writer) inverse, built once at fleet construction."""

    def __init__(self, groups: list[ReplicaGroup]):
        self.groups = groups
        self.by_replica: dict[int, tuple[ReplicaGroup, int]] = {}
        for g in groups:
            for w, rid in enumerate(g.replica_ids):
                self.by_replica[rid] = (g, w)

    def __iter__(self):
        return iter(self.groups)

    def __len__(self) -> int:
        return len(self.groups)

    def group_of(self, replica_id: int) -> tuple[ReplicaGroup, int]:
        return self.by_replica[replica_id]


def build_writer_groups(
    sessions: list[Session], writers: int,
) -> tuple[list[Session], GroupTable]:
    """Expand logical sessions into replica sessions and the group
    table.  Blocks are attached later (:func:`attach_turn_blocks`) —
    the turn split needs the COALESCED op count, which only exists
    after ``prepare_streams`` tensorizes the traces."""
    replica_sessions = replicate_sessions(sessions, writers)
    groups = [
        ReplicaGroup(
            logical_id=s.doc_id,
            writers=writers,
            replica_ids=tuple(
                s.doc_id * writers + w for w in range(writers)
            ),
        )
        for s in sessions
    ]
    return replica_sessions, GroupTable(groups)


def attach_turn_blocks(table: GroupTable, streams, turn_ops: int) -> None:
    """Compute every group's turn split from the tensorized stream
    lengths (identical across a group's replicas — they share the
    trace).  Deterministic: recovery rebuilds the same split from the
    workload alone."""
    for g in table.groups:
        st = streams[g.replica_ids[0]]
        g.n_ops = st.n_total
        g.blocks = split_turns(g.n_ops, g.writers, turn_ops)
