"""Convergence + replication-aware-linearizability verification tier.

Byte parity alone says the right *string* came out; it does not say the
replication protocol behaved.  This module is the explicit checker the
replicated bench family gates on, in two halves:

**Convergence** (:func:`check_convergence`): after drain, every replica
of every logical document must decode byte-identical to the sequential
oracle replay of the logical stream — and therefore to each other.
This is the CRDT convergence property ("all replicas that delivered the
same ops have the same state") made total: the arbitration order is the
turn-block sequence, and its sequential replay is the specification.

**RA-linearizability** (:func:`check_ra_linearizability`): following
"Replication-Aware Linearizability" (PAPERS.md, arXiv 1903.06560), a
replicated history is RA-linearizable when per-replica behavior can be
explained by a linearization of the *effector* events that (i) respects
each session's program order, (ii) delivers each effector exactly once
per replica, (iii) applies effectors consistently with the arbitration
order, and (iv) eventually delivers everything everywhere.  Our bus
arbitrates by total block sequence and replicas apply assembled
prefixes, so the axioms instantiate to concrete checks over the
recorded delivery histories (``BroadcastBus.histories``, sampled
per-doc):

- **A1 session order** — for every replica, the blocks authored by any
  single writer appear in its delivery history in ascending sequence
  (a writer's effects are never observed out of program order);
- **A2 exactly-once** — no block is delivered twice to a replica (the
  bus reassembly is idempotent; a duplicate in the *history* would
  mean an op could integrate twice);
- **A3 read-your-writes** — a writer's own block is delivered to its
  own replica in the round it was published (local effectors apply
  immediately; RA-linearizability's requirement that the generator's
  source replica observes its own update);
- **A4 eventual visibility** — every replica's final delivered set is
  the complete block sequence;
- **A5 arbitration-consistent apply** — the replica's *applied* stream
  (its assembled prefix) is exactly the ascending-sequence order: the
  delivered set reassembles into the arbitration total order with no
  gaps or inversions.  Combined with A4 this is what makes every
  replica's integration a linearization of the same sequential
  specification — the reduction the paper's Theorem 4.1-style argument
  needs for CRDTs with a total arbitration.

Each violated axiom yields a structured finding; the bench exits
nonzero on any.  Tests feed doctored histories to prove the checker
actually discriminates (a checker that cannot fail checks nothing).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ...oracle.text_oracle import replay_trace
from .broadcast import BroadcastBus
from .group import GroupTable


@dataclass
class ConvergenceReport:
    """What the post-drain verification tier found."""

    groups_checked: int = 0
    replicas_checked: int = 0
    byte_mismatches: list[dict] = field(default_factory=list)
    ra_groups_checked: int = 0
    ra_violations: list[dict] = field(default_factory=list)
    lossy_groups: list[int] = field(default_factory=list)

    @property
    def converged(self) -> bool:
        return not self.byte_mismatches and self.replicas_checked > 0

    @property
    def ra_ok(self) -> bool:
        return not self.ra_violations

    def to_dict(self) -> dict:
        return {
            "groups_checked": self.groups_checked,
            "replicas_checked": self.replicas_checked,
            "converged": self.converged,
            "byte_mismatches": self.byte_mismatches[:16],
            "ra_groups_checked": self.ra_groups_checked,
            "ra_ok": self.ra_ok,
            "ra_violations": self.ra_violations[:16],
            "lossy_groups": self.lossy_groups[:16],
        }


def check_convergence(
    pool,
    table: GroupTable,
    sessions,
    streams,
    report: ConvergenceReport | None = None,
) -> ConvergenceReport:
    """Decode EVERY replica of every logical doc and byte-compare it
    against the sequential oracle replay of the logical stream.  Groups
    containing a lossy replica (explicit shed/quarantine) are excluded
    from parity — their loss is a surfaced decision — and reported in
    ``lossy_groups`` instead."""
    rep = report or ConvergenceReport()
    session_of = {s.doc_id: s for s in sessions}
    for g in table:
        if any(streams[rid].lossy for rid in g.replica_ids):
            rep.lossy_groups.append(g.logical_id)
            continue
        want = replay_trace(session_of[g.logical_id].trace)
        rep.groups_checked += 1
        for w, rid in enumerate(g.replica_ids):
            rep.replicas_checked += 1
            got = pool.decode(rid)
            if got != want:
                rep.byte_mismatches.append({
                    "group": g.logical_id, "writer": w, "replica": rid,
                    "got_len": len(got), "want_len": len(want),
                })
    return rep


def _axiom_violations(
    gid: int,
    group,
    histories: list[list[tuple[int, int]]],
    publish_log: list[tuple[int, int]],
) -> list[dict]:
    """The A1-A5 checks for ONE group's recorded histories (see module
    docstring).  Pure host data — callable on doctored histories by the
    tests."""
    out: list[dict] = []
    n_blocks = group.n_blocks
    publish_round = {seq: rnd for rnd, seq in publish_log}

    for w, hist in enumerate(histories):
        seqs = [seq for _rnd, seq in hist]
        # A2 exactly-once
        if len(seqs) != len(set(seqs)):
            dup = sorted(
                s for s in set(seqs) if seqs.count(s) > 1
            )[0]
            out.append({
                "axiom": "A2-exactly-once", "group": gid, "writer": w,
                "detail": f"block {dup} delivered more than once",
            })
        # A1 session order, per authoring writer
        last_by_author: dict[int, int] = {}
        for seq in seqs:
            a = group.owner(seq)
            prev = last_by_author.get(a)
            if prev is not None and seq < prev:
                out.append({
                    "axiom": "A1-session-order", "group": gid,
                    "writer": w,
                    "detail": (
                        f"writer {a}'s block {seq} delivered after its "
                        f"block {prev}"
                    ),
                })
                break
            last_by_author[a] = seq
        # A3 read-your-writes (only checkable where the publish log
        # was recorded)
        own_delivery = {
            seq: rnd for rnd, seq in hist if group.owner(seq) == w
        }
        for seq, prnd in publish_round.items():
            if group.owner(seq) != w:
                continue
            drnd = own_delivery.get(seq)
            if drnd is None or drnd > prnd:
                out.append({
                    "axiom": "A3-read-your-writes", "group": gid,
                    "writer": w,
                    "detail": (
                        f"own block {seq} published round {prnd} but "
                        f"locally delivered "
                        f"{'never' if drnd is None else f'round {drnd}'}"
                    ),
                })
                break
        # A4 eventual visibility
        if set(seqs) != set(range(n_blocks)):
            missing = sorted(set(range(n_blocks)) - set(seqs))
            out.append({
                "axiom": "A4-eventual-visibility", "group": gid,
                "writer": w,
                "detail": f"{len(missing)} blocks never delivered "
                          f"(first: {missing[:4]})",
            })
        # A5 arbitration-consistent apply: the assembled (applied)
        # stream is the delivered set reassembled by sequence — it must
        # be the gap-free arbitration prefix order.  With A2/A4 green
        # this means sorted(seqs) == range(n_blocks); check explicitly
        # so a doctored assembly is caught even when A4 was skipped.
        applied = sorted(set(seqs))
        if applied != list(range(len(applied))):
            out.append({
                "axiom": "A5-arbitration-prefix", "group": gid,
                "writer": w,
                "detail": "delivered set does not reassemble into a "
                          "gap-free arbitration prefix",
            })
    return out


def check_ra_linearizability(
    bus: BroadcastBus,
    table: GroupTable,
    report: ConvergenceReport | None = None,
) -> ConvergenceReport:
    """Validate the A1-A5 visibility axioms over every group the bus
    recorded histories for (the sampled set)."""
    rep = report or ConvergenceReport()
    by_id = {g.logical_id: g for g in table}
    for gid in sorted(bus.histories):
        group = by_id[gid]
        rep.ra_groups_checked += 1
        rep.ra_violations.extend(_axiom_violations(
            gid, group, bus.histories[gid],
            bus.publish_log.get(gid, []),
        ))
    return rep
