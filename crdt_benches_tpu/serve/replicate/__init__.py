"""serve/replicate/ — multi-writer replication over the document fleet.

The reference paper benchmarks two op families: *upstream* (local
edits) and *downstream* (remote-update apply).  The serve engine only
ever exercised the upstream shape — one patch stream per doc, so
"millions of users" meant concurrent *documents*.  This package turns
every served document into a **writer group**: N writer replicas per
doc, each a real pool row, each consuming its own authored slice of the
workload stream, with op broadcast and batched downstream merge routed
through the existing engine merge paths INSIDE the macro-round scan —
concurrent *editors*, device-resident end to end.

- :mod:`.group`     — writer groups: the round-robin turn-block
  authorship split (``serve/workload.py split_turns``), dense replica
  doc ids, local/remote op attribution;
- :mod:`.broadcast` — the broadcast bus: paced publish, lagged remote
  delivery, sequence-keyed reassembly (delivery order commutes),
  partition backlogs + heal, journaled ``bcast`` records for crash
  recovery, sampled per-replica delivery histories;
- :mod:`.scheduler` — ``ReplicatedScheduler``: the fleet scheduler
  with bus-owned delivery; remote ops merge through the same macro
  dispatch as local ones (``engine/merge_fleet.py`` scan body / its
  parity-pinned fused twin), replica rows evict/promote/recover like
  any pool row;
- :mod:`.checker`   — the new verification tier: full-fleet byte
  convergence against the sequential oracle AND the
  RA-linearizability visibility axioms (arXiv 1903.06560) over sampled
  broadcast histories;
- :mod:`.bench`     — bench family ``serve/repl/<mix>/<fleet>x<writers>``
  with merge-throughput / broadcast-fan-out / divergence-window /
  convergence-round artifact blocks, gated on the checker.
"""

from .broadcast import BroadcastBus, replay_journal_broadcasts
from .checker import (
    ConvergenceReport,
    check_convergence,
    check_ra_linearizability,
)
from .group import (
    GroupTable,
    ReplicaGroup,
    attach_turn_blocks,
    build_writer_groups,
)
from .scheduler import ReplicatedScheduler, recover_replicated_fleet

__all__ = [
    "BroadcastBus",
    "ConvergenceReport",
    "GroupTable",
    "ReplicaGroup",
    "ReplicatedScheduler",
    "attach_turn_blocks",
    "build_writer_groups",
    "check_convergence",
    "check_ra_linearizability",
    "recover_replicated_fleet",
    "replay_journal_broadcasts",
]
